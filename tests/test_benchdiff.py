"""tools/benchdiff.py tests — the cross-round regression detector the
acceptance criterion names: `benchdiff BENCH_r04.json BENCH_r05.json`
must name each changed metric with old/new/delta and exit non-zero on a
regression, including when one side is a tail-truncated artifact whose
rows only exist via the summary line."""

import importlib.util
import json
import os
import sys

import pytest

from deeplearning4j_tpu.telemetry import Recorder
from deeplearning4j_tpu.telemetry.artifact import build_summary

pytestmark = pytest.mark.telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "benchdiff", os.path.join(ROOT, "tools", "benchdiff.py"))
benchdiff = importlib.util.module_from_spec(spec)
sys.modules.setdefault("benchdiff", benchdiff)
spec.loader.exec_module(benchdiff)


def _lines(**metrics):
    return {m: dict(line, metric=m) for m, line in metrics.items()}


def test_value_drop_past_threshold_is_named_with_old_new_delta():
    old = _lines(tps={"value": 100.0})
    new = _lines(tps={"value": 80.0})
    result = benchdiff.diff(old, new, threshold=0.1)
    (row,) = result["regressions"]
    assert row["metric"] == "tps" and row["field"] == "value"
    assert row["old"] == 100.0 and row["new"] == 80.0
    assert row["delta_pct"] == -20.0
    assert "fell 20.0%" in row["reason"]


def test_small_drop_and_improvement_are_changes_not_regressions():
    old = _lines(a={"value": 100.0}, b={"value": 50.0})
    new = _lines(a={"value": 95.0}, b={"value": 70.0})
    result = benchdiff.diff(old, new, threshold=0.1)
    assert result["regressions"] == []
    deltas = {r["metric"]: r["delta_pct"] for r in result["changes"]}
    assert deltas == {"a": -5.0, "b": 40.0}


def test_gate_scale_grants_chip_state_slack():
    """A 15% drop measured on a window the probe read at 0.8x healthy is
    chip state, not code — bench.py's own gate philosophy."""
    old = _lines(tps={"value": 100.0})
    new = _lines(tps={"value": 85.0, "gate_scale": 0.8})
    assert benchdiff.diff(old, new, threshold=0.1)["regressions"] == []
    # without the gate_scale field the same drop regresses
    new_plain = _lines(tps={"value": 85.0})
    assert benchdiff.diff(old, new_plain, threshold=0.1)["regressions"]


def test_serve_latency_is_lower_is_better():
    """SERVE artifact rows (serving/replay.py) invert the direction:
    p99 GROWING past threshold regresses, p99 falling is a change; the
    flag comes from the line itself or the metric-name pattern (the
    summary reconstruction drops the flag)."""
    old = _lines(serving_replay_p99_ms={"value": 10.0,
                                        "lower_is_better": True})
    worse = _lines(serving_replay_p99_ms={"value": 14.0,
                                          "lower_is_better": True})
    (row,) = benchdiff.diff(old, worse, threshold=0.1)["regressions"]
    assert "lower is better" in row["reason"] and row["delta_pct"] == 40.0
    better = _lines(serving_replay_p99_ms={"value": 6.0,
                                           "lower_is_better": True})
    assert benchdiff.diff(old, better, threshold=0.1)["regressions"] == []
    # name-pattern fallback: summary-reconstructed rows keep only value
    old_bare = _lines(serving_replay_p50_ms={"value": 10.0})
    new_bare = _lines(serving_replay_p50_ms={"value": 14.0})
    assert benchdiff.diff(old_bare, new_bare, threshold=0.1)["regressions"]
    # QPS stays higher-is-better even in a SERVE artifact
    assert benchdiff.diff(_lines(serving_replay_qps={"value": 100.0}),
                          _lines(serving_replay_qps={"value": 80.0}),
                          threshold=0.1)["regressions"]


def test_input_pipeline_rows_direction():
    """INPUT artifact rows (bench input_pipeline): the input_wait stall
    percentiles are lower-is-better (growth past threshold = the step
    loop started starving), by flag and by name pattern; the speedup
    row stays higher-is-better (a falling pipelined/sync ratio is the
    overlap regression)."""
    old = _lines(input_pipeline_input_wait_p99_ms={
        "value": 0.05, "lower_is_better": True})
    worse = _lines(input_pipeline_input_wait_p99_ms={
        "value": 12.0, "lower_is_better": True})
    (row,) = benchdiff.diff(old, worse, threshold=0.1)["regressions"]
    assert "lower is better" in row["reason"]
    # name-pattern fallback for summary-reconstructed rows (flag lost)
    assert benchdiff.diff(
        _lines(input_pipeline_input_wait_p99_ms={"value": 0.05}),
        _lines(input_pipeline_input_wait_p99_ms={"value": 12.0}),
        threshold=0.1)["regressions"]
    # the speedup headline keeps the default direction
    assert benchdiff.diff(
        _lines(input_pipeline_speedup={"value": 1.54}),
        _lines(input_pipeline_speedup={"value": 1.02}),
        threshold=0.1)["regressions"]
    assert benchdiff.diff(
        _lines(input_pipeline_speedup={"value": 1.54}),
        _lines(input_pipeline_speedup={"value": 1.7}),
        threshold=0.1)["regressions"] == []


def test_fleet_rows_direction():
    """FLEET artifact rows (trafficreplay --fleet, SERVE_r03):
    swap_ms/respawn_ms ride the `_ms` rule, autoscale occupancy the
    `occupancy` rule, and failed_requests has its own name pattern —
    all lower-is-better by flag AND by summary-reconstructed name
    (dropped traffic growing is never an improvement); the two QPS arms
    stay higher-is-better."""
    for metric in ("fleet_swap_ms", "fleet_respawn_ms",
                   "fleet_autoscale_occupancy"):
        worse = benchdiff.diff(
            _lines(**{metric: {"value": 10.0, "lower_is_better": True}}),
            _lines(**{metric: {"value": 20.0, "lower_is_better": True}}),
            threshold=0.1)["regressions"]
        assert worse, f"{metric} growth did not regress"
        # summary-reconstructed rows keep only the value: name pattern
        bare = benchdiff.diff(_lines(**{metric: {"value": 10.0}}),
                              _lines(**{metric: {"value": 20.0}}),
                              threshold=0.1)["regressions"]
        assert bare, f"{metric} name pattern lost its direction"
        better = benchdiff.diff(_lines(**{metric: {"value": 10.0}}),
                                _lines(**{metric: {"value": 5.0}}),
                                threshold=0.1)["regressions"]
        assert better == [], f"{metric} improvement flagged"
    # failed requests rising from zero ALWAYS regresses (no ratio
    # exists for a zero base — any dropped request is a drop)
    (row,) = benchdiff.diff(
        _lines(fleet_failed_requests={"value": 0}),
        _lines(fleet_failed_requests={"value": 3}),
        threshold=0.1)["regressions"]
    assert "lower is better" in row["reason"]
    # QPS arms keep the default direction
    assert benchdiff.diff(
        _lines(fleet_autoscale_qps={"value": 50.0}),
        _lines(fleet_autoscale_qps={"value": 30.0}),
        threshold=0.1)["regressions"]
    assert benchdiff.diff(
        _lines(fleet_fixed_qps={"value": 50.0}),
        _lines(fleet_fixed_qps={"value": 55.0}),
        threshold=0.1)["regressions"] == []


def test_reshard_artifact_rows_are_lower_is_better():
    """RESHARD artifact rows (cli reshard --artifact): bytes_moved /
    bytes_lower_bound / plan_us GROWING past threshold regresses — a
    plan that moves more bytes for the same placement pair lost
    collective efficiency. The name patterns also cover rows
    reconstructed from a summary line (flag dropped)."""
    old = _lines(reshard_bytes_moved={"value": 57312,
                                      "lower_is_better": True})
    worse = _lines(reshard_bytes_moved={"value": 229248,
                                        "lower_is_better": True})
    (row,) = benchdiff.diff(old, worse, threshold=0.1)["regressions"]
    assert "lower is better" in row["reason"]
    better = _lines(reshard_bytes_moved={"value": 40000,
                                         "lower_is_better": True})
    assert benchdiff.diff(old, better, threshold=0.1)["regressions"] == []
    # name-pattern fallback for summary-reconstructed rows
    assert benchdiff.diff(_lines(reshard_bytes_moved={"value": 100.0}),
                          _lines(reshard_bytes_moved={"value": 200.0}),
                          threshold=0.1)["regressions"]
    assert benchdiff.diff(_lines(reshard_plan_us={"value": 100.0}),
                          _lines(reshard_plan_us={"value": 200.0}),
                          threshold=0.1)["regressions"]
    # leaf/total counts stay direction-neutral higher-is-better rows
    assert benchdiff.diff(_lines(reshard_plan_leaves={"value": 89}),
                          _lines(reshard_plan_leaves={"value": 91}),
                          threshold=0.1)["regressions"] == []


def test_plan_artifact_rows_direction():
    """PLAN artifact rows (cli plan / bench placement_search): scores,
    predicted scores, and measured ms are lower-is-better by flag AND
    by summary-reconstructed name; a rank-violation count regresses on
    ANY increase (even from a nonzero base — stricter than the retrace
    rise-from-zero rule); the Kendall tau row stays higher-is-better;
    and a changed winner string is NAMED as a change, never silent."""
    for metric in ("plan_winner_score", "plan_score::8 (data=data) p1",
                   "plan_predicted::2x4::8 (data=data) p1",
                   "plan_measured_ms::2x4::8 (data=data) p1"):
        worse = benchdiff.diff(
            _lines(**{metric: {"value": 100.0}}),
            _lines(**{metric: {"value": 200.0}}),
            threshold=0.1)["regressions"]
        assert worse, f"{metric} growth did not regress"
        better = benchdiff.diff(
            _lines(**{metric: {"value": 100.0}}),
            _lines(**{metric: {"value": 50.0}}),
            threshold=0.1)["regressions"]
        assert better == [], f"{metric} improvement flagged"
    # rank violations: any increase regresses, zero or nonzero base
    (row,) = benchdiff.diff(
        _lines(plan_predicted_rank_violations={"value": 0}),
        _lines(plan_predicted_rank_violations={"value": 1}),
        threshold=0.5)["regressions"]
    assert "lower is better" in row["reason"]
    assert benchdiff.diff(
        _lines(plan_predicted_rank_violations={"value": 1}),
        _lines(plan_predicted_rank_violations={"value": 2}),
        threshold=10.0)["regressions"], \
        "nonzero-base violation increase slipped through"
    # tau falling past threshold regresses (higher-is-better default)
    assert benchdiff.diff(
        _lines(**{"plan_rank_kendall_tau::2x4": {"value": 1.0}}),
        _lines(**{"plan_rank_kendall_tau::2x4": {"value": 0.3}}),
        threshold=0.1)["regressions"]
    # winner change: named in changes, not a regression by itself
    result = benchdiff.diff(
        _lines(**{"plan_winner::2x4": {"value": 100.0,
                                       "winner": "8 (data=data) p1"}}),
        _lines(**{"plan_winner::2x4": {
            "value": 100.0, "winner": "4x2 (data=data,model=model) p1"}}),
        threshold=0.1)
    assert result["regressions"] == []
    (chg,) = result["changes"]
    assert chg["field"] == "winner"
    assert chg["old"] == "8 (data=data) p1"
    assert chg["new"] == "4x2 (data=data,model=model) p1"


def test_trace_artifact_rows_direction():
    """TRACE artifact rows (tools/tracetool.py stats --artifact): the
    per-(process, span) p50/p99 rows are lower-is-better via the _ms
    rule — growth past threshold regresses, improvement is a change —
    even when the flag was lost to a summary-line reconstruction."""
    old = _lines(**{
        "trace_span_p99_ms::p0::forward": {"value": 10.0},
        "trace_span_p50_ms::p1::decode_step": {"value": 4.0}})
    worse = _lines(**{
        "trace_span_p99_ms::p0::forward": {"value": 14.0},
        "trace_span_p50_ms::p1::decode_step": {"value": 4.0}})
    result = benchdiff.diff(old, worse, threshold=0.1)
    (row,) = result["regressions"]
    assert row["metric"] == "trace_span_p99_ms::p0::forward"
    assert "lower is better" in row["reason"]
    better = _lines(**{
        "trace_span_p99_ms::p0::forward": {"value": 6.0},
        "trace_span_p50_ms::p1::decode_step": {"value": 4.0}})
    result = benchdiff.diff(old, better, threshold=0.1)
    assert result["regressions"] == [] and len(result["changes"]) == 1


def test_anomaly_count_and_straggler_skew_regress_on_any_increase():
    """The detector rows have NO acceptable growth: one new anomaly or
    a 1% skew increase regresses regardless of threshold (like retraces
    and rank violations); decreases are plain changes."""
    old = _lines(trace_anomaly_count={"value": 0.0},
                 straggler_skew_ms={"value": 100.0})
    worse = _lines(trace_anomaly_count={"value": 1.0},
                   straggler_skew_ms={"value": 101.0})
    result = benchdiff.diff(old, worse, threshold=0.5)
    assert {r["metric"] for r in result["regressions"]} == {
        "trace_anomaly_count", "straggler_skew_ms"}
    # a sub-threshold skew increase still regresses (any-increase rule)
    assert all("grew" in r["reason"] for r in result["regressions"])
    better = _lines(trace_anomaly_count={"value": 0.0},
                    straggler_skew_ms={"value": 50.0})
    result = benchdiff.diff(old, better, threshold=0.5)
    assert result["regressions"] == []
    # nonzero -> bigger nonzero anomaly count also regresses
    old2 = _lines(trace_anomaly_count={"value": 10.0})
    new2 = _lines(trace_anomaly_count={"value": 11.0})
    assert benchdiff.diff(old2, new2, threshold=0.5)["regressions"]


def test_serve_recompiles_rising_from_zero_always_regress():
    """A retrace count has no ratio base at 0 — ANY rise means the
    bucket lattice leaked and must trip regardless of threshold."""
    old = _lines(serving_replay_recompiles_after_warmup={"value": 0})
    new = _lines(serving_replay_recompiles_after_warmup={"value": 1})
    (row,) = benchdiff.diff(old, new, threshold=0.5)["regressions"]
    assert row["old"] == 0 and row["new"] == 1


def test_new_regression_flag_trips_even_with_stable_value():
    old = _lines(vgg={"value": 100.0})
    new = _lines(vgg={"value": 99.0, "regression": True})
    (row,) = benchdiff.diff(old, new)["regressions"]
    assert row["field"] == "regression" and "newly set" in row["reason"]


def test_quality_ratio_falling_below_its_floor_trips():
    old = _lines(w2v={"value": 800e3, "quality_ratio_vs_host": 0.98,
                      "quality_gate_min_ratio": 0.95})
    new = _lines(w2v={"value": 900e3, "quality_ratio_vs_host": 0.90,
                      "quality_gate_min_ratio": 0.95})
    rows = benchdiff.diff(old, new)["regressions"]
    assert any(r["field"] == "quality_ratio_vs_host"
               and "below its" in r["reason"] for r in rows)


def test_added_and_removed_metrics_are_listed():
    result = benchdiff.diff(_lines(gone={"value": 1.0}),
                            _lines(fresh={"value": 2.0}))
    assert result["added"] == ["fresh"] and result["removed"] == ["gone"]


def test_main_exit_codes_and_render(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({"metric": "tps", "value": 100.0}) + "\n")
    new.write_text(json.dumps({"metric": "tps", "value": 50.0}) + "\n")
    assert benchdiff.main([str(old), str(new)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED tps.value: 100.0 -> 50.0 (-50.0%)" in out
    # same artifact on both sides: clean exit
    assert benchdiff.main([str(old), str(old)]) == 0
    capsys.readouterr()


def test_main_json_output_is_machine_readable(tmp_path, capsys):
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({"metric": "tps", "value": 100.0}) + "\n")
    new.write_text(json.dumps({"metric": "tps", "value": 50.0}) + "\n")
    assert benchdiff.main([str(old), str(new), "--json"]) == 1
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["regressions"][0]["metric"] == "tps"


def test_missing_file_is_a_usage_error(tmp_path, capsys):
    some = tmp_path / "some.json"
    some.write_text("{}")
    assert benchdiff.main([str(some), str(tmp_path / "absent.json")]) == 2


def test_diff_works_on_a_tail_truncated_artifact(tmp_path):
    """The round-trip half benchdiff owns: the NEW side is only a
    2000-byte tail whose rows come back via the summary line's gates —
    the DP regression and the MoE ratio must still be diffable."""
    old_lines = [
        {"metric": "resnet20_dp_allreduce_vs_paramavg_speedup",
         "value": 1.2067, "unit": "x", "vs_baseline": 1.2067},
        {"metric": "moe_tps", "value": 1.0e6, "unit": "tokens/sec",
         "vs_baseline": 1.0, "vs_dense_ratio": 0.78, "ratio_floor": 0.65},
    ]
    new_lines = [
        {"metric": "resnet20_dp_allreduce_vs_paramavg_speedup",
         "value": 0.9597, "unit": "x", "vs_baseline": 0.9597},
        {"metric": "moe_tps", "value": 1.1e6, "unit": "tokens/sec",
         "vs_baseline": 1.1, "vs_dense_ratio": 0.60, "ratio_floor": 0.65},
    ]

    def artifact_path(name, lines):
        pad = json.dumps({"metric": "noise", "value": 0,
                          "filler": "x" * 1500})
        text = "\n".join([pad] + [json.dumps(l) for l in lines]
                         + [json.dumps(build_summary(lines))]) + "\n"
        path = tmp_path / name
        path.write_text(text[-2000:])
        return str(path)

    rc = benchdiff.main([artifact_path("old.json", old_lines),
                         artifact_path("new.json", new_lines)])
    assert rc == 1


def test_diff_reads_telemetry_jsonl_logs(tmp_path):
    """A telemetry log is a first-class artifact: metric events diff
    exactly like bench stdout lines."""
    old = Recorder(str(tmp_path / "old.jsonl"))
    old.meta(role="bench")
    old.metric({"metric": "tps", "value": 100.0})
    old.close()
    new = Recorder(str(tmp_path / "new.jsonl"))
    new.metric({"metric": "tps", "value": 80.0})
    new.error("mode:tps", error="noise event, must be ignored")
    new.close()
    assert benchdiff.main([old.path, new.path]) == 1


def test_speculative_rows_direction():
    """SPECULATIVE artifact rows (SERVE_r04): the acceptance headline
    `accepted_tokens_per_step` stays higher-is-better (a falling median
    means drafts stopped paying for their verify step), the overhead
    rows ride the `_us` rule by flag and by summary-reconstructed name,
    and the parity gates regress on ANY growth — greedy output is
    bit-identical by construction, so one mismatch is a correctness
    break, not a drift."""
    drop = benchdiff.diff(
        _lines(serving_speculative_accepted_tokens_per_step={"value": 2.0}),
        _lines(serving_speculative_accepted_tokens_per_step={"value": 1.2}),
        threshold=0.1)["regressions"]
    assert drop and drop[0]["delta_pct"] == -40.0
    assert benchdiff.diff(
        _lines(serving_speculative_accepted_tokens_per_step={"value": 1.5}),
        _lines(serving_speculative_accepted_tokens_per_step={"value": 2.5}),
        threshold=0.1)["regressions"] == []
    for metric in ("serving_speculative_draft_overhead_us",
                   "serving_sample_us"):
        worse = benchdiff.diff(
            _lines(**{metric: {"value": 40.0, "lower_is_better": True}}),
            _lines(**{metric: {"value": 80.0, "lower_is_better": True}}),
            threshold=0.1)["regressions"]
        assert worse, f"{metric} growth did not regress"
        bare = benchdiff.diff(_lines(**{metric: {"value": 40.0}}),
                              _lines(**{metric: {"value": 80.0}}),
                              threshold=0.1)["regressions"]
        assert bare, f"{metric} name pattern lost its direction"
        assert benchdiff.diff(_lines(**{metric: {"value": 40.0}}),
                              _lines(**{metric: {"value": 20.0}}),
                              threshold=0.1)["regressions"] == []
    # a parity mismatch rising from ZERO always regresses (no ratio
    # exists for a zero base — any divergence breaks the bit-identity
    # contract), flag or summary-reconstructed bare value alike
    for metric in ("serving_speculative_parity_mismatches",
                   "serving_quantized_parity_mismatches"):
        (row,) = benchdiff.diff(_lines(**{metric: {"value": 0}}),
                                _lines(**{metric: {"value": 1}}),
                                threshold=0.1)["regressions"]
        assert row["metric"] == metric
    # the int8 capacity headline stays higher-is-better
    assert benchdiff.diff(
        _lines(serving_quantized_slots_per_hbm_byte_x={"value": 3.9}),
        _lines(serving_quantized_slots_per_hbm_byte_x={"value": 1.2}),
        threshold=0.1)["regressions"]


def test_memory_and_cost_rows_direction():
    """MEM/COST rows (bench.py `_memory_rows`, tracetool metric_lines,
    bench_arm plan rows): every byte headline — hbm_peak_bytes, the
    mem_*_bytes family, the compiled peak_temp_bytes — is
    lower-is-better by flag AND by summary-reconstructed name (more
    resident HBM for the same work is a footprint regression); the MFU
    gauge keeps the default higher-is-better direction (utilization
    falling means the flops stopped flowing)."""
    for metric in ("hbm_peak_bytes", "trace_hbm_peak_bytes",
                   "mem_params_bytes", "mem_kv_pages_bytes",
                   "serving_peak_temp_bytes",
                   "plan_measured_bytes::2x2::8 (data=data) p1"):
        worse = benchdiff.diff(
            _lines(**{metric: {"value": 1 << 20,
                               "lower_is_better": True}}),
            _lines(**{metric: {"value": 4 << 20,
                               "lower_is_better": True}}),
            threshold=0.1)["regressions"]
        assert worse, f"{metric} growth did not regress"
        bare = benchdiff.diff(_lines(**{metric: {"value": 1 << 20}}),
                              _lines(**{metric: {"value": 4 << 20}}),
                              threshold=0.1)["regressions"]
        assert bare, f"{metric} name pattern lost its direction"
        better = benchdiff.diff(_lines(**{metric: {"value": 4 << 20}}),
                                _lines(**{metric: {"value": 1 << 20}}),
                                threshold=0.1)["regressions"]
        assert better == [], f"{metric} improvement flagged"
    # MFU dropping past threshold regresses as higher-is-better
    assert benchdiff.diff(_lines(mfu_live={"value": 0.42}),
                          _lines(mfu_live={"value": 0.20}),
                          threshold=0.1)["regressions"]
    assert benchdiff.diff(_lines(mfu_live={"value": 0.42}),
                          _lines(mfu_live={"value": 0.55}),
                          threshold=0.1)["regressions"] == []


def test_leak_count_and_cost_drift_regress_on_any_increase():
    """The memory detector rows have NO acceptable growth: a leak
    appearing (0 -> 1) or the calibration drift widening at all
    regresses regardless of threshold — like retraces and rank
    violations, there is no ratio base that excuses a leak."""
    for metric, old_v, new_v in (
            ("leak_count", 0, 1),
            ("trace_leak_count", 0, 1),
            ("leak_count", 1, 2),               # nonzero base too
            ("cost_drift_ratio", 0.0, 12.5),
            ("trace_cost_drift_ratio", 1.5, 1.6),  # sub-threshold rise
            ("plan_cost_drift_ratio::2x2", 0.0, 9.0)):
        rows = benchdiff.diff(
            _lines(**{metric: {"value": old_v}}),
            _lines(**{metric: {"value": new_v}}),
            threshold=10.0)["regressions"]
        assert rows, f"{metric} {old_v}->{new_v} slipped through"
    # decreases are plain changes, never regressions
    for metric in ("leak_count", "cost_drift_ratio",
                   "trace_cost_drift_ratio"):
        assert benchdiff.diff(
            _lines(**{metric: {"value": 5.0}}),
            _lines(**{metric: {"value": 0.0}}),
            threshold=0.1)["regressions"] == [], metric


def test_committed_serve_r04_self_diff_is_clean(capsys):
    """The round gate's trivial fixed point, against the real committed
    artifact: SERVE_r04 diffed against itself reports no regression and
    exits 0 — proving every r04 row parses and no direction rule
    misfires on its own values."""
    path = os.path.join(ROOT, "SERVE_r04.json")
    rc = benchdiff.main([path, path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "REGRESSED" not in out


def test_committed_r04_vs_r05_names_the_dp_regression(capsys):
    """The acceptance-criterion invocation, against the real committed
    artifacts: r05's DP-speedup flip below parity (VERDICT r5 #2) is
    named with old/new/delta and exits non-zero."""
    rc = benchdiff.main([os.path.join(ROOT, "BENCH_r04.json"),
                         os.path.join(ROOT, "BENCH_r05.json")])
    out = capsys.readouterr().out
    assert rc == 1
    assert ("REGRESSED resnet20_dp_allreduce_vs_paramavg_speedup.value: "
            "1.2067 -> 0.9597 (-20.5%)") in out


def test_embed_rows_direction():
    """EMBED artifact rows (bench.py embed, EMBED_r01.json): serving
    throughput (`queries_per_sec`) and ANN quality (`recall_at_k`)
    keep the default higher-is-better direction; the scatter-add step
    time rides the `_us` rule and the per-device gather traffic
    (`ep_gather_bytes`) is lower-is-better by name — growth means the
    ep sharding stopped splitting the table."""
    for metric in ("embed_queries_per_sec", "embed_recall_at_k"):
        drop = benchdiff.diff(_lines(**{metric: {"value": 100.0}}),
                              _lines(**{metric: {"value": 70.0}}),
                              threshold=0.1)["regressions"]
        assert drop, f"{metric} drop did not regress"
        rise = benchdiff.diff(_lines(**{metric: {"value": 100.0}}),
                              _lines(**{metric: {"value": 140.0}}),
                              threshold=0.1)["regressions"]
        assert rise == [], f"{metric} improvement flagged"
    for metric in ("embed_scatter_add_us", "embed_ep2_ep_gather_bytes"):
        worse = benchdiff.diff(
            _lines(**{metric: {"value": 10.0, "lower_is_better": True}}),
            _lines(**{metric: {"value": 20.0, "lower_is_better": True}}),
            threshold=0.1)["regressions"]
        assert worse, f"{metric} growth did not regress"
        # summary-reconstructed rows keep only the value: name pattern
        bare = benchdiff.diff(_lines(**{metric: {"value": 10.0}}),
                              _lines(**{metric: {"value": 20.0}}),
                              threshold=0.1)["regressions"]
        assert bare, f"{metric} name pattern lost its direction"
        better = benchdiff.diff(_lines(**{metric: {"value": 20.0}}),
                                _lines(**{metric: {"value": 10.0}}),
                                threshold=0.1)["regressions"]
        assert better == [], f"{metric} improvement flagged"
