"""Test configuration: force a pure-CPU JAX with 8 virtual devices so
sharding tests run without TPU hardware (SURVEY.md §4 item 5 — the reference
simulates clusters with Spark local[*]; XLA host devices play that role).

The platform-forcing dance lives in
deeplearning4j_tpu.util.virtual_devices.ensure_cpu_devices, shared with
__graft_entry__.dryrun_multichip. It must run before any jax backend
initialization (sitecustomize registers an `axon` TPU backend whose
get_backend hook initializes the TPU tunnel on first lookup).
"""

from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

ensure_cpu_devices(8)

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu"


@pytest.fixture
def rng():
    return np.random.default_rng(42)
