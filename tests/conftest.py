"""Test configuration: force a pure-CPU JAX with 8 virtual devices so
sharding tests run without TPU hardware (SURVEY.md §4 item 5 — the reference
simulates clusters with Spark local[*]; XLA host devices play that role).

The environment's sitecustomize registers an `axon` TPU backend in every
python process; merely setting JAX_PLATFORMS=cpu is not enough because the
axon get_backend hook initializes all backends (including the TPU tunnel)
on first lookup. De-register the axon factory before any backend init.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# sitecustomize imports jax before conftest runs, so the env var above is
# too late for jax's config — update it through the config API instead.
jax.config.update("jax_platforms", "cpu")

try:  # pragma: no cover - only relevant inside the axon image
    from jax._src import xla_bridge as _xb

    if not _xb.backends_are_initialized():
        _xb._backend_factories.pop("axon", None)
except Exception:
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

assert jax.default_backend() == "cpu"


@pytest.fixture
def rng():
    return np.random.default_rng(42)
