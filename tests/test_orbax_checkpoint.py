"""Orbax sharded checkpointing (util/orbax_checkpoint.py): sharded
save/restore preserving NamedShardings, retention pruning, meta counters."""

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.models.transformer import transformer_lm
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.tensor_parallel import shard_params
from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer


def _net():
    net = transformer_lm(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_length=16)
    net.init()
    return net


@pytest.mark.slow
def test_sharded_save_restore_round_trip(tmp_path):
    mesh = make_mesh({"data": 2, "model": 4})
    net = _net()
    net.params = shard_params(net.params, mesh)
    toks = np.arange(4 * 16, dtype=np.int32).reshape(4, 16) % 64
    net.fit(toks, np.roll(toks, -1, 1))
    ref = np.asarray(net.output(toks))

    ck = ShardedCheckpointer(str(tmp_path), keep=2)
    ck.save(net)
    ck.save(net, step=net.iteration_count + 5)
    ck.save(net, step=net.iteration_count + 9)
    assert len(ck.steps()) == 2  # retention pruning

    net2 = _net()
    net2.params = shard_params(net2.params, mesh)
    ck.restore(net2)
    np.testing.assert_allclose(np.asarray(net2.output(toks)), ref, atol=1e-6)
    assert net2.params["blk0_attn"]["Wqkv"].sharding.spec == (None, "model")
    assert net2.iteration_count == net.iteration_count


def test_restore_onto_unsharded_net(tmp_path):
    """Orbax reshards on read: a checkpoint written sharded restores onto
    a plain single-layout net."""
    mesh = make_mesh({"data": 2, "model": 4})
    net = _net()
    net.params = shard_params(net.params, mesh)
    ck = ShardedCheckpointer(str(tmp_path))
    ck.save(net)
    plain = _net()
    ck.restore(plain)
    toks = np.arange(2 * 16, dtype=np.int32).reshape(2, 16) % 64
    np.testing.assert_allclose(np.asarray(plain.output(toks)),
                               np.asarray(net.output(toks)), atol=1e-6)


def test_restore_empty_dir_raises(tmp_path):
    net = _net()
    with pytest.raises(FileNotFoundError):
        ShardedCheckpointer(str(tmp_path)).restore(net)


def test_restore_bridges_optimizer_layouts(tmp_path):
    """A checkpoint saved with the per-leaf (tree) updater state restores
    into a net whose default optimizer is the flat fused layout, and vice
    versa (the r4 flat-view optimizer changed the opt-state pytree)."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.nn.updater import (
        FlatViewTransform,
        build_optimizer,
        named_layer_confs,
    )
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    def build(flat):
        net = transformer_lm(vocab_size=64, d_model=16, n_heads=2,
                             n_layers=2, d_ff=32, max_length=8)
        net.init()
        net.set_optimizer(build_optimizer(net.conf.conf,
                                          named_layer_confs(net), flat=flat))
        return net

    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 64, (4, 8)), np.int32)
    ds = DataSet(toks, np.eye(64, dtype=np.float32)[np.roll(toks, -1, 1)])

    src = build(flat=True)
    assert isinstance(src.tx, FlatViewTransform)
    src.fit(ds)
    mgr = ShardedCheckpointer(str(tmp_path / "ck"))
    mgr.save(src, step=1)
    mgr.wait()

    dst = build(flat=False)  # the OTHER layout
    mgr2 = ShardedCheckpointer(str(tmp_path / "ck"))
    mgr2.restore(dst)
    np.testing.assert_allclose(
        np.asarray(dst.output(toks)[0], np.float32),
        np.asarray(src.output(toks)[0], np.float32), atol=1e-6)
    dst.fit(ds)  # training continues with the restored (flat) state
    assert np.isfinite(float(dst.score_value))


def test_host_mode_round_trip_and_resume_entry(tmp_path):
    """host=True writes host-materialized values (the elastic-fleet
    checkpoint form: process-count-portable) that restore bit-identically
    through the containers' `resume_from` entry; an empty directory is a
    cold start (step 0), not an error."""
    from deeplearning4j_tpu.util.orbax_checkpoint import host_materialize
    from tests.cluster_worker import build_net, full_data

    net = build_net().init()
    assert net.resume_from(str(tmp_path / "empty")) == 0  # cold start
    x, y = full_data()
    net.fit(x, y)
    ref = np.asarray(net.params_flat())

    host = host_materialize({"params": net.params})
    assert all(isinstance(l, np.ndarray)
               for l in jax.tree.leaves(host))

    ck = ShardedCheckpointer(str(tmp_path / "ck"))
    ck.save(net, host=True)

    net2 = build_net()
    assert net2.resume_from(str(tmp_path / "ck")) == net.iteration_count
    assert np.array_equal(np.asarray(net2.params_flat()), ref)
    # a NAMED missing step still raises (only the latest-of-none case
    # maps to a cold start)
    with pytest.raises(FileNotFoundError):
        net2.resume_from(str(tmp_path / "ck"), step=999)
