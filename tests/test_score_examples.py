"""Per-example scoring API (reference spark ScoreExamplesFunction /
ScoreExamplesWithKeyFunction: per-example — not aggregate — scores for
ranking/anomaly use, distributed)."""

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    GravesLSTM,
    Updater,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.mesh import make_mesh


def _mlp(l2=0.0):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(7)
        .learning_rate(0.1)
        .updater(Updater.SGD)
        .l2(l2)
        .regularization(l2 > 0)
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=16, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


def test_score_examples_matches_aggregate_score():
    net = _mlp()
    ds = _data()
    per = net.score_examples(ds)
    assert per.shape == (16,)
    # the aggregate score is the mean of the per-example scores (no reg)
    assert np.isclose(per.mean(), net.score(ds), rtol=1e-5)


def test_score_examples_singletons_agree():
    """Scoring one example alone must equal its row in the batch call
    (reference: ScoreExamplesFunction scores rows independently)."""
    net = _mlp()
    ds = _data(8)
    per = net.score_examples(ds)
    for i in (0, 3, 7):
        one = DataSet(ds.features[i:i + 1], ds.labels[i:i + 1])
        assert np.isclose(net.score_examples(one)[0], per[i], rtol=1e-5)


def test_score_examples_regularization_term():
    net = _mlp(l2=0.05)
    ds = _data()
    plain = net.score_examples(ds)
    reg = net.score_examples(ds, add_regularization=True)
    d = reg - plain
    # the same scalar penalty is added to every example's score
    assert np.all(d > 0)
    assert np.allclose(d, d[0], rtol=1e-5)


def test_score_examples_rnn_masked():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .learning_rate(0.1)
        .updater(Updater.SGD)
        .list()
        .layer(GravesLSTM(n_in=2, n_out=4, activation="tanh"))
        .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax",
                              loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    B, T = 4, 6
    x = rng.standard_normal((B, T, 2)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (B, T))]
    lmask = (np.arange(T)[None, :] < rng.integers(2, T + 1, B)[:, None])
    ds = DataSet(x, y, labels_mask=lmask.astype(np.float32))
    per = net.score_examples(ds)
    assert per.shape == (B,)
    assert np.all(np.isfinite(per))


def test_score_examples_graph_and_sharded():
    g = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .learning_rate(0.1)
        .updater(Updater.SGD)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                      loss_function="mcxent"), "d")
        .set_outputs("out")
        .build()
    )
    net = ComputationGraph(g).init()
    ds = _data(10)  # NOT a multiple of the mesh: exercises pad-and-slice
    per = net.score_examples(ds)
    assert per.shape == (10,)
    assert np.isclose(per.mean(), net.score(ds), rtol=1e-5)

    sharded = ComputationGraph(g).init()
    sharded.params = net.params  # same weights -> same scores
    sharded.set_mesh(make_mesh({"data": 8}))
    per_sh = sharded.score_examples(ds)
    assert np.allclose(per_sh, per, rtol=1e-4)
