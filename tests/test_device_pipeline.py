"""On-device skip-gram pipeline (nlp/device_pipeline.py): correctness of
pack/pair-generation/alias sampling, learning signal, and DP-5 mesh parity
(reference Word2VecPerformer.java semantics — device count must not change
results)."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.device_pipeline import (
    build_alias_table,
    pack_corpus,
)
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.parallel.mesh import make_mesh


def _structured_corpus(n=400, groups=20, seed=0):
    """a_i and b_i only ever co-occur with each other."""
    rng = np.random.default_rng(seed)
    sents = []
    for _ in range(n):
        i = rng.integers(0, groups)
        sents.append([f"a{i}", f"b{i}"] * 3)
    return sents


def test_pack_corpus_pads_and_separates_sentences():
    toks, sids = pack_corpus([np.array([1, 2, 3]), np.array([4, 5])], 8)
    assert toks.shape == (8,) and sids.shape == (8,)
    assert list(sids[:5]) == [0, 0, 0, 1, 1]
    assert all(s == -1 for s in sids[5:])  # padding never pairs


def test_pack_corpus_empty_raises():
    with pytest.raises(ValueError):
        pack_corpus([np.array([])], 8)


def test_alias_table_matches_distribution():
    rng = np.random.default_rng(0)
    p = rng.random(50)
    p /= p.sum()
    J, q = build_alias_table(p)
    # exact check: alias tables encode p as mixture of uniforms
    recon = q / 50.0
    recon_full = recon.copy()
    for i in range(50):
        recon_full[J[i]] += (1.0 - q[i]) / 50.0
    np.testing.assert_allclose(recon_full, p, atol=1e-6)


def test_device_pipeline_learns_cooccurrence():
    sents = _structured_corpus()
    w2v = (Word2Vec.builder().layer_size(32).window_size(2)
           .min_word_frequency(1).negative_sample(5).epochs(3).seed(1)
           .use_device_pipeline(True).build())
    w2v.fit(sents)
    assert w2v.loss_history and all(np.isfinite(l) for l in w2v.loss_history)
    # co-occurring pair must be closer than a cross-group pair
    assert w2v.similarity("a3", "b3") > w2v.similarity("a3", "b11")


def test_device_pipeline_rejects_unsupported_modes():
    sents = _structured_corpus(n=50)
    w2v = (Word2Vec.builder().layer_size(8).window_size(2)
           .min_word_frequency(1).use_hierarchic_softmax(True)
           .use_device_pipeline(True).build())
    with pytest.raises(ValueError):
        w2v.fit(sents)


def test_mesh_parity_with_single_device():
    """DP-5: psum-merged gradients == single-device grouped update."""
    sents = _structured_corpus(n=300, seed=2)
    mesh = make_mesh({"data": 4})

    def build(mesh_arg):
        w = (Word2Vec.builder().layer_size(16).window_size(2)
             .min_word_frequency(1).negative_sample(3).epochs(1).seed(7)
             .use_device_pipeline(True).build())
        w.pipeline_chunk, w.pipeline_group = 128, 4
        w.device_mesh = mesh_arg
        return w

    w_single = build(None)
    w_single.fit(sents)
    w_mesh = build(mesh)
    w_mesh.fit(sents)
    np.testing.assert_allclose(np.asarray(w_single.lookup_table.syn0),
                               np.asarray(w_mesh.lookup_table.syn0),
                               atol=1e-5)
    # loss streams match too
    np.testing.assert_allclose(w_single.loss_history, w_mesh.loss_history,
                               rtol=1e-4)


def test_group_not_divisible_by_mesh_raises():
    from deeplearning4j_tpu.nlp.device_pipeline import make_sgns_epoch

    mesh = make_mesh({"data": 4})
    with pytest.raises(ValueError):
        make_sgns_epoch(window=2, negative=3, chunk=64, group=3, mesh=mesh)


def test_quality_on_zipf_corpus_with_trust_region():
    """The MAX_ROW_STEP trust region must not destroy learning on a
    realistic zipf-distributed corpus (VERDICT r1 weak #7): semantically
    paired words end up closer than unrelated words of similar rank."""
    rng = np.random.default_rng(0)
    vocab, n_words = 300, 60_000
    zipf = 1.0 / np.arange(1, vocab + 1)
    p = zipf / zipf.sum()
    # words come in pairs (2i, 2i+1); each sentence repeats ONE pair, so
    # partner co-occurrence dominates and cross-pair co-occurrence is zero
    # within sentences, while pair frequency stays zipf-skewed (the regime
    # where summed batched updates hit the trust region hardest)
    draws = rng.choice(vocab // 2, size=n_words // 8, p=(
        p[::2] / p[::2].sum()))
    sents = [[f"w{2 * j}", f"w{2 * j + 1}"] * 4 for j in draws]
    w = (Word2Vec.builder().layer_size(48).window_size(3)
         .min_word_frequency(1).negative_sample(5).epochs(4).seed(1)
         .use_device_pipeline(True).build())
    w.pipeline_chunk, w.pipeline_group = 256, 4
    w.fit(sents)
    # paired similarity beats cross-pair similarity for frequent words
    paired, cross = [], []
    for j in range(0, 20, 2):
        if w.has_word(f"w{j}") and w.has_word(f"w{j + 1}"):
            paired.append(w.similarity(f"w{j}", f"w{j + 1}"))
        if w.has_word(f"w{j}") and w.has_word(f"w{j + 4}"):
            cross.append(w.similarity(f"w{j}", f"w{j + 4}"))
    assert np.mean(paired) > np.mean(cross) + 0.05, (
        np.mean(paired), np.mean(cross))


@pytest.mark.slow
def test_cbow_device_pipeline_learns_and_mesh_parity():
    """CBOW on the device pipeline: learns co-occurrence structure and is
    device-count invariant (same psum'd-gradient contract as SGNS)."""
    sents = _structured_corpus(n=400, seed=4)

    def build(mesh_arg):
        w = (Word2Vec.builder().layer_size(24).window_size(2)
             .min_word_frequency(1).negative_sample(4).epochs(3).seed(5)
             .elements_learning_algorithm("cbow")
             .use_device_pipeline(True).build())
        w.pipeline_chunk, w.pipeline_group = 128, 4
        w.device_mesh = mesh_arg
        return w

    w = build(None)
    w.fit(sents)
    assert w.loss_history and all(np.isfinite(l) for l in w.loss_history)
    assert w.similarity("a3", "b3") > w.similarity("a3", "b11")

    w_mesh = build(make_mesh({"data": 4}))
    w_mesh.fit(sents)
    np.testing.assert_allclose(np.asarray(w.lookup_table.syn0),
                               np.asarray(w_mesh.lookup_table.syn0),
                               atol=1e-5)


def test_strict_per_pair_negative_sampling_opt_out():
    """share_negatives=False restores per-pair draws; both modes learn."""
    sents = _structured_corpus(n=300, seed=6)
    w = (Word2Vec.builder().layer_size(16).window_size(2)
         .min_word_frequency(1).negative_sample(3).epochs(2).seed(3)
         .use_device_pipeline(True).share_negatives(False).build())
    w.fit(sents)
    assert w.pipeline_share_negatives is False
    assert w.similarity("a3", "b3") > w.similarity("a3", "b11")


def test_raw_string_corpus_with_subsampling_tokenizes():
    """Raw-string sentences + subsampling force the per-sentence fallback;
    sentences must be tokenized by whitespace, not iterated char-by-char
    (regression: the flat-path refactor once dropped the split)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    words = [f"tok{i}" for i in range(50)]
    sents = [" ".join(words[j] for j in rng.integers(0, 50, 12))
             for _ in range(120)]
    w2v = (Word2Vec.builder().layer_size(8).window_size(3)
           .min_word_frequency(1).negative_sample(2).sampling(1e-3)
           .use_device_pipeline(True).epochs(1).seed(4).build())
    w2v.build_vocab([s.split() for s in sents])
    assert w2v.vocab.index_of("tok0") >= 0
    w2v.fit(sents)  # raw strings on purpose
    v = w2v.word_vector("tok0")
    assert v is not None and np.isfinite(np.asarray(v)).all()
