"""Gradient checks — the correctness backbone of the reference test strategy
(SURVEY.md §4.1; reference GradientCheckTests.java:30-43,
CNNGradientCheckTest.java, BNGradientCheckTest.java,
GradientCheckTestsComputationGraph.java, GradientCheckTestsMasking.java).

Central finite differences vs jax.grad in float64, eps 1e-6,
maxRelError 1e-3 — the same tolerances the reference forces with
DataTypeUtil.setDTypeForContext(DOUBLE).
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.gradientcheck import check_gradients, check_gradients_graph
from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet
from deeplearning4j_tpu.nn.conf import (
    AutoEncoder,
    BatchNormalization,
    ComputationGraphConfiguration,  # noqa: F401  (graph config built via builder)
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GravesLSTM,
    GravesBidirectionalLSTM,
    GRU,
    InputType,
    LocalResponseNormalization,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.conf.graph_conf import ElementWiseVertexConf
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import ComputationGraph

EPS = 1e-6
MAX_REL = 1e-3


@pytest.fixture(autouse=True)
def f64():
    """Force double precision (reference forces DOUBLE dtype for every
    gradient check — GradientCheckTests.java:33)."""
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", False)


def _builder(l1=0.0, l2=0.0):
    b = (NeuralNetConfiguration.builder()
         .seed(12345)
         .dtype("float64")
         .param_dtype("float64")
         .learning_rate(1.0))
    if l1 or l2:
        b = b.l1(l1).l2(l2).regularization(True)
    return b


def _iris_like(rng, n=6, n_in=4, n_out=3):
    x = rng.standard_normal((n, n_in))
    y = np.eye(n_out)[rng.integers(0, n_out, n)]
    return DataSet(x, y)


# ---------------------------------------------------------------- MLP sweeps
@pytest.mark.parametrize("hidden_act", ["sigmoid", "tanh", "relu"])
@pytest.mark.parametrize("out_act,loss", [
    ("softmax", "mcxent"),
    ("identity", "mse"),
    ("tanh", "mse"),
])
def test_mlp_activation_loss_grid(rng, hidden_act, out_act, loss):
    """Reference GradientCheckTests.java: activation x loss grid on an
    Iris-sized MLP."""
    conf = (_builder().list()
            .layer(DenseLayer(n_in=4, n_out=5, activation=hidden_act))
            .layer(OutputLayer(n_in=5, n_out=3, activation=out_act,
                               loss_function=loss))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _iris_like(rng), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


def test_mlp_l1_l2(rng):
    """Regularization terms differentiate correctly (reference checks
    l1/l2 on every grid point)."""
    conf = (_builder(l1=0.01, l2=0.02).list()
            .layer(DenseLayer(n_in=4, n_out=5, activation="tanh"))
            .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _iris_like(rng), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


# --------------------------------------------------------------------- CNN
@pytest.mark.parametrize("pooling", ["max", "avg"])
def test_cnn_conv_subsampling(rng, pooling):
    """Reference CNNGradientCheckTest: conv + pooling + dense head."""
    conf = (_builder().list()
            .layer(ConvolutionLayer(n_out=3, kernel_size=(3, 3), stride=(1, 1),
                                    activation="tanh"))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2),
                                    pooling_type=pooling))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.convolutional(6, 6, 2))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((4, 6, 6, 2))
    y = np.eye(3)[rng.integers(0, 3, 4)]
    assert check_gradients(net, DataSet(x, y), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


def test_batchnorm(rng):
    """Reference BNGradientCheckTest: BN gamma/beta + upstream weights."""
    conf = (_builder().list()
            .layer(DenseLayer(n_in=4, n_out=6, activation="identity"))
            .layer(BatchNormalization(n_in=6, n_out=6))
            .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _iris_like(rng, n=8), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


def test_lrn(rng):
    """LocalResponseNormalization backward (reference
    CNNGradientCheckTest#testCnnWithLRN)."""
    conf = (_builder().list()
            .layer(ConvolutionLayer(n_out=4, kernel_size=(2, 2), stride=(1, 1),
                                    activation="tanh"))
            .layer(LocalResponseNormalization())
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.convolutional(5, 5, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.standard_normal((3, 5, 5, 1))
    y = np.eye(2)[rng.integers(0, 2, 3)]
    assert check_gradients(net, DataSet(x, y), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


# --------------------------------------------------------------- embedding
def test_embedding(rng):
    """Gather-based embedding lookup: grads are scatter-adds (reference
    GradientCheckTests#testEmbeddingLayerSimple)."""
    conf = (_builder().list()
            .layer(EmbeddingLayer(n_in=7, n_out=5, activation="tanh"))
            .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    x = rng.integers(0, 7, (6, 1)).astype(np.int32)
    y = np.eye(3)[rng.integers(0, 3, 6)]
    assert check_gradients(net, DataSet(x, y), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


def test_autoencoder_as_layer(rng):
    """AutoEncoder used inside a supervised stack (encode path)."""
    conf = (_builder().list()
            .layer(AutoEncoder(n_in=4, n_out=5, activation="sigmoid"))
            .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _iris_like(rng), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)


# -------------------------------------------------------------------- RNNs
def _seq_data(rng, b=2, t=4, n_in=3, n_out=2, mask=False):
    x = rng.standard_normal((b, t, n_in))
    y = np.eye(n_out)[rng.integers(0, n_out, (b, t))]
    lm = None
    if mask:
        lm = np.ones((b, t))
        lm[0, t - 1] = 0  # variable-length: first sequence ends early
        lm[1, 0] = 0
    return DataSet(x, y, labels_mask=lm)


@pytest.mark.parametrize("layer_cls", [GravesLSTM, GravesBidirectionalLSTM, GRU])
def test_recurrent_layers(rng, layer_cls):
    """Scan-based LSTM/BiLSTM/GRU backward through time (reference
    GradientCheckTests#testGradientLSTMFull etc.)."""
    conf = (_builder().list()
            .layer(layer_cls(n_in=3, n_out=4, activation="tanh"))
            .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _seq_data(rng), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True,
                           subset=120)


def test_rnn_label_masking(rng):
    """Masked timesteps contribute zero gradient (reference
    GradientCheckTestsMasking)."""
    conf = (_builder().list()
            .layer(GravesLSTM(n_in=3, n_out=4, activation="tanh"))
            .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _seq_data(rng, mask=True), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True,
                           subset=120)


# --------------------------------------------------------------- DAG graph
def test_computation_graph_vertices(rng):
    """Merge + elementwise-add DAG (reference
    GradientCheckTestsComputationGraph#testBasicIrisWithMerging &
    #testBasicIrisWithElementWiseNode)."""
    g = (_builder()
         .graph_builder()
         .add_inputs("in")
         .add_layer("d1", DenseLayer(n_in=4, n_out=5, activation="tanh"), "in")
         .add_layer("d2", DenseLayer(n_in=4, n_out=5, activation="sigmoid"), "in")
         .add_vertex("add", ElementWiseVertexConf(op="add"), "d1", "d2")
         .add_layer("out", OutputLayer(n_in=5, n_out=3, activation="softmax",
                                       loss_function="mcxent"), "add")
         .set_outputs("out")
         .build())
    net = ComputationGraph(g).init()
    ds = _iris_like(rng)
    assert check_gradients_graph(net, MultiDataSet([ds.features], [ds.labels]),
                                 epsilon=EPS, max_rel_error=MAX_REL,
                                 print_results=True)


def test_moe_layer_gradients(rng):
    """Mixture-of-Experts: top-k gated expert FFNs (the gate top_k mask is
    piecewise-constant, so finite differences remain valid away from
    routing boundaries — tanh-bounded inputs keep logits well-separated).
    FD runs against the smooth dense oracle; the routed path's analytic
    gradients are checked against the dense path's in test_pipeline_moe."""
    from deeplearning4j_tpu.nn.layers.moe import MixtureOfExpertsLayer

    conf = (_builder().list()
            .layer(MixtureOfExpertsLayer(n_in=4, n_out=5, n_experts=3,
                                         top_k=2, d_hidden=6,
                                         activation="tanh", routing="dense"))
            .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert check_gradients(net, _iris_like(rng), epsilon=EPS,
                           max_rel_error=MAX_REL, print_results=True)
