"""Portable resharding engine (ISSUE 10): planner purity + the
mesh-transfer matrix.

Planner tests treat the plan as pure data — no fleet spawn, no devices:
the same placements must yield the byte-identical plan under simulated
process_index 0 vs 1, the cost model must hold gather >= slice (so
preferring collectives over host gathers is structural, not tuned), and
malformed placements (target-mesh-larger-than-checkpoint and friends)
must be refused before a plan exists.

The matrix is the acceptance arc: params AND optimizer state saved
under one placement restore BIT-identically under another —
2x4 -> 1x1 (train TP, serve solo), 1x1 -> 2x2 (grow onto a TP mesh),
2x2 -> 3x2 (a non-power-of-two fleet), a dp<->tp role transpose, and a
zero1 8-way -> 4-way optimizer-moment reshard — each verified against
the uninterrupted single-mesh reference values and leaving a
`reshard_plan` telemetry event (and zero `host_gather` events) behind.

TP *training* on this container's CPU jax hits the known donation-alias
XlaRuntimeError (the pre-existing test_unified_mesh failure class), so
the matrix warms optimizer moments with a dense fit and applies the TP
placement via set_mesh — the save/restore path under test is identical.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from deeplearning4j_tpu.reshard.planner import (
    ALLGATHER_SHARD,
    HOST_FALLBACK,
    KEEP,
    SLICE_EXCHANGE,
    LeafLayout,
    Placement,
    PlacementError,
    plan_leaf,
    plan_reshard,
)

pytestmark = pytest.mark.reshard

DEVS = np.asarray(jax.devices())

SRC = Placement.of({"data": 2, "model": 4},
                   {"data": "data", "model": "model"})
DST = Placement.of({"data": 2, "model": 2},
                   {"data": "data", "model": "model"})
LEAVES = [
    LeafLayout("w", (8, 24), 4, (None, "model"), (None, "model")),
    LeafLayout("b", (24,), 4, ("model",), ()),
    LeafLayout("r", (8, 8), 4, (), ()),
]


# ------------------------------------------------------------ pure planner

def test_plan_is_deterministic_under_simulated_rank():
    """The same placements yield the byte-identical plan on every
    process — what lets a fleet execute its plan slices without
    coordination. Simulated via the stage-3 rank harness (env contract
    + patched jax.process_index; no fleet)."""
    from deeplearning4j_tpu.analysis.collective_audit import \
        simulated_process_index

    plans = []
    for pid in (0, 1):
        with simulated_process_index(pid):
            plans.append(plan_reshard(SRC, DST, LEAVES))
    assert plans[0] == plans[1]
    assert plans[0].summary() == plans[1].summary()


def test_bytes_monotonicity_gather_ge_slice():
    """For every leaf: the gather plan costs at least the slice plan
    (which IS the reported lower bound), the host fallback never beats
    the lower bound either, and the chosen action's bytes never beat
    it — preferring collective plans is structural, not tuned."""
    placements = [SRC, DST, Placement.of({"data": 1}, {"data": "data"}),
                  Placement.of({"data": 8}, {"data": "data"}, zero1=True)]
    for a in placements:
        for b in placements:
            for leaf in LEAVES:
                specs_ok = all(
                    ax is None or ax in a.axis_sizes
                    for ax in leaf.src_spec) and all(
                    ax is None or ax in b.axis_sizes
                    for ax in leaf.dst_spec)
                if not specs_ok:
                    continue
                lp = plan_leaf(leaf, a, b)
                assert lp.bytes_slice <= lp.bytes_gather
                assert lp.bytes_slice <= lp.bytes_host
                assert lp.bytes_lower_bound == lp.bytes_slice
                assert lp.bytes_moved >= lp.bytes_lower_bound
                forced = plan_leaf(leaf, a, b, force_host=True)
                assert forced.action == HOST_FALLBACK
                assert forced.bytes_moved >= lp.bytes_lower_bound


def test_plan_actions_cover_the_vocabulary():
    # identical placement -> keep, zero bytes
    kp = plan_leaf(LEAVES[0], SRC, SRC)
    assert kp.action == KEEP and kp.bytes_moved == 0
    # pure refinement (replicated -> sharded) -> slice exchange at bound
    solo = Placement.of({"data": 1}, {"data": "data"})
    se = plan_leaf(LeafLayout("w", (8, 24), 4, (), (None, "model")),
                   solo, SRC)
    assert se.action == SLICE_EXCHANGE
    assert se.bytes_moved == se.bytes_lower_bound
    # coarsening (sharded -> replicated) gathers
    ag = plan_leaf(LeafLayout("w", (8, 24), 4, (None, "model"), ()),
                   SRC, solo)
    assert ag.action == ALLGATHER_SHARD
    s = plan_reshard(SRC, DST, LEAVES).summary()
    assert s["n_leaves"] == 3 and s["bytes_total"] == sum(
        l.bytes for l in LEAVES)
    assert set(s["actions"]) <= set((KEEP, SLICE_EXCHANGE,
                                     ALLGATHER_SHARD, HOST_FALLBACK))


@pytest.mark.parametrize("bad", [
    lambda: Placement.of({}, {}),
    lambda: Placement.of({"data": 0}, {"data": "data"}),
    lambda: Placement.of({"data": 2}, {"bogus": "data"}),
    lambda: Placement.of({"data": 2}, {"model": "absent"}),
    lambda: Placement.of({"data": 2}, {"data": "data"}, process_count=3),
    lambda: Placement.of({"data": 2, "model": 2},
                         {"data": "data", "model": "model"}, zero1=True),
])
def test_malformed_placements_are_rejected(bad):
    with pytest.raises(PlacementError):
        bad()


def test_malformed_leaf_layouts_are_rejected():
    # target-mesh-larger-than-checkpoint: a dim that cannot split
    with pytest.raises(PlacementError, match="does not divide"):
        plan_reshard(SRC, SRC,
                     [LeafLayout("w", (9, 7), 4, (None, "model"), ())])
    # spec naming an axis the mesh lacks
    with pytest.raises(PlacementError, match="absent from the mesh"):
        plan_reshard(SRC, SRC, [LeafLayout("w", (8, 8), 4, ("seq",), ())])
    # more spec entries than dims
    with pytest.raises(PlacementError, match="more entries than dims"):
        plan_reshard(SRC, SRC,
                     [LeafLayout("w", (8,), 4, (None, "model"), ())])


def test_placement_json_round_trip():
    for p in (SRC, Placement.of({"data": 8}, {"data": "data"},
                                process_count=2, zero1=True)):
        assert Placement.from_json(p.to_json()) == p
    assert SRC.describe() == "2x4 (data=data,model=model) p1"
    assert Placement.solo().describe() == "1 (data=data) p1"


def test_planner_is_importable_without_jax():
    """The planner is pure stdlib (CLI dry-runs and lint stubs import
    it without a backend) — proven in a jax-poisoned subprocess."""
    import subprocess

    code = (
        "import os, sys, types\n"
        "poison = types.ModuleType('jax')\n"
        "def _boom(*a, **k): raise AssertionError('jax imported')\n"
        "poison.__getattr__ = lambda n: _boom()\n"
        "sys.modules['jax'] = poison\n"
        # the graftlint stub idiom: namespace-stub the package parents
        # so planner.py loads without the root __init__'s jax imports
        "for name in ('deeplearning4j_tpu', 'deeplearning4j_tpu.reshard'):\n"
        "    mod = types.ModuleType(name)\n"
        "    mod.__path__ = [os.path.join(os.getcwd(),\n"
        "                                 *name.split('.'))]\n"
        "    sys.modules[name] = mod\n"
        "from deeplearning4j_tpu.reshard.planner import (Placement,\n"
        "    LeafLayout, plan_reshard)\n"
        "p = plan_reshard(\n"
        "    Placement.of({'data': 2}, {'data': 'data'}),\n"
        "    Placement.of({'data': 1}, {'data': 'data'}),\n"
        "    [LeafLayout('w', (8, 8), 4, (), ())])\n"
        "print(p.summary()['n_leaves'])\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.dirname(
                             os.path.abspath(__file__))), timeout=60)
    assert out.returncode == 0 and out.stdout.strip() == "1", out.stderr


# -------------------------------------------------------- matrix helpers

V, D, H, L, FF, T, B = 64, 16, 2, 2, 32, 8, 8


def _lm_data():
    from deeplearning4j_tpu.datasets.api import DataSet

    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, V, (B, T)), np.int32)
    labs = np.eye(V, dtype=np.float32)[np.roll(toks, -1, axis=1)]
    return DataSet(toks, labs)


def _build_lm():
    from deeplearning4j_tpu.models.transformer import transformer_lm

    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T)
    return net.init()


@pytest.fixture(scope="module")
def dense_ckpt(tmp_path_factory):
    """One dense-trained step, checkpointed solo: every matrix case
    rebuilds its source net from this (no per-case refit/compile)."""
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    d = str(tmp_path_factory.mktemp("dense") / "ckpt")
    net = _build_lm()
    net.fit(_lm_data())
    ShardedCheckpointer(d).save(net)
    return d


def _host_leaves(tree):
    return [np.asarray(l) for l in jax.tree.leaves(tree)]


def _mesh(shape, names, n=None):
    count = int(np.prod(shape))
    return Mesh(DEVS[:count].reshape(shape), names)


def _events_of(rec, kind):
    return [e for e in rec.events if e.get("event") == kind]


def _run_case(dense_ckpt, tmp_path, src_mesh, src_axes, dst_mesh,
              dst_axes, *, zero1=False):
    """Save under the source placement, restore through the planner
    under the target placement, and prove params + optimizer state are
    bit-identical to the uninterrupted reference values."""
    from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    net = _build_lm()
    if dense_ckpt is not None:
        net.resume_from(dense_ckpt)
    if src_mesh is not None:
        net.set_mesh(src_mesh, axes=src_axes, zero1=zero1)
        if zero1:
            net.fit(_lm_data())  # one DP step so moments SHARD on disk
    ref_p = _host_leaves(net.params)
    ref_o = _host_leaves(net.opt_state)
    ckpt = str(tmp_path / "ckpt")
    ShardedCheckpointer(ckpt).save(net)

    net2 = _build_lm()
    if zero1:
        net2.set_mesh(dst_mesh, zero1=True)
    rec = Recorder()
    prev = set_default(rec)
    try:
        step = net2.resume_from(ckpt, target_mesh=dst_mesh,
                                target_axes=dst_axes)
    finally:
        set_default(prev)
    assert step == net.iteration_count
    got_p = _host_leaves(net2.params)
    got_o = _host_leaves(net2.opt_state)
    assert len(ref_p) == len(got_p)
    assert all(np.array_equal(a, b) for a, b in zip(ref_p, got_p)), \
        "params not bit-identical across the mesh transfer"
    assert len(ref_o) == len(got_o)
    assert all(np.array_equal(a, b) for a, b in zip(ref_o, got_o)), \
        "optimizer state not bit-identical across the mesh transfer"
    plans = _events_of(rec, "reshard_plan")
    assert plans and plans[0]["path"] == "checkpoint"
    assert not _events_of(rec, "host_gather")
    return net2, plans[0]


# ----------------------------------------------------------- the matrix

def test_matrix_2x4_to_1x1(dense_ckpt, tmp_path):
    """Train 2x4 (dp x tp) -> serve 1x1: the ROADMAP headline case."""
    net2, plan = _run_case(
        dense_ckpt, tmp_path,
        _mesh((2, 4), ("data", "model")),
        {"data": "data", "model": "model"},
        _mesh((1,), ("data",)), {"data": "data"})
    assert plan["src"].startswith("2x4") and plan["dst"].startswith("1 ")
    # everything landed on the single target device
    assert all(len(l.sharding.device_set) == 1
               for l in jax.tree.leaves(net2.params))


def test_matrix_1x1_to_2x2(dense_ckpt, tmp_path):
    """Solo checkpoint grows onto a 2x2 dp x tp mesh: restored TP-rule
    leaves arrive SHARDED (the restore read slices, not the whole)."""
    net2, plan = _run_case(
        dense_ckpt, tmp_path, None, None,
        _mesh((2, 2), ("data", "model")),
        {"data": "data", "model": "model"})
    assert plan["src"].startswith("1 ")
    sharded = [l for l in jax.tree.leaves(net2.params)
               if not l.sharding.is_fully_replicated]
    assert sharded, "no leaf took a TP sharding on the target mesh"


def test_matrix_2x2_to_3x2(dense_ckpt, tmp_path):
    """A non-power-of-two re-form (the elastic N'=3 shape, in-process)."""
    _run_case(
        dense_ckpt, tmp_path,
        _mesh((2, 2), ("data", "model")),
        {"data": "data", "model": "model"},
        _mesh((3, 2), ("data", "model")),
        {"data": "data", "model": "model"})


def test_matrix_dp_tp_role_transpose(dense_ckpt, tmp_path):
    """Same device grid, dp and tp roles swapped across the transfer."""
    net2, plan = _run_case(
        dense_ckpt, tmp_path,
        _mesh((2, 4), ("data", "model")),
        {"data": "data", "model": "model"},
        _mesh((4, 2), ("data", "model")),
        {"data": "data", "model": "model"})
    assert plan["src"].startswith("2x4") and plan["dst"].startswith("4x2")


def test_matrix_zero1_moments_reshard_8_to_4(tmp_path):
    """zero1 optimizer moments written SHARDED over an 8-way data axis
    restore bit-identically resharded over a 4-way axis — the
    arXiv:2004.13336 composition the ISSUE names. (Trains from scratch
    under the zero1 mesh: a restored net's committed single-device
    arrays cannot feed the zero1-sharded pjit inputs.)"""
    net2, _ = _run_case(
        None, tmp_path,
        _mesh((8,), ("data",)), {"data": "data"},
        _mesh((4,), ("data",)), {"data": "data"}, zero1=True)
    sharded = [l for l in jax.tree.leaves(net2.opt_state)
               if hasattr(l, "sharding")
               and not l.sharding.is_fully_replicated]
    assert sharded, "no zero1 moment leaf took the target data sharding"


def test_set_mesh_replacement_routes_through_plans(dense_ckpt, tmp_path):
    """Re-placing an already-placed net (set_mesh after set_mesh) goes
    through the live executor: bit-identical values, a `reshard_plan`
    telemetry event with path=live, and the new placement applied."""
    from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default

    net = _build_lm()
    net.resume_from(dense_ckpt)
    net.set_mesh(_mesh((2, 4), ("data", "model")),
                 axes={"data": "data", "model": "model"})
    ref = _host_leaves(net.params)
    rec = Recorder()
    prev = set_default(rec)
    try:
        net.set_mesh(_mesh((4, 2), ("data", "model")),
                     axes={"data": "data", "model": "model"})
    finally:
        set_default(prev)
    got = _host_leaves(net.params)
    assert all(np.array_equal(a, b) for a, b in zip(ref, got))
    plans = _events_of(rec, "reshard_plan")
    assert plans and plans[0]["path"] == "live"
    assert not _events_of(rec, "host_gather")


# -------------------------------------------------- serving + CLI rides

def test_engine_accepts_any_mesh_checkpoint(tmp_path):
    """serve --checkpoint: a checkpoint written under an 8-way training
    mesh restores into a solo serving engine through the planner (plan
    on the record) and predictions match the source net."""
    from deeplearning4j_tpu.serving import BucketLattice, InferenceEngine
    from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer
    from tests.cluster_worker import C, F, build_net

    rng = np.random.default_rng(7)
    x = rng.random((8, F), dtype=np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, 8)]
    net = build_net().init()
    net.set_mesh(_mesh((8,), ("data",)))
    net.fit(x, y)
    ckpt = str(tmp_path / "ckpt")
    ShardedCheckpointer(ckpt).save(net)
    expected = np.asarray(net.output(x[:1]))

    net2 = build_net()
    rec = Recorder()
    prev = set_default(rec)
    try:
        engine = InferenceEngine(net2, BucketLattice([1, 2]),
                                 checkpoint=ckpt, recorder=rec)
        engine.start()
        got = np.asarray(engine.predict(x[0]))
        engine.drain()
    finally:
        set_default(prev)
    assert engine.restored_step == net.iteration_count
    plans = _events_of(rec, "reshard_plan")
    assert plans and plans[0]["path"] == "checkpoint"
    assert plans[0]["src"].startswith("8 ")
    np.testing.assert_allclose(got, expected.reshape(got.shape),
                               rtol=0, atol=0)


def test_cli_reshard_dry_run(tmp_path, capsys):
    """`cli reshard --checkpoint --target-mesh` prints the plan with
    bytes moved and writes a benchdiff-consumable RESHARD artifact;
    an impossible target mesh is refused with the planner's message."""
    from deeplearning4j_tpu.cli import driver
    from deeplearning4j_tpu.telemetry import artifact
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    net = _build_lm()
    net.fit(_lm_data())
    net.set_mesh(_mesh((2, 4), ("data", "model")),
                 axes={"data": "data", "model": "model"})
    ckpt = str(tmp_path / "ckpt")
    ShardedCheckpointer(ckpt).save(net)

    art = str(tmp_path / "RESHARD_r01.json")
    rc = driver.main(["reshard", "--checkpoint", ckpt,
                      "--target-mesh", "data=1", "--artifact", art])
    out = capsys.readouterr().out
    assert rc == 0
    assert "reshard plan:" in out and "bytes" in out
    rows = artifact.load(art)
    assert rows["reshard_bytes_moved"]["value"] > 0
    assert rows["reshard_bytes_moved"].get("lower_is_better")
    assert rows["reshard_plan_us"]["value"] > 0
    assert rows["reshard_bytes_lower_bound"]["value"] <= \
        rows["reshard_bytes_moved"]["value"]
    # planner refusal surfaces as a usage error, not a traceback
    with pytest.raises(SystemExit, match="does not divide"):
        driver.main(["reshard", "--checkpoint", ckpt,
                     "--target-mesh", "data=1,model=3"])
