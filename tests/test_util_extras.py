"""Tests for util/text auxiliary components: Viterbi, moving windows,
time-series utils, inverted index, tree parsing."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.invertedindex import InvertedIndex
from deeplearning4j_tpu.nlp.treeparser import (
    HeadWordFinder,
    Tree,
    TreeParser,
    TreeVectorizer,
    binarize,
    collapse_unaries,
)
from deeplearning4j_tpu.util.moving_window import MovingWindowMatrix
from deeplearning4j_tpu.util.time_series import (
    moving_average,
    reshape_time_series_mask_to_vector,
)
from deeplearning4j_tpu.util.viterbi import Viterbi


class TestViterbi:
    def test_smooths_isolated_flip(self):
        """A single frame disagreeing with its sticky context is corrected."""
        v = Viterbi([0, 1], meta_stability=0.95, p_correct=0.8)
        obs = [0, 0, 0, 1, 0, 0, 0]
        score, path = v.decode(obs)
        assert path.tolist() == [0] * 7
        assert score < 0  # log-likelihood

    def test_keeps_sustained_switch(self):
        v = Viterbi([0, 1], meta_stability=0.9, p_correct=0.99)
        obs = [0, 0, 0, 1, 1, 1, 1]
        _, path = v.decode(obs)
        assert path.tolist() == obs

    def test_binary_label_matrix_input(self):
        v = Viterbi([0, 1, 2])
        onehot = np.eye(3)[[0, 0, 1, 1, 2]]
        _, path = v.decode(onehot)
        assert path.tolist() == [0, 0, 1, 1, 2]

    def test_requires_two_states(self):
        with pytest.raises(ValueError):
            Viterbi([0])


class TestMovingWindow:
    def test_window_count_and_content(self):
        m = np.arange(16).reshape(4, 4)
        w = MovingWindowMatrix(m, 2, 2).windows()
        assert len(w) == 9
        np.testing.assert_array_equal(w[0], [[0, 1], [4, 5]])
        np.testing.assert_array_equal(w[-1], [[10, 11], [14, 15]])

    def test_flattened_and_rotate(self):
        m = np.arange(4).reshape(2, 2)
        w = MovingWindowMatrix(m, 2, 2, add_rotate=True).windows(flattened=True)
        assert len(w) == 4  # 1 window x 4 rotations
        assert all(v.shape == (4,) for v in w)

    def test_window_too_large(self):
        with pytest.raises(ValueError):
            MovingWindowMatrix(np.zeros((2, 2)), 3, 1)


class TestTimeSeries:
    def test_moving_average(self):
        out = moving_average([1, 2, 3, 4, 5], 2)
        np.testing.assert_allclose(out, [1.5, 2.5, 3.5, 4.5])

    def test_mask_reshape(self):
        mask = np.array([[1, 1, 0], [1, 0, 0]])
        np.testing.assert_array_equal(
            reshape_time_series_mask_to_vector(mask), [1, 1, 0, 1, 0, 0])


class TestInvertedIndex:
    def _index(self):
        ix = InvertedIndex(seed=1)
        ix.add_doc("the cat sat on the mat".split(), labels=["animals"])
        ix.add_doc("the dog sat".split(), labels=["animals"])
        ix.add_doc("stocks fell sharply".split(), labels=["finance"])
        return ix

    def test_postings_and_search(self):
        ix = self._index()
        assert ix.num_documents() == 3
        assert ix.documents("sat") == [0, 1]
        assert ix.search("the", "sat") == [0, 1]
        assert ix.search("the", "stocks") == []

    def test_tfidf_ranking(self):
        ix = self._index()
        hits = ix.tfidf_search("cat", "sat")
        assert hits[0][0] == 0  # doc 0 has both terms
        assert all(s > 0 for _, s in hits)

    def test_minibatches_and_sample(self):
        ix = self._index()
        batches = list(ix.mini_batches(2))
        assert [len(b) for b in batches] == [2, 1]
        assert len(ix.sample()) > 0
        words, labels = ix.document_with_labels(2)
        assert labels == ["finance"]

    def test_incremental_add_same_doc(self):
        ix = InvertedIndex()
        ix.add_words_to_doc(0, ["a", "b"])
        ix.add_words_to_doc(0, ["b", "c"])
        assert ix.document(0) == ["a", "b", "b", "c"]
        assert ix.documents("b") == [0]  # no duplicate posting


class TestTreeParser:
    SENT = "(S (NP (DT the) (NN cat)) (VP (VBD sat) (PP (IN on) (NP (DT the) (NN mat)))))"

    def test_parse_and_yield(self):
        t = TreeParser.parse(self.SENT)
        assert t.label == "S"
        assert t.yield_words() == ["the", "cat", "sat", "on", "the", "mat"]
        assert t.depth() >= 3

    def test_roundtrip_to_string(self):
        t = TreeParser.parse(self.SENT)
        assert TreeParser.parse(t.to_string()).yield_words() == t.yield_words()

    def test_binarize(self):
        t = TreeParser.parse("(X (A a) (B b) (C c) (D d))")
        b = binarize(t)
        def max_arity(n):
            if not n.children:
                return 0
            return max([len(n.children)] + [max_arity(c) for c in n.children])
        assert max_arity(b) <= 2
        assert b.yield_words() == ["a", "b", "c", "d"]

    def test_collapse_unaries(self):
        t = TreeParser.parse("(S (VP (NP (NN dog))))")
        c = collapse_unaries(t)
        assert c.label == "S_VP_NP"
        assert c.yield_words() == ["dog"]

    def test_head_word(self):
        t = TreeParser.parse(self.SENT)
        assert HeadWordFinder.find_head(t) == "mat"

    def test_vectorizer(self):
        t = TreeParser.parse("(S (A a) (B b))")
        table = {"a": np.ones(4, np.float32), "b": np.zeros(4, np.float32)}
        tv = TreeVectorizer(lambda w: table.get(w), dim=4)
        np.testing.assert_allclose(tv.vectorize(t), 0.5 * np.ones(4))
        assert len(tv.vectorize_all(t)) == 5  # S, A, a, B, b


def test_performance_listener_reports_throughput_and_mfu():
    from deeplearning4j_tpu.optimize.listeners import PerformanceListener

    msgs = []
    lst = PerformanceListener(frequency=2, printer=msgs.append,
                              examples_per_iteration=64,
                              flops_per_example=1e9, peak_flops=1e12)

    class FakeModel:
        score_value = 0.5

    lst.iteration_done(FakeModel(), 2)   # primes the clock
    lst.iteration_done(FakeModel(), 4)
    assert msgs and "MFU" in msgs[-1] and "ex/s" in msgs[-1]
    stats = lst.last_stats
    assert stats["examples_per_sec"] > 0
    # mfu = eps * flops / peak
    assert abs(stats["mfu"] - stats["examples_per_sec"] * 1e9 / 1e12) < 1e-9
