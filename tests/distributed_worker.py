"""One member of the N-process x K-virtual-device pjit fleet spawned by
tests/test_distributed.py via `distributed.launch_local`.

Run: python tests/distributed_worker.py <out_dir>

The launcher provides the whole rendezvous env contract
(DL4J_TPU_COORDINATOR/PROCESS_ID/NUM_PROCESSES/LOCAL_DEVICE_COUNT plus
the virtual-CPU XLA flags); this script only has to call
`bootstrap.initialize()`, build the global mesh, and run ONE jitted
allreduce train step through the ordinary `set_mesh` + `fit` path on its
local batch shard — TWICE: once with the monolithic GSPMD formulation
and once with the ISSUE 7 bucketed-overlap step (`set_mesh(mesh,
overlap=...)`, per-bucket psums under shard_map). It saves both
resulting flat parameter vectors so the test can assert bit-identical
replicas across processes for BOTH formulations, plus overlap parity
with the unbucketed step at tight atol.
"""

import os
import sys


def main() -> int:
    out_dir = sys.argv[1]

    from deeplearning4j_tpu.distributed import bootstrap

    info = bootstrap.initialize(connect_timeout=60.0)
    print(f"rendezvous up: {info}", flush=True)

    import numpy as np

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.distributed.global_mesh import (
        local_shard,
        make_global_mesh,
        spans_processes,
    )
    from tests.cluster_worker import build_net, full_data

    mesh = make_global_mesh({"data": -1})
    assert spans_processes(mesh), "mesh does not span processes"
    pid = info["process_id"]
    x, y = full_data()
    ds = DataSet(local_shard(x), local_shard(y))  # this process's rows

    net = build_net().init()  # same seed everywhere -> identical replicas
    net.set_mesh(mesh)
    net.fit(ds)  # ONE jitted allreduce train step over the global mesh
    np.save(os.path.join(out_dir, f"params_p{pid}.npy"),
            np.asarray(net.params_flat()))
    print(f"p{pid}: monolithic step done, score={net.score_value:.6f}, "
          f"devices={info['global_devices']}", flush=True)

    # the bucketed-overlap formulation of the SAME step: tiny bucket
    # size -> several per-bucket psums (the frozen
    # distributed/overlap_step_2x4 collective sequence), executed live
    # across processes
    net_ov = build_net().init()
    net_ov.set_mesh(mesh, overlap=128)
    net_ov.fit(ds)
    np.save(os.path.join(out_dir, f"params_overlap_p{pid}.npy"),
            np.asarray(net_ov.params_flat()))
    print(f"p{pid}: overlap step done, score={net_ov.score_value:.6f}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
