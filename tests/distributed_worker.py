"""One member of the 2-process x 4-virtual-device pjit fleet spawned by
tests/test_distributed.py via `distributed.launch_local`.

Run: python tests/distributed_worker.py <out_dir>

The launcher provides the whole rendezvous env contract
(DL4J_TPU_COORDINATOR/PROCESS_ID/NUM_PROCESSES/LOCAL_DEVICE_COUNT plus
the virtual-CPU XLA flags); this script only has to call
`bootstrap.initialize()`, build the global mesh, and run ONE jitted
allreduce train step through the ordinary `set_mesh` + `fit` path on its
local batch shard. It saves the resulting flat parameter vector so the
test can assert bit-identical replicas across processes and parity with
the single-process full-batch reference.
"""

import os
import sys


def main() -> int:
    out_dir = sys.argv[1]

    from deeplearning4j_tpu.distributed import bootstrap

    info = bootstrap.initialize(connect_timeout=60.0)
    print(f"rendezvous up: {info}", flush=True)

    import numpy as np

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.distributed.global_mesh import (
        local_shard,
        make_global_mesh,
        spans_processes,
    )
    from tests.cluster_worker import build_net, full_data

    mesh = make_global_mesh({"data": -1})
    assert spans_processes(mesh), "mesh does not span processes"
    net = build_net().init()  # same seed everywhere -> identical replicas
    net.set_mesh(mesh)

    x, y = full_data()
    ds = DataSet(local_shard(x), local_shard(y))  # this process's rows
    net.fit(ds)  # ONE jitted allreduce train step over the global mesh

    pid = info["process_id"]
    flat = np.asarray(net.params_flat())
    np.save(os.path.join(out_dir, f"params_p{pid}.npy"), flat)
    print(f"p{pid}: step done, score={net.score_value:.6f}, "
          f"devices={info['global_devices']}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
