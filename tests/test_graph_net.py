"""ComputationGraph runtime parity tests (reference
nn/graph/ComputationGraph.java: fit with tbptt branch:545-672, rnnTimeStep,
pretrain; TestComputationGraphNetwork / ComputationGraphTestRNN patterns)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    AutoEncoder,
    DenseLayer,
    GravesLSTM,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.conf.enums import BackpropType, OptimizationAlgorithm
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import DataParallelTrainer, make_mesh


def _lstm_graph(tbptt=False, L=4):
    b = (NeuralNetConfiguration.builder()
         .seed(11).learning_rate(0.05).updater(Updater.ADAM)
         .graph_builder()
         .add_inputs("in")
         .add_layer("lstm", GravesLSTM(n_in=3, n_out=8, activation="tanh"), "in")
         .add_layer("out", RnnOutputLayer(n_in=8, n_out=3, activation="softmax",
                                          loss_function="mcxent"), "lstm")
         .set_outputs("out"))
    if tbptt:
        b = (b.backprop_type(BackpropType.TRUNCATED_BPTT)
             .t_bptt_forward_length(L).t_bptt_backward_length(L))
    return ComputationGraph(b.build()).init()


def _seq_data(rng, b=4, t=12, n_in=3, n_out=3):
    x = rng.standard_normal((b, t, n_in)).astype(np.float32)
    # learnable: label tracks sign pattern of a fixed input channel
    lab = (x[..., 0] > 0).astype(int) + (x[..., 1] > 0).astype(int)
    y = np.eye(n_out, dtype=np.float32)[lab]
    return DataSet(x, y)


class TestGraphTBPTT:
    def test_tbptt_trains_and_segments(self, rng):
        net = _lstm_graph(tbptt=True, L=4)
        ds = _seq_data(rng, t=12)
        before = net.score(ds)
        net.fit(ds, epochs=30)
        after = net.score(ds)
        assert after < before
        # 12 timesteps / window 4 = 3 segments per batch pass
        assert net.iteration_count == 30 * 3

    def test_tbptt_carries_flow_between_segments(self, rng):
        """With carries threaded, segment 2 must see segment 1's final
        hidden state: verify by checking a TBPTT step sequence differs from
        training each window as an independent sequence (carry reset)."""
        rng2 = np.random.default_rng(7)
        ds = _seq_data(rng2, b=2, t=8)
        net_a = _lstm_graph(tbptt=True, L=4)
        net_b = _lstm_graph(tbptt=True, L=4)
        net_b.params = jax.tree.map(jnp.copy, net_a.params)
        net_b.opt_state = net_b.tx.init(net_b.params)

        net_a.fit(ds, epochs=1)
        # net_b: train on the two windows as separate datasets (fresh carries)
        net_b.fit(DataSet(ds.features[:, :4], ds.labels[:, :4]), epochs=1)
        net_b.fit(DataSet(ds.features[:, 4:], ds.labels[:, 4:]), epochs=1)
        pa, pb = net_a.params_flat(), net_b.params_flat()
        assert not np.allclose(pa, pb, atol=1e-7), \
            "TBPTT carries had no effect — state is not flowing"


class TestGraphRnnTimeStep:
    def test_streaming_matches_full_sequence(self, rng):
        net = _lstm_graph()
        x = rng.standard_normal((2, 8, 3)).astype(np.float32)
        full = np.asarray(net.output(x))  # [B, T, n_out]

        net.rnn_clear_previous_state()
        chunks = [np.asarray(net.rnn_time_step(x[:, :3])),
                  np.asarray(net.rnn_time_step(x[:, 3:6])),
                  np.asarray(net.rnn_time_step(x[:, 6:]))]
        streamed = np.concatenate(chunks, axis=1)
        np.testing.assert_allclose(full, streamed, atol=1e-5)

    def test_single_step_2d(self, rng):
        net = _lstm_graph()
        net.rnn_clear_previous_state()
        y1 = net.rnn_time_step(rng.standard_normal((2, 3)).astype(np.float32))
        assert y1.shape == (2, 3)
        # second step continues the carry (different from a fresh call)
        x2 = rng.standard_normal((2, 3)).astype(np.float32)
        y2 = np.asarray(net.rnn_time_step(x2))
        net.rnn_clear_previous_state()
        y2_fresh = np.asarray(net.rnn_time_step(x2))
        assert not np.allclose(y2, y2_fresh, atol=1e-7)


class TestGraphPretrain:
    def test_greedy_pretrain_reduces_reconstruction_loss(self, rng):
        g = (NeuralNetConfiguration.builder()
             .seed(3).learning_rate(0.05).updater(Updater.ADAM)
             .graph_builder()
             .add_inputs("in")
             .add_layer("ae", AutoEncoder(n_in=8, n_out=4, activation="sigmoid"),
                        "in")
             .add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                           loss_function="mcxent"), "ae")
             .set_outputs("out")
             .pretrain(True)
             .build())
        net = ComputationGraph(g).init()
        x = rng.standard_normal((32, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        impl, lc = net.impls["ae"], net.layer_vertices["ae"].layer
        loss0 = float(impl.pretrain_loss(lc, net.params["ae"],
                                         jnp.asarray(x), jax.random.PRNGKey(0)))
        net.pretrain(DataSet(x, y), epochs=40)
        loss1 = float(impl.pretrain_loss(lc, net.params["ae"],
                                         jnp.asarray(x), jax.random.PRNGKey(0)))
        assert loss1 < loss0
        # full fit path runs pretrain then backprop without error
        net2 = ComputationGraph(g).init()
        net2.fit(DataSet(x, y), epochs=2)
        assert np.isfinite(net2.score_value)


class TestGraphSolver:
    def test_lbfgs_path(self, rng):
        g = (NeuralNetConfiguration.builder()
             .seed(5)
             .optimization_algo(OptimizationAlgorithm.LBFGS)
             .iterations(10)
             .graph_builder()
             .add_inputs("in")
             .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
             .add_layer("out", OutputLayer(n_in=8, n_out=2, activation="softmax",
                                           loss_function="mcxent"), "d")
             .set_outputs("out")
             .build())
        net = ComputationGraph(g).init()
        x = rng.standard_normal((32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        ds = DataSet(x, y)
        before = net.score(ds)
        net.fit(ds, epochs=3)
        after = net.score(ds)
        assert after < before
        assert net.iteration_count > 0


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
class TestGraphDistributed:
    def test_cg_allreduce_dp_matches_single_device(self, rng):
        """ComputationGraph under DataParallelTrainer == single-device
        training (VERDICT weak #5 — CG mesh path was untested)."""
        def build():
            g = (NeuralNetConfiguration.builder()
                 .seed(9).learning_rate(0.1).updater(Updater.SGD)
                 .graph_builder()
                 .add_inputs("in")
                 .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"),
                            "in")
                 .add_layer("out", OutputLayer(n_in=8, n_out=2,
                                               activation="softmax",
                                               loss_function="mcxent"), "d1")
                 .set_outputs("out")
                 .build())
            return ComputationGraph(g).init()

        x = rng.standard_normal((64, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
        ds = DataSet(x, y)

        net_a, net_b = build(), build()
        net_b.params = jax.tree.map(jnp.copy, net_a.params)
        net_b.opt_state = net_b.tx.init(net_b.params)

        net_a.fit(ListDataSetIterator([ds]), epochs=3)
        mesh = make_mesh({"data": 8})
        DataParallelTrainer(net_b, mesh).fit(ListDataSetIterator([ds]), epochs=3)
        np.testing.assert_allclose(net_a.params_flat(), net_b.params_flat(),
                                   atol=2e-5)


class TestGraphGuards:
    """Regression tests for silent-wrong-result paths (round-2 review)."""

    def test_rnn_time_step_rejects_bidirectional(self, rng):
        from deeplearning4j_tpu.nn.conf import GravesBidirectionalLSTM

        g = (NeuralNetConfiguration.builder().seed(1)
             .graph_builder()
             .add_inputs("in")
             .add_layer("bi", GravesBidirectionalLSTM(n_in=3, n_out=4,
                                                      activation="tanh"), "in")
             .add_layer("out", RnnOutputLayer(n_in=4, n_out=2,
                                              activation="softmax"), "bi")
             .set_outputs("out")
             .build())
        net = ComputationGraph(g).init()
        with pytest.raises(ValueError, match="cannot stream"):
            net.rnn_time_step(rng.standard_normal((2, 3)).astype(np.float32))

    def test_rnn_time_step_rejects_mixed_ranks(self, rng):
        g = (NeuralNetConfiguration.builder().seed(1)
             .graph_builder()
             .add_inputs("a", "b")
             .add_layer("l1", GravesLSTM(n_in=3, n_out=4, activation="tanh"),
                        "a")
             .add_layer("l2", GravesLSTM(n_in=3, n_out=4, activation="tanh"),
                        "b")
             .add_vertex("m", __import__(
                 "deeplearning4j_tpu.nn.conf.graph_conf",
                 fromlist=["MergeVertexConf"]).MergeVertexConf(), "l1", "l2")
             .add_layer("out", RnnOutputLayer(n_in=8, n_out=2,
                                              activation="softmax"), "m")
             .set_outputs("out")
             .build())
        net = ComputationGraph(g).init()
        with pytest.raises(ValueError, match="mixed input ranks"):
            net.rnn_time_step(
                rng.standard_normal((2, 3)).astype(np.float32),
                rng.standard_normal((2, 5, 3)).astype(np.float32))

    def test_tbptt_rejects_per_sequence_labels(self, rng):
        net = _lstm_graph(tbptt=True, L=4)
        x = rng.standard_normal((2, 12, 3)).astype(np.float32)
        y2d = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 2)]
        with pytest.raises(ValueError, match="time-distributed labels"):
            net.fit(DataSet(x, y2d), epochs=1)

    def test_pretrain_honors_per_layer_lr(self, rng):
        """Per-layer learning_rate=0 must freeze the pretrain layer (the
        multi_transform labels key on layer names)."""
        g = (NeuralNetConfiguration.builder()
             .seed(3).learning_rate(0.05).updater(Updater.SGD)
             .graph_builder()
             .add_inputs("in")
             .add_layer("ae", AutoEncoder(n_in=8, n_out=4, activation="sigmoid",
                                          learning_rate=0.0), "in")
             .add_layer("out", OutputLayer(n_in=4, n_out=2,
                                           activation="softmax"), "ae")
             .set_outputs("out")
             .build())
        net = ComputationGraph(g).init()
        x = rng.standard_normal((16, 8)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
        before = np.array(net.params["ae"]["W"])
        net.pretrain(DataSet(x, y), epochs=3)
        np.testing.assert_allclose(before, np.array(net.params["ae"]["W"]))


def test_cg_fit_scanned():
    from deeplearning4j_tpu.models.transformer import transformer_lm

    net = transformer_lm(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                         d_ff=32, max_length=8)
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 32, (4, 8)), np.int32)
    net.fit_scanned(toks, np.roll(toks, -1, 1), epochs=4)
    first = float(net._epoch_losses[0])
    last = float(net._epoch_losses[-1])
    assert np.isfinite(last) and last < first
    assert net.iteration_count == 4


@pytest.mark.slow
def test_cg_remat_matches_plain_gradients():
    """conf.remat wraps each layer vertex in jax.checkpoint — a pure
    HBM-for-FLOPs trade that must not change the math: loss and every
    gradient leaf agree with the un-rematted graph to float tolerance
    (the flag was silently ignored by this container before r5)."""
    from deeplearning4j_tpu.models.transformer import transformer_lm

    rng = np.random.default_rng(3)
    toks = np.asarray(rng.integers(0, 32, (4, 8)), np.int32)
    nets = {}
    for remat in (False, True):
        net = transformer_lm(vocab_size=32, d_model=16, n_heads=2,
                             n_layers=2, d_ff=32, max_length=8, remat=remat)
        net.init()
        assert net.conf.conf.remat is remat
        nets[remat] = net

    def loss_and_grads(net):
        batch = {"features": [toks],
                 "labels": [np.roll(toks, -1, 1)]}
        def f(p):
            loss, _ = net._loss(p, net.state, jax.random.PRNGKey(0), batch)
            return loss
        return jax.value_and_grad(f)(net.params)

    (l0, g0), (l1, g1) = loss_and_grads(nets[False]), loss_and_grads(nets[True])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g0, g1)


def test_cg_remat_fit_scanned_trains():
    from deeplearning4j_tpu.models.transformer import transformer_lm

    net = transformer_lm(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                         d_ff=32, max_length=8, remat=True)
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 32, (4, 8)), np.int32)
    net.fit_scanned(toks, np.roll(toks, -1, 1), epochs=4)
    assert np.isfinite(float(net._epoch_losses[-1]))
    assert float(net._epoch_losses[-1]) < float(net._epoch_losses[0])
