"""Multi-process control plane (parallel/cluster.py): registration,
heartbeat dead-worker removal, config registry, averaging rounds, and the
elastic training loop — including true multi-PROCESS training parity with
single-process full-batch SGD and a kill-one-worker-and-resume recovery
test (SURVEY.md §4.5; reference MasterActor heartbeat semantics)."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.cluster import (
    ClusterClient,
    ClusterCoordinator,
)


@pytest.fixture()
def coord():
    c = ClusterCoordinator(heartbeat_timeout=2.0).start()
    yield c
    c.shutdown()


def test_register_ranks_and_config(coord):
    a = ClusterClient(coord.address, "wA")
    b = ClusterClient(coord.address, "wB")
    assert {a.rank, b.rank} == {0, 1}
    assert a.workers() == ["wA", "wB"]
    a.set_config("training", {"lr": 0.1, "layers": [4, 3]})
    assert b.get_config("training") == {"lr": 0.1, "layers": [4, 3]}
    assert b.get_config("missing") is None  # unset key -> default
    assert b.get_config("missing", 7) == 7
    a.close()
    b.close()


def test_dead_worker_removed_after_heartbeat_timeout(coord):
    a = ClusterClient(coord.address, "wA", heartbeat_interval=0.2)
    b = ClusterClient(coord.address, "wB", heartbeat_interval=0.2)
    assert sorted(coord.alive_workers()) == ["wA", "wB"]
    b._hb_stop.set()  # b stops heartbeating (simulated crash)
    time.sleep(2.5)
    assert sorted(coord.alive_workers()) == ["wA"]
    a.close()


def test_average_round_means_contributions(coord):
    a = ClusterClient(coord.address, "wA")
    b = ClusterClient(coord.address, "wB")
    out = {}

    def go(client, vec):
        out[client.worker_id] = client.average(1, np.asarray(vec, np.float32))

    ta = threading.Thread(target=go, args=(a, [1.0, 3.0]))
    tb = threading.Thread(target=go, args=(b, [3.0, 5.0]))
    ta.start(); tb.start(); ta.join(); tb.join()
    np.testing.assert_allclose(out["wA"], [2.0, 4.0])
    np.testing.assert_allclose(out["wB"], [2.0, 4.0])
    a.close()
    b.close()


def test_average_completes_elastically_when_worker_dies(coord):
    a = ClusterClient(coord.address, "wA", heartbeat_interval=0.2)
    b = ClusterClient(coord.address, "wB", heartbeat_interval=0.2)
    b._hb_stop.set()  # b will be declared dead mid-round
    result = {}

    def go():
        result["avg"] = a.average(5, np.asarray([2.0, 2.0], np.float32))

    t = threading.Thread(target=go)
    t.start()
    t.join(timeout=15)
    assert not t.is_alive(), "round never completed after worker death"
    np.testing.assert_allclose(result["avg"], [2.0, 2.0])
    a.close()


# --------------------------------------------------------------- processes

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spawn(address, wid, shard, ckpt="-", crash_at="none", local_mesh=0,
           kind="mln"):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO_ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, "tests/cluster_worker.py", address, wid, shard,
         ckpt, crash_at, str(local_mesh), kind], env=env, cwd=_REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)


@pytest.mark.slow
def test_two_process_training_matches_single_process(tmp_path):
    """2 workers x half batch with per-step averaging == 1 process x full
    batch, for plain SGD (gradient linearity). True multi-process CPU run
    (SURVEY.md §4.5)."""
    from tests.cluster_worker import STEPS, build_net, full_data
    from deeplearning4j_tpu.datasets.api import DataSet

    coord = ClusterCoordinator(heartbeat_timeout=30.0).start()
    try:
        pa = _spawn(coord.address, "w0", "0", ckpt=str(tmp_path / "w0.zip"))
        pb = _spawn(coord.address, "w1", "1", ckpt=str(tmp_path / "w1.zip"))
        for p in (pa, pb):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        coord.shutdown()

    flat0 = np.load(str(tmp_path / "w0.zip.params.npy"))
    flat1 = np.load(str(tmp_path / "w1.zip.params.npy"))
    np.testing.assert_allclose(flat0, flat1, atol=1e-6)  # synced replicas

    # single-process reference: full batch, same config and seed
    x, y = full_data()
    ref = build_net().init()
    for _ in range(STEPS):
        ref.fit(DataSet(x, y))
    np.testing.assert_allclose(flat0, np.asarray(ref.params_flat()),
                               atol=5e-4)


@pytest.mark.slow
def test_kill_one_worker_then_resume_from_checkpoint(tmp_path):
    """One worker crashes after 2 syncs; the survivor finishes its rounds
    elastically; the crashed worker restarts from its checkpoint and
    completes the remaining steps."""
    coord = ClusterCoordinator(heartbeat_timeout=3.0).start()
    ckpt = str(tmp_path / "w1.zip")
    try:
        pa = _spawn(coord.address, "w0", "0", ckpt=str(tmp_path / "w0.zip"))
        pb = _spawn(coord.address, "w1", "1", ckpt=ckpt, crash_at="2")
        out, err = pb.communicate(timeout=300)
        assert pb.returncode == 1  # crashed as scripted
        assert os.path.exists(ckpt), "no checkpoint before crash"
        # survivor completes all rounds despite the death
        out, err = pa.communicate(timeout=300)
        assert pa.returncode == 0, err.decode()[-2000:]

        # restart the crashed worker: resumes at the checkpointed step
        pb2 = _spawn(coord.address, "w1", "1", ckpt=ckpt)
        out, err = pb2.communicate(timeout=300)
        assert pb2.returncode == 0, err.decode()[-2000:]
        flat = np.load(ckpt + ".params.npy")
        assert np.isfinite(flat).all()
    finally:
        coord.shutdown()


@pytest.mark.slow
def test_two_process_times_four_device_hierarchy(tmp_path):
    """SURVEY.md §4.5 topology: 2 processes x 4 virtual devices each —
    in-process XLA allreduce + cross-process coordinator averaging gives
    the same result as plain 2-process training (gradient linearity)."""
    coord = ClusterCoordinator(heartbeat_timeout=30.0).start()
    try:
        pa = _spawn(coord.address, "w0", "0", ckpt=str(tmp_path / "w0.zip"),
                    local_mesh=4)
        pb = _spawn(coord.address, "w1", "1", ckpt=str(tmp_path / "w1.zip"),
                    local_mesh=4)
        for p in (pa, pb):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        coord.shutdown()

    from tests.cluster_worker import STEPS, build_net, full_data
    from deeplearning4j_tpu.datasets.api import DataSet

    flat0 = np.load(str(tmp_path / "w0.zip.params.npy"))
    x, y = full_data()
    ref = build_net().init()
    for _ in range(STEPS):
        ref.fit(DataSet(x, y))
    np.testing.assert_allclose(flat0, np.asarray(ref.params_flat()),
                               atol=5e-4)


def test_two_process_computation_graph_training(tmp_path):
    """The elastic worker loop serves DAG networks too (DP-3 across
    processes): replicas converge and stay synchronized."""
    coord = ClusterCoordinator(heartbeat_timeout=30.0).start()
    try:
        pa = _spawn(coord.address, "w0", "0", ckpt=str(tmp_path / "w0.zip"),
                    kind="cg")
        pb = _spawn(coord.address, "w1", "1", ckpt=str(tmp_path / "w1.zip"),
                    kind="cg")
        for p in (pa, pb):
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, err.decode()[-2000:]
    finally:
        coord.shutdown()
    flat0 = np.load(str(tmp_path / "w0.zip.params.npy"))
    flat1 = np.load(str(tmp_path / "w1.zip.params.npy"))
    np.testing.assert_allclose(flat0, flat1, atol=1e-6)
    assert np.isfinite(flat0).all()


def test_coordinator_snapshot_roundtrip(tmp_path):
    """Registry/rank/config/claim state persists on every mutation and a
    fresh coordinator reloads it from the JSON snapshot."""
    import json

    snap = str(tmp_path / "coord.json")
    c1 = ClusterCoordinator(heartbeat_timeout=5.0, snapshot_path=snap).start()
    try:
        a = ClusterClient(c1.address, "wA", heartbeat_interval=0.2)
        b = ClusterClient(c1.address, "wB", heartbeat_interval=0.2)
        a.set_config("training", {"lr": 0.1})
        sa, sb = a.claim_slot(2), b.claim_slot(2)
        assert {sa, sb} == {0, 1}
        a.close(deregister=False)
        b.close(deregister=False)
    finally:
        c1.shutdown()
    data = json.load(open(snap))
    assert data["ranks"] == {"wA": 0, "wB": 1}
    assert data["configs"]["training"] == {"lr": 0.1}
    assert data["configs"][f"shard_owner/{sa}"] == "wA"
    assert sorted(data["workers"]) == ["wA", "wB"]

    c2 = ClusterCoordinator(heartbeat_timeout=5.0, snapshot_path=snap).start()
    try:
        # reloaded: ranks stable, claims intact, restored workers count
        # as provisionally alive so nothing is stealable
        a2 = ClusterClient(c2.address, "wA", heartbeat_interval=0.2)
        assert a2.rank == 0
        assert a2.get_config("training") == {"lr": 0.1}
        assert a2.claim_slot(2) == sa  # idempotent re-claim, not a steal
        c = ClusterClient(c2.address, "wC", heartbeat_interval=0.2)
        assert c.claim_slot(2) is None  # wB's slot survived the restart
        a2.close(); c.close()
    finally:
        c2.shutdown()


def test_kill_coordinator_and_restart_preserves_claims(tmp_path):
    """The acceptance-criterion recovery: kill the coordinator mid-fleet,
    restart it on the same port from its snapshot, and the SAME live
    clients ride through — reconnect + re-register, keep their ranks and
    shard claims, and finish an averaging round together."""
    snap = str(tmp_path / "coord.json")
    c1 = ClusterCoordinator(heartbeat_timeout=5.0, round_timeout=10.0,
                            snapshot_path=snap).start()
    port = c1.port
    a = ClusterClient(c1.address, "wA", heartbeat_interval=0.2,
                      reconnect_timeout=30.0)
    b = ClusterClient(c1.address, "wB", heartbeat_interval=0.2,
                      reconnect_timeout=30.0)
    sa, sb = a.claim_slot(2), b.claim_slot(2)
    assert {sa, sb} == {0, 1}
    rank_a, rank_b = a.rank, b.rank

    c1.shutdown()  # coordinator dies with the fleet still running
    time.sleep(0.5)
    c2 = ClusterCoordinator(port=port, heartbeat_timeout=5.0,
                            round_timeout=10.0, snapshot_path=snap).start()
    try:
        # the LIVE clients reconnect on their next call and keep identity
        assert a.claim_slot(2) == sa
        assert b.claim_slot(2) == sb
        assert (a.rank, b.rank) == (rank_a, rank_b)

        # the fleet finishes the round through the restarted coordinator
        out = {}

        def go(client, vec):
            out[client.worker_id] = client.average(
                1, np.asarray(vec, np.float32))

        ta = threading.Thread(target=go, args=(a, [1.0, 3.0]))
        tb = threading.Thread(target=go, args=(b, [3.0, 5.0]))
        ta.start(); tb.start()
        ta.join(timeout=30); tb.join(timeout=30)
        assert not ta.is_alive() and not tb.is_alive(), \
            "round never completed after coordinator restart"
        np.testing.assert_allclose(out["wA"], [2.0, 4.0])
        np.testing.assert_allclose(out["wB"], [2.0, 4.0])
        a.close()
        b.close()
    finally:
        c2.shutdown()


def test_claim_slot_atomic_and_elastic(coord):
    a = ClusterClient(coord.address, "wA", heartbeat_interval=0.2)
    b = ClusterClient(coord.address, "wB", heartbeat_interval=0.2)
    sa, sb = a.claim_slot(2), b.claim_slot(2)
    assert {sa, sb} == {0, 1}          # distinct slots
    assert a.claim_slot(2) == sa       # idempotent re-claim
    c = ClusterClient(coord.address, "wC", heartbeat_interval=0.2)
    assert c.claim_slot(2) is None     # full: nothing stealable
    # close WITHOUT deregistering: wB stays alive until heartbeat expiry,
    # so its slot still can't be stolen
    b.close(deregister=False)
    assert c.claim_slot(2) is None
    time.sleep(2.5)                    # > coord heartbeat_timeout (2.0)
    assert c.claim_slot(2) == sb       # dead owner's slot is reassigned
    a.close(); c.close()


def test_replacement_worker_adopts_dead_rank(coord, tmp_path):
    """Elastic re-form (ISSUE 6): a NEW worker registering with
    replace_dead=True adopts the lowest dead worker's rank instead of
    minting a fresh one — the [0, N') rank space stays dense across a
    death — and the reassignment survives a coordinator restart."""
    a = ClusterClient(coord.address, "wA", heartbeat_interval=0.2)
    b = ClusterClient(coord.address, "wB", heartbeat_interval=0.2)
    assert (a.rank, b.rank) == (0, 1)
    # a REJOINING worker (known id) always keeps its own rank, even when
    # it asks for replacement
    b.close(deregister=False)
    rejoin = ClusterClient(coord.address, "wB", heartbeat_interval=0.2,
                           replace_dead=True)
    assert rejoin.rank == 1 and rejoin.reassigned_from is None
    # wB dies for real; its heartbeats stop and the alive set drops it
    rejoin.close(deregister=True)
    replacement = ClusterClient(coord.address, "wC",
                                heartbeat_interval=0.2, replace_dead=True)
    assert replacement.rank == 1 and replacement.reassigned_from == "wB"
    # without the flag a newcomer still gets a fresh rank
    fresh = ClusterClient(coord.address, "wD", heartbeat_interval=0.2)
    assert fresh.rank == 2
    a.close(); replacement.close(); fresh.close()


def test_rank_reassignment_persists_in_snapshot(tmp_path):
    snap = str(tmp_path / "coord.json")
    c1 = ClusterCoordinator(heartbeat_timeout=1.0,
                            snapshot_path=snap).start()
    port = c1.port
    try:
        a = ClusterClient(c1.address, "wA", heartbeat_interval=0.2)
        b = ClusterClient(c1.address, "wB", heartbeat_interval=0.2)
        b.close(deregister=True)
        c = ClusterClient(c1.address, "wC", heartbeat_interval=0.2,
                          replace_dead=True)
        assert c.rank == 1 and c.reassigned_from == "wB"
        a.close(); c.close()
    finally:
        c1.shutdown()
    c2 = ClusterCoordinator(port=port, heartbeat_timeout=1.0,
                            snapshot_path=snap).start()
    try:
        # the restarted registry knows wC's adopted rank and forgot wB
        assert c2._ranks == {"wA": 0, "wC": 1}
    finally:
        c2.shutdown()


def test_drop_heartbeat_fault_silences_worker(coord, monkeypatch):
    """The injected drop-heartbeat fault (distributed/faults.py): the
    worker process stays alive but goes silent, the coordinator reaps it
    after heartbeat_timeout, and its claims become stealable — the
    partial-failure mode a kill cannot simulate."""
    from deeplearning4j_tpu.distributed import bootstrap

    # the schedule targets process 1 only; the heartbeat thread reads the
    # env when it starts, so pin the non-victim id BEFORE each client
    monkeypatch.setenv(bootstrap.ENV_FAULTS, "p1:drop-heartbeat")
    monkeypatch.setenv(bootstrap.ENV_PROCESS_ID, "0")
    healthy = ClusterClient(coord.address, "wA", heartbeat_interval=0.2)
    slot = healthy.claim_slot(2)
    monkeypatch.setenv(bootstrap.ENV_PROCESS_ID, "1")
    silent = ClusterClient(coord.address, "wSilent",
                           heartbeat_interval=0.2)
    silent_slot = silent.claim_slot(2)
    monkeypatch.setenv(bootstrap.ENV_PROCESS_ID, "0")
    assert {slot, silent_slot} == {0, 1}
    assert sorted(coord.alive_workers()) == ["wA", "wSilent"]
    time.sleep(2.5)  # > heartbeat_timeout (2.0): the fault bites
    assert sorted(coord.alive_workers()) == ["wA"]
    # the silenced worker's slot is now claimable by a newcomer
    taker = ClusterClient(coord.address, "wB", heartbeat_interval=0.2)
    assert taker.claim_slot(2) == silent_slot
    healthy.close(); taker.close()
    silent.close(deregister=False)  # it was already reaped
