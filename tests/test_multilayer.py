"""MultiLayerNetwork integration tests (reference test strategy §4 item 4:
MultiLayerTest, convergence smoke tests on tiny data)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    GravesLSTM,
    InputType,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener


def make_xor_data(n=128, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-1, 1, size=(n, 2)).astype(np.float32)
    y = ((x[:, 0] > 0) ^ (x[:, 1] > 0)).astype(np.int64)
    labels = np.eye(2, dtype=np.float32)[y]
    return DataSet(x, labels)


def test_mlp_learns_xor():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(42)
        .learning_rate(0.1)
        .updater(Updater.ADAM)
        .list()
        .layer(DenseLayer(n_in=2, n_out=16, activation="tanh"))
        .layer(OutputLayer(n_in=16, n_out=2, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    ds = make_xor_data()
    collector = CollectScoresIterationListener()
    net.set_listeners(collector)
    net.fit(ListDataSetIterator([ds]), epochs=150)
    first = collector.scores[0][1]
    last = collector.scores[-1][1]
    assert last < first * 0.5, f"score did not decrease: {first} -> {last}"
    ev = net.evaluate(ds)
    assert ev.accuracy() > 0.9


def test_output_shapes_and_predict():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(DenseLayer(n_in=4, n_out=8))
        .layer(OutputLayer(n_in=8, n_out=3, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    out = np.asarray(net.output(x))
    assert out.shape == (5, 3)
    assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)
    preds = net.predict(x)
    assert preds.shape == (5,)
    acts = net.feed_forward(x)
    assert len(acts) == 2 and acts[0].shape == (5, 8)


def test_num_params_and_flat_round_trip():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(DenseLayer(n_in=4, n_out=8))
        .layer(OutputLayer(n_in=8, n_out=3))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    # (4*8+8) + (8*3+3) = 40 + 27
    assert net.num_params() == 67
    flat = net.params_flat()
    assert flat.shape == (67,)
    flat2 = flat * 2.0
    net.set_params_flat(flat2)
    assert np.allclose(net.params_flat(), flat2)


def test_rnn_fit_and_time_step():
    T, B, F = 6, 4, 3
    rng = np.random.default_rng(1)
    x = rng.normal(size=(B, T, F)).astype(np.float32)
    # predict sign of first feature per step
    y = (x[..., :1] > 0).astype(np.float32)
    labels = np.concatenate([y, 1 - y], axis=-1)
    conf = (
        NeuralNetConfiguration.builder()
        .seed(12)
        .learning_rate(0.05)
        .updater(Updater.ADAM)
        .list()
        .layer(GravesLSTM(n_out=8, activation="tanh"))
        .layer(RnnOutputLayer(n_out=2, activation="softmax"))
        .set_input_type(InputType.recurrent(F))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(DataSet(x, labels), epochs=30)
    out = np.asarray(net.output(x))
    assert out.shape == (B, T, 2)
    # streaming matches batch forward
    net.rnn_clear_previous_state()
    stream_out = []
    for t in range(T):
        stream_out.append(np.asarray(net.rnn_time_step(x[:, t, :])))
    stream = np.stack(stream_out, axis=1)
    assert np.allclose(stream, out, atol=1e-4)


def test_masking_in_loss_and_eval():
    B, T, F = 3, 5, 2
    rng = np.random.default_rng(3)
    x = rng.normal(size=(B, T, F)).astype(np.float32)
    labels = np.zeros((B, T, 2), np.float32)
    labels[..., 0] = 1
    mask = np.ones((B, T), np.float32)
    mask[:, 3:] = 0
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesLSTM(n_out=4))
        .layer(RnnOutputLayer(n_out=2, activation="softmax"))
        .set_input_type(InputType.recurrent(F))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    ds = DataSet(x, labels, features_mask=mask, labels_mask=mask)
    net.fit(ds, epochs=2)
    ev = net.evaluate(ds)
    assert ev.examples == int(mask.sum())


@pytest.mark.parametrize("updater", ["sgd", "adam", "rmsprop", "adagrad",
                                     "adadelta", "nesterovs"])
def test_all_updaters_run(updater):
    conf = (
        NeuralNetConfiguration.builder()
        .updater(updater)
        .learning_rate(0.01)
        .list()
        .layer(DenseLayer(n_in=2, n_out=4))
        .layer(OutputLayer(n_in=4, n_out=2, activation="softmax"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    ds = make_xor_data(32)
    net.fit(ds, epochs=2)
    assert np.isfinite(net.score_value)


def test_fit_scanned_matches_fit():
    """fit_scanned (whole-epoch fused scan) trains identically to fit()
    for SGD on uniform batches (rng only differs under dropout)."""
    from deeplearning4j_tpu.datasets.api import DataSet

    def build():
        conf = (
            NeuralNetConfiguration.builder()
            .seed(3)
            .learning_rate(0.1)
            .updater("sgd")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build()
        )
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    batches = [DataSet(rng.random((16, 4), dtype=np.float32),
                       np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)])
               for _ in range(5)]
    a, b = build(), build()
    for _ in range(3):
        a.fit(ListDataSetIterator(batches))
    b.fit_scanned(ListDataSetIterator(batches), epochs=3)
    np.testing.assert_allclose(np.asarray(a.params_flat()),
                               np.asarray(b.params_flat()), atol=1e-5)
    assert abs(a.score_value - b.score_value) < 1e-5
    assert b.iteration_count == 15


def test_fit_scanned_rejects_ragged_batches():
    from deeplearning4j_tpu.datasets.api import DataSet

    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(DenseLayer(n_in=4, n_out=4, activation="tanh"))
        .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    mk = lambda n: DataSet(rng.random((n, 4), dtype=np.float32),
                           np.eye(2, dtype=np.float32)[rng.integers(0, 2, n)])
    with pytest.raises(ValueError):
        net.fit_scanned(ListDataSetIterator([mk(16), mk(7)]))


def test_mln_remat_matches_plain_gradients():
    """conf.remat (jax.checkpoint per layer, multilayer.py:169) is a pure
    HBM-for-FLOPs trade: loss and every gradient leaf must agree with the
    un-rematted network to float tolerance."""
    import jax

    from deeplearning4j_tpu.nn.conf import DenseLayer, OutputLayer

    rng = np.random.default_rng(5)
    x = rng.standard_normal((8, 12)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    nets = {}
    for remat in (False, True):
        b = (NeuralNetConfiguration.builder()
             .seed(9).learning_rate(0.05).updater(Updater.ADAM)
             .remat(remat)
             .list()
             .layer(DenseLayer(n_in=12, n_out=16, activation="tanh"))
             .layer(DenseLayer(n_in=16, n_out=8, activation="relu"))
             .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                                loss_function="mcxent"))
             .build())
        nets[remat] = MultiLayerNetwork(b).init()

    def loss_and_grads(net):
        batch = {"features": x, "labels": y}
        def f(p):
            loss, _ = net._loss(p, net.state, jax.random.PRNGKey(0), batch)
            return loss
        return jax.value_and_grad(f)(net.params)

    (l0, g0), (l1, g1) = loss_and_grads(nets[False]), loss_and_grads(nets[True])
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), g0, g1)
