"""Unified distributed entry point: net.set_mesh(mesh, axes={...}).

VERDICT r2 #1: TP/PP/EP join the container API the way SP did in round 2 —
per-axis loss parity through the PUBLIC API, and dp x tp x pp composed in
one jitted train step on the builder-API transformer (reference anchor:
distribution is the reference's flagship capability,
spark/impl/multilayer/SparkDl4jMultiLayer.java:335; TP/PP/EP are the
TPU-first capabilities beyond its data-parallel-only design).
"""

import numpy as np
import jax
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.models.transformer import (
    transformer_lm,
    transformer_moe_lm,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh

V, D, H, L, FF, T, B = 64, 16, 2, 4, 32, 8, 8
ATOL = 2e-4


@pytest.fixture(scope="module")
def lm_data():
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, V, (B, T)), np.int32)
    labs = np.eye(V, dtype=np.float32)[np.roll(toks, -1, axis=1)]
    return DataSet(toks, labs)


def _dense_lm(data, epochs=3):
    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T)
    net.init()
    net.fit(data, epochs=epochs)
    return net


@pytest.fixture(scope="module")
def dense(lm_data):
    return _dense_lm(lm_data)


def _fresh_lm():
    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T)
    net.init()
    return net


def test_tp_via_set_mesh_matches_dense(dense, lm_data):
    """Megatron TP is conf/mesh-driven now — no hand-wired param_shardings
    or custom jit (the r2 'TP must be hand-wired' gap)."""
    net = _fresh_lm()
    net.set_mesh(make_mesh({"data": 2, "model": 4}),
                 axes={"data": "data", "model": "model"})
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL
    # rule-based placement really sharded the QKV projection
    spec = net.params["blk0_attn"]["Wqkv"].sharding.spec
    assert "model" in tuple(spec)


def test_tp_set_mesh_before_init(dense, lm_data):
    """set_mesh before init() must still place the TP shardings (the
    placement applies at set_mesh via auto-init, not silently never)."""
    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T)
    net.set_mesh(make_mesh({"data": 2, "model": 4}),
                 axes={"data": "data", "model": "model"})
    assert "model" in tuple(net.params["blk0_attn"]["Wqkv"].sharding.spec)
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL


def test_pp_via_set_mesh_matches_dense(dense, lm_data):
    """GPipe PP stages are partitioned from the REAL builder conf
    (heterogeneous embed/posenc pre and ln_f/head post segments)."""
    net = _fresh_lm()
    net.set_mesh(make_mesh({"pipe": 4}), axes={"pipe": "pipe"},
                 n_microbatches=4)
    plan = net._pp_plan
    assert plan.pre_layers == ["embed", "posenc"]
    assert plan.post_layers == ["ln_f", "out"]
    assert [len(g) for g in plan.group_layers] == [5, 5, 5, 5]
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL
    # params trained identically (same seed, same math)
    cp = net._canonical_params()
    for k in dense.params:
        for a, b in zip(jax.tree.leaves(dense.params[k]),
                        jax.tree.leaves(cp[k])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)


def test_dp_tp_pp_combined_one_step(dense, lm_data):
    """The flagship composition: data x model x pipe in ONE jitted train
    step — the microbatch schedule is manual over 'pipe' only; GSPMD
    propagates batch and Megatron shardings through the stage compute."""
    net = _fresh_lm()
    mesh = make_mesh({"data": 2, "model": 2, "pipe": 2})
    net.set_mesh(mesh, axes={"data": "data", "model": "model",
                             "pipe": "pipe"}, n_microbatches=4)
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL
    specs = {tuple(l.sharding.spec) for l in net.params["stages"]}
    assert any("pipe" in s and "model" in s for s in specs)


def test_pp_output_eval_and_serializer_roundtrip(dense, lm_data):
    """output()/score()/ModelSerializer keep working while the pipelined
    layout is active (canonical conversion at the boundaries)."""
    import os
    import tempfile

    from deeplearning4j_tpu.util.model_serializer import ModelSerializer

    net = _fresh_lm()
    net.set_mesh(make_mesh({"pipe": 4}), axes={"pipe": "pipe"},
                 n_microbatches=4)
    net.fit(lm_data, epochs=1)
    ref = _dense_lm(lm_data, epochs=1)
    toks = np.asarray(lm_data.features)
    np.testing.assert_allclose(np.asarray(net.output(toks)),
                               np.asarray(ref.output(toks)), atol=1e-4)
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "pp.zip")
        ModelSerializer.write_model(net, p)
        restored = ModelSerializer.restore(p)
        # the checkpoint is canonical: restores WITHOUT any mesh
        np.testing.assert_allclose(np.asarray(restored.output(toks)),
                                   np.asarray(net.output(toks)), atol=1e-5)


def test_pp_set_mesh_none_restores_canonical(lm_data):
    net = _fresh_lm()
    before = jax.tree.map(np.asarray, net.params)
    net.set_mesh(make_mesh({"pipe": 4}), axes={"pipe": "pipe"})
    assert "stages" in net.params
    net.set_mesh(None)
    assert set(net.params) == set(before)
    for k in before:
        for a, b in zip(jax.tree.leaves(before[k]),
                        jax.tree.leaves(net.params[k])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # and the net still trains
    net.fit(lm_data, epochs=1)


def test_pp_fit_scanned(dense, lm_data):
    """The fused whole-epoch scan path drives the PP step too."""
    net = _fresh_lm()
    net.set_mesh(make_mesh({"pipe": 4}), axes={"pipe": "pipe"},
                 n_microbatches=4)
    net.fit_scanned(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL


def test_ep_train_via_set_mesh_matches_dense(lm_data):
    """EP is a differentiable TRAIN path now (r2: forward-only): expert
    tensors shard over the 'expert' axis, GSPMD inserts the combine psum,
    and the training trajectory matches the dense single-device run."""
    def moe():
        net = transformer_moe_lm(vocab_size=V, d_model=D, n_heads=H,
                                 n_layers=2, n_experts=8, top_k=2,
                                 d_expert_hidden=32, max_length=T)
        net.init()
        return net

    ref = moe()
    ref.fit(lm_data, epochs=3)
    net = moe()
    net.set_mesh(make_mesh({"data": 2, "expert": 4}),
                 axes={"data": "data", "expert": "expert"})
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - ref.score_value) < ATOL
    assert tuple(net.params["blk0_moe"]["We1"].sharding.spec)[0] == "expert"


@pytest.mark.slow
def test_sp_via_set_mesh_matches_dense(lm_data):
    """The fifth axis joins the entry point: axes={'seq': ...} routes fit()
    through the ring-attention sequence-parallel step (time sharded over
    the mesh, grads pmean'd). Int next-token labels keep the SP step's
    per-shard loss exact."""
    toks = np.asarray(lm_data.features)
    labs_int = np.roll(toks, -1, axis=1).astype(np.int32)
    from deeplearning4j_tpu.datasets.api import DataSet as DS

    data_int = DS(toks, labs_int)
    dense_net = transformer_lm(vocab_size=V, d_model=D, n_heads=H,
                               n_layers=L, d_ff=FF, max_length=T)
    dense_net.init()
    dense_net.fit(data_int, epochs=3)
    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T, seq_parallel_axis="seq")
    net.init()
    net.set_mesh(make_mesh({"data": 2, "seq": 4}),
                 axes={"data": "data", "seq": "seq"})
    net.fit(data_int, epochs=3)
    assert abs(net.score_value - dense_net.score_value) < ATOL


def test_seq_pipe_via_set_mesh_matches_dense(lm_data):
    """seq x pipe (VERDICT r4 #9): the PP schedule runs manual over the
    seq axis too — time-sharded ring attention inside the pipeline stage
    bodies — so long-context pipelined models have a path. Composed with
    data for the full pipe x seq x data step."""
    toks = np.asarray(lm_data.features)
    labs_int = np.roll(toks, -1, axis=1).astype(np.int32)
    from deeplearning4j_tpu.datasets.api import DataSet as DS

    data_int = DS(toks, labs_int)
    dense_net = transformer_lm(vocab_size=V, d_model=D, n_heads=H,
                               n_layers=L, d_ff=FF, max_length=T)
    dense_net.init()
    dense_net.fit(data_int, epochs=3)
    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T, seq_parallel_axis="seq")
    net.init()
    net.set_mesh(make_mesh({"pipe": 2, "seq": 2, "data": 2}),
                 axes={"pipe": "pipe", "seq": "seq", "data": "data"},
                 n_microbatches=2)
    net.fit(data_int, epochs=3)
    assert abs(net.score_value - dense_net.score_value) < ATOL
    # params trained identically through the composed schedule
    cp = net._canonical_params()
    for k in dense_net.params:
        for a, b in zip(jax.tree.leaves(dense_net.params[k]),
                        jax.tree.leaves(cp[k])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4)


def test_seq_pipe_masked_loss_matches_dense(lm_data):
    """seq x pipe with a LABELS mask: each seq shard holds a different
    number of valid positions, so the exact global combine is the
    valid-count-weighted psum over {pipe, data, seq} — must equal the
    dense masked loss."""
    rng = np.random.default_rng(3)
    toks = np.asarray(lm_data.features)
    labs_int = np.roll(toks, -1, axis=1).astype(np.int32)
    lmask = (rng.random(toks.shape) < 0.7).astype(np.float32)
    lmask[:, 0] = 1.0
    from deeplearning4j_tpu.datasets.api import DataSet as DS

    ds = DS(toks, labs_int, labels_mask=lmask)
    dense_net = transformer_lm(vocab_size=V, d_model=D, n_heads=H,
                               n_layers=L, d_ff=FF, max_length=T)
    dense_net.init()
    dense_net.fit(ds, epochs=2)
    net = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                         d_ff=FF, max_length=T, seq_parallel_axis="seq")
    net.init()
    net.set_mesh(make_mesh({"pipe": 2, "seq": 2, "data": 2}),
                 axes={"pipe": "pipe", "seq": "seq", "data": "data"},
                 n_microbatches=2)
    net.fit(ds, epochs=2)
    assert abs(net.score_value - dense_net.score_value) < ATOL


def test_seq_axis_requires_sp_conf():
    net = _fresh_lm()  # built WITHOUT seq_parallel_axis
    with pytest.raises(ValueError, match="seq_parallel_axis"):
        net.set_mesh(make_mesh({"seq": 8}), axes={"seq": "seq"})


@pytest.mark.slow
def test_zero1_with_renamed_data_axis(dense, lm_data):
    """zero1 must follow the MAPPED data axis name, not the literal
    'data' (regression: zero1_opt_shardings hardcoded the default)."""
    net = _fresh_lm()
    net.set_mesh(make_mesh({"dp": 8}), zero1=True, axes={"data": "dp"})
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL


def test_axes_validation_errors():
    net = _fresh_lm()
    mesh = make_mesh({"data": 8})
    with pytest.raises(ValueError, match="unknown mesh roles"):
        net.set_mesh(mesh, axes={"sequence": "data"})
    with pytest.raises(ValueError, match="not a mesh axis"):
        net.set_mesh(mesh, axes={"model": "mdl"})
    with pytest.raises(ValueError, match="zero1"):
        net.set_mesh(make_mesh({"data": 4, "model": 2}), zero1=True,
                     axes={"data": "data", "model": "model"})


def test_pp_requires_graph_container():
    from deeplearning4j_tpu.models.lenet import lenet5

    net = lenet5()
    net.init()
    with pytest.raises(ValueError, match="ComputationGraph"):
        net.set_mesh(make_mesh({"pipe": 8}), axes={"pipe": "pipe"})


def test_pp_rejects_stage_mismatch():
    net = _fresh_lm()  # 4 blocks
    with pytest.raises(ValueError, match="do not divide"):
        net.set_mesh(make_mesh({"pipe": 8}), axes={"pipe": "pipe"})


def test_pp_masked_matches_dense(lm_data):
    """VERDICT r3 #5a: [B, T] masks ride the microbatch stream — a
    masked-LM trains under pp with the same loss as the dense masked
    path (features mask to every stage's attention, labels mask to the
    head loss)."""
    rng = np.random.default_rng(3)
    toks = np.asarray(lm_data.features)
    labs = np.asarray(lm_data.labels)
    mask = (rng.random((B, T)) < 0.8).astype(np.float32)
    mask[:, 0] = 1.0
    ds = DataSet(toks, labs, features_mask=mask, labels_mask=mask)

    dense_net = transformer_lm(vocab_size=V, d_model=D, n_heads=H,
                               n_layers=L, d_ff=FF, max_length=T)
    dense_net.init()
    dense_net.fit(ds, epochs=2)

    pp = transformer_lm(vocab_size=V, d_model=D, n_heads=H, n_layers=L,
                        d_ff=FF, max_length=T)
    pp.init()
    pp.set_mesh(make_mesh({"pipe": 4}), axes={"pipe": "pipe"},
                n_microbatches=4)
    pp.fit(ds, epochs=2)
    assert abs(float(pp.score_value) - float(dense_net.score_value)) < 2e-3


def test_pp_batchnorm_stack_trains():
    """VERDICT r3 #5b: BatchNorm-bearing stacks pipeline — per-stage
    running stats thread the tick scan (per-microbatch statistics, like
    per-worker stats under the reference's Spark DP), and the updated
    state survives the round-trip back to canonical layout."""
    from deeplearning4j_tpu.nn.conf import (
        BatchNormalization,
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )

    D_in, Dh, C = 16, 16, 3  # uniform width: all 4 fc+bn blocks stack
    g = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.01)
         .updater("sgd").graph_builder())
    g.add_inputs("in")
    src = "in"
    for b in range(4):
        g.add_layer(f"blk{b}_fc", DenseLayer(
            n_in=Dh, n_out=Dh, activation="relu"), src)
        g.add_layer(f"blk{b}_bn", BatchNormalization(n_in=Dh, n_out=Dh), f"blk{b}_fc")
        src = f"blk{b}_bn"
    g.add_layer("out", OutputLayer(n_in=Dh, n_out=C, activation="softmax",
                                   loss_function="mcxent"), src)
    g.set_outputs("out")
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    net = ComputationGraph(g.build()).init()
    rng = np.random.default_rng(0)
    x = rng.standard_normal((16, D_in)).astype(np.float32) * 2 + 1
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, 16)]
    ds = DataSet(x, y)
    net.set_mesh(make_mesh({"pipe": 4}), axes={"pipe": "pipe"},
                 n_microbatches=4)
    before = {k: np.asarray(v) for k, v in net.state["blk0_bn"].items()}
    for _ in range(3):
        net.fit(ds)
    assert np.isfinite(float(net.score_value))
    after = net.state["blk0_bn"]
    # running stats moved off their init values (mean 0, var 1)
    assert not np.allclose(np.asarray(after["mean"]), before["mean"])
    # canonical round-trip: clearing the mesh keeps the updated stats
    net.set_mesh(None)
    assert "blk0_bn" in net.state and not np.allclose(
        np.asarray(net.state["blk0_bn"]["mean"]), before["mean"])
    # and the restored net still evaluates (eval path uses the stats)
    out = net.output(x)
    assert np.isfinite(np.asarray(out[0])).all()


def test_pp_ep_moe_matches_dense(lm_data):
    """VERDICT r3 #5c: pp x expert — MoE blocks as the repeated pipeline
    unit with expert tensors sharded over an 'expert' axis inside the
    stage shard_map (stacked-leaf EP rules), matching the dense MoE."""
    def _moe():
        # ample capacity: zero drops, so routing is independent of the
        # data/microbatch grouping and the PP step matches dense exactly
        net = transformer_moe_lm(vocab_size=V, d_model=D, n_heads=H,
                                 n_layers=4, n_experts=4, top_k=2,
                                 d_expert_hidden=24, max_length=T,
                                 capacity_factor=2.0)
        net.init()
        return net

    dense_net = _moe()
    dense_net.fit(lm_data, epochs=2)
    pp = _moe()
    pp.set_mesh(make_mesh({"pipe": 2, "expert": 2, "data": 2}),
                axes={"pipe": "pipe", "expert": "expert", "data": "data"},
                n_microbatches=2)
    pp.fit(lm_data, epochs=2)
    assert abs(float(pp.score_value) - float(dense_net.score_value)) < 2e-3


def test_dp_only_axes_still_works(dense, lm_data):
    """axes={'data': ...} is the same math as legacy set_mesh(mesh)."""
    net = _fresh_lm()
    net.set_mesh(make_mesh({"data": 8}), axes={"data": "data"})
    net.fit(lm_data, epochs=3)
    assert abs(net.score_value - dense.score_value) < ATOL


def test_mid_training_set_mesh_preserves_flat_moments(lm_data):
    """The flat fused optimizer's accumulated moments unflatten into the
    tree layout when a param-placement mesh arrives mid-training — no
    silent Adam warm-restart."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn.updater import (
        FlatViewTransform,
        build_optimizer,
        named_layer_confs,
    )

    net = _fresh_lm()
    # the tiny test LM is below _FLAT_MIN_PARAMS — force the flat layout
    # so the migration path under test is actually exercised
    net.set_optimizer(build_optimizer(net.conf.conf, named_layer_confs(net),
                                      flat=True))
    net.fit(lm_data, epochs=2)
    assert isinstance(net.tx, FlatViewTransform)
    # the flat mu vector, for comparison after the re-shard
    flat_mu = None
    for leaf in jax.tree.leaves(net.opt_state):
        if getattr(leaf, "ndim", 0) == 1 and leaf.size > 1000:
            flat_mu = np.asarray(leaf)
            break
    assert flat_mu is not None and np.abs(flat_mu).max() > 0
    net.set_mesh(make_mesh({"model": 2}), axes={"model": "model"})
    assert not isinstance(net.tx, FlatViewTransform)
    tree_leaves = [np.ravel(np.asarray(l)) for l in
                   jax.tree.leaves(net.opt_state)
                   if getattr(l, "ndim", 0) >= 1]
    total = np.abs(np.concatenate(tree_leaves)).max()
    assert total > 0, "moments were zeroed by the re-shard"
    # and training continues
    net.fit(lm_data, epochs=1)
    assert np.isfinite(float(net.score_value))


def test_pp_conv_stack_fails_with_documented_reason():
    """VERDICT r3 #5b: a VGG-style conv stack (channel widths growing
    between blocks) cannot stack into identical pipeline stages — it must
    fail with an error explaining WHY and what to use instead, not a
    bare divide error."""
    from deeplearning4j_tpu.models.vgg import vgg16

    net = vgg16(num_classes=10)
    net.init()
    with pytest.raises(ValueError, match="IDENTICAL.*data axis"):
        net.set_mesh(make_mesh({"pipe": 2}), axes={"pipe": "pipe"})


@pytest.mark.slow
def test_four_axis_composition_in_subprocess():
    """ALL FOUR param/compute axes at once — data x model x pipe x expert
    on a 2x2x2x2 16-device mesh, routed-MoE transformer, one jitted train
    step matching dense. Runs in a subprocess: the suite process is
    pinned to 8 virtual devices."""
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = root
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, "tests/four_axis_worker.py"], env=env, cwd=root,
        capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "FOUR_AXIS_OK" in out.stdout


def test_pp_runs_flash_kernels_inside_stage_shard_map(monkeypatch):
    """At kernel-eligible sequence lengths (T >= 512) the stage compute
    inside the manual-pipe shard_map runs the Pallas flash kernels — the
    other PP tests use tiny T where attention routes dense, so this is
    the only coverage of pallas_call under the GPipe schedule (the
    realistic PP transformer shape). A counting wrapper asserts the
    kernel path actually executed (the dense fallback is mathematically
    equivalent, so loss parity alone cannot tell)."""
    import deeplearning4j_tpu.nn.layers.attention as attn

    calls = {"n": 0}
    orig = attn.flash_attention

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(attn, "flash_attention", counting)

    V2, T2, B2 = 64, 512, 4
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, V2, (B2, T2)), np.int32)
    ds = DataSet(toks, np.roll(toks, -1, 1))

    def build():
        n = transformer_lm(vocab_size=V2, d_model=32, n_heads=2,
                           n_layers=2, d_ff=64, max_length=T2)
        n.init()
        return n

    dense_net = build()
    dense_net.fit(ds)
    pp = build()
    pp.set_mesh(make_mesh({"pipe": 2}), axes={"pipe": "pipe"},
                n_microbatches=2)
    calls["n"] = 0
    pp.fit(ds)
    assert calls["n"] > 0, "flash path not taken inside the PP stages"
    assert abs(float(pp.score_value) - float(dense_net.score_value)) < 2e-3
