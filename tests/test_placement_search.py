"""Automatic placement search (ISSUE 14): the cost model picks the
mesh, not just moves to it.

Three proof layers:

1. **Pure cost model** — exact-rational memory/collective/bubble
   accounting checked against HAND-COMPUTED values on a 2-layer toy
   profile at three fleet shapes; feasibility prunes (zero1 x TP,
   non-dividing axes, spanning data-only, HBM budget) refuse before any
   plan exists; the ranking is rank-independent (simulated
   process_index 0 vs 1) and the whole search stage imports under a
   poisoned `jax`.
2. **Integration** — `search_placement(...).winner` is a `Placement`
   that `set_mesh` consumes UNMODIFIED: the dp winner trains to
   reference parity, a TP placement actually shards params.
3. **Surfaces** — the CLI `plan` dry-run prints the ranked table,
   writes a benchdiff-consumable PLAN artifact, emits the
   `placement_search` telemetry event, and refuses infeasible requests
   as usage errors; the COMMITTED PLAN_r01.json parses, carries zero
   predicted-rank violations, and a doctored violation trips benchdiff
   exit 1 (the always-regress contract).

The predicted-vs-measured gate itself runs in bench.py
`placement_search` (it spawns an arm subprocess per candidate); the
elastic re-plan is asserted by tests/test_elastic.py's timeline test.
"""

import json
import os
import subprocess
import sys
from fractions import Fraction

import numpy as np
import pytest

from deeplearning4j_tpu.reshard.planner import Placement, PlacementError
from deeplearning4j_tpu.reshard.search import (
    BUILTIN_PROFILES,
    FleetShape,
    ModelProfile,
    Objective,
    ParamLeaf,
    SearchError,
    enumerate_placements,
    score_placement,
    search_placement,
)

pytestmark = pytest.mark.reshard

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PLAN_ARTIFACT = os.path.join(ROOT, "PLAN_r01.json")

# ------------------------------------------------------ the 2-layer toy
# param_bytes = (8*16 + 16 + 16*8 + 6) * 4 = 278 * 4 = 1112
# activation width = 16 + 8 = 24 (last dims of the ndim>=2 leaves)
TOY = ModelProfile(
    name="toy",
    leaves=(ParamLeaf("l0/W", (8, 16)), ParamLeaf("l0/b", (16,)),
            ParamLeaf("l1/W", (16, 8)), ParamLeaf("norm/g", (6,))),
    n_layers=2, seq_len=1, supports=("data", "model", "pipe"),
    rules=((r".*l\d/W$", (None, "model")), (r".*l\d/b$", ("model",))))

OBJ = Objective(global_batch=8)  # compute_weight 1/16, hbm default


def _candidate(result, desc):
    for c in result.candidates:
        if c.describe() == desc:
            return c
    raise AssertionError(
        f"{desc} not in {[c.describe() for c in result.candidates]}")


# ---------------------------------------------------------- enumeration

def test_enumeration_uses_all_devices_and_prunes_for_free():
    """Feasibility IS the planner's PlacementError validation: every
    candidate covers the fleet's full device grid, zero1 variants exist
    only for pure-dp assignments, and a process-spanning fleet prunes
    non-data roles with the set_mesh guard's reason."""
    candidates, pruned = enumerate_placements(FleetShape(1, 8))
    assert candidates
    for p in candidates:
        assert p.n_devices == 8
        if p.zero1:
            assert {r for r, _ in p.roles} == {"data"}
    spanning, span_pruned = enumerate_placements(FleetShape(2, 4))
    assert all({r for r, _ in p.roles} == {"data"} for p in spanning)
    reasons = [reason for _, reason in span_pruned]
    assert any("'data' role only" in r for r in reasons)


def test_non_dividing_axes_prune_with_the_planner_error():
    """The builtin lm profile's d_model=80 cannot split 3 or 6 ways:
    the 3x2 grid's tp3/tp6 assignments die as PlacementError prunes
    (the target-dim-not-divisible class), never as scored candidates."""
    res = search_placement(BUILTIN_PROFILES["lm"], FleetShape(1, 6),
                           objective=Objective(global_batch=48))
    assert {c.describe() for c in res.candidates} == {
        "6 (data=data) p1", "6 (data=data) p1+zero1",
        "3x2 (data=data,model=model) p1"}
    assert any("does not divide" in reason for _, reason in res.pruned)


# ------------------------------------------- hand-computed cost model

def test_cost_model_dp_tp_hand_computed():
    """Exact-rational accounting vs hand-computed values (fleet 1x4,
    B=8): dp2 x tp2 shards every matched leaf 2-way, pays the grad ring
    on dp and two activation allreduces per layer on tp."""
    res = search_placement(TOY, FleetShape(1, 4), objective=OBJ)
    c = _candidate(res, "2x2 (data=data,model=model) p1")
    # params/device: (512/2 + 64/2 + 512/2)/1 + 24 unmatched = 568
    assert c.params_bytes == Fraction(568)
    assert c.moments_bytes == Fraction(1136)       # 2x params (no zero1)
    # activations: rows 8/2=4, width 24, f32
    assert c.activation_bytes == Fraction(4 * 24 * 4)
    assert c.memory_bytes == Fraction(568 + 568 + 1136 + 384)
    # collectives: dp ring 2*568*(1/2) + tp 2 passes * 2 layers * 384
    # * (1/2) = 568 + 768
    assert c.collective_bytes == Fraction(568 + 768)
    # both axes divide real work -> no idle, no pp -> no bubble
    assert c.idle_cost == 0 and c.bubble_cost == 0
    assert c.score == Fraction(1336)

    dp4 = _candidate(res, "4 (data=data) p1")
    assert dp4.params_bytes == Fraction(1112)
    assert dp4.collective_bytes == Fraction(2 * 1112 * 3, 4)  # 1668
    assert dp4.score == Fraction(1668)
    # the toy's verdict: the sharded layouts beat pure dp4 — the
    # search finds non-obvious winners (dp2 x pp2 cheapest: tiny p2p +
    # a 1/5 bubble at 4 microbatches)
    assert c.score < dp4.score
    assert res.best.describe() == "2x2 (data=data,pipe=pipe) p1"

    z = _candidate(res, "4 (data=data) p1+zero1")
    # zero1: moments shard 4-way, but the param all-gather costs
    # another 1112*(3/4) on the wire
    assert z.moments_bytes == Fraction(2 * 1112, 4)
    assert z.collective_bytes == Fraction(1668) + Fraction(3 * 1112, 4)


def test_cost_model_pp_bubble_hand_computed():
    """dp2 x pp2 (fleet 1x4): stage-split params, 4 microbatches, the
    GPipe bubble term = (pp-1)/(n_micro+pp-1) x per-device compute x
    compute_weight, stage-boundary p2p on the wire."""
    res = search_placement(TOY, FleetShape(1, 4), objective=OBJ)
    c = _candidate(res, "2x2 (data=data,pipe=pipe) p1")
    assert c.params_bytes == Fraction(1112, 2)          # stage split
    # rows 4 over n_micro 4 -> 1 row/micro; act = 1*24*4 = 96
    assert c.activation_bytes == Fraction(96)
    # dp ring 2*556*(1/2)=556; pp p2p = 1 pass * 96*4*(1/2) = 192
    assert c.collective_bytes == Fraction(556 + 192)
    # compute C = 2*8*1112 = 17792; denom dp*pp = 4 -> 4448/device;
    # bubble = (1/5) * 4448 * (1/16)
    assert c.bubble_cost == Fraction(4448, 5 * 16)
    # all 4 devices carry real work -> no idle
    assert c.idle_cost == 0
    assert c.score == Fraction(748) + Fraction(4448, 80)


def test_cost_model_idle_penalty_and_forward_surface():
    """A model axis whose rules shard nothing leaves its devices
    redundant (idle penalty); the forward objective drops gradient and
    optimizer terms and halves the activation collectives."""
    no_tp = ModelProfile(name="plain", leaves=TOY.leaves, n_layers=2,
                         supports=("data", "model"), rules=())
    # compute_weight 1 here: the toy is miniature (its wire bytes swamp
    # its compute proxy), so the structural claim — a redundant axis is
    # penalized by the compute it wastes — is asserted at unit weight
    res = search_placement(no_tp, FleetShape(1, 4),
                           objective=Objective(global_batch=8,
                                               compute_weight=Fraction(1)))
    c = _candidate(res, "2x2 (data=data,model=model) p1")
    # tp shards nothing: compute divides over dp only -> half the
    # devices are redundant. C=17792, compute_dev=C/2, idle=C/2-C/4
    assert c.idle_cost == Fraction(17792, 4)
    assert res.best.describe() == "4 (data=data) p1"  # dp wins here

    fwd = search_placement(
        TOY, FleetShape(1, 4),
        objective=Objective(global_batch=8, step="forward",
                            zero1_options=(False,)))
    c = _candidate(fwd, "2x2 (data=data,model=model) p1")
    assert c.moments_bytes == 0
    assert c.memory_bytes == Fraction(568 + 384)   # params + activations
    # one activation pass: 1 * 2 layers * 384 * (1/2); no grad ring
    assert c.collective_bytes == Fraction(384)


def test_hbm_budget_rejects_and_no_feasible_is_a_search_error():
    tight = Objective(global_batch=8, hbm_bytes_per_device=2000)
    res = search_placement(TOY, FleetShape(1, 4), objective=tight)
    # dp4 (4448 B/device) dies; the sharded candidates survive
    assert "4 (data=data) p1" not in {c.describe()
                                      for c in res.candidates}
    assert any("HBM budget" in reason for _, reason in res.pruned)
    with pytest.raises(SearchError, match="no feasible placement"):
        search_placement(TOY, FleetShape(1, 4),
                         objective=Objective(global_batch=8,
                                             hbm_bytes_per_device=10))


def test_batch_and_layers_divisibility_prunes():
    assert score_placement(TOY, Placement.of({"data": 4},
                                             {"data": "data"}),
                           OBJ, FleetShape(1, 4))
    with pytest.raises(PlacementError, match="does not divide over"):
        score_placement(TOY, Placement.of({"data": 3}, {"data": "data"}),
                        OBJ, FleetShape(1, 3))
    with pytest.raises(PlacementError, match="pipeline stages"):
        score_placement(
            TOY, Placement.of({"pipe": 4}, {"pipe": "pipe"}),
            Objective(global_batch=8, microbatch_factor=1),
            FleetShape(1, 4))


# ------------------------------------------------ determinism + purity

def test_search_is_rank_independent():
    """Same discipline as plan_reshard: the ranking is a pure function
    of (profile, fleet, objective) — byte-identical under simulated
    process_index 0 vs 1, which is what lets every fleet member derive
    the elastic re-plan winner without coordination."""
    from deeplearning4j_tpu.analysis.collective_audit import \
        simulated_process_index

    results = []
    for pid in (0, 1):
        with simulated_process_index(pid):
            results.append(search_placement(TOY, FleetShape(2, 2),
                                            objective=OBJ))
    assert results[0].to_json() == results[1].to_json()
    assert results[0].winner == results[1].winner


def test_search_stage_is_pure_stdlib():
    """The whole search stage — module import, enumeration, scoring,
    ranking — under a poisoned `jax`: the CLI plans a pod placement
    without a backend, and the lint stubs import it for free."""
    code = (
        "import os, sys, types\n"
        "poison = types.ModuleType('jax')\n"
        "def _boom(*a, **k): raise AssertionError('jax imported')\n"
        "poison.__getattr__ = lambda n: _boom()\n"
        "sys.modules['jax'] = poison\n"
        "for name in ('deeplearning4j_tpu', 'deeplearning4j_tpu.reshard'):\n"
        "    mod = types.ModuleType(name)\n"
        "    mod.__path__ = [os.path.join(os.getcwd(),\n"
        "                                 *name.split('.'))]\n"
        "    sys.modules[name] = mod\n"
        "from deeplearning4j_tpu.reshard.search import (\n"
        "    BUILTIN_PROFILES, FleetShape, Objective, search_placement)\n"
        "res = search_placement(BUILTIN_PROFILES['lm'],\n"
        "                       FleetShape.parse('2x4'),\n"
        "                       objective=Objective(global_batch=48))\n"
        "print(res.winner.describe())\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, cwd=ROOT,
                         timeout=60)
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "8 (data=data) p2"


# ------------------------------------------------- set_mesh integration

def test_winner_feeds_set_mesh_and_trains_to_parity():
    """The acceptance contract: `search_placement(...).winner` goes to
    `set_mesh` UNMODIFIED and the placed net optimizes to the same
    params as the unplaced reference (float32 reduction-order
    tolerance, the test_distributed bound)."""
    from deeplearning4j_tpu.datasets.api import DataSet
    from tests.cluster_worker import C, F, build_net

    rng = np.random.default_rng(0)
    x = rng.random((24, F), dtype=np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, 24)]

    ref = build_net().init()
    for _ in range(2):
        ref.fit(DataSet(x, y))

    net = build_net().init()
    result = search_placement(net, FleetShape(1, 8),
                              objective=Objective(global_batch=24))
    net.set_mesh(result.winner)
    for _ in range(2):
        net.fit(DataSet(x, y))
    np.testing.assert_allclose(np.asarray(net.params_flat()),
                               np.asarray(ref.params_flat()), atol=1e-5)


def test_set_mesh_consumes_tp_placement():
    """A declarative TP Placement actually places: the mesh is built
    from the placement's axes and rule-matched leaves arrive sharded."""
    import jax

    from deeplearning4j_tpu.models.transformer import transformer_lm

    net = transformer_lm(vocab_size=32, d_model=16, n_heads=2,
                         n_layers=1, d_ff=32, max_length=8)
    net.init()
    net.set_mesh(Placement.of({"data": 2, "model": 2},
                              {"data": "data", "model": "model"}))
    assert set(net._mesh.axis_names) == {"data", "model"}
    sharded = [l for l in jax.tree.leaves(net.params)
               if not l.sharding.is_fully_replicated]
    assert sharded, "no leaf took the TP sharding from the Placement"


# -------------------------------------------------------- CLI + events

def test_cli_plan_dry_run_table_and_artifact(tmp_path, capsys):
    from deeplearning4j_tpu.cli import driver
    from deeplearning4j_tpu.telemetry import artifact

    art = str(tmp_path / "PLAN_test.json")
    rc = driver.main(["plan", "--model", "lm", "--fleet", "2x4",
                      "--global-batch", "48", "--artifact", art])
    out = capsys.readouterr().out
    assert rc == 0
    assert "placement search: lm on fleet 2x4" in out
    assert "coll B/step" in out and "bubble" in out  # score breakdown
    rows = artifact.load(art)
    assert rows["plan_candidates"]["value"] == 2
    assert rows["plan_winner_score"]["winner"] == "8 (data=data) p2"
    assert rows["plan_winner_score"].get("lower_is_better")
    assert rows["plan_search_ms"]["value"] >= 0
    assert any(m.startswith("plan_score::") for m in rows)


def test_cli_plan_usage_and_no_feasible_errors(tmp_path):
    from deeplearning4j_tpu.cli import driver

    with pytest.raises(SystemExit, match="exactly one of"):
        driver.main(["plan", "--fleet", "2x4"])
    with pytest.raises(SystemExit, match="no feasible placement"):
        driver.main(["plan", "--model", "mlp", "--fleet", "2x4",
                     "--hbm-gb", "0.0000001"])
    with pytest.raises(SystemExit, match="expected PxK"):
        driver.main(["plan", "--model", "mlp", "--fleet", "2x4x2"])


def test_cli_plan_emits_placement_search_event():
    from deeplearning4j_tpu.cli import driver
    from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default

    rec = Recorder()
    prev = set_default(rec)
    try:
        driver.main(["plan", "--model", "mlp", "--fleet", "1x8"])
    finally:
        set_default(prev)
    events = [e for e in rec.events if e["event"] == "placement_search"]
    assert len(events) == 1
    ev = events[0]
    assert ev["path"] == "cli" and ev["fleet"] == "1x8"
    assert ev["candidates_considered"] == \
        ev["candidates_feasible"] + ev["pruned"]
    # the mlp profile's verdict on one process x 8 devices: dp4 x tp2
    # halves the grad ring for less than its activation psums cost
    assert ev["winner"] == "4x2 (data=data,model=model) p1"
    assert "winner_collective_bytes" in ev and "search_ms" in ev


def test_supervisor_replan_is_deterministic_and_journals():
    """The supervisor half of the elastic re-plan (no fleet spawn): the
    generic-profile search for a fleet shape is deterministic and emits
    the placement_search event with path=reform."""
    from deeplearning4j_tpu.distributed.elastic import ElasticSupervisor
    from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default

    sup = ElasticSupervisor(["true"], n_processes=3, min_processes=2,
                            checkpoint_dir="/tmp", total_steps=1,
                            local_device_count=2)
    rec = Recorder()
    prev = set_default(rec)
    try:
        result = sup._replan(2, gen=1)
    finally:
        set_default(prev)
        sup.close()
    assert result.winner.describe() == "4 (data=data) p2"
    events = [e for e in rec.events if e["event"] == "placement_search"]
    assert len(events) == 1 and events[0]["path"] == "reform"
    assert events[0]["gen"] == 1


# ---------------------------------------------- the committed artifact

def test_committed_plan_artifact_parses_and_gates(tmp_path):
    """PLAN_r01.json (bench.py placement_search on this container): it
    parses, the predicted-vs-measured gate passed (zero rank
    violations, every grid's Kendall tau positive), the winner rows
    name pure-dp placements, and benchdiff (a) self-diffs clean and
    (b) trips exit-style regression on a doctored rank violation."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import benchdiff
    finally:
        sys.path.pop(0)
    from deeplearning4j_tpu.telemetry import artifact

    rows = artifact.load(PLAN_ARTIFACT)
    assert rows["plan_predicted_rank_violations"]["value"] == 0
    for grid in ("2x2", "3x2", "2x4"):
        assert rows[f"plan_winner::{grid}"]["winner"].endswith(
            "(data=data) p1")
        assert rows[f"plan_rank_kendall_tau::{grid}"]["value"] > 0
        assert rows[f"plan_winner::{grid}"]["candidates"] >= 2
    # the 3x2 grid proves the non-dividing prune reached the artifact
    assert rows["plan_winner::3x2"]["pruned"] >= 1

    self_diff = benchdiff.diff(rows, rows)
    assert not self_diff["regressions"]

    doctored = dict(rows)
    bad = dict(rows["plan_predicted_rank_violations"])
    bad["value"] = 1
    doctored["plan_predicted_rank_violations"] = bad
    win = dict(rows["plan_winner::2x4"])
    win["winner"] = "4x2 (data=data,model=model) p1"
    doctored["plan_winner::2x4"] = win
    result = benchdiff.diff(rows, doctored)
    assert any(r["metric"] == "plan_predicted_rank_violations"
               for r in result["regressions"])
    assert any(r["field"] == "winner" for r in result["changes"])
    # and the violation regresses even from a NONZERO base (stricter
    # than the retrace rise-from-zero rule)
    worse = dict(doctored)
    worse2 = dict(bad)
    worse2["value"] = 2
    worse["plan_predicted_rank_violations"] = worse2
    again = benchdiff.diff(doctored, worse)
    assert any(r["metric"] == "plan_predicted_rank_violations"
               for r in again["regressions"])
