"""MoE + expert parallelism (nn/layers/moe.py, parallel/expert_parallel.py):
parity with dense/sequential references on the virtual mesh,
differentiability, and training integration. (The r2 hand-stacked GPipe
demo once tested here was folded into parallel/pipeline.py's PipelinePlan
— the production PP path, covered by test_unified_mesh.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf import (
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.layers.moe import (
    MixtureOfExpertsImpl,
    MixtureOfExpertsLayer,
    moe_gates,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.expert_parallel import (
    expert_parallel_apply,
    shard_expert_params,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh


# -------------------------------------------------------------------- MoE

def test_moe_gates_top_k_structure():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    Wg = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)
    gates = np.asarray(moe_gates(x, Wg, 2))
    assert ((gates > 0).sum(-1) == 2).all()  # exactly top-2 active
    np.testing.assert_allclose(gates.sum(-1), 1.0, atol=1e-6)  # renormalized


def test_moe_layer_trains_in_network():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(0)
        .learning_rate(0.05)
        .updater("adam")
        .list()
        .layer(MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=4, top_k=2,
                                     d_hidden=16, activation="gelu"))
        .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.random((32, 8), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    net.fit(x, y)
    first = net.score_value
    for _ in range(15):
        net.fit(x, y)
    assert net.score_value < first


def test_moe_conf_json_round_trip():
    from deeplearning4j_tpu.nn.conf import serde

    lc = MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=6, top_k=1,
                               d_hidden=12)
    back = serde.from_json(serde.to_json(lc))
    assert back.n_experts == 6 and back.top_k == 1 and back.d_hidden == 12


def test_routed_matches_dense_at_ample_capacity():
    """The routed dispatch path is exact vs the dense oracle when no token
    drops (capacity_factor >= E/top_k): same per-token FFN + gate math."""
    from deeplearning4j_tpu.nn.layers.moe import (
        moe_apply_dense,
        moe_apply_routed,
    )

    lc = MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=4, top_k=2,
                               d_hidden=16, activation="gelu",
                               weight_init="xavier")
    params, _ = MixtureOfExpertsImpl().init(lc, jax.random.PRNGKey(1),
                                            jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((50, 8)),
                    jnp.float32)
    dense = moe_apply_dense(params, x, top_k=2, activation="gelu")
    # group_size 16 also exercises the pad-to-group path (50 = 3*16 + 2)
    routed = moe_apply_routed(params, x, top_k=2, capacity_factor=2.0,
                              activation="gelu", group_size=16)
    np.testing.assert_allclose(np.asarray(routed), np.asarray(dense),
                               atol=1e-5)
    # analytic gradients agree too (routing is piecewise-constant; away
    # from drops the two paths are the same differentiable function)
    gd = jax.grad(lambda p: jnp.sum(
        moe_apply_dense(p, x, top_k=2, activation="gelu") ** 2))(params)
    gr = jax.grad(lambda p: jnp.sum(
        moe_apply_routed(p, x, top_k=2, capacity_factor=2.0,
                         activation="gelu", group_size=16) ** 2))(params)
    for k in gd:
        np.testing.assert_allclose(np.asarray(gr[k]), np.asarray(gd[k]),
                                   atol=1e-4)


def test_gather_dispatch_matches_einsum_dispatch():
    """The r5 gather dispatch (index-based; no [G,S,E,C] one-hot
    contractions) is the same function as the GShard einsum formulation —
    values AND gradients, including dropped tokens at tight capacity."""
    from deeplearning4j_tpu.nn.layers.moe import (
        MixtureOfExpertsImpl,
        MixtureOfExpertsLayer,
        moe_apply_routed,
    )

    lc = MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=4, top_k=2,
                               d_hidden=16, activation="gelu",
                               weight_init="xavier")
    params, _ = MixtureOfExpertsImpl().init(lc, jax.random.PRNGKey(1),
                                            jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((50, 8)),
                    jnp.float32)
    for cf in (2.0, 0.5):  # ample AND over-capacity (drops) regimes
        ein = moe_apply_routed(params, x, top_k=2, capacity_factor=cf,
                               activation="gelu", group_size=16,
                               dispatch="einsum")
        gat = moe_apply_routed(params, x, top_k=2, capacity_factor=cf,
                               activation="gelu", group_size=16,
                               dispatch="gather")
        np.testing.assert_allclose(np.asarray(gat), np.asarray(ein),
                                   atol=1e-5)
        ge = jax.grad(lambda p: jnp.sum(moe_apply_routed(
            p, x, top_k=2, capacity_factor=cf, activation="gelu",
            group_size=16, dispatch="einsum") ** 2))(params)
        gg = jax.grad(lambda p: jnp.sum(moe_apply_routed(
            p, x, top_k=2, capacity_factor=cf, activation="gelu",
            group_size=16, dispatch="gather") ** 2))(params)
        for k in ge:
            np.testing.assert_allclose(np.asarray(gg[k]), np.asarray(ge[k]),
                                       atol=1e-4)


def test_routed_drops_over_capacity_and_balances():
    """At a tight capacity factor, over-capacity tokens produce exactly-zero
    output rows (the residual carries them), and the Switch aux loss is >= 1
    with equality only at uniform routing."""
    from deeplearning4j_tpu.nn.layers.moe import (
        moe_apply_routed,
        moe_load_balance_loss,
    )

    lc = MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=4, top_k=2,
                               d_hidden=16, activation="gelu",
                               weight_init="xavier")
    params, _ = MixtureOfExpertsImpl().init(lc, jax.random.PRNGKey(1),
                                            jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((64, 8)),
                    jnp.float32)
    y, aux = moe_apply_routed(params, x, top_k=2, capacity_factor=0.25,
                              activation="gelu", return_aux=True)
    dropped = np.asarray(jnp.abs(y).sum(-1) == 0)
    assert dropped.any()          # tight capacity must drop something
    assert not dropped.all()
    # E * sum(f*P) ~ 1 near balance (exactly 1 when f == P == uniform; the
    # top-k assignment fraction f can differ slightly from the softmax mass P)
    assert 0.8 <= float(aux) <= 4.0
    # perfectly balanced top-2 assignments + uniform router probs -> aux == 1
    g = jnp.zeros((32, 4)).at[jnp.arange(32)[:, None],
                              jnp.stack([jnp.arange(32) % 4,
                                         (jnp.arange(32) + 1) % 4], 1)].set(0.5)
    uniform = moe_load_balance_loss(jnp.zeros((32, 4)), g, 2)
    np.testing.assert_allclose(float(uniform), 1.0, atol=1e-5)


def test_moe_aux_loss_reaches_training_loss():
    """The router load-balance loss flows through the state channel into
    the container training loss (train only; eval score excludes it)."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(0)
        .learning_rate(0.05)
        .updater("adam")
        .list()
        .layer(MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=4, top_k=2,
                                     d_hidden=16, activation="gelu",
                                     router_aux_weight=0.5))
        .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.random((32, 8), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    batch = {"features": jnp.asarray(x), "labels": jnp.asarray(y)}
    train_loss, _ = net._loss(net.params, net.state, jax.random.PRNGKey(0),
                              batch, train=True)
    eval_loss, _ = net._loss(net.params, net.state, jax.random.PRNGKey(0),
                             batch, train=False)
    # aux >= weight * 1.0 at any routing; train loss strictly above eval
    assert float(train_loss) > float(eval_loss) + 0.45


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_expert_parallel_matches_dense(n_dev):
    lc = MixtureOfExpertsLayer(n_in=8, n_out=8, n_experts=8, top_k=2,
                               d_hidden=16, activation="gelu",
                               weight_init="xavier", routing="dense")
    impl = MixtureOfExpertsImpl()
    params, _ = impl.init(lc, jax.random.PRNGKey(1), jnp.float32)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((64, 8)), jnp.float32)
    dense, _ = impl.apply(lc, params, {}, x)
    mesh = make_mesh({"expert": n_dev})
    ep = expert_parallel_apply(shard_expert_params(params, mesh), x,
                               mesh=mesh, top_k=2, activation="gelu")
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense), atol=1e-5)


def test_expert_parallel_rejects_indivisible():
    lc = MixtureOfExpertsLayer(n_in=4, n_out=4, n_experts=6, top_k=1,
                               d_hidden=8, weight_init="xavier")
    params, _ = MixtureOfExpertsImpl().init(lc, jax.random.PRNGKey(0),
                                            jnp.float32)
    mesh = make_mesh({"expert": 4})
    with pytest.raises(ValueError):
        expert_parallel_apply(shard_expert_params(params, mesh),
                              jnp.zeros((4, 4)), mesh=mesh, top_k=1)
