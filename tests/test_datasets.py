"""Data pipeline: fetchers/iterators (MNIST/CIFAR/Iris/LFW/Curves), the
image loader, and the image record reader (Canova bridge equivalent).
Reference: datasets/fetchers + datasets/iterator/impl + util/ImageLoader."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import (
    CifarDataSetIterator,
    CurvesDataFetcher,
    CurvesDataSetIterator,
    ImageRecordReader,
    ImageRecordReaderDataSetIterator,
    IrisDataSetIterator,
    LFWDataSetIterator,
    MnistDataSetIterator,
)
from deeplearning4j_tpu.util.image_loader import ImageLoader, crop_to_square


def test_mnist_iterator_shapes_and_epoch():
    it = MnistDataSetIterator(batch_size=32, num_examples=96)
    seen = 0
    it.reset()
    while it.has_next():
        ds = it.next()
        assert ds.features.shape[1] == 784
        assert ds.labels.shape[1] == 10
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
        seen += ds.num_examples()
    assert seen == 96
    # one-hot labels
    np.testing.assert_allclose(ds.labels.sum(-1), 1.0)


def test_mnist_reshaped_images():
    it = MnistDataSetIterator(batch_size=8, num_examples=8,
                              reshape_images=True)
    ds = it.next()
    assert ds.features.shape == (8, 28, 28, 1)


def test_cifar_iterator():
    it = CifarDataSetIterator(batch_size=16, num_examples=32)
    ds = it.next()
    assert ds.features.shape == (16, 32, 32, 3)
    assert ds.labels.shape == (16, 10)


def test_iris_iterator_full_pass():
    it = IrisDataSetIterator(batch_size=150)
    ds = it.next()
    assert ds.features.shape == (150, 4)
    assert ds.labels.shape == (150, 3)
    assert not it.has_next()


def test_curves_fetcher_is_autoencoder_style():
    f = CurvesDataFetcher(num_examples=12)
    ds = f.fetch(5)
    assert ds.features.shape == (5, 784)
    np.testing.assert_allclose(ds.features, ds.labels)
    it = CurvesDataSetIterator(batch_size=4, num_examples=12)
    n = 0
    it.reset()
    while it.has_next():
        n += it.next().num_examples()
    assert n == 12


def test_image_loader_round_trip(tmp_path):
    rng = np.random.default_rng(0)
    img = rng.random((20, 30, 3)).astype(np.float32)
    for name in ("a.png", "a.ppm"):
        path = str(tmp_path / name)
        ImageLoader.save(img, path)
        back = ImageLoader(channels=3).as_array(path)
        assert back.shape == (20, 30, 3)
        np.testing.assert_allclose(back, img, atol=1 / 255 + 1e-6)


def test_image_loader_resize_and_grayscale(tmp_path):
    img = np.zeros((16, 16, 3), np.float32)
    img[:8] = 1.0
    path = str(tmp_path / "half.png")
    ImageLoader.save(img, path)
    arr = ImageLoader(8, 8, channels=1).as_array(path)
    assert arr.shape == (8, 8, 1)
    assert arr[:3].mean() > 0.9 and arr[-3:].mean() < 0.1


def test_crop_to_square():
    arr = np.arange(6 * 4 * 1, dtype=np.float32).reshape(6, 4, 1)
    sq = crop_to_square(arr)
    assert sq.shape == (4, 4, 1)


def test_image_record_reader_labels_from_directories(tmp_path):
    rng = np.random.default_rng(1)
    for label in ("cat", "dog"):
        os.makedirs(tmp_path / label)
        for i in range(3):
            ImageLoader.save(rng.random((10, 10, 3)).astype(np.float32),
                             str(tmp_path / label / f"{i}.png"))
    rr = ImageRecordReader(str(tmp_path), 10, 10, 3)
    assert rr.labels == ["cat", "dog"]
    assert rr.num_examples() == 6
    recs = list(rr)
    assert recs[0][0].shape == (10, 10, 3)
    assert {lbl for _, lbl in recs} == {0, 1}

    it = ImageRecordReaderDataSetIterator(rr, batch_size=4, shuffle=True,
                                          seed=7)
    ds = it.next()
    assert ds.features.shape == (4, 10, 10, 3)
    assert ds.labels.shape == (4, 2)
    assert it.total_outcomes() == 2


def test_image_record_reader_empty_dir_raises(tmp_path):
    os.makedirs(tmp_path / "empty_label")
    with pytest.raises(IOError):
        ImageRecordReader(str(tmp_path), 8, 8)


def test_lfw_iterator_synthetic_corpus(tmp_path):
    it = LFWDataSetIterator(batch_size=10, data_dir=str(tmp_path),
                            image_size=16, n_people=4, images_per_person=5)
    assert it.total_examples() == 20
    assert len(it.get_labels()) == 4
    ds = it.next()
    assert ds.features.shape == (10, 16, 16, 3)
    # second construction reuses the cached corpus (no regeneration)
    it2 = LFWDataSetIterator(batch_size=5, data_dir=str(tmp_path),
                             image_size=16)
    assert it2.total_examples() == 20


def test_lfw_trains_a_small_conv_net(tmp_path):
    """End-to-end: LFW images -> conv net fit (the reference LFW example)."""
    from deeplearning4j_tpu.nn.conf import (
        ConvolutionLayer,
        InputType,
        NeuralNetConfiguration,
        OutputLayer,
        SubsamplingLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    it = LFWDataSetIterator(batch_size=8, data_dir=str(tmp_path),
                            image_size=16, n_people=3, images_per_person=4)
    conf = (
        NeuralNetConfiguration.builder()
        .seed(0)
        .learning_rate(0.01)
        .updater("adam")
        .list()
        .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3),
                                convolution_mode="same", activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(OutputLayer(n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .set_input_type(InputType.convolutional(16, 16, 3))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    net.fit(it, epochs=2)
    assert np.isfinite(net.score_value)
