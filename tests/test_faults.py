"""Fault-injection harness (distributed/faults.py), connect backoff
(bootstrap.Backoff), and launcher exit classification — the fast,
mostly-in-process half of the elastic-recovery gate (the spawned
N-process recovery proof lives in tests/test_elastic.py).

No real sleeps in the unit tests (fake clock / injected sleep); the one
spawned-fleet test here uses tiny no-jax interpreters under a hard
launcher deadline.
"""

import os
import sys

import pytest

from deeplearning4j_tpu.distributed import bootstrap
from deeplearning4j_tpu.distributed.faults import (
    EXIT_CLEAN,
    EXIT_DEADLINE,
    EXIT_ERROR,
    EXIT_INJECTED_KILL,
    EXIT_RESUMABLE,
    EXIT_SIGABRT,
    RESUMABLE_EXIT_CODE,
    Fault,
    FaultRuntime,
    FaultSchedule,
    active_faults,
    parse_fault,
)
from deeplearning4j_tpu.distributed.launcher import classify_exit, launch_local
from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default

pytestmark = [pytest.mark.distributed, pytest.mark.faults]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ spec parsing

def test_parse_every_fault_kind():
    assert parse_fault("p1:kill@step3") == Fault(1, "kill", step=3)
    assert parse_fault("p2:hang@step4") == Fault(2, "hang", step=4)
    assert parse_fault("p0:delay-connect:1.5") == \
        Fault(0, "delay-connect", seconds=1.5)
    assert parse_fault("p3:drop-heartbeat") == Fault(3, "drop-heartbeat")
    # bare step number is accepted too
    assert parse_fault("p1:kill@3") == Fault(1, "kill", step=3)


def test_parse_replica_scope_specs_round_trip():
    """The serving chaos grammar (ISSUE 13): `r` scope targets a
    REPLICA, triggering on its own batch/decode counters; specs
    round-trip through spec() and schedules filter by scope."""
    f = parse_fault("r0:kill@batch3")
    assert f == Fault(0, "kill", step=3, scope="replica", unit="batch")
    assert f.spec() == "r0:kill@batch3"
    assert parse_fault("r1:hang@batch2").spec() == "r1:hang@batch2"
    assert parse_fault("r0:kill@decode5").unit == "decode"
    sched = FaultSchedule.parse("p1:kill@step3;r1:kill@batch2")
    # scope filtering: a replica spec never targets a process and
    # vice versa, even with a matching index
    assert [f.spec() for f in sched.for_process(1)] == ["p1:kill@step3"]
    assert [f.spec() for f in sched.for_replica(1)] == ["r1:kill@batch2"]
    assert sched.for_replica(0) == []
    assert sched.to_env() == "p1:kill@step3;r1:kill@batch2"


@pytest.mark.parametrize("bad", [
    "kill@step3",          # no process
    "p1:kill",             # kill needs a step
    "p1:delay-connect",    # delay needs seconds
    "p1:oom@step2",        # unknown kind
    "px:kill@step1",       # bad process id
    "p1:kill@stepX",       # bad step
    "r1:drop-heartbeat",   # replica scope takes only kill/hang
    "r1:delay-connect:1",  # replica scope takes only kill/hang
    "r1:kill@step3",       # replica faults trigger on batch/decode
    "p1:kill@batch3",      # process faults trigger on steps
    "r1:kill",             # replica kill needs a trigger
])
def test_parse_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        parse_fault(bad)


def test_schedule_env_roundtrip_and_filtering():
    sched = FaultSchedule.parse("p1:kill@step3;p0:delay-connect:0.5")
    assert FaultSchedule.parse(sched.to_env()).to_env() == sched.to_env()
    assert [f.kind for f in sched.for_process(1)] == ["kill"]
    assert sched.kill_scheduled(1) and not sched.kill_scheduled(0)
    assert len(FaultSchedule.parse("")) == 0


def test_seeded_schedule_is_deterministic():
    a = FaultSchedule.seeded(7, n_processes=3, max_step=5)
    b = FaultSchedule.seeded(7, n_processes=3, max_step=5)
    assert a.to_env() == b.to_env()
    (fault,) = list(a)
    assert 0 <= fault.process_id < 3 and 1 <= fault.step <= 5
    assert fault.kind in ("kill", "hang")
    # some other seed produces a different schedule (not all collide)
    assert any(FaultSchedule.seeded(s, 3, 5).to_env() != a.to_env()
               for s in range(20))


# ---------------------------------------------------------- fault runtime

def test_active_faults_filters_by_process_and_reparses(monkeypatch):
    monkeypatch.setenv(bootstrap.ENV_FAULTS, "p1:kill@step3")
    monkeypatch.setenv(bootstrap.ENV_PROCESS_ID, "0")
    assert active_faults().faults == []  # not our process
    monkeypatch.setenv(bootstrap.ENV_PROCESS_ID, "1")
    rt = active_faults()
    assert [f.kind for f in rt.faults] == ["kill"]
    monkeypatch.delenv(bootstrap.ENV_FAULTS)
    assert active_faults().faults == []  # re-parsed per call


def test_kill_fires_at_its_step_only_and_emits_fault_event():
    rec = Recorder()  # in-memory
    prev = set_default(rec)
    try:
        kills = []
        rt = FaultRuntime([Fault(1, "kill", step=3)], process_id=1,
                          kill=lambda pid, sig: kills.append((pid, sig)))
        rt.check_step(1)
        rt.check_step(2)
        assert kills == []
        rt.check_step(3)
        assert len(kills) == 1 and kills[0][0] == os.getpid()
    finally:
        set_default(prev)
    faults = [e for e in rec.events if e["event"] == "fault"]
    assert faults and faults[0]["kind"] == "kill" \
        and faults[0]["step"] == 3 and faults[0]["fired"]


def test_hang_sleeps_until_reaped():
    sleeps = []

    class Stop(Exception):
        pass

    def fake_sleep(s):
        sleeps.append(s)
        if len(sleeps) >= 3:
            raise Stop  # stand-in for the launcher's SIGKILL

    rt = FaultRuntime([Fault(0, "hang", step=2)], process_id=0,
                      sleep=fake_sleep)
    rt.check_step(1)
    assert sleeps == []
    with pytest.raises(Stop):
        rt.check_step(2)
    assert len(sleeps) == 3  # kept sleeping, never returned


def test_delay_connect_sleeps_scheduled_seconds():
    sleeps = []
    rt = FaultRuntime([Fault(0, "delay-connect", seconds=1.5)],
                      process_id=0, sleep=sleeps.append)
    assert rt.delay_connect() == 1.5
    assert sleeps == [1.5]
    assert not rt.drop_heartbeat


def test_drop_heartbeat_flag():
    rt = FaultRuntime([Fault(2, "drop-heartbeat")], process_id=2)
    assert rt.drop_heartbeat
    rt.delay_connect()  # no delay scheduled: no sleep, returns 0
    rt.check_step(1)    # no step faults: no-op


# -------------------------------------------------------- backoff (fake clock)

class FakeClock:
    """Deterministic clock whose sleep() advances time — asserts the
    bounded-total-wait contract with zero real sleeping."""

    def __init__(self):
        self.now = 0.0
        self.slept = []

    def clock(self):
        return self.now

    def sleep(self, seconds):
        self.slept.append(seconds)
        self.now += seconds


def _backoff(clk, **kw):
    import random

    kw.setdefault("rng", random.Random(0))
    return bootstrap.Backoff(clock=clk.clock, sleep=clk.sleep, **kw)


def test_backoff_delays_are_full_jitter_bounded():
    clk = FakeClock()
    bo = _backoff(clk, base=0.25, cap=5.0, max_elapsed=1e9)
    for attempt in range(12):
        d = bo.next_delay()
        assert 0.0 <= d <= min(5.0, 0.25 * 2 ** attempt)
        clk.now += d


def test_backoff_total_wait_bounded_by_max_elapsed():
    clk = FakeClock()
    bo = _backoff(clk, base=0.5, cap=4.0, max_elapsed=10.0)
    while bo.pause():
        pass
    # every sleep happened inside the budget, and the sum can never
    # exceed it (the last delay is clipped to the remaining window)
    assert sum(clk.slept) <= 10.0
    assert clk.now <= 10.0
    assert bo.next_delay() is None  # stays exhausted


def test_backoff_exhausts_even_when_attempts_are_slow():
    """Time spent in the failing attempt itself (not just in sleeps)
    counts against the budget: a 100 s connect timeout per attempt must
    not multiply max_elapsed."""
    clk = FakeClock()
    bo = _backoff(clk, base=0.1, cap=1.0, max_elapsed=5.0)
    assert bo.pause()
    clk.now += 100.0  # a glacial attempt
    assert not bo.pause()


def test_backoff_jitter_decorrelates_processes():
    import random

    clk = FakeClock()
    a = bootstrap.Backoff(rng=random.Random(1), clock=clk.clock,
                          sleep=clk.sleep, max_elapsed=1e9)
    b = bootstrap.Backoff(rng=random.Random(2), clock=clk.clock,
                          sleep=clk.sleep, max_elapsed=1e9)
    da = [a.next_delay() for _ in range(8)]
    db = [b.next_delay() for _ in range(8)]
    assert da != db  # full jitter: two workers never retry in lockstep


# -------------------------------------------------- exit classification

def test_classify_exit_all_classes():
    assert classify_exit(0, False) == EXIT_CLEAN
    assert classify_exit(RESUMABLE_EXIT_CODE, False) == EXIT_RESUMABLE
    assert classify_exit(None, True) == EXIT_DEADLINE
    assert classify_exit(-6, False) == EXIT_SIGABRT
    assert classify_exit(-9, False, kill_injected=True) == \
        EXIT_INJECTED_KILL
    # an unscheduled SIGKILL is NOT attributed to the harness
    assert classify_exit(-9, False, kill_injected=False) == EXIT_ERROR
    assert classify_exit(1, False) == EXIT_ERROR
    # deadline wins over any code the reaper observed afterwards
    assert classify_exit(-15, True) == EXIT_DEADLINE


_STEP_LOOP = (
    "import sys\n"
    "sys.path.insert(0, {root!r})\n"
    "from deeplearning4j_tpu.distributed.faults import active_faults\n"
    "rt = active_faults()\n"
    "for step in range(1, 6):\n"
    "    print('step', step, flush=True)\n"
    "    rt.check_step(step)\n"
    "print('done', flush=True)\n")


def test_launcher_applies_faults_and_classifies_exits(tmp_path):
    """The spawned proof (no jax: bare interpreters running a 5-step
    loop): p0 finishes clean, p1 dies by injected kill@step3, p2 hangs
    at step4 until the deadline reaps it — and the launcher classifies
    all three, appends the [pN] epilogue, and leaves the full
    fault→exit record in telemetry."""
    rec = Recorder(str(tmp_path / "sup.jsonl"))
    prev = set_default(rec)
    echoed = []
    try:
        results = launch_local(
            [sys.executable, "-c", _STEP_LOOP.format(root=ROOT)],
            n_processes=3, local_device_count=None,
            timeout=20.0, grace=2.0,
            faults="p1:kill@step3;p2:hang@step4", echo=echoed.append)
    finally:
        set_default(prev)

    classes = [r.exit_class for r in results]
    assert classes == [EXIT_CLEAN, EXIT_INJECTED_KILL, EXIT_DEADLINE]
    assert "done" in results[0].output
    assert "step 3" in results[1].output  # died after its step-3 line
    assert "done" not in results[1].output
    assert "step 4" in results[2].output and "done" not in results[2].output
    # the [pN] epilogue names the classification
    assert any(line.startswith("[p1] -- exit: injected-kill")
               for line in echoed)
    # telemetry: injected faults + every observed exit class
    faults = [e for e in rec.events if e["event"] == "fault"]
    injected = {(e["kind"], e["process_id"]) for e in faults
                if e.get("injected")}
    assert injected == {("kill", 1), ("hang", 2)}
    observed = {e["process_id"]: e["kind"] for e in faults
                if e.get("observed_exit")}
    assert observed == {0: EXIT_CLEAN, 1: EXIT_INJECTED_KILL,
                        2: EXIT_DEADLINE}


def test_resumable_exit_classifies_without_schedule():
    results = launch_local(
        [sys.executable, "-c", f"raise SystemExit({RESUMABLE_EXIT_CODE})"],
        n_processes=1, local_device_count=None, timeout=15.0)
    assert results[0].exit_class == EXIT_RESUMABLE


def test_death_grace_reaps_survivors_early():
    """Responsive teardown: once one member dies, the rest get
    `death_grace` seconds — not the whole wall-clock deadline — before
    the launcher reaps them (the elastic supervisor's fast path on jax
    generations where survivors block forever in the dead collective)."""
    import time as _time

    t0 = _time.monotonic()
    results = launch_local(
        [sys.executable, "-c",
         "import os, sys, time\n"
         "if os.environ['DL4J_TPU_PROCESS_ID'] == '0':\n"
         "    sys.exit(1)\n"
         "time.sleep(600)\n"],
        n_processes=2, local_device_count=None,
        timeout=60.0, grace=1.0, death_grace=2.0)
    elapsed = _time.monotonic() - t0
    assert results[0].exit_class == EXIT_ERROR
    assert results[1].exit_class == EXIT_DEADLINE
    assert elapsed < 30.0, f"death_grace did not shortcut ({elapsed:.1f}s)"


def test_resumable_exit_does_not_trip_death_grace():
    """A worker exiting RESUMABLE is a survivor, not a death: the rest
    of the fleet keeps its full deadline."""
    results = launch_local(
        [sys.executable, "-c",
         "import os, sys, time\n"
         "if os.environ['DL4J_TPU_PROCESS_ID'] == '0':\n"
         f"    sys.exit({RESUMABLE_EXIT_CODE})\n"
         "time.sleep(4)\n"],
        n_processes=2, local_device_count=None,
        timeout=30.0, grace=1.0, death_grace=0.5)
    assert results[0].exit_class == EXIT_RESUMABLE
    assert results[1].exit_class == EXIT_CLEAN  # outlived the grace: ran
