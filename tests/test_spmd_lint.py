"""Tier-1 gate for graftlint stage 3 (ISSUE 5): the collective-
consistency audit (analysis/collective_audit.py). Proves that every
frozen entry point's ordered collective signature matches the shipped
analysis/collective_budget.json and is rank-divergence-free, that the
2-process allreduce entry from tests/test_distributed.py has a frozen
NON-EMPTY signature (the stage actually sees the PR 4 runtime), that a
mutated frozen signature trips a named C001 finding with a non-zero CLI
exit, and that a rank-conditional collective is reported as a C003
DEADLOCK finding naming both divergent sequences — the SIGABRT
"Deadline Exceeded" failure mode caught before launch instead of as a
wedged fleet."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from deeplearning4j_tpu.analysis import collective_audit

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(ROOT, "tools", "graftlint.py")
FIXTURE = os.path.join(ROOT, "tests", "fixtures",
                       "spmd_divergent_entry.py")


def _cli_main():
    spec = importlib.util.spec_from_file_location("_graftlint_cli", CLI)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


# ------------------------------------------------ the shipped entry set

@pytest.mark.parametrize("entry", collective_audit.entry_names())
def test_entry_matches_frozen_signature_and_never_diverges(entry):
    findings, sigs = collective_audit.audit([entry])
    assert not findings, "\n".join(f.format() for f in findings)
    assert sigs[entry] == collective_audit.load_budget()[entry]


def test_allreduce_entry_signature_is_nonempty():
    """The set_mesh/fit allreduce step tests/test_distributed.py proves
    on a live 2-process x 4-device fleet must be VISIBLE to the stage:
    pjit hides collectives from the jaxpr, so its frozen signature is
    the post-GSPMD HLO sequence — and it must not be empty."""
    sig = collective_audit.load_budget()["distributed/allreduce_step_2x4"]
    assert sig, "the allreduce entry's frozen signature is empty"
    assert all(item.startswith("hlo:all-reduce") for item in sig)


def test_overlap_entry_freezes_the_per_rank_bucket_sequence():
    """ISSUE 7: the bucketed-overlap train step's frozen signature IS
    the per-rank bucket schedule — one psum@data per bucket in reverse
    layer order (the 128-byte plan splits the 83-param net into three
    gradient buckets), then the loss pmean. The parametrized audit test
    above already proves it identical under simulated ranks (zero
    C003); here the shape of the deliberate refreeze is pinned."""
    sig = collective_audit.load_budget()["distributed/overlap_step_2x4"]
    assert sig, "the overlap entry's frozen signature is empty"
    assert all(item.startswith("psum@data") for item in sig)
    grad_psums = [item for item in sig if not item.endswith("[]")]
    assert len(grad_psums) == 3  # the bucket count of the frozen plan


def test_shard_map_entries_carry_jaxpr_collectives():
    frozen = collective_audit.load_budget()
    ring = frozen["ring_attention/seq4"]
    assert any(item.startswith("ppermute@seq") for item in ring)
    sp = frozen["sequence_parallel/sp_step_seq2"]
    assert any(item.startswith("psum@seq") for item in sp)
    assert set(frozen) == set(collective_audit.entry_names())


# ------------------------------------------------------ drift tripping

def test_signature_drift_trips_named_finding_and_cli_exit(
        tmp_path, monkeypatch, capsys):
    frozen = collective_audit.load_budget()
    mutated = dict(frozen)
    mutated["ring_attention/seq4"] = ["psum@bogus float32[2]"]
    bad = tmp_path / "collective_budget.json"
    bad.write_text(json.dumps({"signatures": mutated}))

    findings, _ = collective_audit.audit(
        ["ring_attention/seq4"], budget_path=str(bad), divergence=False)
    assert [f.rule for f in findings] == ["C001"]
    assert findings[0].path == "ring_attention/seq4"
    assert findings[0].stage == "spmd"
    assert "signature drift" in findings[0].message
    assert "psum@bogus" in findings[0].message  # names the frozen side

    # deadlock findings are NOT budget diffs: a divergent budget file
    # must not be able to mask a C003 (different rule, always emitted)
    monkeypatch.setattr(collective_audit, "BUDGET_PATH", str(bad))
    assert _cli_main()(["--check", "--stage", "spmd"]) == 1
    out = capsys.readouterr().out
    assert "C001" in out and "ring_attention/seq4" in out


def test_missing_signature_is_a_finding(tmp_path):
    empty = tmp_path / "collective_budget.json"
    empty.write_text(json.dumps({"signatures": {}}))
    findings, _ = collective_audit.audit(
        ["ring_attention/seq4"], budget_path=str(empty), divergence=False)
    assert [f.rule for f in findings] == ["C002"]
    assert "--update-collectives" in findings[0].fixit


# ------------------------------------------------- divergence/deadlock

def test_rank_conditional_collective_is_a_deadlock_finding():
    """Satellite: inject a rank-conditional collective into a toy entry
    (the checked-in demo fixture) and assert a DEADLOCK finding that
    names both divergent sequences."""
    findings, sigs = collective_audit.audit_paths([FIXTURE])
    assert [f.rule for f in findings] == ["C003"]
    msg = findings[0].message
    assert "DEADLOCK" in msg
    assert "process 0 issues" in msg and "process 1 issues" in msg
    assert "psum@data" in msg and "[]" in msg  # both sequences named
    assert findings[0].stage == "spmd"
    assert sigs["demo/rank_conditional_psum"]  # pid-unsimulated trace


def test_rank_divergent_op_count_is_the_same_class():
    """A rank-dependent value baked into the trace (no collective in
    sight) still desyncs the replicas: caught as C003 via op counts."""

    def build():
        import jax

        def fn(x):
            if jax.process_index() == 0:
                return x + 1.0
            return (x * 2.0) + (x * 3.0)

        return fn, (jax.ShapeDtypeStruct((2,), "float32"),)

    findings = collective_audit.check_divergence("toy/op_count", build)
    assert [f.rule for f in findings] == ["C003"]
    assert "traced ops" in findings[0].message


def test_simulated_process_index_restores_state():
    import jax

    from deeplearning4j_tpu.distributed import bootstrap

    before_env = os.environ.get(bootstrap.ENV_PROCESS_ID)
    before_fn = jax.process_index
    with collective_audit.simulated_process_index(1):
        assert jax.process_index() == 1
        assert os.environ[bootstrap.ENV_PROCESS_ID] == "1"
    assert jax.process_index is before_fn
    assert os.environ.get(bootstrap.ENV_PROCESS_ID) == before_env


# --------------------------------------------------------------- CLI

def test_cli_spmd_demo_exits_nonzero_with_both_finding_classes():
    """The acceptance demo: `--stage spmd` on the divergent fixture must
    exit non-zero with the G010 AST finding AND the C003 deadlock
    finding naming both sequences."""
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "--stage", "spmd", FIXTURE],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "G010" in proc.stdout and "C003" in proc.stdout
    assert "DEADLOCK" in proc.stdout
    assert "process 0 issues" in proc.stdout


def test_cli_spmd_clean_tree_emits_labeled_json():
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "--stage", "spmd", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    sigs = payload["collective_signatures"]
    assert set(sigs) == set(collective_audit.entry_names())
    assert sigs["distributed/allreduce_step_2x4"]
