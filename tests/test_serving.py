"""Tier-1 gate for the continuous-batching serving subsystem (ISSUE 9):
bucket-selection determinism, padding proofs at atol 0, batcher
deadline/coalescing on a FAKE clock (no real sleeps), HTTP round-trip
parity vs the in-process forward, the zero-retrace promise over a
mixed-length replay, worker-death containment, and the telemetry-only
scoreboard reconstruction behind tools/trafficreplay.py."""

import json
import os
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.serving.batcher import (Batcher, PendingRequest,
                                                assemble, plan_batch)
from deeplearning4j_tpu.serving.buckets import Bucket, BucketLattice
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.server import ServingServer
from deeplearning4j_tpu.serving import replay
from deeplearning4j_tpu.telemetry import Recorder

pytestmark = pytest.mark.serving


def _mlp():
    return replay._tiny_mlp()


def _req(features, t=0.0, mask=None):
    return PendingRequest(features=np.asarray(features), mask=mask,
                          t_enqueue=t)


# ------------------------------------------------------------- lattice

def test_bucket_selection_is_deterministic():
    lat = BucketLattice(batch_sizes=(1, 2, 4, 8), seq_lens=(8, 16, 32))
    picks = [lat.select(3, 11) for _ in range(5)]
    assert picks == [Bucket(4, 16)] * 5
    assert lat.select(1, 8) == Bucket(1, 8)
    assert lat.select(8, 32) == Bucket(8, 32)
    # boundary: exact fits choose the bucket itself, not the next one
    assert lat.select(2, 16) == Bucket(2, 16)


def test_lattice_rejects_out_of_envelope():
    lat = BucketLattice(batch_sizes=(1, 2), seq_lens=(8,))
    with pytest.raises(ValueError, match="exceeds lattice max"):
        lat.seq_bucket(9)
    with pytest.raises(ValueError, match="exceeds lattice max"):
        lat.batch_bucket(3)
    fixed = BucketLattice(batch_sizes=(1, 2))
    with pytest.raises(ValueError, match="no seq dimension"):
        fixed.seq_bucket(4)


def test_bucket_spec_grammars():
    lat = BucketLattice.from_spec("1,2,4")
    assert lat.batch_sizes == (1, 2, 4) and lat.seq_lens is None
    lat = BucketLattice.from_spec("1x64,4x64,4x256")
    assert lat.batch_sizes == (1, 4) and lat.seq_lens == (64, 256)
    with pytest.raises(ValueError, match="mixes"):
        BucketLattice.from_spec("1x64,4")


def test_seq_lattice_validated_against_ops_dispatch():
    """Long-prompt buckets are checked against the attention dispatch
    envelope at construction time: a tileable long T passes, an
    un-tileable one fails with the dispatch's own reason string."""
    from deeplearning4j_tpu.ops import flash_attention as fa

    assert fa.servable_seq(512, 64)          # fused envelope
    assert fa.servable_seq(16384, 128)       # chunked envelope
    assert not fa.servable_seq(25000, 64)    # not tileable, > monolithic max
    BucketLattice(batch_sizes=(1,), seq_lens=(512, 16384)) \
        .validate_attention(head_dim=128)
    with pytest.raises(ValueError, match="cannot be tiled"):
        BucketLattice(batch_sizes=(1,), seq_lens=(25000,)) \
            .validate_attention(head_dim=64)


# ---------------------------------------------- batcher (fake clock)

def test_plan_batch_waits_under_deadline():
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    pending = [_req(np.zeros(3, np.float32), t=0.0)]
    assert plan_batch(pending, 0.001, 0.005, lat) == 0


def test_plan_batch_cuts_on_deadline():
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    pending = [_req(np.zeros(3, np.float32), t=0.0),
               _req(np.zeros(3, np.float32), t=0.004)]
    assert plan_batch(pending, 0.0049, 0.005, lat) == 0
    assert plan_batch(pending, 0.005, 0.005, lat) == 2


def test_plan_batch_full_bucket_never_waits():
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    pending = [_req(np.zeros(3, np.float32), t=0.0) for _ in range(6)]
    # full largest bucket cuts immediately even at now == enqueue time
    assert plan_batch(pending, 0.0, 0.005, lat) == 4


def test_plan_batch_drain_flushes():
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    pending = [_req(np.zeros(3, np.float32), t=0.0)]
    assert plan_batch(pending, 0.0, 10.0, lat) == 0
    assert plan_batch(pending, 0.0, 10.0, lat, closed=True) == 1


def test_plan_batch_incompatible_request_ends_group():
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    pending = [_req(np.zeros(3, np.float32), t=0.0),
               _req(np.zeros(5, np.float32), t=0.0),  # different shape
               _req(np.zeros(3, np.float32), t=0.0)]
    # FIFO order preserved: the incompatible head-adjacent request caps
    # the cut at 1 even past the deadline
    assert plan_batch(pending, 1.0, 0.005, lat) == 1


def test_batcher_live_coalescing_without_sleeps():
    """The threaded Batcher on a manual clock: deadline expiry is
    simulated by advancing the clock, not by sleeping."""
    now = {"t": 0.0}
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    b = Batcher(lat, max_wait_ms=5.0, clock=lambda: now["t"])
    b.submit(np.zeros(3, np.float32))
    b.submit(np.ones(3, np.float32))
    assert b.next_batch(timeout=0.0) is None  # deadline not reached
    now["t"] = 0.006
    batch = b.next_batch(timeout=0.5)
    assert batch is not None and batch.n_real == 2
    assert batch.bucket == Bucket(2, None)
    b.close()
    assert b.next_batch(timeout=0.0) is None
    with pytest.raises(RuntimeError, match="draining"):
        b.submit(np.zeros(3, np.float32))


def test_assemble_pads_shapes_and_masks():
    lat = BucketLattice(batch_sizes=(1, 2, 4), seq_lens=(8, 16))
    reqs = [_req(np.arange(5, dtype=np.int32)),
            _req(np.arange(11, dtype=np.int32))]
    batch = assemble(reqs, lat, sequence=True)
    assert batch.bucket == Bucket(2, 16)
    assert batch.features.shape == (2, 16)
    assert batch.features.dtype == np.int32
    assert batch.mask.shape == (2, 16)
    np.testing.assert_array_equal(batch.mask[0],
                                  ([1.0] * 5 + [0.0] * 11))
    np.testing.assert_array_equal(batch.features[0, :5], np.arange(5))
    assert batch.features[0, 5:].sum() == 0  # zero padding


# ------------------------------------------------- padding correctness

def test_padded_rows_do_not_change_real_rows_atol0_mlp():
    """The row-padding proof the whole bucket scheme rests on: with the
    SAME bucket shape, garbage in the padding rows leaves the real
    rows' outputs BIT-identical (inference forwards are
    row-independent)."""
    import jax

    net = _mlp()
    fwd = jax.jit(net.inference_fn())
    rng = np.random.default_rng(0)
    real = rng.normal(size=(2, 8)).astype(np.float32)
    zeros = np.concatenate([real, np.zeros((2, 8), np.float32)])
    garbage = np.concatenate(
        [real, 1e6 * rng.normal(size=(2, 8)).astype(np.float32)])
    y_zero = np.asarray(fwd(net.params, net.state, zeros))
    y_garb = np.asarray(fwd(net.params, net.state, garbage))
    np.testing.assert_array_equal(y_zero[:2], y_garb[:2])


def test_padded_rows_and_tail_do_not_change_real_outputs_atol0_lm():
    """Sequence twin of the row proof, plus the causal-tail property:
    garbage token ids in the padded ROWS and in the padded TAIL of a
    real row (mask unchanged) leave the real row's real positions
    bit-identical — padded batch rows are independent sequences, and
    causal attention never reads a future (padded) key."""
    import jax

    net = replay._tiny_lm(16)
    fwd = jax.jit(net.inference_fn())
    rng = np.random.default_rng(1)
    toks = rng.integers(0, 64, 10).astype(np.int32)
    mask = np.zeros((2, 16), np.float32)
    mask[0, :10] = 1.0

    def batch_with(pad_fill):
        feats = np.full((2, 16), 0, np.int32)
        feats[0, :10] = toks
        feats[0, 10:] = pad_fill[0]   # real row's padded tail
        feats[1, :] = pad_fill[1]     # whole padding row
        return feats

    a = batch_with((0, 0))
    b = batch_with((rng.integers(1, 64), rng.integers(1, 64)))
    y_a = np.asarray(fwd(net.params, net.state, a, mask))
    y_b = np.asarray(fwd(net.params, net.state, b, mask))
    np.testing.assert_array_equal(y_a[0, :10], y_b[0, :10])


# ------------------------------------------- engine + server round trip

@pytest.fixture(scope="module")
def mlp_stack():
    net = _mlp()
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1, 2, 4))
    engine = InferenceEngine(net, lat, max_wait_ms=2.0, recorder=rec)
    engine.warmup(np.zeros(8, np.float32))
    server = ServingServer(engine, port=0).start()
    yield net, engine, server, rec
    server.stop()


def _post(url, payload):
    req = urllib.request.Request(
        f"{url}/predict", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_server_round_trip_parity_vs_direct_predict(mlp_stack):
    net, engine, server, _ = mlp_stack
    rng = np.random.default_rng(3)
    x = rng.normal(size=(5, 8)).astype(np.float32)
    direct_out = np.asarray(net.output(x))
    direct_pred = net.predict(x)
    for i in range(5):
        resp = _post(server.url, {"features": x[i].tolist()})
        assert resp["prediction"] == int(direct_pred[i])
        np.testing.assert_allclose(np.asarray(resp["output"]),
                                   direct_out[i], atol=1e-5)
        assert resp["timing"]["total_s"] >= resp["timing"]["queue_s"] >= 0


def test_healthz_and_stats(mlp_stack):
    _, engine, server, _ = mlp_stack
    with urllib.request.urlopen(f"{server.url}/healthz", timeout=10) as r:
        health = json.loads(r.read())
    assert health["status"] == "serving"
    assert health["replicas"] == 1
    assert health["lattice"]["batch_sizes"] == [1, 2, 4]
    with urllib.request.urlopen(f"{server.url}/stats", timeout=10) as r:
        stats = json.loads(r.read())
    assert stats["served"] >= 5


def test_server_rejects_malformed_and_oversized(mlp_stack):
    _, _, server, _ = mlp_stack
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server.url, {"nope": 1})
    assert e.value.code == 400


def test_metrics_endpoint_round_trip_under_concurrent_requests(mlp_stack):
    """GET /metrics serves Prometheus text exposition format while
    /predict traffic runs concurrently — zero failed requests on either
    side, and the scraped series agree with the engine's own counters
    (ISSUE 15 acceptance: /metrics mid-replay)."""
    from deeplearning4j_tpu.telemetry.metrics import (CONTENT_TYPE,
                                                      parse_exposition)

    _, engine, server, _ = mlp_stack
    rng = np.random.default_rng(11)
    failures = []
    scrapes = []

    def client(i):
        try:
            _post(server.url, {"features": rng.normal(size=8).tolist()})
        except Exception as exc:  # noqa: BLE001 - recorded, not raised
            failures.append(exc)

    def scraper():
        try:
            with urllib.request.urlopen(f"{server.url}/metrics",
                                        timeout=10) as r:
                assert r.status == 200
                assert r.headers["Content-Type"] == CONTENT_TYPE
                scrapes.append(r.read().decode())
        except Exception as exc:  # noqa: BLE001
            failures.append(exc)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(12)]
    threads += [threading.Thread(target=scraper) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not failures, failures
    # the final scrape reflects every completed request
    with urllib.request.urlopen(f"{server.url}/metrics", timeout=10) as r:
        parsed = parse_exposition(r.read().decode())
    assert parsed["serving_request_latency_seconds_count"] >= 12
    assert parsed['serving_requests_total{kind="predict",outcome="ok"}'] \
        >= 12
    assert parsed["serving_weight_generation"] == \
        engine.weights.generation
    assert parsed['serving_replica_up{replica="0"}'] == 1.0
    assert parsed["serving_request_latency_seconds_p99"] >= \
        parsed["serving_request_latency_seconds_p50"] >= 0
    # exposition shape: every histogram has its +Inf bucket and
    # bucket counts are monotone in le
    text = scrapes[-1]
    assert 'serving_request_latency_seconds_bucket{le="+Inf"}' in text


def test_request_span_tree_reconstructs_from_telemetry(mlp_stack):
    """The correlation contract end to end on the REAL engine: a served
    request's telemetry joins one trace — queue -> batch_assemble ->
    {forward, request} — reconstructable as a tree from the recorder's
    events alone (ISSUE 15: request chains become real trees)."""
    from deeplearning4j_tpu.telemetry import trace as trace_mod

    net, engine, server, rec = mlp_stack
    out = engine.predict(np.zeros(8, np.float32), timeout=30)
    assert out is not None
    reqs = [e for e in rec.events if e.get("event") == "request"]
    assert reqs and reqs[-1].get("trace_id"), \
        "request events must carry their batch's trace id"
    tid = reqs[-1]["trace_id"]
    tl = trace_mod.timeline_from_events(rec.events)
    roots = trace_mod.span_tree(tl, tid)
    assert len(roots) == 1
    root = roots[0]
    assert root["event"]["name"] == "queue"
    assert len(root["children"]) == 1
    assemble = root["children"][0]
    assert assemble["event"]["name"] == "batch_assemble"
    kinds = {c["event"].get("name") or c["event"]["event"]
             for c in assemble["children"]}
    assert "forward" in kinds and "request" in kinds
    # every event of the trace shares the trace id
    members = [e for e in tl.events if e.get("trace_id") == tid]
    assert len(members) >= 4


# ------------------------------------------------- zero-retrace promise

def test_zero_recompiles_after_warmup_across_mixed_lengths():
    """THE acceptance property: warm the lattice once, then a
    mixed-length request stream adds ZERO compiles — asserted on both
    the telemetry compile-span count and the trace-time counter."""
    net = replay._tiny_lm(16)
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1, 2), seq_lens=(8, 16))
    engine = InferenceEngine(net, lat, max_wait_ms=1.0, sequence=True,
                             recorder=rec)
    warmed = engine.warmup(np.zeros(16, np.int32))
    assert warmed == 4  # 2 batch x 2 seq buckets, 1 replica
    assert engine.trace_count == 4

    def compile_spans():
        return [e for e in rec.events
                if e.get("event") == "span" and e.get("name") == "compile"]

    assert len(compile_spans()) == 4
    assert all(e.get("warmup") for e in compile_spans())
    engine.start()
    rng = np.random.default_rng(5)
    for seq_len in (3, 8, 11, 16, 5, 1, 13, 16, 2, 7):
        out = engine.predict(rng.integers(0, 64, seq_len).astype(np.int32),
                             timeout=30)
        assert np.asarray(out).shape[0] == seq_len  # padding sliced off
    assert engine.trace_count == 4, "a request escaped the bucket lattice"
    assert len(compile_spans()) == 4
    # the per-request telemetry breakdown is on the record
    reqs = [e for e in rec.events if e.get("event") == "request"]
    assert len(reqs) == 10
    for ev in reqs:
        assert ev["ok"] and ev["total_s"] >= 0
        assert {"queue_s", "batch_assemble_s", "forward_s",
                "bucket", "seq_len", "padded_seq"} <= set(ev)
    engine.drain()


# --------------------------------------------- worker death containment

def test_worker_dying_mid_batch_fails_requests_not_replica():
    net = _mlp()
    rec = Recorder(path=None)
    engine = InferenceEngine(net, BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=1.0, recorder=rec)
    engine.warmup(np.zeros(8, np.float32))
    replica = engine._replicas[0]
    orig = replica._jit
    state = {"bombs": 1}

    def flaky(*args, **kwargs):
        if state["bombs"]:
            state["bombs"] -= 1
            raise RuntimeError("injected worker death")
        return orig(*args, **kwargs)

    replica._jit = flaky
    engine.start()
    x = np.zeros(8, np.float32)
    with pytest.raises(RuntimeError, match="injected worker death"):
        engine.predict(x, timeout=30)
    # the replica survived its batch dying: the next request serves
    out = engine.predict(x, timeout=30)
    assert np.asarray(out).shape == (4,)
    errors = [e for e in rec.events if e.get("event") == "error"]
    assert any("injected worker death" in e.get("error", "")
               for e in errors)
    failed = [e for e in rec.events
              if e.get("event") == "request" and not e.get("ok")]
    assert failed and "injected worker death" in failed[0]["error"]
    engine.drain()


# ------------------------------------------------ trace + reconstruction

def test_make_trace_is_seeded_and_bursty():
    t1 = replay.make_trace(7, 40, burst=4, lengths=(8, 16, 32))
    t2 = replay.make_trace(7, 40, burst=4, lengths=(8, 16, 32))
    assert t1 == t2
    t3 = replay.make_trace(8, 40, burst=4, lengths=(8, 16, 32))
    assert t1 != t3
    offsets = [t for t, _ in t1]
    assert offsets == sorted(offsets)
    # bursts share their arrival instant
    assert offsets[0] == offsets[1] == offsets[2] == offsets[3]
    assert offsets[4] > offsets[3]
    assert {l for _, l in t1} <= {8, 16, 32}


def test_reconstruct_from_telemetry_alone(tmp_path):
    """The scoreboard math, from a synthesized JSONL with known
    latencies — no serving stack involved."""
    path = str(tmp_path / "t.jsonl")
    lat_ms = [10.0, 20.0, 30.0, 40.0, 1000.0]
    with open(path, "w") as fh:
        for i, ms in enumerate(lat_ms):
            fh.write(json.dumps({
                "event": "request", "id": f"r{i}", "ok": True,
                "ts": 100.0 + i, "total_s": ms / 1000.0}) + "\n")
        fh.write(json.dumps({"event": "request", "id": "bad",
                             "ok": False, "ts": 105.0,
                             "total_s": 0.5}) + "\n")
        fh.write(json.dumps({"event": "span", "name": "compile",
                             "warmup": True, "seconds": 1.0}) + "\n")
        fh.write(json.dumps({"event": "span", "name": "compile",
                             "seconds": 1.0}) + "\n")
    sb = replay.reconstruct(path)
    assert sb["n_requests"] == 6 and sb["n_ok"] == 5 and sb["n_failed"] == 1
    assert sb["p50_ms"] == 30.0
    assert sb["p99_ms"] == 1000.0
    assert sb["warmup_compiles"] == 1
    assert sb["recompiles_after_warmup"] == 1
    # QPS span: first enqueue (ts - total_s) to last completion (ts)
    first = min(100.0 + i - ms / 1000.0 for i, ms in enumerate(lat_ms))
    assert sb["qps"] == round(5 / (104.0 - first), 2)


def test_end_to_end_replay_truncation_proof(tmp_path):
    """The full rc=0 path at small scale: replay over real HTTP,
    reconstruct from telemetry alone, write the SERVE artifact — then
    truncate the artifact to its LAST LINE and recover every metric
    from the summary (the BENCH truncation contract)."""
    from deeplearning4j_tpu.telemetry import artifact as art

    tpath = str(tmp_path / "telemetry.jsonl")
    apath = str(tmp_path / "SERVE_test.json")
    sb = replay.run_replay(model="mlp", seed=0, n_requests=20,
                           telemetry_path=tpath, artifact_path=apath)
    assert sb["n_ok"] == 20
    assert sb["recompiles_after_warmup"] == 0
    assert sb["qps"] > 0 and sb["p99_ms"] >= sb["p50_ms"] > 0
    full = art.load(apath)
    assert full["serving_replay_qps"]["value"] == sb["qps"]
    # tail-truncate to the summary line alone: every number survives
    with open(apath) as fh:
        last = fh.read().splitlines()[-1]
    cut = str(tmp_path / "cut.json")
    with open(cut, "w") as fh:
        fh.write(last + "\n")
    recovered = art.load(cut)
    for metric in ("serving_replay_qps", "serving_replay_p50_ms",
                   "serving_replay_p99_ms",
                   "serving_replay_recompiles_after_warmup"):
        assert recovered[metric]["value"] == full[metric]["value"]
    # the happy path is anomaly-free: the fleet-timeline detector over
    # the same telemetry finds no retrace, no straggler, no spike
    from deeplearning4j_tpu.telemetry import trace as trace_mod

    findings = trace_mod.detect_anomalies(trace_mod.load_timeline(tpath))
    assert findings == [], findings


# ---------------------------------------------------------------- CLI

def test_cli_predict_via_server(tmp_path, mlp_stack):
    from deeplearning4j_tpu.cli.driver import main

    net, _, server, _ = mlp_stack
    rng = np.random.default_rng(11)
    x = rng.normal(size=(6, 8)).astype(np.float32)
    csv_in = str(tmp_path / "in.csv")
    with open(csv_in, "w") as fh:
        for row in x:
            fh.write(",".join(f"{v:.8g}" for v in row) + "\n")
    out_csv = str(tmp_path / "preds.csv")
    rc = main(["predict", "--server", server.url, "--input", csv_in,
               "--output", out_csv])
    assert rc == 0
    preds = np.loadtxt(out_csv, delimiter=",", dtype=np.float32)
    np.testing.assert_allclose(preds, np.asarray(net.output(x)), atol=1e-4)


def test_cli_predict_requires_one_source(tmp_path):
    from deeplearning4j_tpu.cli.driver import main

    csv_in = str(tmp_path / "in.csv")
    with open(csv_in, "w") as fh:
        fh.write("1,2\n")
    with pytest.raises(SystemExit, match="exactly one"):
        main(["predict", "--input", csv_in, "--output",
              str(tmp_path / "o.csv")])


def test_cli_serve_multiprocess_plan(capsys):
    from deeplearning4j_tpu.cli.driver import main

    rc = main(["serve", "--model", "unused.zip", "--multiprocess", "2",
               "--port", "9300"])
    assert rc == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.endswith("&")]
    assert len(lines) == 2
    assert all("DL4J_TPU_" in l and "serve" in l for l in lines)
    assert "--port 9300" in lines[0] and "--port 9301" in lines[1]
    # the plan flags themselves are scrubbed from the worker argv
    assert "--multiprocess" not in lines[0]
