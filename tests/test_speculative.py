"""Speculative decoding + int8 paged KV cache correctness gates.

These are the parity gates the raw-speed arc hangs off: the n-gram
proposer and greedy acceptance mask are unit-proven, the int8 page
round-trip error bound from the quantize_pages docstring is verified
numerically, and the engine-level contract — greedy speculative (and
int8, and both together) emits a BIT-IDENTICAL stream to plain greedy
decode, with zero post-warmup retraces and every page returned to the
pool — is asserted end to end on the real GenerationEngine.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.ops.decode_attention import (dequantize_pages,
                                                     quantize_pages)
from deeplearning4j_tpu.serving import replay
from deeplearning4j_tpu.serving.buckets import BucketLattice
from deeplearning4j_tpu.serving.engine import GenerationEngine
from deeplearning4j_tpu.serving.speculative import (NgramProposer,
                                                    accept_greedy)
from deeplearning4j_tpu.telemetry import Recorder


# ------------------------------------------------------------- proposer

def test_ngram_proposer_mines_repeating_structure():
    """A history that repeats an n-gram proposes the tokens that
    followed its earlier occurrence — the prompt-lookup oracle."""
    p = NgramProposer(max_order=3)
    # ... 7 8 9 [5 6] 1 2 3 [5 6] -> the earlier [5 6] was followed by 1 2 3
    hist = [7, 8, 9, 5, 6, 1, 2, 3, 5, 6]
    assert p.propose(hist, 3) == [1, 2, 3]
    # continuation running off the end extends cyclically from the match
    assert p.propose([1, 2, 3, 1, 2, 3], 5) == [1, 2, 3, 1, 2]


def test_ngram_proposer_fallbacks():
    p = NgramProposer(max_order=3)
    # no repeat anywhere: order-0 guess repeats the last token
    assert p.propose([4, 9, 2], 3) == [2, 2, 2]
    assert p.propose([], 2) == [0, 0]
    assert p.propose([5], 0) == []
    # most RECENT precedent wins over an older one
    hist = [1, 2, 7, 7, 1, 2, 9, 9, 1, 2]
    assert p.propose(hist, 2) == [9, 9]
    with pytest.raises(ValueError):
        NgramProposer(max_order=0)


def test_accept_greedy_mask():
    """n_accepted = longest prefix of drafts matching the argmax before
    them; emitted = those argmaxes plus the bonus token ending the run,
    so every emitted token is an argmax given its true prefix."""
    # all drafts right: k-1 accepted, k emitted
    assert accept_greedy([5, 6, 7], [5, 6, 7, 8]) == (3, [5, 6, 7, 8])
    # first draft wrong: 0 accepted, bonus token m_0 still emitted
    assert accept_greedy([9, 6, 7], [5, 6, 7, 8]) == (0, [5])
    # middle rejection truncates the window there
    assert accept_greedy([5, 0, 7], [5, 6, 7, 8]) == (1, [5, 6])
    with pytest.raises(ValueError):
        accept_greedy([1, 2], [1, 2])  # k-1 drafts need k verify rows


# ---------------------------------------------------- int8 paged cache

def test_int8_page_roundtrip_error_bound():
    """quantize_pages promises |x - dequant(quant(x))| <= scale/2 per
    element, with scale = per-(row, page, head) maxabs / 127 — the
    symmetric-rounding bound, checked on adversarial magnitudes."""
    rng = np.random.default_rng(0)
    B, S, H, D, ps = 3, 32, 2, 8, 8
    x = rng.normal(0, 1, (B, S, H, D)).astype(np.float32)
    # mix in wildly different page magnitudes so scales actually vary
    x[:, :ps] *= 100.0
    x[:, ps:2 * ps] *= 1e-3
    codes, scales = quantize_pages(x, ps)
    assert codes.dtype == np.int8 and codes.shape == x.shape
    assert scales.shape == (B, S // ps, H)
    back = np.asarray(dequantize_pages(codes, scales, ps))
    err = np.abs(x - back).reshape(B, S // ps, ps, H, D)
    bound = np.asarray(scales)[:, :, None, :, None] / 2.0
    assert np.all(err <= bound + 1e-7)
    # re-quantizing the round-trip is exact: values already sit on the
    # int8 grid, so codes and scales are both fixed points
    codes2, scales2 = quantize_pages(back, ps)
    np.testing.assert_array_equal(np.asarray(codes), np.asarray(codes2))
    np.testing.assert_allclose(np.asarray(scales), np.asarray(scales2),
                               rtol=1e-6)


# --------------------------------------------- engine-level parity gate

_PROMPT_MIX = ((3, 2), (8, 5), (11, 1), (16, 8), (5, 3),
               (1, 4), (13, 2), (16, 1), (2, 6), (7, 8))


def _run_engine(net, k, kv_dtype):
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1,), seq_lens=(8, 16))
    eng = GenerationEngine(net, lat, slots=2, max_new_tokens=8,
                           page_size=8, recorder=rec,
                           speculative_k=k, kv_dtype=kv_dtype)
    eng.warmup()
    traced = eng.trace_count
    eng.start()
    rng = np.random.default_rng(11)
    outs = []
    for plen, olen in _PROMPT_MIX:
        out = eng.generate(rng.integers(0, 64, plen).astype(np.int32),
                           olen, timeout=60)
        assert len(out) == olen
        outs.append(list(out))
    # zero-retrace contract: the mixed stream adds no shapes, in plain,
    # speculative ([B, k] verify step), and quantized modes alike
    assert eng.trace_count == traced, "a shape escaped warmup"
    # rollback/teardown gate: every page is back in the pool
    pools = [e for e in rec.events if e.get("event") == "page_pool"]
    assert pools and pools[-1]["pages_in_use"] == 0
    assert max(p["pages_in_use"] for p in pools) > 0
    stats = eng.stats()
    eng.drain()
    return outs, stats, rec


def test_greedy_speculative_bit_identity():
    """The arc's headline gate: speculative greedy emits a token stream
    bit-identical to plain greedy decode — acceptance is a mask over
    verify rows, never a sampler."""
    net = replay._tiny_lm(24)
    base, s0, _ = _run_engine(net, 0, "f32")
    assert not s0["speculative"]["enabled"]

    spec, s1, rec1 = _run_engine(net, 4, "f32")
    assert spec == base
    sp = s1["speculative"]
    assert sp["enabled"] and sp["k"] == 4
    assert sp["verify_steps"] > 0
    # each verify step emits >= 1 token, so the headline floor is 1.0;
    # the n-gram proposer must beat it on this repeat-heavy tiny LM
    assert sp["accepted_tokens_per_step"] > 1.0
    assert 0.0 <= sp["draft_acceptance_rate"] <= 1.0
    drafts = [e for e in rec1.events if e.get("event") == "draft"]
    assert drafts and all(e["k"] == 4 for e in drafts)
    assert any(e.get("event") == "span" and e.get("name") == "verify_step"
               for e in rec1.events)


@pytest.mark.slow
def test_int8_arms_bit_identity():
    """int8 greedy — alone and stacked with speculation — matches the
    f32 baseline stream exactly: per-page scales keep enough precision
    to preserve every argmax at this scale. (Slow tier: three engine
    warmups; the committed SERVE_r04 parity rows re-check the same
    contract on every round, and the round-trip bound test above stays
    in tier-1.)"""
    net = replay._tiny_lm(24)
    base, _, _ = _run_engine(net, 0, "f32")
    q8, _, _ = _run_engine(net, 0, "int8")
    assert q8 == base
    both, s3, _ = _run_engine(net, 4, "int8")
    assert both == base
    assert s3["speculative"]["accepted_tokens_per_step"] > 1.0
