"""Sequence-parallel training (parallel/sequence_parallel.py): the stock
transformer with time sharded over the mesh must produce the same loss and
the same parameter updates as the unsharded model — ring attention,
position-offset encodings, and pmean'd gradients compose to an exact
redistribution of the computation, not an approximation."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.util.compat import shard_map
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.models.transformer import transformer_lm
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.sequence_parallel import (
    SequenceParallelTrainer,
)

VOCAB, T, B = 101, 32, 4


def _data(rng):
    toks = np.asarray(rng.integers(0, VOCAB, (B, T)), np.int32)
    return DataSet(toks, np.roll(toks, -1, axis=1))


def _lm(axis="", sgd=False):
    net = transformer_lm(vocab_size=VOCAB, d_model=32, n_heads=2,
                        n_layers=2, d_ff=64, max_length=T, seed=99,
                        seq_parallel_axis=axis)
    net.init()
    if sgd:
        # Adam's first step saturates updates at ±lr for ANY nonzero
        # gradient, so float reduction-order noise can flip signs; a
        # linear updater keeps the SP-vs-dense comparison meaningful
        import optax

        net.set_optimizer(optax.sgd(0.1))
    return net


@pytest.mark.parametrize("mesh_axes,data_axis", [
    ({"seq": 4}, None),
    ({"data": 2, "seq": 2}, "data"),
])
def test_sp_step_matches_unsharded(mesh_axes, data_axis):
    rng = np.random.default_rng(0)
    ds = _data(rng)

    ref = _lm(sgd=True)
    ref.fit(ListDataSetIterator([ds]), epochs=1)

    mesh = make_mesh(mesh_axes)
    sp = _lm("seq", sgd=True)
    trainer = SequenceParallelTrainer(sp, mesh, seq_axis="seq",
                                      data_axis=data_axis)
    trainer.fit(ListDataSetIterator([ds]), epochs=1)

    # same init seed, same batch, exact redistribution -> same params
    for name in ref.params:
        for k in ref.params[name]:
            np.testing.assert_allclose(
                np.asarray(sp.params[name][k]),
                np.asarray(ref.params[name][k]),
                rtol=2e-4, atol=2e-5,
                err_msg=f"{name}/{k} diverged under SP")


@pytest.mark.slow
def test_sp_loss_decreases_over_epochs():
    rng = np.random.default_rng(1)
    ds = _data(rng)
    mesh = make_mesh({"seq": 4})
    net = _lm("seq")
    trainer = SequenceParallelTrainer(net, mesh)
    trainer.fit(ListDataSetIterator([ds]), epochs=1)
    first = net.score_value
    trainer.fit(ListDataSetIterator([ds]), epochs=6)
    assert net.score_value < first


def test_sp_net_runs_dense_outside_shard_map():
    """An SP-configured net used outside shard_map (ordinary inference
    after SP training, a reloaded config) falls back to the dense path
    instead of crashing on an unbound axis."""
    rng = np.random.default_rng(2)
    ds = _data(rng)
    sp = _lm("seq")
    dense = _lm()
    dense.params = sp.params  # same seed; same params either way
    out_sp = np.asarray(sp.output(ds.features))
    out_dense = np.asarray(dense.output(ds.features))
    np.testing.assert_allclose(out_sp, out_dense, rtol=1e-5, atol=1e-6)


def test_sp_dropout_is_applied():
    """Dropout must not be silently disabled under SP: two different step
    keys give different losses on identical data when dropout > 0."""
    rng = np.random.default_rng(3)
    ds = _data(rng)
    mesh = make_mesh({"seq": 4})
    net = transformer_lm(vocab_size=VOCAB, d_model=32, n_heads=2,
                         n_layers=1, d_ff=64, max_length=T, seed=5,
                         dropout=0.5, seq_parallel_axis="seq")
    net.init()
    from deeplearning4j_tpu.parallel.sequence_parallel import (
        make_sp_train_step,
    )

    step = make_sp_train_step(net, mesh)
    x = jnp.asarray(ds.features)
    y = jnp.asarray(ds.labels)
    losses = {
        float(step(net.params, net.opt_state, net.state,
                   jax.random.PRNGKey(k), x, y)[3])
        for k in (0, 1, 2)
    }
    assert len(losses) == 3, f"dropout inert under SP: {losses}"


def test_sp_learned_posenc_overflow_raises():
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.util.compat import shard_map
    from deeplearning4j_tpu.nn.conf.layers import PositionalEncodingLayer
    from deeplearning4j_tpu.nn.layers.base import get_impl

    mesh = make_mesh({"seq": 4})
    conf = PositionalEncodingLayer(max_length=T // 2, n_features=8,
                                   learned=True, seq_parallel_axis="seq")
    impl = get_impl(conf)
    params = {"pe": jnp.zeros((T // 2, 8), jnp.float32)}

    def local(xl):
        y, _ = impl.apply(conf, params, {}, xl)
        return y

    with pytest.raises(ValueError, match="exceeds learned"):
        shard_map(local, mesh=mesh, in_specs=P(None, "seq", None),
                  out_specs=P(None, "seq", None))(
            jnp.zeros((2, T, 8), jnp.float32))


def test_sp_posenc_offsets_match_dense():
    """The encodings each shard adds are the global-position rows."""
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.util.compat import shard_map
    from deeplearning4j_tpu.nn.conf.layers import PositionalEncodingLayer
    from deeplearning4j_tpu.nn.layers.base import get_impl

    mesh = make_mesh({"seq": 4})
    conf_sp = PositionalEncodingLayer(max_length=T, n_features=8,
                                      seq_parallel_axis="seq")
    conf_dense = PositionalEncodingLayer(max_length=T, n_features=8)
    impl = get_impl(conf_sp)
    x = jnp.zeros((2, T, 8), jnp.float32)

    def local(xl):
        y, _ = impl.apply(conf_sp, {}, {}, xl)
        return y

    y_sp = shard_map(local, mesh=mesh, in_specs=P(None, "seq", None),
                     out_specs=P(None, "seq", None))(x)
    y_dense, _ = impl.apply(conf_dense, {}, {}, x)
    np.testing.assert_allclose(np.asarray(y_sp), np.asarray(y_dense),
                               rtol=1e-6)


def test_ring_flash_hop_matches_reference():
    """VERDICT r3 #4: kernel-legal local blocks (Tl % 128 == 0) run the
    Pallas flash kernel per hop with the two-way lse merge — forward and
    gradients match the unsharded reference (the lse cotangent folds into
    the kernel backward's delta term)."""
    from functools import partial

    import jax
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.ring_attention import (
        ring_attention,
        ring_self_attention,
        sequence_sharded_attention_reference,
    )

    mesh = make_mesh({"seq": 4})
    B, H, T, D = 2, 2, 512, 32  # Tl = 128: flash hop path
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
               for _ in range(3))
    for causal in (True, False):
        out = ring_self_attention(q, k, v, mesh, causal=causal)
        ref = sequence_sharded_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    spec = P(None, None, "seq", None)
    fn = shard_map(partial(ring_attention, axis_name="seq", causal=True),
                       mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
    g_ring = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                      (0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        sequence_sharded_attention_reference(q, k, v, causal=True) ** 2),
        (0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_sp_composes_with_model_axis():
    """VERDICT r3 #4: set_mesh accepts {data, seq, model} — the SP
    shard_map is manual over seq/data only, so Megatron TP placements on
    the model axis propagate GSPMD-auto; loss matches dense."""
    from deeplearning4j_tpu.datasets.api import DataSet

    V, T, B = 64, 16, 8
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, V, (B, T)), np.int32)
    labs = np.eye(V, dtype=np.float32)[np.roll(toks, -1, axis=1)]
    ds = DataSet(toks, labs)

    def build(sp):
        net = transformer_lm(vocab_size=V, d_model=16, n_heads=2,
                             n_layers=2, d_ff=32, max_length=T,
                             seq_parallel_axis=("seq" if sp else ""))
        net.init()
        return net

    dense = build(False)
    dense.fit(ds, epochs=3)
    sp = build(True)
    sp.set_mesh(make_mesh({"data": 2, "seq": 2, "model": 2}),
                axes={"data": "data", "seq": "seq", "model": "model"})
    sp.fit(ds, epochs=3)
    assert abs(float(dense.score_value) - float(sp.score_value)) < 2e-3


@pytest.mark.slow
def test_sp_train_step_runs_flash_hops(monkeypatch):
    """Full SP training with local blocks long enough for the Pallas
    flash hop path (Tl = 128): the other SP train tests use tiny T where
    the ring falls back to the einsum hop, so this is the only coverage
    of the kernel-in-ring path through the public set_mesh/fit API. A
    counting wrapper asserts the hop kernel actually ran (the einsum
    fallback is mathematically equivalent)."""
    import deeplearning4j_tpu.ops.flash_attention as fa

    calls = {"n": 0}
    orig = fa.flash_attention_lse

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(fa, "flash_attention_lse", counting)

    V2, T2, B2 = 64, 512, 2
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, V2, (B2, T2)), np.int32)
    labs = np.roll(toks, -1, 1).astype(np.int32)
    ds = DataSet(toks, labs)

    def build(sp):
        n = transformer_lm(vocab_size=V2, d_model=32, n_heads=2,
                           n_layers=2, d_ff=64, max_length=T2,
                           seq_parallel_axis=("seq" if sp else ""))
        n.init()
        return n

    dense = build(False)
    dense.fit(ds, epochs=2)
    sp = build(True)
    sp.set_mesh(make_mesh({"seq": 4, "data": 2}),
                axes={"seq": "seq", "data": "data"})
    calls["n"] = 0
    sp.fit(ds, epochs=2)
    assert calls["n"] > 0, "flash hop not taken inside the ring"
    assert abs(float(dense.score_value) - float(sp.score_value)) < 2e-3


def test_ring_chunked_hop_matches_reference():
    """r5: local blocks past MAX_FLASH_T run each ring hop through
    chunked_flash_attention_lse (tile loop + lse merge INSIDE the hop) —
    seq parallelism composes with single-chip chunking to n_shards x
    128k-token sequences. Tested by forcing hop_chunk at a small Tl so
    CPU interpret mode exercises the exact long-block code path."""
    from functools import partial

    import jax
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.ring_attention import (
        ring_attention,
        sequence_sharded_attention_reference,
    )

    mesh = make_mesh({"seq": 2})
    B, H, T, D = 2, 2, 512, 32  # Tl = 256, forced into 128-tiles per hop
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
               for _ in range(3))
    spec = P(None, None, "seq", None)
    for causal in (True, False):
        fn = shard_map(
            partial(ring_attention, axis_name="seq", causal=causal,
                    hop_chunk=128),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        out = fn(q, k, v)
        ref = sequence_sharded_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)
    fn = shard_map(
        partial(ring_attention, axis_name="seq", causal=True, hop_chunk=128),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    g_ring = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                      (0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(
        sequence_sharded_attention_reference(q, k, v, causal=True) ** 2),
        (0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_dropout_matches_single_chip_kernel():
    """r6 tentpole, ring leg: per-hop in-kernel dropout hashes GLOBAL
    coordinates, so a 4-shard ring drops exactly what the single-chip
    monolithic kernel at T = 4*Tl does — outputs match for the same rng
    on both the flash-hop path (Tl % 128 == 0) and, below, the einsum
    fallback against the host keep-mask oracle."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.ring_attention import ring_attention
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    mesh = make_mesh({"seq": 4})
    B, H, T, D = 1, 2, 512, 32  # Tl = 128: flash hop path
    rate = 0.2
    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
               for _ in range(3))
    key = jax.random.PRNGKey(17)
    spec = P(None, None, "seq", None)
    fn = shard_map(partial(ring_attention, axis_name="seq", causal=True,
                           dropout=rate, dropout_rng=key),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    out = fn(q, k, v)
    ref = flash_attention(q, k, v, causal=True, dropout=rate,
                          dropout_rng=key)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    # gradients flow through the dropout hops (lse merge + custom VJPs)
    g_ring = jax.grad(lambda q: jnp.sum(fn(q, k, v) ** 2))(q)
    g_ref = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, causal=True, dropout=rate, dropout_rng=key) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=2e-4)


def test_ring_dropout_einsum_fallback_matches_host_oracle():
    """Odd local blocks (Tl % 128 != 0) run the einsum fallback, whose
    jnp keep mask must be bit-identical to the kernels' counter-hash —
    checked against the dropout_keep_mask_host oracle at the global T."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.ring_attention import ring_attention
    from deeplearning4j_tpu.ops.flash_attention import (
        _step_seed,
        dropout_keep_mask_host,
    )

    mesh = make_mesh({"seq": 2})
    B, H, T, D = 2, 2, 16, 8  # Tl = 8: einsum path
    rate = 0.25
    rng = np.random.default_rng(1)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
               for _ in range(3))
    key = jax.random.PRNGKey(3)
    seed = int(np.asarray(_step_seed(key))[0, 0])
    spec = P(None, None, "seq", None)
    fn = shard_map(partial(ring_attention, axis_name="seq", causal=True,
                           dropout=rate, dropout_rng=key),
                   mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=False)
    out = fn(q, k, v)

    # dense reference applying the exact host keep mask (dense
    # semantics: dropout on the softmax output, l from undropped p)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(float(D))
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    keeps = np.stack([dropout_keep_mask_host(seed, b * H + h, T, rate)
                      for b in range(B) for h in range(H)]).reshape(
                          B, H, T, T)
    w = w * jnp.asarray(keeps, jnp.float32) / (1.0 - rate)
    ref = jnp.einsum("bhqk,bhkd->bhqd", w, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_monolithic_hop_tier_gates_head_dim():
    """ADVICE r5 #3: the ring's extended monolithic per-hop tier
    (MAX_FLASH_T < Tl <= MONOLITHIC_COMPILE_MAX) applies the same
    D <= 128 gate as supports_monolithic_fallback — a D=256 block near
    the compile ceiling raises with instructions instead of busting
    VMEM on-chip. Blocks inside the proven envelope keep any D."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.ring_attention import ring_attention

    mesh = make_mesh({"seq": 2})
    spec = P(None, None, "seq", None)

    def trace(Tl, D):
        q = jnp.zeros((1, 1, 2 * Tl, D), jnp.float32)
        fn = shard_map(partial(ring_attention, axis_name="seq",
                               causal=True),
                       mesh=mesh, in_specs=(spec, spec, spec),
                       out_specs=spec, check_vma=False)
        return jax.eval_shape(fn, q, q, q)

    # extended tier + D=256: rejected with the head_dim named
    with pytest.raises(ValueError, match="head_dim"):
        trace(8320, 256)
    # extended tier + D=128: accepted (pre-r5 behavior preserved)
    trace(8320, 128)
    # proven envelope + D=256: accepted (single-chip dispatch parity)
    trace(256, 256)
