"""One member of the elastic fleet driven by
`distributed.elastic.ElasticSupervisor` in tests/test_elastic.py.

Run: python tests/elastic_worker.py <checkpoint_dir> <out_dir>

The worker follows the elastic contract end to end: rendezvous via
`bootstrap.initialize()` (which honors injected delay-connect faults),
resume from the latest checkpoint BEFORE `set_mesh`, rebuild the global
mesh at whatever process count this generation has, and train to the
supervisor-announced step budget through `elastic.run_elastic_steps`
(per-step host checkpoints, kill/hang faults firing between steps, the
rescue path on a peer's death).

`batch_for_step` regenerates the SAME deterministic global batch for a
given step at any fleet size — each process feeds its `local_shard` —
so a kill-interrupted, re-formed N'=2 run must land on the same params
as an uninterrupted single-process run over the full batches
(tests/test_elastic.py asserts parity within the documented tolerance).
"""

import os
import sys

import numpy as np

# the global batch: 24 rows divide over 3, 2, or 1 processes and over
# the 6- or 4-device global meshes those fleets build (K=2 local devices)
GLOBAL_BATCH = 24


def batch_for_step(step: int):
    """The full deterministic global batch for one 1-based step."""
    from tests.cluster_worker import C, F

    rng = np.random.default_rng(1000 + step)
    x = rng.random((GLOBAL_BATCH, F), dtype=np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, GLOBAL_BATCH)]
    return x, y


def main() -> int:
    ckpt_dir, out_dir = sys.argv[1], sys.argv[2]

    from deeplearning4j_tpu.distributed import bootstrap, elastic

    total_steps = elastic.worker_total_steps()
    info = bootstrap.initialize(connect_timeout=60.0)
    pid = info["process_id"]
    print(f"rendezvous up: {info}", flush=True)

    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.distributed.global_mesh import (
        local_shard,
        spans_processes,
    )
    from tests.cluster_worker import build_net

    net = build_net()
    # the elastic re-plan: search the best placement for THIS
    # generation's fleet shape (rank-independent — every member derives
    # the identical winner and emits a placement_search event) instead
    # of hand-specifying the roles; the objective models the run's real
    # global batch
    from deeplearning4j_tpu.reshard.search import Objective

    mesh, axes, _search = elastic.searched_global_mesh(
        net, objective=Objective(global_batch=GLOBAL_BATCH))
    assert spans_processes(mesh), "mesh does not span processes"
    # restore THROUGH the portable resharding engine: the checkpoint may
    # have been written by a different fleet size (N=3 -> N'=2 re-form),
    # and the planner maps its recorded placement onto this generation's
    # mesh — each process reads only what its devices need, no full-tree
    # host gathers (tests/test_elastic.py asserts both from telemetry)
    start = net.resume_from(ckpt_dir, target_mesh=mesh)
    print(f"p{pid}: resuming from step {start}/{total_steps}", flush=True)
    net.set_mesh(mesh, axes=axes)

    def local_batch(step):
        x, y = batch_for_step(step)
        return DataSet(local_shard(x), local_shard(y))

    elastic.run_elastic_steps(net, local_batch, total_steps,
                              checkpoint_dir=ckpt_dir, checkpoint_every=1)

    assert net.iteration_count == total_steps
    if pid == 0:
        flat = np.asarray(net.params_flat())
        np.save(os.path.join(out_dir, "final_params.npy"), flat)
        with open(os.path.join(out_dir, "done.txt"), "w") as fh:
            fh.write(f"steps={net.iteration_count} "
                     f"n_processes={info['num_processes']}\n")
    print(f"p{pid}: finished at step {net.iteration_count}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
