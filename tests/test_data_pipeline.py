"""Tier-1 gate for the async sharded input pipeline (ISSUE 12):

- `data/prefetcher.Channel` — event-driven blocking (no polling
  timeouts), every shutdown path (EOS, producer error, consumer stop)
  proven to wake the blocked side, including the r6 drain hole (a
  producer dying against a full queue).
- `data/sharding.ShardAssignment` — the reconstruction invariant (the
  N processes' local index sets tile the global window exactly) and
  N→N' elastic bit-identity (the global batch sequence never depends on
  the process count).
- `data/pipeline.iter_prefetched` — order preservation, producer-error
  propagation into the step loop, the depth-0 synchronous fallback, the
  queue-depth knob resolution chain, and `input_wait` span emission.
- fit integration — the pipelined fit path produces BIT-identical
  params to the synchronous path on both containers (off-TPU), epoch
  reset determinism, and producer errors surfacing from `net.fit`.
"""

import os
import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.data.prefetcher import EOS, Channel, Prefetcher
from deeplearning4j_tpu.data.pipeline import (
    ShardedDataSetIterator,
    iter_prefetched,
    prefetch_depth,
    set_prefetch_depth,
)
from deeplearning4j_tpu.data.sharding import (
    ShardAssignment,
    epoch_permutation,
    local_rows,
    process_slice,
)
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.async_iterator import AsyncDataSetIterator
from deeplearning4j_tpu.datasets.iterators import (
    DataSetIterator,
    ListDataSetIterator,
)
from deeplearning4j_tpu.telemetry.recorder import Recorder

pytestmark = pytest.mark.data


# ------------------------------------------------------------ helpers
def make_datasets(n_batches=6, rows=4, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.random((rows, 3), dtype=np.float32) + i,
                    np.eye(2, dtype=np.float32)[rng.integers(0, 2, rows)])
            for i in range(n_batches)]


class FailingIterator(DataSetIterator):
    """Yields `ok` batches then raises on the next pull — the producer-
    death harness."""

    def __init__(self, datasets, fail_after):
        super().__init__()
        self._data = datasets
        self._fail_after = fail_after
        self._i = 0

    def has_next(self):
        return self._i < len(self._data)

    def next(self, num=None):
        if self._i >= self._fail_after:
            raise RuntimeError(f"record decode failed at batch {self._i}")
        ds = self._data[self._i]
        self._i += 1
        return self._apply_pre(ds)

    def reset(self):
        self._i = 0

    def batch(self):
        return self._data[0].num_examples()


def build_mln(seed=7):
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
        Updater,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.05)
        .updater(Updater.SGD)
        .list()
        .layer(DenseLayer(n_in=3, n_out=8, activation="tanh"))
        .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


# ------------------------------------------------------------- channel
def test_channel_fifo_and_eos():
    ch = Channel(depth=4)
    for i in range(3):
        assert ch.put(i)
    ch.close()
    assert [ch.get(), ch.get(), ch.get()] == [0, 1, 2]
    assert ch.get() is EOS
    assert ch.get() is EOS  # EOS is sticky


def test_channel_error_raised_after_buffered_items_drain():
    ch = Channel(depth=4)
    ch.put("a")
    ch.close(error=RuntimeError("boom"))
    assert ch.get() == "a"  # buffered items first
    with pytest.raises(RuntimeError, match="boom"):
        ch.get()
    assert ch.get() is EOS  # raised once, then EOS


def test_channel_stop_wakes_producer_blocked_on_full_buffer():
    """The r6 drain hole: a producer stuck against a full queue must be
    woken by the consumer's stop, not spin on a timeout."""
    ch = Channel(depth=1)
    assert ch.put(0)
    outcome = {}

    def producer():
        outcome["second_put"] = ch.put(1)  # blocks: buffer full

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()  # parked event-driven on the condition
    ch.stop()
    t.join(timeout=5)
    assert not t.is_alive()
    assert outcome["second_put"] is False  # told to exit, not retried
    assert ch.get() is EOS  # stopped channel yields nothing


def test_channel_get_blocks_until_put():
    ch = Channel(depth=2)
    got = {}

    def consumer():
        got["item"] = ch.get()

    t = threading.Thread(target=consumer, daemon=True)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()
    ch.put("late")
    t.join(timeout=5)
    assert got["item"] == "late"


def test_channel_rejects_nonpositive_depth():
    with pytest.raises(ValueError):
        Channel(depth=0)


# ---------------------------------------------------------- prefetcher
def test_prefetcher_transform_runs_on_producer_thread():
    seen = []

    def transform(x):
        seen.append(threading.current_thread())
        return x * 10

    pf = Prefetcher(iter(range(4)), depth=2, transform=transform)
    out = []
    while True:
        item = pf.get()
        if item is EOS:
            break
        out.append(item)
    assert out == [0, 10, 20, 30]
    assert all(t is not threading.main_thread() for t in seen)


def test_prefetcher_source_error_propagates_to_consumer():
    def source():
        yield 1
        raise ValueError("bad record")

    pf = Prefetcher(source, depth=2)
    assert pf.get() == 1
    with pytest.raises(ValueError, match="bad record"):
        pf.get()


def test_prefetcher_stop_joins_thread():
    pf = Prefetcher(iter(range(1000)), depth=1)
    assert pf.get() == 0
    assert pf.stop()
    assert not pf.alive


# ------------------------------------------------- async iterator shim
def test_async_iterator_underlying_error_propagates():
    it = AsyncDataSetIterator(FailingIterator(make_datasets(6), 2),
                              queue_size=2)
    assert it.next() is not None
    assert it.next() is not None
    with pytest.raises(RuntimeError, match="record decode failed"):
        it.has_next()


def test_async_iterator_reset_after_producer_error():
    """reset() must recover an iterator whose producer died mid-stream
    (the drain-immunity satellite)."""
    under = FailingIterator(make_datasets(6), 3)
    it = AsyncDataSetIterator(under, queue_size=1)
    it.next()
    with pytest.raises(RuntimeError):
        while it.has_next():
            it.next()
    under._fail_after = 99  # "fixed" source
    it.reset()
    count = 0
    while it.has_next():
        it.next()
        count += 1
    assert count == 6


def test_async_iterator_reset_with_producer_blocked_on_full_queue():
    data = make_datasets(8)
    it = AsyncDataSetIterator(ListDataSetIterator(data), queue_size=1)
    it.next()
    time.sleep(0.05)  # let the producer park on the full channel
    it.reset()
    got = []
    while it.has_next():
        got.append(float(it.next().features[0, 0]))
    assert got == [float(d.features[0, 0]) for d in data]


# ------------------------------------------------------------ sharding
def test_process_slice_validation():
    assert process_slice(8, 1, 2) == slice(4, 8)
    with pytest.raises(ValueError, match="do not split"):
        process_slice(9, 0, 2)
    with pytest.raises(ValueError, match="out of range"):
        process_slice(8, 2, 2)


def test_local_rows_matches_manual_split():
    x = np.arange(24).reshape(8, 3)
    np.testing.assert_array_equal(local_rows(x, 1, 4), x[2:4])
    np.testing.assert_array_equal(local_rows(x, 0, 1), x)


def test_epoch_permutation_keyed_off_seed_and_epoch_only():
    a = epoch_permutation(100, epoch=3, seed=11)
    assert (a == epoch_permutation(100, epoch=3, seed=11)).all()
    assert not (a == epoch_permutation(100, epoch=4, seed=11)).all()
    assert not (a == epoch_permutation(100, epoch=3, seed=12)).all()
    assert sorted(a.tolist()) == list(range(100))  # a true permutation


@pytest.mark.parametrize("n_procs", [1, 2, 4])
def test_shard_reconstruction_invariant(n_procs):
    """Concatenating the N processes' local windows in process order is
    exactly the global window — no example skipped or duplicated."""
    ref = ShardAssignment(96, 16, seed=5)
    for epoch in (0, 1):
        for step in range(ref.steps_per_epoch):
            parts = [
                ref.for_process(p, n_procs).local_indices(epoch, step)
                for p in range(n_procs)
            ]
            np.testing.assert_array_equal(
                np.concatenate(parts), ref.global_indices(epoch, step))


def test_shard_assignment_elastic_reform_bit_identity():
    """N→N' re-form: the global batch sequence is identical at every
    fleet size, so a run resumed at step s under N'=2 consumes exactly
    the windows an uninterrupted N=3 run would have."""
    n3 = [ShardAssignment(48, 12, process_index=p, process_count=3, seed=9)
          for p in range(3)]
    n2 = [ShardAssignment(48, 12, process_index=p, process_count=2, seed=9)
          for p in range(2)]
    for step in range(4):
        g3 = np.concatenate([a.local_indices(0, step) for a in n3])
        g2 = np.concatenate([a.local_indices(0, step) for a in n2])
        np.testing.assert_array_equal(g3, g2)
    # every epoch covers every example exactly once
    all_idx = np.concatenate(
        [n2[0].global_indices(0, s) for s in range(n2[0].steps_per_epoch)])
    assert sorted(all_idx.tolist()) == list(range(48))


def test_shard_assignment_rejects_bad_shapes():
    with pytest.raises(ValueError, match="exceeds"):
        ShardAssignment(8, 16)
    with pytest.raises(ValueError, match="do not split"):
        ShardAssignment(32, 9, process_index=0, process_count=2)


def test_sharded_iterator_walks_local_rows_deterministically():
    x = np.arange(64, dtype=np.float32).reshape(32, 2)
    y = np.eye(2, dtype=np.float32)[np.arange(32) % 2]
    its = [ShardedDataSetIterator(x, y, 8, process_index=p,
                                  process_count=2, seed=3)
           for p in range(2)]
    ref = ShardAssignment(32, 8, seed=3)
    for step in range(ref.steps_per_epoch):
        rows = np.concatenate([it.next().features for it in its])
        np.testing.assert_array_equal(
            rows, x[ref.global_indices(0, step)])
    assert not its[0].has_next()
    # reset() replays the SAME epoch; set_epoch re-keys it
    its[0].reset()
    np.testing.assert_array_equal(
        its[0].next().features,
        x[ref.global_indices(0, 0)[process_slice(8, 0, 2)]])
    its[0].set_epoch(1)
    np.testing.assert_array_equal(
        its[0].next().features,
        x[ShardAssignment(32, 8, process_index=0, process_count=2,
                          seed=3).local_indices(1, 0)])


# ------------------------------------------------------ iter_prefetched
def test_iter_prefetched_preserves_order_and_converts_off_thread():
    data = make_datasets(5)
    threads = []

    def convert(ds):
        threads.append(threading.current_thread())
        return float(ds.features[0, 0])

    out = [b for _ds, b in iter_prefetched(ListDataSetIterator(data),
                                           convert, depth=2)]
    assert out == [float(d.features[0, 0]) for d in data]
    assert all(t is not threading.main_thread() for t in threads)


def test_iter_prefetched_depth_zero_is_synchronous():
    data = make_datasets(3)
    threads = []

    def convert(ds):
        threads.append(threading.current_thread())
        return ds

    out = list(iter_prefetched(ListDataSetIterator(data), convert,
                               depth=0))
    assert len(out) == 3
    assert all(t is threading.main_thread() for t in threads)


def test_iter_prefetched_respects_async_supported_false():
    data = make_datasets(3)
    it = ListDataSetIterator(data)
    it.async_supported = lambda: False
    threads = []

    def convert(ds):
        threads.append(threading.current_thread())
        return ds

    assert len(list(iter_prefetched(it, convert, depth=4))) == 3
    assert all(t is threading.main_thread() for t in threads)


def test_iter_prefetched_propagates_convert_error():
    data = make_datasets(4)

    def convert(ds):
        if float(ds.features[0, 0]) >= 2.0:
            raise RuntimeError("globalize failed")
        return ds

    consumed = 0
    with pytest.raises(RuntimeError, match="globalize failed"):
        for _ds, _b in iter_prefetched(ListDataSetIterator(data), convert,
                                       depth=2):
            consumed += 1
    assert consumed >= 1  # batches before the failure were delivered


def test_iter_prefetched_records_input_wait_spans():
    rec = Recorder(path=None)
    data = make_datasets(4)
    list(iter_prefetched(ListDataSetIterator(data), lambda ds: ds,
                         depth=2, recorder=rec))
    spans = [e for e in rec.events
             if e.get("event") == "span" and e.get("name") == "input_wait"]
    # one span per dequeue INCLUDING the EOS dequeue
    assert len(spans) == 5
    assert all(s["pipelined"] for s in spans)
    assert all("buffered" in s for s in spans)
    sync_rec = Recorder(path=None)
    list(iter_prefetched(ListDataSetIterator(data), lambda ds: ds,
                         depth=0, recorder=sync_rec))
    sync_spans = [e for e in sync_rec.events
                  if e.get("event") == "span"
                  and e.get("name") == "input_wait"]
    assert len(sync_spans) == 4
    assert not any(s["pipelined"] for s in sync_spans)
    # the happy path is anomaly-free: the fleet-timeline detector over
    # BOTH arms' telemetry finds no input_wait spike (ISSUE 15 — the
    # INPUT replay's zero-anomaly gate; the sync arm's whole-conversion
    # spans are exempt by design)
    from deeplearning4j_tpu.telemetry import trace as trace_mod

    for r in (rec, sync_rec):
        findings = trace_mod.detect_anomalies(
            trace_mod.timeline_from_events(r.events))
        assert findings == [], findings


def test_prefetch_depth_resolution_chain(monkeypatch):
    assert prefetch_depth(5) == 5
    prev = set_prefetch_depth(3)
    try:
        assert prefetch_depth() == 3
        assert prefetch_depth(1) == 1  # explicit arg wins
    finally:
        set_prefetch_depth(prev)
    monkeypatch.setenv("DL4J_TPU_PREFETCH_DEPTH", "7")
    assert prefetch_depth() == 7
    monkeypatch.setenv("DL4J_TPU_PREFETCH_DEPTH", "nope")
    with pytest.raises(ValueError, match="not an integer"):
        prefetch_depth()


# ------------------------------------------------------ fit integration
def test_pipelined_fit_bit_identical_to_sync_mln():
    """The acceptance determinism gate: pipelined and synchronous fit
    produce bit-identical parameters (same conversion order, same rng
    stream — the pipeline only moves WHERE conversion runs)."""
    data = make_datasets(6, seed=1)
    prev = set_prefetch_depth(0)
    try:
        sync_net = build_mln()
        sync_net.fit(ListDataSetIterator(list(data)), epochs=3)
        set_prefetch_depth(2)
        pipe_net = build_mln()
        pipe_net.fit(ListDataSetIterator(list(data)), epochs=3)
    finally:
        set_prefetch_depth(prev)
    a, b = sync_net.params_flat(), pipe_net.params_flat()
    np.testing.assert_array_equal(a, b)
    assert sync_net.iteration_count == pipe_net.iteration_count == 18


def test_pipelined_fit_bit_identical_to_sync_graph():
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    def build():
        conf = (NeuralNetConfiguration.builder()
                .seed(11).learning_rate(0.05)
                .graph_builder()
                .add_inputs("in")
                .add_layer("h", DenseLayer(n_in=3, n_out=8,
                                           activation="tanh"), "in")
                .add_layer("out", OutputLayer(n_in=8, n_out=2,
                                              activation="softmax",
                                              loss_function="mcxent"), "h")
                .set_outputs("out")
                .build())
        return ComputationGraph(conf).init()

    data = make_datasets(5, seed=2)
    prev = set_prefetch_depth(0)
    try:
        sync_net = build()
        sync_net.fit(ListDataSetIterator(list(data)), epochs=2)
        set_prefetch_depth(3)
        pipe_net = build()
        pipe_net.fit(ListDataSetIterator(list(data)), epochs=2)
    finally:
        set_prefetch_depth(prev)
    np.testing.assert_array_equal(sync_net.params_flat(),
                                  pipe_net.params_flat())


def test_fit_surfaces_producer_error():
    net = build_mln()
    with pytest.raises(RuntimeError, match="record decode failed"):
        net.fit(FailingIterator(make_datasets(6, seed=3), 2), epochs=1)
    # the net consumed the batches before the failure
    assert net.iteration_count == 2


# ------------------------------------------------------- bench harness
def test_bench_worker_structure_single_process():
    """The input-pipeline bench core, off-fleet and fast: both
    workloads x both arms run through the stock fit path, the result
    carries every headline field, and the steady-state wait
    percentiles come from the expected span count."""
    from deeplearning4j_tpu.data.bench_worker import run_bench

    r = run_bench(steps=3, repeats=1, input_bound_passes=1,
                  input_bound_io_s=0.002, compute_bound_passes=1,
                  compute_bound_io_s=0.0)
    assert r["n_processes"] == 1 and r["depth"] == 2
    for workload in ("input_bound", "compute_bound"):
        w = r[workload]
        assert w["speedup"] > 0
        assert len(w["sync_s"]) == len(w["pipelined_s"]) == 1
        assert w["ratio_spread"][0] <= w["speedup"] <= w["ratio_spread"][1]
        assert w["input_wait_p99_ms"] >= w["input_wait_p50_ms"] >= 0
        # steps+1 spans per repeat minus the dropped cold dequeue
        assert w["n_wait_spans"] == 3


def test_committed_input_artifact_parses_and_gates():
    """The committed INPUT_r01 artifact round-trips through the
    artifact parser and benchdiff: self-diff is green (exit 0), and a
    synthetic input_wait blow-up or speedup collapse trips the gate
    (exit 1) — the 'gated via benchdiff' acceptance wiring."""
    import importlib.util
    import json

    from deeplearning4j_tpu.telemetry import artifact as artifact_mod

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "benchdiff", os.path.join(root, "tools", "benchdiff.py"))
    benchdiff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(benchdiff)
    path = os.path.join(root, "INPUT_r01.json")
    lines = artifact_mod.load(path)
    assert lines["input_pipeline_speedup"]["value"] > 1.0
    assert lines["input_pipeline_input_wait_p99_ms"]["value"] < 1.0
    assert benchdiff.main([path, path]) == 0
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        worse_path = os.path.join(td, "INPUT_worse.json")
        with open(path) as fh, open(worse_path, "w") as out:
            for raw in fh:
                line = json.loads(raw)
                if line.get("metric") == "input_pipeline_input_wait_p99_ms":
                    line["value"] = 50.0
                out.write(json.dumps(line) + "\n")
        assert benchdiff.main([path, worse_path]) == 1


@pytest.mark.slow
def test_input_pipeline_fleet_bench_runs_at_2x4():
    """The reduced 2x4 fleet bench end to end: both processes exit
    clean, p0 prints the RESULT line, and the compute-bound steady
    state shows no starvation (p99 well under the measured step
    time)."""
    import json
    import subprocess
    import sys as _sys

    from deeplearning4j_tpu.distributed.launcher import launch_local

    overrides = json.dumps({"steps": 4, "repeats": 1,
                            "input_bound_io_s": 0.02})
    results = launch_local(
        [_sys.executable, "-m", "deeplearning4j_tpu.data.bench_worker",
         overrides],
        n_processes=2, local_device_count=4, timeout=420.0)
    assert all(r.returncode == 0 for r in results), \
        "\n".join(r.output[-1500:] for r in results)
    payload = None
    for line in results[0].lines:
        if line.startswith("RESULT "):
            payload = json.loads(line[len("RESULT "):])
    assert payload is not None
    assert payload["n_processes"] == 2
    cb = payload["compute_bound"]
    assert cb["input_wait_p99_ms"] < cb["sync_step_ms"] / 2


def test_fit_epoch_reset_determinism():
    """Each epoch re-walks the iterator through a FRESH pipeline
    generation; two one-epoch fits == one two-epoch fit, bitwise."""
    data = make_datasets(4, seed=4)
    net_a = build_mln(seed=13)
    net_a.fit(ListDataSetIterator(list(data)), epochs=2)
    net_b = build_mln(seed=13)
    net_b.fit(ListDataSetIterator(list(data)), epochs=1)
    net_b.fit(ListDataSetIterator(list(data)), epochs=1)
    # identical batch sequence; rng streams match because fit draws one
    # key per step regardless of the epoch split
    np.testing.assert_array_equal(net_a.params_flat(),
                                  net_b.params_flat())
