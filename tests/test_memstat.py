"""Memory-ledger + cost-book unit tests (telemetry/memstat.py,
telemetry/costbook.py): subsystem attribution and the activation
residual, sampler cadence/rate-limit/no-op contracts, the compiled-cost
harvest off a warmed jit (with the zero-retrace guarantee the serving
gates freeze), and the predicted-vs-measured reconcile loop."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.telemetry import NullRecorder, Recorder
from deeplearning4j_tpu.telemetry import costbook as costbook_mod
from deeplearning4j_tpu.telemetry import memstat as memstat_mod
from deeplearning4j_tpu.telemetry.costbook import CostBook
from deeplearning4j_tpu.telemetry.memstat import (
    MemoryLedger,
    MemorySampler,
    sampler_for_net,
)

pytestmark = pytest.mark.telemetry


# ------------------------------------------------------------------ ledger

def test_tree_bytes_sums_array_leaves():
    tree = {"w": np.zeros((4, 8), dtype=np.float32),
            "b": np.zeros(8, dtype=np.float32),
            "meta": "not-an-array"}
    assert memstat_mod.tree_bytes(tree) == 4 * 8 * 4 + 8 * 4


def test_ledger_rejects_unknown_subsystem():
    with pytest.raises(ValueError, match="unknown ledger subsystem"):
        MemoryLedger().register("heap", lambda: {})


def test_ledger_attribution_and_activation_residual():
    params = {"w": np.zeros(100, dtype=np.float32)}   # 400 B
    opt = {"m": np.zeros(25, dtype=np.float32)}       # 100 B
    ledger = (MemoryLedger()
              .register("params", lambda: params)
              .register("opt_state", opt))  # plain tree registers too
    assert ledger.attributed() == {"params": 400, "opt_state": 100}
    # whatever the sources miss is the activation envelope
    assert ledger.breakdown(1000) == {
        "params": 400, "opt_state": 100, "activations": 500}
    # an over-attributed snapshot clamps the residual at zero
    assert ledger.breakdown(300)["activations"] == 0


def test_ledger_residual_moves_to_other_when_activations_registered():
    acts = {"a": np.zeros(10, dtype=np.float32)}      # 40 B
    out = MemoryLedger().register("activations", lambda: acts) \
                        .breakdown(100)
    assert out == {"activations": 40, "other": 60}


def test_ledger_source_tracks_replacement_and_failure_is_zero():
    box = {"tree": np.zeros(10, dtype=np.float32)}
    ledger = MemoryLedger().register("params", lambda: box["tree"])
    assert ledger.attributed()["params"] == 40
    box["tree"] = np.zeros(20, dtype=np.float32)  # hot-swap: no re-register
    assert ledger.attributed()["params"] == 80

    def boom():
        raise RuntimeError("source died")

    ledger.register("kv_pages", boom)
    assert ledger.attributed() == {"params": 80, "kv_pages": 0}


# ----------------------------------------------------------------- sampler

def test_sampler_disabled_under_null_recorder():
    s = MemorySampler(NullRecorder(), mem_every=1)
    assert not s.enabled
    assert s.sample("x") == {}
    assert s.on_step(0) == {}
    assert s.maybe_sample("x") == {}


def test_sample_emits_ledger_annotated_memory_event():
    rec = Recorder(path=None)
    keep = jnp.zeros((16, 16), dtype=jnp.float32)  # pin a live array
    ledger = MemoryLedger().register("params", lambda: keep)
    s = MemorySampler(rec, ledger, mem_every=1)
    ev = s.sample("test", iteration=7)
    assert ev["event"] == "memory" and ev["source"] == "test"
    assert ev["iteration"] == 7
    assert ev["live_array_bytes"] >= keep.nbytes
    assert ev["ledger"]["params"] == keep.nbytes
    assert ev["ledger_total_bytes"] == sum(ev["ledger"].values())
    assert ev["live_array_count"] >= 1
    # CPU backends expose no memory_stats: devices dict stays empty
    for stats in ev["devices"].values():
        assert stats.get("bytes_limit", 0) >= 0
    # cached surfaces for the scrape path
    assert s.last["live_array_bytes"] == ev["live_array_bytes"]
    assert s.peak_live_bytes == ev["live_array_bytes"]


def test_on_step_cadence_is_modulo_mem_every():
    rec = Recorder(path=None)
    s = MemorySampler(rec, mem_every=3)
    hits = [i for i in range(7) if s.on_step(i)]
    assert hits == [0, 3, 6]
    assert all(e["event"] == "memory" and e["source"] == "fit"
               for e in rec.events if e["event"] == "memory")
    # cadence off: one modulo, zero sampling
    off = MemorySampler(rec, mem_every=0)
    assert off.on_step(0) == {} and off.on_step(3) == {}


def test_mem_every_reads_env_and_tolerates_garbage(monkeypatch):
    monkeypatch.setenv(memstat_mod.ENV_MEM_EVERY, "5")
    assert MemorySampler(Recorder(path=None)).mem_every == 5
    monkeypatch.setenv(memstat_mod.ENV_MEM_EVERY, "banana")
    assert MemorySampler(Recorder(path=None)).mem_every == 0
    monkeypatch.delenv(memstat_mod.ENV_MEM_EVERY)
    assert MemorySampler(Recorder(path=None)).mem_every == 0


def test_maybe_sample_rate_limits_scrape_storms():
    rec = Recorder(path=None)
    s = MemorySampler(rec, min_interval_s=3600.0, mem_every=1)
    assert s.maybe_sample("stats_tick")  # first tick samples
    assert s.maybe_sample("stats_tick") == {}  # storm absorbed
    assert sum(1 for e in rec.events if e["event"] == "memory") == 1
    eager = MemorySampler(rec, min_interval_s=0.0, mem_every=1)
    assert eager.maybe_sample("t1") and eager.maybe_sample("t2")


def test_sampler_thread_starts_and_stops_cleanly():
    s = MemorySampler(Recorder(path=None), mem_every=1)
    s.start(interval_s=3600.0)
    thread = s._thread
    assert thread is not None and thread.daemon
    s.stop()
    assert s._thread is None and not thread.is_alive()
    # NullRecorder never spawns the thread at all
    null = MemorySampler(NullRecorder()).start(interval_s=0.001)
    assert null._thread is None


def test_sampler_for_net_caches_per_recorder():
    class Net:
        params = {"w": np.zeros(8, dtype=np.float32)}
        opt_state = {"m": np.zeros(2, dtype=np.float32)}

    net = Net()
    rec = Recorder(path=None)
    s1 = sampler_for_net(net, rec)
    assert sampler_for_net(net, rec) is s1  # cached on the net
    assert s1.ledger.attributed() == {"params": 32, "opt_state": 8}
    rec2 = Recorder(path=None)
    s2 = sampler_for_net(net, rec2)  # new recorder: rebuilt
    assert s2 is not s1 and s2.recorder is rec2


# --------------------------------------------------------------- cost book

def _warm_jit():
    """A warmed jit wrapper with a host-side trace counter."""
    calls = {"n": 0}

    @jax.jit
    def f(x):
        calls["n"] += 1
        return (x @ x.T).sum()

    x = jnp.ones((8, 8), dtype=jnp.float32)
    f(x).block_until_ready()  # warm: populates the jaxpr + exec caches
    return f, x, calls


def test_harvest_pulls_xla_cost_and_memory_analyses():
    f, x, calls = _warm_jit()
    fields = costbook_mod.harvest(f, x)
    assert fields["flops"] > 0
    assert fields["bytes_accessed"] > 0
    assert "peak_temp_bytes" in fields
    # the zero-retrace guarantee: lower() after the warm call is a
    # jaxpr-cache hit — the traced fn body ran exactly once
    assert calls["n"] == 1


def test_costbook_records_once_per_entry_shape():
    rec = Recorder(path=None)
    book = CostBook(rec)
    f, x, _ = _warm_jit()
    ev = book.record("forward", [8, 8], f, (x,))
    assert ev["event"] == "cost" and ev["entry"] == "forward"
    assert ev["shape"] == [8, 8] and ev["flops"] > 0
    # dedup: a respawn re-warm emits nothing
    assert book.record("forward", [8, 8], f, (x,)) == {}
    assert sum(1 for e in rec.events if e["event"] == "cost") == 1
    # a new shape key is a new book entry
    assert book.record("forward", [8, 16], f, (x,))["shape"] == [8, 16]
    assert book.record("forward", [8, 8], f, (x,), ) == {}
    assert len(book.entries()) == 2


def test_costbook_disabled_and_flops_lookups():
    assert CostBook(NullRecorder()).record("e", [1], None, ()) == {}
    book = CostBook(Recorder(path=None))
    f, x, _ = _warm_jit()
    book.record("forward", [8, 8], f, (x,))
    book.record("fit_scanned", [2, 4], f, (x,))
    per_shape = book.flops("forward", [8, 8])
    assert per_shape > 0
    assert book.flops("forward") == per_shape
    assert book.flops() == pytest.approx(
        per_shape + book.flops("fit_scanned"))
    assert book.flops("forward", [9, 9]) == 0.0
    assert book.peak_temp_bytes() >= 0


def test_mfu_is_clamped_and_guarded():
    assert CostBook.mfu(1e12, 1.0, 1e12) == 1.0
    assert CostBook.mfu(5e11, 1.0, 1e12) == 0.5
    assert CostBook.mfu(1e15, 0.001, 1e12) == 1.0  # clamped at 1
    assert CostBook.mfu(0.0, 1.0, 1e12) == 0.0
    assert CostBook.mfu(1e12, 0.0, 1e12) == 0.0
    assert CostBook.mfu(1e12, 1.0, 0.0) == 0.0


def test_peak_flops_matches_device_kind_substring():
    assert costbook_mod.peak_flops("TPU v4") == 275e12
    assert costbook_mod.peak_flops("TPU v5p pod") == 459e12
    assert costbook_mod.peak_flops("cpu") == costbook_mod.DEFAULT_PEAK_FLOPS
    assert costbook_mod.peak_flops(None) == costbook_mod.DEFAULT_PEAK_FLOPS


# --------------------------------------------------------------- reconcile

def test_reconcile_emits_typed_cost_drift_event():
    rec = Recorder(path=None)
    ev = costbook_mod.reconcile(rec, 1000, measured_bytes=32000,
                                source="placement", grid="2x2")
    assert ev["event"] == "cost_drift"
    assert ev["predicted_bytes"] == 1000 and ev["measured_bytes"] == 32000
    assert ev["ratio"] == pytest.approx(32.0)
    assert ev["factor"] == costbook_mod.DEFAULT_DRIFT_FACTOR
    assert ev["source"] == "placement" and ev["grid"] == "2x2"


def test_reconcile_measures_live_arrays_off_tpu():
    keep = jnp.zeros((32, 32), dtype=jnp.float32)
    ev = costbook_mod.reconcile(Recorder(path=None), 10_000)
    assert ev["measured_bytes"] >= keep.nbytes  # live-array fallback
    assert ev["ratio"] > 0


def test_reconcile_skips_null_recorder_and_empty_prediction():
    assert costbook_mod.reconcile(NullRecorder(), 1000,
                                  measured_bytes=1) == {}
    assert costbook_mod.reconcile(Recorder(path=None), 0,
                                  measured_bytes=1) == {}


def test_costbook_record_is_thread_safe_single_emit():
    """Concurrent warmups of the same (entry, shape) — the D002-shaped
    race — emit exactly one cost event."""
    rec = Recorder(path=None)
    book = CostBook(rec)
    f, x, _ = _warm_jit()
    results = []

    def worker():
        results.append(book.record("forward", [8, 8], f, (x,)))

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(1 for r in results if r) <= 1
    assert sum(1 for e in rec.events if e["event"] == "cost") == 1
