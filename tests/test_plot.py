"""t-SNE tests: exact (device) and Barnes-Hut (host SpTree)."""

import numpy as np

from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def _two_clusters(rng, per=20, dim=10, sep=8.0):
    a = rng.normal(size=(per, dim)) + sep
    b = rng.normal(size=(per, dim)) - sep
    x = np.concatenate([a, b]).astype(np.float32)
    labels = np.repeat([0, 1], per)
    return x, labels


def _separation(y, labels):
    """Ratio of between-cluster distance to mean within-cluster spread."""
    c0, c1 = y[labels == 0], y[labels == 1]
    between = np.linalg.norm(c0.mean(0) - c1.mean(0))
    within = (c0.std() + c1.std()) / 2 + 1e-9
    return between / within


class TestExactTsne:
    def test_separates_clusters(self, rng):
        x, labels = _two_clusters(rng)
        ts = Tsne(max_iter=300, perplexity=10.0, learning_rate=100.0, seed=0)
        y = ts.calculate(x, 2)
        assert y.shape == (40, 2)
        assert np.isfinite(y).all()
        assert _separation(y, labels) > 2.0

    def test_kl_decreases(self, rng):
        x, _ = _two_clusters(rng, per=15)
        ts = Tsne(max_iter=400, perplexity=8.0, learning_rate=100.0,
                  stop_lying_iteration=100, seed=1)
        ts.calculate(x, 2)
        h = ts.kl_divergences if hasattr(ts, "kl_divergences") else ts.kl_history
        # after exaggeration stops (iter 100 → from the 2nd of the 50-spaced
        # samples on) KL should be lower at the end than right after
        assert h[-1] < h[2]


class TestBarnesHutTsne:
    def test_separates_clusters(self, rng):
        x, labels = _two_clusters(rng, per=16, dim=8)
        bh = BarnesHutTsne(max_iter=150, perplexity=5.0, theta=0.5,
                           learning_rate=100.0, stop_lying_iteration=50,
                           momentum_switch=50, seed=0)
        y = bh.fit(x, 2)
        assert y.shape == (32, 2)
        assert np.isfinite(y).all()
        assert _separation(y, labels) > 2.0
        assert bh.get_data() is y
