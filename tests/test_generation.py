"""Tier-1 gate for autoregressive generation serving (ISSUE 11):
decode-vs-full-forward parity (the incremental step IS the forward),
chunked-prefill parity, the page-pool accounting contract (exhaustion
queues or refuses, never crashes), the zero-retrace promise across a
mixed prompt/output-length replay, decode-step cost independent of
prompt length (telemetry span timings), and the generation scoreboard
reconstruction behind tools/trafficreplay.py --generate."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.serving import replay
from deeplearning4j_tpu.serving.batcher import DecodeSlots, GenRequest
from deeplearning4j_tpu.serving.buckets import BucketLattice
from deeplearning4j_tpu.serving.engine import (GenerationEngine,
                                               QueueFullError)
from deeplearning4j_tpu.serving.kvcache import (CachePlan, PagePool,
                                                pages_for, quantize)
from deeplearning4j_tpu.serving.server import ServingServer
from deeplearning4j_tpu.telemetry import Recorder

pytestmark = pytest.mark.serving


def _greedy_full_forward(net, prompt, k):
    """Reference decode: argmax over k FULL-sequence forwards."""
    toks = [int(t) for t in prompt]
    out = []
    for _ in range(k):
        probs = np.asarray(net.output(np.asarray(toks, np.int32)[None, :]))
        nxt = int(np.argmax(probs[0, -1]))
        out.append(nxt)
        toks.append(nxt)
    return out


def _greedy_incremental(net, prompt, k, *, capacity=32, bucket=8,
                        chunk=None):
    """Incremental decode: one prefill (optionally chunked) + k-1
    single-token steps through the container's decode entries."""
    import jax

    prefill = jax.jit(net.prefill_fn())
    step = jax.jit(net.incremental_decode_fn())
    cache = net.init_kv_cache(1, capacity)
    L = len(prompt)
    starts = ([0] if chunk is None
              else list(range(0, L, chunk)))
    tok = None
    for s in starts:
        n_real = min((chunk or L), L - s)
        Tb = chunk if (chunk and n_real == chunk) else max(
            bucket, 1 << (n_real - 1).bit_length())
        tokens = np.zeros((1, Tb), np.int32)
        tokens[0, :n_real] = prompt[s:s + n_real]
        kmask = np.zeros((1, Tb), np.float32)
        kmask[0, :n_real] = 1.0
        probs, cache = prefill(net.params, net.state, cache, tokens,
                               kmask, np.zeros(1, np.int32),
                               np.asarray([s], np.int32),
                               np.asarray([n_real - 1], np.int32))
        tok = int(np.argmax(np.asarray(probs)[0]))
    out = [tok]
    pos = L
    for _ in range(k - 1):
        probs, cache = step(net.params, net.state, cache,
                            np.asarray([tok], np.int32),
                            np.asarray([pos], np.int32))
        tok = int(np.argmax(np.asarray(probs)[0]))
        out.append(tok)
        pos += 1
    return out, np.asarray(probs)[0]


# ------------------------------------------------------ decode parity

def test_incremental_decode_matches_full_forward_graph_lm():
    """THE tentpole property: greedy decode of K tokens from the
    incremental step (prefill + KV-cache decode) matches argmax over K
    full-sequence forwards — same tokens, probs at atol 1e-5."""
    net = replay._tiny_lm(32)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 64, 6).astype(np.int32)
    k = 6
    ref = _greedy_full_forward(net, prompt, k)
    inc, last_probs = _greedy_incremental(net, prompt, k)
    assert inc == ref
    # the final step's probs match the full forward's last row
    toks = list(prompt) + ref
    full = np.asarray(net.output(np.asarray(toks[:-1], np.int32)[None]))
    np.testing.assert_allclose(last_probs, full[0, -1], atol=1e-5)


def test_chunked_prefill_matches_single_shot():
    """A long prompt prefilled in bucket-shaped chunks (the interleave
    unit) fills the cache identically to one-shot prefill: the decode
    that follows produces the same tokens."""
    net = replay._tiny_lm(32)
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, 64, 13).astype(np.int32)
    one_shot, _ = _greedy_incremental(net, prompt, 5, bucket=16)
    chunked, _ = _greedy_incremental(net, prompt, 5, chunk=8)
    ref = _greedy_full_forward(net, prompt, 5)
    assert one_shot == ref
    assert chunked == ref


def test_incremental_decode_matches_full_forward_mln():
    """Both containers carry the contract: a sequential MultiLayerNetwork
    transformer stack decodes incrementally to the same greedy tokens
    as its full forward."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import (EmbeddingLayer,
                                                   LayerNormalization,
                                                   PositionalEncodingLayer,
                                                   RnnOutputLayer,
                                                   SelfAttentionLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(9).list()
            .layer(EmbeddingLayer(n_in=32, n_out=16,
                                  activation="identity", has_bias=False))
            .layer(PositionalEncodingLayer(max_length=32, n_features=16))
            .layer(SelfAttentionLayer(n_in=16, n_out=16, n_heads=2,
                                      causal=True, activation="identity"))
            .layer(LayerNormalization(n_in=16, n_out=16))
            .layer(RnnOutputLayer(n_in=16, n_out=32, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, 32, 5).astype(np.int32)
    ref = _greedy_full_forward(net, prompt, 4)
    inc, _ = _greedy_incremental(net, prompt, 4, capacity=16)
    assert inc == ref


def test_non_causal_attention_is_rejected():
    from deeplearning4j_tpu.nn.decode import make_decode_fn
    from deeplearning4j_tpu.models.transformer import transformer_lm

    net = transformer_lm(vocab_size=32, d_model=16, n_heads=2,
                        n_layers=1, d_ff=16, max_length=8)
    for v in net.conf.vertices.values():
        lc = getattr(v, "layer", None)
        if lc is not None and hasattr(lc, "causal"):
            lc.causal = False
    net.init()
    with pytest.raises(ValueError, match="cannot stream"):
        make_decode_fn(net)


def test_prefill_bucket_set_is_lattice_owned():
    """The prefill warmup set lives on the lattice: every seq bucket up
    to the chunk, and a chunk off the lattice is rejected (an unwarmed
    chunk shape would be a guaranteed mid-traffic retrace)."""
    lat = BucketLattice(batch_sizes=(1,), seq_lens=(8, 16, 32))
    assert lat.prefill_buckets(16) == [8, 16]
    assert lat.prefill_buckets(32) == [8, 16, 32]
    with pytest.raises(ValueError, match="lattice seq bucket"):
        lat.prefill_buckets(12)
    with pytest.raises(ValueError, match="sequence lattice"):
        BucketLattice(batch_sizes=(1, 2)).prefill_buckets(8)


# ----------------------------------------------------- page accounting

def test_page_math_quantizes_to_grid():
    assert pages_for(0, 16) == 0
    assert pages_for(1, 16) == 1
    assert pages_for(16, 16) == 1
    assert pages_for(17, 16) == 2
    assert quantize(17, 16) == 32
    plan = CachePlan(max_seq_bucket=32, max_new_tokens=16, n_slots=4,
                     page_size=16)
    assert plan.capacity == 48 and plan.pages_per_slot == 3
    assert plan.pool_pages == 12
    assert plan.request_pages(8, 4) == 1
    assert plan.request_pages(32, 16) == 3


def test_page_pool_reserve_release_occupancy():
    pool = PagePool(4, page_size=8)
    assert pool.try_reserve(3)
    assert not pool.try_reserve(2)  # all-or-nothing, no partial grant
    assert pool.try_reserve(1)
    assert pool.occupancy == 1.0 and pool.peak_occupancy == 1.0
    pool.release(3)
    assert pool.in_use == 1
    assert pool.peak_in_use == 4  # high-water mark survives release
    with pytest.raises(ValueError, match="double release"):
        pool.release(2)


def test_decode_slots_state_machine():
    slots = DecodeSlots(2)
    assert slots.free_index() == 0 and not slots.busy()
    r1 = GenRequest(tokens=np.arange(4), max_new_tokens=2, t_enqueue=0.0)
    r1.t_admitted = 1.0
    s1 = slots.admit(0, r1, pages=2)
    r2 = GenRequest(tokens=np.arange(6), max_new_tokens=2, t_enqueue=0.0)
    r2.t_admitted = 2.0
    slots.admit(1, r2, pages=2)
    assert slots.free_index() is None
    # oldest-first prefill; a slot starts decoding once its prompt is in
    assert slots.next_prefill() == 0
    s1.start = 4
    assert slots.next_prefill() == 1
    assert slots.decoding() == [0]
    r1.emitted = [1, 2]  # budget spent: no longer decoding
    assert slots.decoding() == []
    assert slots.release(0) == 2
    assert slots.free_index() == 0


def test_pool_exhaustion_queues_then_503_never_crashes():
    """The acceptance failure mode: a saturated page pool queues
    admissions; a full queue is a graceful QueueFullError (HTTP 503) —
    and every ACCEPTED request still completes after the pool frees."""
    net = replay._tiny_lm(16)
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1,), seq_lens=(8,))
    engine = GenerationEngine(net, lat, slots=1, max_new_tokens=8,
                              page_size=8, max_queue=2, recorder=rec)
    engine.warmup()
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, 64, 5).astype(np.int32) for _ in range(6)]
    accepted, refused = [], 0
    for p in prompts:  # engine not started: the queue can only grow
        try:
            accepted.append(engine.submit_generate(p, 4))
        except QueueFullError:
            refused += 1
    # engine not started, so nothing drains: exactly max_queue admitted
    assert len(accepted) == 2 and refused == 4
    engine.start()
    for req in accepted:
        assert req.wait(60), "accepted request starved after exhaustion"
        assert req.error is None and len(req.emitted) == 4
    # a request that can NEVER fit the pool is refused outright
    big = GenerationEngine(net, lat, slots=1, max_new_tokens=8,
                           page_size=8, pool_pages=1, recorder=rec)
    with pytest.raises(ValueError, match="exceed the cache geometry"):
        big.submit_generate(prompts[0], 8)
    engine.drain()


# ---------------------------------------------------- zero-retrace gate

def test_zero_retrace_across_mixed_generation_replay():
    """Warmup compiles each (replica, prefill-bucket) and the decode
    shape ONCE; a mixed prompt-length x output-length stream adds zero
    — on both the telemetry compile-span count and the trace counter."""
    net = replay._tiny_lm(24)
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1,), seq_lens=(8, 16))
    engine = GenerationEngine(net, lat, slots=2, max_new_tokens=8,
                              page_size=8, recorder=rec)
    warmed = engine.warmup()
    assert warmed == 3  # 2 prefill buckets + 1 decode shape, 1 replica
    assert engine.trace_count == 3

    def compile_spans():
        return [e for e in rec.events
                if e.get("event") == "span" and e.get("name") == "compile"]

    assert len(compile_spans()) == 3
    assert all(e.get("warmup") for e in compile_spans())
    engine.start()
    rng = np.random.default_rng(11)
    for plen, olen in ((3, 2), (8, 5), (11, 1), (16, 8), (5, 3),
                       (1, 4), (13, 2), (16, 1), (2, 6), (7, 8)):
        out = engine.generate(rng.integers(0, 64, plen).astype(np.int32),
                              olen, timeout=60)
        assert len(out) == olen
    assert engine.trace_count == 3, "a shape escaped the page grid"
    assert len(compile_spans()) == 3
    reqs = [e for e in rec.events if e.get("event") == "request"]
    assert len(reqs) == 10
    for ev in reqs:
        assert ev["ok"] and ev["kind"] == "generate"
        assert {"ttft_s", "total_s", "queue_s", "prompt_len",
                "prompt_bucket", "new_tokens"} <= set(ev)
    # page accounting is on the record and returns to empty
    pools = [e for e in rec.events if e.get("event") == "page_pool"]
    assert pools and pools[-1]["pages_in_use"] == 0
    assert max(p["pages_in_use"] for p in pools) > 0
    engine.drain()


def test_decode_step_cost_independent_of_prompt_length():
    """Decode always attends the full (page-quantized) cache with a
    position mask, so step shape — and cost — is identical whether the
    prompt filled one page or all of them. Asserted on telemetry
    decode_step span medians across the shortest and longest prompt
    buckets (generous 3x bound: the computation is literally the same
    jit executable, only scheduler noise differs)."""
    net = replay._tiny_lm(40)
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1,), seq_lens=(8, 32))
    engine = GenerationEngine(net, lat, slots=1, max_new_tokens=16,
                              page_size=8, recorder=rec)
    engine.warmup()
    engine.start()
    rng = np.random.default_rng(13)

    def decode_medians(prompt_len):
        mark = len(rec.events)
        out = engine.generate(
            rng.integers(0, 64, prompt_len).astype(np.int32), 16,
            timeout=60)
        assert len(out) == 16
        spans = [e["seconds"] for e in list(rec.events)[mark:]
                 if e.get("event") == "span"
                 and e.get("name") == "decode_step"]
        assert len(spans) == 15  # token 1 comes from prefill
        return float(np.median(spans))

    short = decode_medians(4)    # bucket 8: one page of prompt
    long = decode_medians(30)    # bucket 32: four pages of prompt
    assert long < 3.0 * short, (
        f"decode step grew with prompt length: {short:.6f}s -> "
        f"{long:.6f}s — the step is reading prompt-dependent state")
    engine.drain()


# ------------------------------------------------- trace + scoreboard

def test_generation_trace_is_seeded_with_length_mix():
    t1 = replay.make_generation_trace(7, 30, prompt_lengths=(8, 16),
                                      output_lengths=(2, 4))
    t2 = replay.make_generation_trace(7, 30, prompt_lengths=(8, 16),
                                      output_lengths=(2, 4))
    assert t1 == t2
    t3 = replay.make_generation_trace(8, 30, prompt_lengths=(8, 16),
                                      output_lengths=(2, 4))
    assert t1 != t3
    offsets = [t for t, _, _ in t1]
    assert offsets == sorted(offsets)
    assert {p for _, p, _ in t1} <= {8, 16}
    assert {o for _, _, o in t1} <= {2, 4}


def test_reconstruct_generation_from_telemetry_alone(tmp_path):
    path = str(tmp_path / "g.jsonl")
    with open(path, "w") as fh:
        for i, (ttft, total, ntok) in enumerate(
                [(0.01, 0.05, 4), (0.02, 0.10, 8), (0.5, 1.0, 8)]):
            fh.write(json.dumps({
                "event": "request", "id": f"g{i}", "ok": True,
                "kind": "generate", "ts": 100.0 + i, "ttft_s": ttft,
                "total_s": total, "new_tokens": ntok}) + "\n")
        fh.write(json.dumps({"event": "request", "id": "bad", "ok": False,
                             "kind": "generate", "ts": 103.0,
                             "total_s": 0.2, "new_tokens": 0}) + "\n")
        fh.write(json.dumps({"event": "request", "id": "pred", "ok": True,
                             "ts": 104.0, "total_s": 0.2}) + "\n")
        fh.write(json.dumps({"event": "span", "name": "compile",
                             "warmup": True, "seconds": 1.0}) + "\n")
        fh.write(json.dumps({"event": "span", "name": "compile",
                             "seconds": 1.0}) + "\n")
        fh.write(json.dumps({"event": "span", "name": "decode_step",
                             "seconds": 0.002}) + "\n")
        fh.write(json.dumps({"event": "page_pool", "pages_in_use": 3,
                             "pages_total": 4}) + "\n")
        fh.write(json.dumps({"event": "page_pool", "pages_in_use": 0,
                             "pages_total": 4}) + "\n")
    sb = replay.reconstruct_generation(path)
    assert sb["n_ok"] == 3 and sb["n_failed"] == 1  # predict row excluded
    assert sb["total_tokens"] == 20
    assert sb["ttft_p50_ms"] == 20.0
    assert sb["ttft_p99_ms"] == 500.0
    assert sb["page_occupancy_peak"] == 0.75
    assert sb["recompiles_after_warmup"] == 1
    assert sb["decode_steps"] == 1
    first = min(100.0 + i - t for i, (_, t, _) in enumerate(
        [(0.01, 0.05, 4), (0.02, 0.10, 8), (0.5, 1.0, 8)]))
    assert sb["tokens_per_sec"] == round(20 / (102.0 - first), 2)


def test_generation_metric_lines_direction_flags():
    sb = dict(tokens_per_sec=100.0, ttft_p50_ms=1.0, ttft_p99_ms=2.0,
              page_occupancy_peak=0.5, recompiles_after_warmup=0,
              warmup_compiles=3, n_ok=5, n_failed=0, total_tokens=40)
    lines = {l["metric"]: l for l in replay.generation_metric_lines(sb)}
    assert not lines["serving_generate_tokens_per_sec"].get(
        "lower_is_better")
    for m in ("serving_generate_ttft_p50_ms",
              "serving_generate_ttft_p99_ms",
              "serving_generate_page_occupancy",
              "serving_generate_recompiles_after_warmup"):
        assert lines[m]["lower_is_better"]


def test_benchdiff_inverts_generation_rows(tmp_path):
    """TTFT/occupancy growth regresses; tokens/sec growth doesn't —
    including rows recovered from a bare summary line (no flags)."""
    import sys
    sys.path.insert(0, "tools")
    import benchdiff

    old = {"serving_generate_tokens_per_sec": {"value": 100.0},
           "serving_generate_ttft_p99_ms": {"value": 10.0},
           "serving_generate_page_occupancy": {"value": 0.5}}
    new = {"serving_generate_tokens_per_sec": {"value": 150.0},
           "serving_generate_ttft_p99_ms": {"value": 20.0},
           "serving_generate_page_occupancy": {"value": 0.9}}
    result = benchdiff.diff(old, new, threshold=0.10)
    regressed = {r["metric"] for r in result["regressions"]}
    assert regressed == {"serving_generate_ttft_p99_ms",
                         "serving_generate_page_occupancy"}


# ------------------------------------------------------- HTTP round trip

@pytest.fixture(scope="module")
def gen_stack():
    net = replay._tiny_lm(24)
    rec = Recorder(path=None)
    lat = BucketLattice(batch_sizes=(1,), seq_lens=(8, 16))
    engine = GenerationEngine(net, lat, slots=2, max_new_tokens=8,
                              page_size=8, recorder=rec)
    engine.warmup()
    server = ServingServer(engine, port=0).start()
    yield net, engine, server, rec
    server.stop()


def test_generate_http_streams_tokens_and_summary(gen_stack):
    net, engine, server, _ = gen_stack
    rng = np.random.default_rng(17)
    prompt = rng.integers(0, 64, 6).astype(np.int32)
    body = json.dumps({"tokens": prompt.tolist(),
                       "max_new_tokens": 5}).encode()
    req = urllib.request.Request(
        f"{server.url}/generate", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        lines = [json.loads(l) for l in resp.read().splitlines() if l]
    assert [l["token"] for l in lines[:-1]] == lines[-1]["tokens"]
    summary = lines[-1]
    assert summary["done"] and len(summary["tokens"]) == 5
    assert summary["timing"]["total_s"] >= summary["timing"]["ttft_s"] > 0
    # HTTP tokens match the engine's own greedy decode
    assert summary["tokens"] == _greedy_full_forward(net, prompt, 5)


def test_generate_http_rejects_oversized_and_post_drain(gen_stack):
    _, _, server, _ = gen_stack
    too_long = {"tokens": list(range(17))}  # lattice max seq is 16
    req = urllib.request.Request(
        f"{server.url}/generate", data=json.dumps(too_long).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 400


def test_end_to_end_generation_replay_artifact(tmp_path):
    """The full rc=0 path at small scale: generation replay over real
    HTTP with streaming reads, scoreboard from telemetry alone, SERVE
    artifact written, truncation-proof via the summary line."""
    from deeplearning4j_tpu.telemetry import artifact as art

    tpath = str(tmp_path / "telemetry.jsonl")
    apath = str(tmp_path / "SERVE_gen.json")
    sb = replay.run_generation_replay(
        seed=0, n_requests=10, prompt_lengths=(8, 16),
        output_lengths=(2, 4), slots=2, page_size=8,
        telemetry_path=tpath, artifact_path=apath)
    assert sb["n_ok"] == 10
    assert sb["recompiles_after_warmup"] == 0
    assert sb["tokens_per_sec"] > 0
    assert sb["ttft_p99_ms"] >= sb["ttft_p50_ms"] > 0
    assert 0 < sb["page_occupancy_peak"] <= 1
    full = art.load(apath)
    assert full["serving_generate_tokens_per_sec"]["value"] == \
        sb["tokens_per_sec"]
    with open(apath) as fh:
        last = fh.read().splitlines()[-1]
    cut = str(tmp_path / "cut.json")
    with open(cut, "w") as fh:
        fh.write(last + "\n")
    recovered = art.load(cut)
    for metric in ("serving_generate_tokens_per_sec",
                   "serving_generate_ttft_p50_ms",
                   "serving_generate_ttft_p99_ms",
                   "serving_generate_page_occupancy",
                   "serving_generate_recompiles_after_warmup"):
        assert recovered[metric]["value"] == full[metric]["value"]
