"""Tier-1 gate for the kernel autotuning layer (ISSUE 8 tentpole):
table schema round-trip, unknown-key fallback to the deterministic
heuristics, interpret-mode parity (tuned vs default block sizes produce
bit-identical kernel outputs for fwd AND grad), the kerneltune sweep's
match-or-beat contract + kernel_tune telemetry, and the off-TPU
bit-identity contract (the checked-in table must NOT activate here)."""

import json
import os
import subprocess
import sys

import numpy as np

import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.ops import autotune

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
KERNELTUNE = os.path.join(ROOT, "tools", "kerneltune.py")
BENCHDIFF = os.path.join(ROOT, "tools", "benchdiff.py")


def _qkv(B=2, H=2, T=256, D=32, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.standard_normal((B, H, T, D)) * 0.3,
                             jnp.float32) for _ in range(3))


# ------------------------------------------------------- schema round-trip

class TestTableSchema:
    def test_key_roundtrip(self):
        key = autotune.config_key("flash_fwd", 512, 64, causal=True,
                                  dropout=False, masked=True)
        assert key == "flash_fwd|T512|D64|c1|d0|m1"
        cfg = autotune.parse_key(key)
        assert cfg == {"kernel": "flash_fwd", "T": 512, "D": 64,
                       "causal": True, "dropout": False, "masked": True}

    def test_valid_table_roundtrips_through_disk(self, tmp_path):
        table = {"version": autotune.SCHEMA_VERSION,
                 "provenance": {"tool": "test", "backend": "cpu"},
                 "entries": {
                     "flash_fwd|T512|D64|c1|d0|m0":
                         {"block_q": 256, "block_k": 512, "g": 2,
                          "best_us": 10, "default_us": 12},
                     "fused_layer_norm|T1024|D512|c0|d0|m0":
                         {"rows": 256},
                 }}
        assert autotune.validate_table(table) == []
        path = tmp_path / "table.json"
        path.write_text(json.dumps(table))
        loaded = autotune.load_table(str(path))
        assert loaded["entries"] == table["entries"]
        # cache: same path returns the cached object, reload re-reads
        assert autotune.load_table(str(path)) is loaded
        autotune.reload_table(autotune.TABLE_PATH)  # restore default

    def test_invalid_tables_name_their_problems(self, tmp_path):
        bad_version = {"version": 99, "entries": {}}
        assert any("version" in p
                   for p in autotune.validate_table(bad_version))
        bad_key = {"version": 1, "entries": {"nonsense": {}}}
        assert any("malformed" in p
                   for p in autotune.validate_table(bad_key))
        bad_kernel = {"version": 1, "entries":
                      {"warp_drive|T1|D1|c0|d0|m0": {}}}
        assert any("unknown kernel" in p
                   for p in autotune.validate_table(bad_kernel))
        bad_param = {"version": 1, "entries":
                     {"flash_fwd|T512|D64|c1|d0|m0": {"rows": 8}}}
        assert any("not tunable" in p
                   for p in autotune.validate_table(bad_param))
        bad_value = {"version": 1, "entries":
                     {"flash_fwd|T512|D64|c1|d0|m0": {"block_q": -4}}}
        assert any("positive int" in p
                   for p in autotune.validate_table(bad_value))
        # a malformed checked-in file fails at LOAD, not mid-compile
        path = tmp_path / "broken.json"
        path.write_text(json.dumps(bad_param))
        with pytest.raises(ValueError, match="invalid tuning table"):
            autotune.load_table(str(path))
        autotune.reload_table(autotune.TABLE_PATH)

    def test_checked_in_table_is_valid(self):
        table = autotune.reload_table(autotune.TABLE_PATH)
        assert autotune.validate_table(table) == []
        assert table["provenance"].get("tool") == "tools/kerneltune.py"
        # every entry matches-or-beats its own default micro-bench
        for key, e in table["entries"].items():
            if "best_us" in e and "default_us" in e:
                assert e["best_us"] <= e["default_us"], key


# ------------------------------------------------- fallback + resolution

class TestResolution:
    def test_unknown_key_falls_back_to_heuristics(self):
        with autotune.override({}):  # no table, no override
            assert autotune.flash_blocks(
                512, 64, causal=True, dropout=False, masked=False) == \
                (512, 512)
            assert autotune.flash_blocks(
                4096, 64, causal=True, dropout=False, masked=False) == \
                (512, 512)
            assert autotune.flash_g("flash_fwd", 8, 512, 64, causal=True,
                                    dropout=False, masked=False) is None
            assert autotune.ln_rows(1024, 512) == 512
            assert autotune.xent_blocks(2048, 256, 10240) == (1024, 2048)

    def test_off_tpu_table_is_inactive(self):
        """The bit-identity contract: off-TPU, checked-in entries never
        apply (DL4J_TPU_TUNING unset) — interpret runs equal HEAD."""
        assert jax.default_backend() != "tpu"
        assert os.environ.get(autotune.ENV_TUNING) in (None, "")
        assert not autotune.table_active()
        assert autotune.lookup("flash_fwd", 512, 64, causal=True) is None

    def test_env_force_and_off(self, monkeypatch, tmp_path):
        table = {"version": 1, "provenance": {},
                 "entries": {"flash_fwd|T512|D64|c1|d0|m0":
                             {"block_q": 256, "block_k": 256, "g": 1}}}
        path = tmp_path / "t.json"
        path.write_text(json.dumps(table))
        monkeypatch.setattr(autotune, "TABLE_PATH", str(path))
        autotune.reload_table(str(path))
        try:
            monkeypatch.setenv(autotune.ENV_TUNING, "force")
            assert autotune.table_active()
            e = autotune.lookup("flash_fwd", 512, 64, causal=True)
            assert e == {"block_q": 256, "block_k": 256, "g": 1}
            monkeypatch.setenv(autotune.ENV_TUNING, "off")
            assert not autotune.table_active()
            assert autotune.lookup("flash_fwd", 512, 64,
                                   causal=True) is None
        finally:
            autotune.reload_table(autotune.TABLE_PATH)

    def test_invalid_entry_params_fall_back(self):
        """A tuned block that does not divide T (or a G that does not
        divide BH) must never reach a kernel grid."""
        with autotune.override({"flash_fwd": {"block_q": 384,
                                              "block_k": 512, "g": 3}}):
            assert autotune.flash_blocks(
                512, 64, causal=True, dropout=False, masked=False) == \
                (512, 512)
            assert autotune.flash_g("flash_fwd", 8, 512, 64, causal=True,
                                    dropout=False, masked=False) is None
        with autotune.override({"fused_layer_norm": {"rows": 320}}):
            assert autotune.ln_rows(1024, 512) == 512  # 320 not lane-tile
        with autotune.override({"flash_chunk": {"chunk": 640}}):
            from deeplearning4j_tpu.ops.flash_attention import (
                chunked_flash_attention_lse,
            )
            q = jnp.zeros((1, 1024, 32), jnp.float32)
            # invalid tuned chunk -> heuristic pick, no raise
            jax.eval_shape(lambda q: chunked_flash_attention_lse(
                q, q, q, 1.0, True), q)

    def test_max_tile_for_dim_envelope(self):
        assert autotune.max_tile_for_dim(None) == 8192
        assert autotune.max_tile_for_dim(128) == 8192
        assert autotune.max_tile_for_dim(256) == 4096
        for D in (64, 128, 160, 256, 384, 512, 1024):
            tile = autotune.max_tile_for_dim(D)
            assert tile * max(D, 128) <= autotune.TILE_ELEM_BUDGET

    def test_tuned_chunk_resolves_through_dispatch(self):
        """A valid flash_chunk entry changes the tile the loop picks."""
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention_lse,
        )

        q = jnp.zeros((1, 1024, 32), jnp.float32)

        def n_outputs(fn):
            out = jax.eval_shape(fn, q)
            return out[0].shape

        with autotune.override({"flash_chunk": {"chunk": 256}}):
            shape = n_outputs(lambda q: chunked_flash_attention_lse(
                q, q, q, 1.0, True))
            assert shape == (1, 1024, 32)


# -------------------------------------------------- interpret-mode parity

class TestTunedParity:
    """Tuned vs default block sizes through the REAL dispatch.
    G-batching is pure batching (per-slice math unchanged), so fwd AND
    grad are BIT-identical; block re-tiling keeps per-row reductions but
    hands XLA different matmul shapes (different CPU micro-kernel/
    threading choices), so it gets a float32-epsilon allclose bound plus
    a correctness check against the dense reference."""

    def _run(self, dropout=0.0, mask=None):
        from deeplearning4j_tpu.ops.flash_attention import flash_attention
        q, k, v = _qkv()
        kw = {}
        if dropout:
            kw = dict(dropout=dropout, dropout_rng=jax.random.PRNGKey(3))
        if mask is not None:
            kw["mask"] = mask

        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True, **kw)
                           ** 2)

        o = flash_attention(q, k, v, causal=True, **kw)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return o, g

    @pytest.mark.parametrize("dropout", [0.0, 0.2])
    def test_g_variants_bit_identical(self, dropout):
        o0, g0 = self._run(dropout=dropout)
        variants = [
            {"flash_fwd": {"block_q": 256, "block_k": 256, "g": 1}},
            {"flash_fwd": {"block_q": 256, "block_k": 256, "g": 2}},
            {"flash_bwd": {"block_q": 256, "block_k": 256, "g": 2}},
            {"flash_fwd": {"block_q": 256, "block_k": 256, "g": 4},
             "flash_bwd": {"block_q": 256, "block_k": 256, "g": 1}},
        ]
        for ov in variants:
            with autotune.override(ov):
                o1, g1 = self._run(dropout=dropout)
            assert bool(jnp.all(o0 == o1)), ov
            for a, b in zip(g0, g1):
                assert bool(jnp.all(a == b)), ov

    @pytest.mark.parametrize("dropout", [0.0, 0.2])
    def test_block_retiling_allclose(self, dropout):
        o0, g0 = self._run(dropout=dropout)
        variants = [
            {"flash_fwd": {"block_q": 128, "block_k": 256, "g": 1}},
            {"flash_fwd": {"block_q": 256, "block_k": 128, "g": 1},
             "flash_bwd": {"block_q": 256, "block_k": 128, "g": 1}},
            {"flash_bwd": {"block_q": 128, "block_k": 256, "g": 1}},
        ]
        for ov in variants:
            with autotune.override(ov):
                o1, g1 = self._run(dropout=dropout)
            np.testing.assert_allclose(np.asarray(o0), np.asarray(o1),
                                       atol=2e-6, err_msg=str(ov))
            for a, b in zip(g0, g1):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           atol=2e-5, err_msg=str(ov))

    def test_block_q_over_block_k_is_correct(self):
        """The r8 causal key-block bound fix: a tuned block_q LARGER
        than block_k must still attend every needed key block (the old
        `qi*bq//bk + 1` bound silently dropped them)."""
        from deeplearning4j_tpu.nn.layers.attention import (
            dot_product_attention,
        )
        q, k, v = _qkv(T=256)
        ref = dot_product_attention(q, k, v, causal=True)
        from deeplearning4j_tpu.ops.flash_attention import flash_attention
        with autotune.override({"flash_fwd": {"block_q": 256,
                                              "block_k": 128, "g": 1}}):
            out = flash_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_ln_and_xent_variants_bit_identical(self):
        from deeplearning4j_tpu.ops.fused_layernorm import fused_layer_norm
        from deeplearning4j_tpu.ops.fused_softmax_xent import (
            softmax_xent_head,
        )
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((512, 256)), jnp.float32)
        g = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
        y0 = fused_layer_norm(x, g, b)
        d0 = jax.grad(lambda x: jnp.sum(fused_layer_norm(x, g, b) ** 2))(x)
        with autotune.override({"fused_layer_norm": {"rows": 128}}):
            y1 = fused_layer_norm(x, g, b)
            d1 = jax.grad(lambda x: jnp.sum(
                fused_layer_norm(x, g, b) ** 2))(x)
        assert bool(jnp.all(y0 == y1))
        assert bool(jnp.all(d0 == d1))

        xx = jnp.asarray(rng.standard_normal((256, 128)) * 0.2,
                         jnp.float32)
        w = jnp.asarray(rng.standard_normal((128, 2560)) * 0.05,
                        jnp.float32)
        bb = jnp.zeros((2560,), jnp.float32)
        lab = jnp.asarray(rng.integers(0, 2560, (256,)), jnp.int32)
        l0 = softmax_xent_head(xx, w, bb, lab)
        gw0 = jax.grad(lambda w: softmax_xent_head(xx, w, bb, lab).sum())(w)
        # block_n re-tiling re-partitions rows: per-token loss is
        # bit-identical; dW re-groups the cross-row accumulation, so it
        # gets the allclose bound
        with autotune.override({"softmax_xent": {"block_n": 128,
                                                 "block_v": 2048}}):
            l1 = softmax_xent_head(xx, w, bb, lab)
            gw1 = jax.grad(lambda w: softmax_xent_head(
                xx, w, bb, lab).sum())(w)
        assert bool(jnp.all(l0 == l1))
        np.testing.assert_allclose(np.asarray(gw0), np.asarray(gw1),
                                   atol=2e-5)
        # block_v re-chunks the online logsumexp: allclose bound
        with autotune.override({"softmax_xent": {"block_n": 256,
                                                 "block_v": 1024}}):
            l2 = softmax_xent_head(xx, w, bb, lab)
        np.testing.assert_allclose(np.asarray(l0), np.asarray(l2),
                                   atol=2e-5)


# ------------------------------------------------------ kerneltune sweep

class TestKernelTune:
    def _kt(self):
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        try:
            import kerneltune
        finally:
            sys.path.pop(0)
        return kerneltune

    def test_sweep_match_or_beat_and_telemetry(self, tmp_path):
        """A real (tiny) sweep through the real kernels: every entry
        matches-or-beats its default in the harness's own micro-bench,
        and every measurement leaves a typed kernel_tune event."""
        from deeplearning4j_tpu.telemetry.recorder import Recorder

        kerneltune = self._kt()
        cfgs = [dict(family="flash_fwd", B=1, H=2, T=256, D=16,
                     causal=True, dropout=False, masked=False),
                dict(family="fused_layer_norm", N=256, C=128)]
        rec = Recorder(str(tmp_path / "tel.jsonl"))
        entries = kerneltune.sweep(cfgs, repeats=1, margin=0.03,
                                   recorder=rec, trust_wins=True)
        rec.close()
        assert set(entries) == {
            "flash_fwd|T256|D16|c1|d0|m0",
            "fused_layer_norm|T256|D128|c0|d0|m0"}
        for key, e in entries.items():
            assert e["best_us"] <= e["default_us"], key
        events = [json.loads(line)
                  for line in open(tmp_path / "tel.jsonl")]
        kt = [e for e in events if e["event"] == "kernel_tune"]
        roles = {e["role"] for e in kt}
        assert roles == {"default", "candidate", "chosen"}
        assert all("params" in e and "seconds" in e for e in kt)
        # the table the sweep would write is schema-valid
        table = {"version": autotune.SCHEMA_VERSION, "provenance": {},
                 "entries": entries}
        assert autotune.validate_table(table) == []

    def test_off_tpu_wins_do_not_displace_defaults(self, tmp_path):
        """trust_wins=False (the off-TPU CLI default): candidates are
        timed but the written params are the deterministic defaults."""
        from deeplearning4j_tpu.telemetry.recorder import NullRecorder

        kerneltune = self._kt()
        cfgs = [dict(family="flash_fwd", B=1, H=2, T=256, D=16,
                     causal=True, dropout=False, masked=False)]
        entries = kerneltune.sweep(cfgs, repeats=1, margin=0.03,
                                   recorder=NullRecorder(),
                                   trust_wins=False)
        (entry,) = entries.values()
        dflt = kerneltune.default_params(cfgs[0])
        assert {k: entry[k] for k in dflt} == dflt

    def test_cli_dry_run_lists_configs(self):
        proc = subprocess.run(
            [sys.executable, KERNELTUNE, "--quick", "--dry-run"],
            cwd=ROOT, capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "flash_fwd|T256" in proc.stdout
        assert "candidates" in proc.stdout


# -------------------------------------------------- benchdiff integration

class TestBenchdiffTables:
    def _tables(self, tmp_path):
        old = {"version": 1, "provenance": {"date": "a"}, "entries": {
            "flash_fwd|T512|D64|c1|d0|m0":
                {"block_q": 512, "block_k": 512, "g": 8,
                 "best_us": 129, "default_us": 263},
            "softmax_xent|T10240|D256|c0|d0|m0":
                {"block_n": 1024, "block_v": 2048,
                 "best_us": 100, "default_us": 100},
        }}
        import copy
        new = copy.deepcopy(old)
        new["entries"]["flash_fwd|T512|D64|c1|d0|m0"].update(
            block_q=256, best_us=110)
        new["entries"]["fused_layer_norm|T2048|D512|c0|d0|m0"] = {
            "rows": 512, "best_us": 10, "default_us": 10}
        op, np_ = tmp_path / "old.json", tmp_path / "new.json"
        op.write_text(json.dumps(old))
        np_.write_text(json.dumps(new))
        return old, new, str(op), str(np_)

    def test_diff_names_changed_entries(self, tmp_path):
        sys.path.insert(0, os.path.join(ROOT, "tools"))
        try:
            import benchdiff
        finally:
            sys.path.pop(0)
        old, new, _, _ = self._tables(tmp_path)
        result = benchdiff.diff_tables(old, new)
        assert not result["regressions"]
        fields = {(r["metric"], r["field"]) for r in result["changes"]}
        assert ("flash_fwd|T512|D64|c1|d0|m0", "params") in fields
        assert ("flash_fwd|T512|D64|c1|d0|m0", "best_us") in fields
        assert result["added"] == ["fused_layer_norm|T2048|D512|c0|d0|m0"]
        # timing regression: best_us GROWS past threshold
        new["entries"]["flash_fwd|T512|D64|c1|d0|m0"]["best_us"] = 260
        result = benchdiff.diff_tables(old, new)
        assert any(r["field"] == "best_us" and "lower-is-better"
                   in r["reason"] for r in result["regressions"])
        # match-or-beat violation always regresses
        new["entries"]["softmax_xent|T10240|D256|c0|d0|m0"][
            "best_us"] = 150
        result = benchdiff.diff_tables(old, new)
        assert any("match-or-beat" in r["reason"]
                   for r in result["regressions"])

    def test_cli_diffs_tables_and_gates(self, tmp_path):
        _, new, op, npath = self._tables(tmp_path)
        proc = subprocess.run(
            [sys.executable, BENCHDIFF, op, npath], cwd=ROOT,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "flash_fwd|T512|D64|c1|d0|m0" in proc.stdout
        # regressing table exits 1
        new["entries"]["flash_fwd|T512|D64|c1|d0|m0"]["best_us"] = 400
        (tmp_path / "new.json").write_text(json.dumps(new))
        proc = subprocess.run(
            [sys.executable, BENCHDIFF, op, npath], cwd=ROOT,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 1
        assert "REGRESSED" in proc.stdout
        # mixed table-vs-bench artifact is a usage error
        bench_art = tmp_path / "bench.txt"
        bench_art.write_text(json.dumps(
            {"metric": "lenet", "value": 1.0, "unit": "x"}) + "\n")
        proc = subprocess.run(
            [sys.executable, BENCHDIFF, op, str(bench_art)], cwd=ROOT,
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 2
