"""Tests for tools/requote_bench.py — previously untested tooling that
is the ONLY writer of the measured-performance blocks in README/PARITY.

The load() recovery path matters most: the driver keeps only the TAIL of
captured stdout, so early metric lines vanish (r5 lost lenet/vgg/w2v/
resnet/flagship) and must be reconstructed from the summary line."""

import importlib.util
import json
import os
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

spec = importlib.util.spec_from_file_location(
    "requote_bench", os.path.join(ROOT, "tools", "requote_bench.py"))
requote = importlib.util.module_from_spec(spec)
sys.modules.setdefault("requote_bench", requote)
spec.loader.exec_module(requote)


def _write(tmp_path, name, payload):
    path = tmp_path / name
    path.write_text(payload)
    return str(path)


def test_load_plain_jsonl(tmp_path):
    art = _write(tmp_path, "b.json", "\n".join([
        json.dumps({"metric": "transformer_lm_mfu", "value": 0.31,
                    "tokens_per_sec": 2.2e6}),
        "not json at all",
        json.dumps({"metric": "summary", "value": 1}),
    ]))
    lines = requote.load(art)
    assert lines["transformer_lm_mfu"]["value"] == 0.31


def test_load_unwraps_driver_tail_object(tmp_path):
    inner = json.dumps({"metric": "ring_hop_flash_tflops", "value": 42.0})
    art = _write(tmp_path, "b.json", json.dumps({"tail": inner + "\n"}))
    lines = requote.load(art)
    assert lines["ring_hop_flash_tflops"]["value"] == 42.0


def test_load_recovers_truncated_metrics_from_summary(tmp_path):
    """The r5 failure mode: only the summary line survived truncation —
    every numeric key it carries becomes a bare {value} row."""
    summary = {"metric": "summary", "value": 9, "unit": "x",
               "vs_baseline": "ok", "regressions": 0,
               "lenet_mnist_images_per_sec": 2.1e6,
               "transformer_lm_mfu": 0.305,
               "notes": "non-numeric, must be ignored"}
    art = _write(tmp_path, "b.json", json.dumps(summary))
    lines = requote.load(art)
    assert lines["lenet_mnist_images_per_sec"] == {
        "metric": "lenet_mnist_images_per_sec", "value": 2.1e6,
        "from_summary": True}
    assert lines["transformer_lm_mfu"]["from_summary"]
    # bookkeeping keys of the summary line are NOT metrics
    for skip in ("value", "unit", "vs_baseline", "regressions", "notes"):
        assert skip not in lines


def test_summary_never_overrides_surviving_tail_line(tmp_path):
    art = _write(tmp_path, "b.json", "\n".join([
        json.dumps({"metric": "transformer_lm_mfu", "value": 0.31,
                    "tokens_per_sec": 2.2e6}),
        json.dumps({"metric": "summary", "value": 1,
                    "transformer_lm_mfu": 0.999}),
    ]))
    line = requote.load(art)["transformer_lm_mfu"]
    assert line["value"] == 0.31 and "from_summary" not in line


def test_render_quotes_recovered_and_tpu_suffixed_rows():
    lines = {
        "transformer_lm_mfu": {"metric": "transformer_lm_mfu",
                               "value": 0.305, "from_summary": True},
        "lenet_mnist_images_per_sec_tpu": {
            "metric": "lenet_mnist_images_per_sec_tpu", "value": 2.0e6},
    }
    block = requote.render(lines, "BENCH_rTEST.json")
    assert "BENCH_rTEST.json" in block
    assert "**0.305 MFU**" in block
    assert "2.00M images/sec" in block


def test_render_flags_regressions():
    lines = {"transformer_lm_mfu": {"metric": "transformer_lm_mfu",
                                    "value": 0.2, "regression": True}}
    assert "⚠regression" in requote.render(lines, "a.json")


def test_splice_replaces_only_the_marked_block(tmp_path):
    doc = tmp_path / "README.md"
    doc.write_text("intro\n<!-- BENCH:BEGIN -->\nstale\n"
                   "<!-- BENCH:END -->\noutro\n")
    requote.splice(str(doc), "FRESH")
    text = doc.read_text()
    assert "FRESH" in text and "stale" not in text
    assert text.startswith("intro\n") and text.endswith("outro\n")


def test_splice_refuses_doc_without_markers(tmp_path):
    doc = tmp_path / "README.md"
    doc.write_text("no markers here\n")
    with pytest.raises(SystemExit):
        requote.splice(str(doc), "FRESH")


def test_load_recovers_gate_fields_from_summary(tmp_path):
    """r6: the summary line carries `gates` + `regressed_metrics`
    (VERDICT r5 #6) — a tail that kept only the summary still yields
    rows with every gate decision on them."""
    summary = {"metric": "summary", "value": 0.5, "regressions": 1,
               "regressed_metrics": ["vgg16_cifar_images_per_sec_tpu"],
               "vgg16_cifar_images_per_sec_tpu": 56436.5,
               "word2vec_sgns_words_per_sec": 850493.5,
               "gates": {
                   "word2vec_sgns_words_per_sec": {
                       "quality_ratio_vs_host": 0.977,
                       "quality_gate_min_ratio": 0.95},
                   "vgg16_cifar_images_per_sec_tpu": {
                       "gate_scale": 0.93, "regression": True}}}
    art = _write(tmp_path, "b.json", json.dumps(summary))
    lines = requote.load(art)
    w2v = lines["word2vec_sgns_words_per_sec"]
    assert w2v["value"] == 850493.5 and w2v["from_summary"]
    assert w2v["quality_ratio_vs_host"] == 0.977
    vgg = lines["vgg16_cifar_images_per_sec_tpu"]
    assert vgg["regression"] is True and vgg["gate_scale"] == 0.93
    # bookkeeping containers never become metric rows
    assert "gates" not in lines and "regressed_metrics" not in lines


def test_gate_fields_never_override_a_surviving_line(tmp_path):
    art = _write(tmp_path, "b.json", "\n".join([
        json.dumps({"metric": "m", "value": 1.0, "gate_scale": 0.5}),
        json.dumps({"metric": "summary", "m": 9.0,
                    "gates": {"m": {"gate_scale": 0.9}}}),
    ]))
    line = requote.load(art)["m"]
    assert line["value"] == 1.0 and line["gate_scale"] == 0.5


def test_mfu_str_labels_conventions():
    with_exec = requote._mfu_str({"value": 0.31, "mfu_executed": 0.62})
    assert "0.310 MFU" in with_exec and "0.620" in with_exec
    legacy = requote._mfu_str({"value": 0.31})
    assert "dense-accounted" in legacy
