"""Stored-config backward compatibility (reference test strategy §4.3:
serialized configs in dl4j-test-resources/confs/ guard the JSON schema).

The JSONs under tests/fixtures/confs/ were frozen from an earlier build;
every future version must keep loading them, building networks, and
running a forward pass. When the schema evolves, loaders must stay
backward compatible — regenerating the fixtures is NOT the fix."""

import os

import numpy as np
import pytest

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "confs")


def _read(name):
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


def test_cnn_mln_fixture_loads_and_runs():
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = MultiLayerConfiguration.from_json(_read("cnn_mln.json"))
    net = MultiLayerNetwork(conf)
    net.init()
    out = net.output(np.zeros((2, 14, 14, 1), np.float32))
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-4)


def test_rnn_tbptt_fixture_loads_and_runs():
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = MultiLayerConfiguration.from_json(_read("rnn_tbptt_mln.json"))
    assert conf.backprop_type == "truncated_bptt"
    assert conf.tbptt_fwd_length == 8
    net = MultiLayerNetwork(conf)
    net.init()
    toks = np.zeros((2, 5), np.int32)
    out = net.output(toks)
    assert out.shape == (2, 5, 50)


def test_transformer_cg_fixture_loads_and_runs():
    from deeplearning4j_tpu.nn.conf import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = ComputationGraphConfiguration.from_json(_read("transformer_cg.json"))
    net = ComputationGraph(conf)
    net.init()
    toks = np.zeros((2, 16), np.int32)
    out = net.output(toks)
    assert out.shape == (2, 16, 100)


def test_fixture_round_trip_is_stable():
    """to_json(from_json(fixture)) must itself load — loaders and dumpers
    stay inverse even as fields accrue."""
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration

    conf = MultiLayerConfiguration.from_json(_read("cnn_mln.json"))
    again = MultiLayerConfiguration.from_json(conf.to_json())
    assert len(again.layers) == len(conf.layers)
