"""Solver tests — reference optimize/solvers/* behavior (SURVEY.md §2.2).

Convergence on a convex quadratic + Rosenbrock (standard solver fixtures),
line-search Armijo property, and end-to-end network fit with each
OptimizationAlgorithm (reference tests ran LBFGS/CG on Iris-sized nets).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.optimize.solvers import (
    ConjugateGradient,
    EpsTermination,
    LBFGS,
    LineGradientDescent,
    Solver,
    StochasticGradientDescent,
    backtrack_line_search,
)


def quad(x):
    # condition number ~100
    scales = jnp.linspace(1.0, 100.0, x.shape[0])
    return 0.5 * jnp.sum(scales * x * x)


def rosenbrock(x):
    return jnp.sum(100.0 * (x[1:] - x[:-1] ** 2) ** 2 + (1.0 - x[:-1]) ** 2)


@pytest.mark.parametrize("cls,iters,tol", [
    # steepest descent on a kappa=100 quadratic is intrinsically slow
    (LineGradientDescent, 200, 1e-3),
    # Armijo (inexact) line search limits CG's conjugacy in float32
    (ConjugateGradient, 60, 1e-4),
    (LBFGS, 40, 1e-5),
])
def test_quadratic_convergence(cls, iters, tol):
    x0 = jnp.ones(10)
    res = cls(quad, max_iterations=iters,
              terminations=[EpsTermination(1e-10, 1e-12)]).optimize(x0)
    assert res.score < tol, f"{cls.__name__} stalled at {res.score}"


def test_lbfgs_rosenbrock():
    x0 = jnp.zeros(8)
    res = LBFGS(rosenbrock, max_iterations=300, m=10,
                terminations=[EpsTermination(1e-12, 1e-14)]).optimize(x0)
    assert res.score < 1e-3


def test_sgd_solver_descends():
    res = StochasticGradientDescent(quad, max_iterations=50, lr=0.005).optimize(
        jnp.ones(10))
    assert res.score < float(quad(jnp.ones(10)))


def test_line_search_armijo():
    import jax

    x = jnp.ones(5)
    f0, g = jax.value_and_grad(quad)(x)
    t, ft = backtrack_line_search(quad, x, f0, g, -g)
    assert float(t) > 0
    assert float(ft) <= float(f0) - 1e-4 * float(t) * float(jnp.vdot(g, g)) + 1e-6


@pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                  "line_gradient_descent"])
def test_network_fit_with_solver(algo, rng):
    """End-to-end: tiny dense net trained by each solver reduces loss
    (reference GradientCheckTests ran these algos on Iris-sized nets)."""
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        NeuralNetConfiguration,
    )
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    x = rng.standard_normal((32, 4)).astype(np.float32)
    w = rng.standard_normal((4, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]

    conf = (NeuralNetConfiguration.builder()
            .seed(12345)
            .optimization_algo(algo)
            .iterations(8)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    before = net.score(__import__("deeplearning4j_tpu.datasets.api",
                                  fromlist=["DataSet"]).DataSet(x, y))
    net.fit(x, y, epochs=2)
    after = net.score(__import__("deeplearning4j_tpu.datasets.api",
                                 fromlist=["DataSet"]).DataSet(x, y))
    assert after < before


def test_hessian_free_quadratic_one_shot():
    """On a quadratic, damped-CG Newton reaches the optimum in ~1 outer
    iteration (reference StochasticHessianFree semantics)."""
    import jax.numpy as jnp
    from deeplearning4j_tpu.optimize.solvers import HessianFree

    A = jnp.asarray([[3.0, 0.5], [0.5, 1.0]])
    b = jnp.asarray([1.0, -2.0])

    def loss(x):
        return 0.5 * x @ A @ x - b @ x

    opt = HessianFree(loss, max_iterations=8, cg_iterations=16,
                      initial_lambda=1e-3)
    res = opt.optimize(jnp.zeros(2))
    x_star = jnp.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(res.x), np.asarray(x_star),
                               atol=1e-3)


def test_hessian_free_rosenbrock_descends():
    import jax.numpy as jnp
    from deeplearning4j_tpu.optimize.solvers import HessianFree

    def rosen(x):
        return (1 - x[0]) ** 2 + 100.0 * (x[1] - x[0] ** 2) ** 2

    opt = HessianFree(rosen, max_iterations=60, cg_iterations=20)
    res = opt.optimize(jnp.asarray([-1.2, 1.0]))
    assert res.score < 1e-2


def test_network_fit_with_hessian_free():
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    rng = np.random.default_rng(0)
    x = rng.random((32, 4), dtype=np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 2).astype(int)]
    conf = (
        NeuralNetConfiguration.builder()
        .seed(0)
        .optimization_algo("hessian_free")
        .iterations(12)
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    net = MultiLayerNetwork(conf).init()
    s0 = float(net.score(DataSet(x, y)))
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    assert net.score_value < s0
