"""CLI end-to-end tests (reference: deeplearning4j-cli test model — drive
Train/Test/Predict subcommands on small CSV data)."""

import numpy as np
import pytest

from deeplearning4j_tpu.cli import main


@pytest.fixture
def blob_csv(tmp_path, rng):
    """Linearly separable 2-class CSV: 4 features + label column."""
    n = 120
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x @ np.array([1.0, -1.0, 0.5, 0.0]) > 0).astype(int)
    x[y == 1] += 1.5
    path = tmp_path / "data.csv"
    with open(path, "w") as f:
        for row, label in zip(x, y):
            f.write(",".join(f"{v:.6f}" for v in row) + f",{label}\n")
    return str(path)


@pytest.fixture
def conf_json(tmp_path):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .updater("adam").list()
            .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
            .layer(OutputLayer(n_in=16, n_out=2, activation="softmax",
                               loss_function="negativeloglikelihood"))
            .build())
    p = tmp_path / "conf.json"
    p.write_text(conf.to_json())
    return str(p)


class TestCliRoundTrip:
    def test_train_test_predict(self, tmp_path, blob_csv, conf_json, capsys):
        model = str(tmp_path / "model.zip")
        rc = main(["train", "--conf", conf_json, "--input", blob_csv,
                   "--model", model, "--num-classes", "2", "--epochs", "10"])
        assert rc == 0
        assert (tmp_path / "model.zip").exists()

        rc = main(["test", "--model", model, "--input", blob_csv,
                   "--num-classes", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "Accuracy" in out
        acc = float([l for l in out.splitlines() if "Accuracy" in l][0]
                    .split()[-1])
        assert acc > 0.85

        # features-only file for predict
        feat_csv = tmp_path / "features.csv"
        with open(blob_csv) as f, open(feat_csv, "w") as g:
            for line in f:
                g.write(",".join(line.strip().split(",")[:-1]) + "\n")
        preds = str(tmp_path / "preds.csv")
        rc = main(["predict", "--model", model, "--input", str(feat_csv),
                   "--output", preds])
        assert rc == 0
        rows = [l.split(",") for l in open(preds).read().splitlines()]
        assert len(rows) == 120
        assert len(rows[0]) == 2
        p = np.array([[float(v) for v in r] for r in rows])
        np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-4)

    def test_train_prefetch_depth_knob(self, tmp_path, blob_csv,
                                       conf_json):
        """--prefetch-depth installs the pipeline depth override (0 =
        synchronous fallback) and training still lands a model."""
        from deeplearning4j_tpu.data import pipeline as data_pipeline

        model = str(tmp_path / "model_sync.zip")
        prev = data_pipeline.set_prefetch_depth(None)
        try:
            rc = main(["train", "--conf", conf_json, "--input", blob_csv,
                       "--model", model, "--num-classes", "2",
                       "--prefetch-depth", "0"])
            assert rc == 0
            assert data_pipeline.prefetch_depth() == 0
        finally:
            data_pipeline.set_prefetch_depth(prev)
        assert (tmp_path / "model_sync.zip").exists()

    def test_missing_model_flag_errors(self, blob_csv, conf_json):
        with pytest.raises(SystemExit):
            main(["train", "--conf", conf_json, "--input", blob_csv,
                  "--num-classes", "2"])

    def test_svmlight_input(self, tmp_path, conf_json):
        rng = np.random.default_rng(0)
        n = 60
        x = rng.normal(size=(n, 4)).astype(np.float32)
        y = (x[:, 0] > 0).astype(int)
        x[y == 1, 0] += 2.0
        svm = tmp_path / "d.svm"
        with open(svm, "w") as f:
            for row, label in zip(x, y):
                feats = " ".join(f"{j + 1}:{v:.5f}" for j, v in enumerate(row))
                f.write(f"{label} {feats}\n")
        model = str(tmp_path / "m.zip")
        rc = main(["train", "--conf", conf_json, "--input", str(svm),
                   "--format", "svmlight", "--num-features", "4",
                   "--model", model, "--num-classes", "2", "--epochs", "5"])
        assert rc == 0


class TestCliDistributed:
    """VERDICT r2 #7: the parallel/ machinery is reachable from the CLI
    (reference Train.java `-runtime local|spark|hadoop` +
    cli-spark/SparkTrain.java)."""

    def test_train_with_mesh(self, tmp_path, blob_csv, conf_json, capsys):
        model = str(tmp_path / "model.zip")
        rc = main(["train", "--conf", conf_json, "--input", blob_csv,
                   "--model", model, "--num-classes", "2", "--epochs", "10",
                   "--mesh", "data=8"])
        assert rc == 0
        assert "mesh: {'data': 8}" in capsys.readouterr().out
        rc = main(["test", "--model", model, "--input", blob_csv,
                   "--num-classes", "2"])
        out = capsys.readouterr().out
        acc = float([l for l in out.splitlines() if "Accuracy" in l][0]
                    .split()[-1])
        assert acc > 0.85

    def test_mesh_too_many_devices_errors(self, blob_csv, conf_json,
                                          tmp_path):
        with pytest.raises(SystemExit, match="devices"):
            main(["train", "--conf", conf_json, "--input", blob_csv,
                  "--model", str(tmp_path / "m.zip"), "--num-classes", "2",
                  "--mesh", "data=64"])

    def test_bad_mesh_role_errors(self, blob_csv, conf_json, tmp_path):
        with pytest.raises(SystemExit, match="unknown mesh role"):
            main(["train", "--conf", conf_json, "--input", blob_csv,
                  "--model", str(tmp_path / "m.zip"), "--num-classes", "2",
                  "--mesh", "rows=2"])

    def test_train_with_cluster(self, tmp_path, blob_csv, conf_json,
                                capsys):
        """Two CLI workers + in-process coordinator: elastic
        parameter-averaging training through the command line."""
        import threading

        from deeplearning4j_tpu.parallel.cluster import ClusterCoordinator

        coord = ClusterCoordinator(heartbeat_timeout=10.0).start()
        models = [str(tmp_path / f"m{i}.zip") for i in range(2)]
        rcs = {}

        def worker(i):
            rcs[i] = main([
                "train", "--conf", conf_json, "--input", blob_csv,
                "--model", models[i], "--num-classes", "2",
                "--epochs", "6", "--batch", "30",
                "--cluster", coord.address, "--num-workers", "2",
                "--worker-id", f"w{i}", "--sync-every", "2"])

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        coord.shutdown()
        assert rcs == {0: 0, 1: 0}
        # both workers converged on the averaged parameters
        rc = main(["test", "--model", models[0], "--input", blob_csv,
                   "--num-classes", "2"])
        assert rc == 0
        out = capsys.readouterr().out
        acc = float([l for l in out.splitlines() if "Accuracy" in l][0]
                    .split()[-1])
        assert acc > 0.85


class TestCloudPaths:
    def test_gs_input_and_model_roundtrip(self, tmp_path, blob_csv,
                                          conf_json, monkeypatch):
        """gs:// inputs/outputs route through datasets/cloud (VERDICT r3
        missing #3) — the transfer layer is mocked (zero-egress), the CLI
        plumbing is real: download for --input/--conf/--model, upload for
        the trained model."""
        import shutil

        from deeplearning4j_tpu.datasets import cloud

        bucket = tmp_path / "bucket"
        bucket.mkdir()
        shutil.copy(blob_csv, bucket / "train.csv")
        shutil.copy(conf_json, bucket / "conf.json")
        transfers = []

        def fake_download(self, uri, dest=None):
            if not uri.startswith("gs://"):
                return uri
            transfers.append(("down", uri))
            return str(bucket / uri.rsplit("/", 1)[1])

        def fake_upload(self, local, uri):
            transfers.append(("up", uri))
            shutil.copy(local, bucket / uri.rsplit("/", 1)[1])

        monkeypatch.setattr(cloud.GcsDownloader, "download", fake_download)
        monkeypatch.setattr(cloud.GcsUploader, "upload", fake_upload)

        rc = main(["train", "--conf", "gs://b/conf.json",
                   "--input", "gs://b/train.csv",
                   "--model", "gs://b/model.zip",
                   "--num-classes", "2", "--epochs", "5"])
        assert rc == 0
        assert ("down", "gs://b/train.csv") in transfers
        assert ("down", "gs://b/conf.json") in transfers
        assert ("up", "gs://b/model.zip") in transfers
        assert (bucket / "model.zip").exists()

        # and test-mode reads the model back through the same layer
        rc = main(["test", "--model", "gs://b/model.zip",
                   "--input", "gs://b/train.csv", "--num-classes", "2"])
        assert rc == 0
