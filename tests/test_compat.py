"""Direct unit tests for util/compat.py — the jax 0.4/0.5 shim layer.

It fixed 60+ seed tests in PR 1 but had no coverage of its own: both
version branches are exercised here by monkeypatching the module-level
probe results, with a recording fake standing in for the real
jax.shard_map so the kwarg translation is asserted exactly."""

import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.util import compat


class _FakeMesh:
    axis_names = ("data", "model", "seq")


def _record(calls):
    def fake_shard_map(f, **kwargs):
        calls.append((f, kwargs))
        return f
    return fake_shard_map


def test_new_jax_passes_kwargs_through(monkeypatch):
    calls = []
    monkeypatch.setattr(compat, "_shard_map", _record(calls))
    monkeypatch.setattr(compat, "_SHARD_MAP_VMA_KW", True)
    fn = lambda x: x  # noqa: E731
    compat.shard_map(fn, mesh=_FakeMesh(), check_vma=False,
                     axis_names=("seq",))
    (_f, kwargs), = calls
    assert _f is fn
    assert kwargs["check_vma"] is False
    assert kwargs["axis_names"] == ("seq",)
    assert "check_rep" not in kwargs and "auto" not in kwargs


def test_old_jax_translates_check_vma_to_check_rep(monkeypatch):
    calls = []
    monkeypatch.setattr(compat, "_shard_map", _record(calls))
    monkeypatch.setattr(compat, "_SHARD_MAP_VMA_KW", False)
    compat.shard_map(lambda x: x, mesh=_FakeMesh(), check_vma=False)
    (_f, kwargs), = calls
    assert kwargs["check_rep"] is False
    assert "check_vma" not in kwargs


def test_old_jax_translates_axis_names_to_auto(monkeypatch):
    calls = []
    monkeypatch.setattr(compat, "_shard_map", _record(calls))
    monkeypatch.setattr(compat, "_SHARD_MAP_VMA_KW", False)
    # manual over seq only -> auto = the other mesh axes
    compat.shard_map(lambda x: x, mesh=_FakeMesh(),
                     axis_names=("seq",))
    (_f, kwargs), = calls
    assert "axis_names" not in kwargs
    assert kwargs["auto"] == frozenset({"data", "model"})


def test_old_jax_fully_manual_drops_auto(monkeypatch):
    calls = []
    monkeypatch.setattr(compat, "_shard_map", _record(calls))
    monkeypatch.setattr(compat, "_SHARD_MAP_VMA_KW", False)
    compat.shard_map(lambda x: x, mesh=_FakeMesh(),
                     axis_names=("data", "model", "seq"))
    (_f, kwargs), = calls
    # manual == all mesh axes: no partial-manual selector at all
    assert "auto" not in kwargs and "axis_names" not in kwargs


def test_shard_map_runs_for_real_on_this_jax():
    """Not a fake: the translated call must be accepted by whichever jax
    generation this container ships."""
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("d",))
    out = compat.shard_map(
        lambda x: x * 2, mesh=mesh, in_specs=P(), out_specs=P(),
        check_vma=False)(jnp.arange(4.0))
    np.testing.assert_allclose(np.asarray(out), [0.0, 2.0, 4.0, 6.0])


def test_tpu_compiler_params_maps_to_available_class(monkeypatch):
    recorded = {}

    class FakeParams:
        def __init__(self, **kw):
            recorded.update(kw)

    monkeypatch.setattr(compat, "_COMPILER_PARAMS_CLS", FakeParams)
    obj = compat.tpu_compiler_params(vmem_limit_bytes=1 << 20)
    assert isinstance(obj, FakeParams)
    assert recorded == {"vmem_limit_bytes": 1 << 20}


def test_tpu_compiler_params_real_class_accepts_vmem_limit():
    obj = compat.tpu_compiler_params(vmem_limit_bytes=64 * 1024 * 1024)
    assert obj.vmem_limit_bytes == 64 * 1024 * 1024


def test_pcast_varying_identity_when_pcast_missing(monkeypatch):
    fake_lax = types.SimpleNamespace()  # no .pcast attribute -> 0.4 path
    monkeypatch.setattr(compat, "_jax",
                        types.SimpleNamespace(lax=fake_lax))
    x = jnp.ones((3,))
    assert compat.pcast_varying(x, ("seq",)) is x


def test_pcast_varying_calls_pcast_when_present(monkeypatch):
    calls = {}

    def fake_pcast(x, axis_names, to):
        calls["args"] = (x, axis_names, to)
        return x

    monkeypatch.setattr(
        compat, "_jax",
        types.SimpleNamespace(lax=types.SimpleNamespace(pcast=fake_pcast)))
    x = jnp.ones((3,))
    assert compat.pcast_varying(x, ("seq",)) is x
    assert calls["args"] == (x, ("seq",), "varying")


def test_module_resolved_a_shard_map_at_import():
    """Whichever generation: the probe must have bound SOME shard_map and
    a compiler-params class, or the whole parallel/ layer is dead."""
    assert callable(compat._shard_map)
    assert compat._COMPILER_PARAMS_CLS is not None
    assert isinstance(compat._SHARD_MAP_VMA_KW, bool)


@pytest.mark.parametrize("bad_kw", [{"check_vma": True},
                                    {"axis_names": ("nope",)}])
def test_old_jax_translation_never_leaks_new_spellings(monkeypatch, bad_kw):
    calls = []
    monkeypatch.setattr(compat, "_shard_map", _record(calls))
    monkeypatch.setattr(compat, "_SHARD_MAP_VMA_KW", False)
    compat.shard_map(lambda x: x, mesh=_FakeMesh(), **bad_kw)
    (_f, kwargs), = calls
    assert not set(kwargs) & {"check_vma", "axis_names"}
