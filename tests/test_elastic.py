"""Elastic fault-tolerant training: the tier-1 recovery proof.

The acceptance arc (ISSUE 6): launch N=3 processes, inject `kill@step3`
into worker 1 mid-fit, and assert the fleet checkpoints, re-forms at
N'=2 through the supervisor, resumes with a CONTINUOUS step counter,
and reaches final params matching an uninterrupted same-total-steps
single-process run — with the whole fault→recovery timeline
reconstructable from the telemetry JSONL alone.

Documented tolerance: the N-process run averages gradients over equal
batch shards via the mesh allreduce while the reference takes the full
batch on one device, so the trajectories agree up to float32 reduction
order — atol 1e-5 on the flat parameter vector (the same bound
tests/test_distributed.py uses for the single-step parity proof).

Every spawned-fleet test runs under a hard wall-clock deadline (the
launcher reaps stragglers; a wedged fleet fails bounded, never hangs).
"""

import json
import os
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.distributed import elastic, faults as faults_mod
from deeplearning4j_tpu.telemetry.recorder import Recorder, set_default

pytestmark = [pytest.mark.distributed, pytest.mark.faults]

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join("tests", "elastic_worker.py")

TOTAL_STEPS = 6


def _events(path):
    if not os.path.exists(path):
        return []
    with open(path) as fh:
        return [json.loads(line) for line in fh]


def _reference_params():
    """The uninterrupted run: one process, full global batches, same
    seed, same TOTAL_STEPS."""
    from deeplearning4j_tpu.datasets.api import DataSet
    from tests.cluster_worker import build_net
    from tests.elastic_worker import batch_for_step

    net = build_net().init()
    for step in range(1, TOTAL_STEPS + 1):
        net.fit(DataSet(*batch_for_step(step)))
    assert net.iteration_count == TOTAL_STEPS
    return np.asarray(net.params_flat())


def test_kill_one_worker_fleet_reforms_and_resumes(tmp_path):
    ckpt = tmp_path / "ckpt"
    out = tmp_path / "out"
    ckpt.mkdir()
    out.mkdir()
    fleet_log = str(tmp_path / "fleet.jsonl")
    sup_log = str(tmp_path / "sup.jsonl")

    rec = Recorder(sup_log)
    prev = set_default(rec)
    sup = elastic.ElasticSupervisor(
        [sys.executable, WORKER, str(ckpt), str(out)],
        n_processes=3, min_processes=2, total_steps=TOTAL_STEPS,
        checkpoint_dir=str(ckpt), max_reforms=2, local_device_count=2,
        gen_timeout=150.0, faults="p1:kill@step3",
        snapshot_path=str(tmp_path / "coord.json"),
        extra_env={"PYTHONPATH": ROOT,
                   "DL4J_TPU_TELEMETRY": fleet_log},
        cwd=ROOT)
    try:
        result = sup.run()
    finally:
        set_default(prev)
        sup.close()

    # --- the generational shape: N=3 with the injected death, then a
    # clean re-form at N'=2
    assert [g.n_processes for g in result.generations] == [3, 2]
    gen0, gen1 = result.generations
    assert gen0.results[1].exit_class == faults_mod.EXIT_INJECTED_KILL
    assert 1 in gen0.dead and not gen0.clean
    assert gen1.clean

    # --- continuous step counter + final-params parity with the
    # uninterrupted reference (documented tolerance: see module docstring)
    done = (out / "done.txt").read_text()
    assert f"steps={TOTAL_STEPS}" in done and "n_processes=2" in done
    final = np.load(str(out / "final_params.npy"))
    np.testing.assert_allclose(final, _reference_params(), atol=1e-5)

    # --- the checkpoint trail: the resumed run's steps committed, and
    # the latest step's meta carries the continuous counter
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    ckptr = ShardedCheckpointer(str(ckpt))
    assert ckptr.steps()[-1] == TOTAL_STEPS
    with open(os.path.join(str(ckpt), f"step_{TOTAL_STEPS}",
                           "meta.json")) as fh:
        assert json.load(fh)["iteration"] == TOTAL_STEPS

    # --- the durable coordinator journaled both generations
    assert int(sup.coordinator.read_config(elastic.GEN_KEY)) == 1
    members = sup.coordinator.read_config("elastic/members/1")
    assert members["n_processes"] == 2

    # ---------------- timeline from telemetry JSONL alone ----------------
    sup_events = _events(sup_log)
    # 1. the injected fault was declared before anything died
    injected = [e for e in sup_events if e["event"] == "fault"
                and e.get("injected")]
    assert [(e["kind"], e["process_id"], e["step"])
            for e in injected] == [("kill", 1, 3)]
    # 3. the re-form decision names the new fleet size and the dead
    reform = [e for e in sup_events if e["event"] == "fault"
              and e["kind"] == "reform"]
    assert len(reform) == 1 and reform[0]["n_processes"] == 2 \
        and 1 in reform[0]["dead"]
    # 2. every generation-0 exit was classified (the events BEFORE the
    # re-form decision; generation 1's clean exits come after it)
    gen0_cut = sup_events.index(reform[0])
    observed = {e["process_id"]: e["kind"] for e in sup_events[:gen0_cut]
                if e["event"] == "fault" and e.get("observed_exit")}
    assert observed[1] == faults_mod.EXIT_INJECTED_KILL
    assert set(observed) == {0, 1, 2}
    # generation 1 then exits clean across the board
    gen1_observed = {e["process_id"]: e["kind"]
                     for e in sup_events[gen0_cut:]
                     if e["event"] == "fault" and e.get("observed_exit")}
    assert gen1_observed == {0: faults_mod.EXIT_CLEAN,
                             1: faults_mod.EXIT_CLEAN}
    # 4. the victim's own log ends with the fault firing at step 3
    p1_events = _events(fleet_log + ".p1")
    fired = [e for e in p1_events if e["event"] == "fault"
             and e.get("fired")]
    assert [(e["kind"], e["step"]) for e in fired] == [("kill", 3)]
    # 5. worker 0's log shows the CONTINUOUS counter: steps up to the
    # kill in one run id, an elastic_resume mark, then the rest in a
    # second run id — 1..TOTAL_STEPS overall with no step repeated
    p0_events = _events(fleet_log + ".p0")
    steps = [e["iteration"] for e in p0_events if e["event"] == "step"]
    assert steps == list(range(1, TOTAL_STEPS + 1))
    resumes = [e for e in p0_events if e["event"] == "span"
               and e.get("name") == "elastic_resume"]
    assert [r["start_step"] for r in resumes] == [0, 3]
    assert resumes[-1]["num_processes"] == 2
    assert len({e["run"] for e in p0_events}) == 2  # two generations
    # 6. the re-formed generation restored THROUGH the portable
    # resharding engine: the gen-1 resume plans the checkpoint's
    # recorded 3-process placement onto the N'=2 mesh (a reshard_plan
    # event per resuming worker), and NO path in the whole run
    # host-gathered a full sharded tree
    plans = [e for e in p0_events if e["event"] == "reshard_plan"]
    assert plans and all(e["path"] == "checkpoint" for e in plans)
    assert any(e["src"].endswith("p3") and e["dst"].endswith("p2")
               for e in plans), plans
    all_fleet = [e for p in range(3) for e in _events(f"{fleet_log}.p{p}")]
    assert not [e for e in all_fleet + sup_events
                if e["event"] == "host_gather"]
    # 7. the elastic re-PLAN (ISSUE 14): every generation SEARCHED its
    # placement instead of inheriting roles — worker 0 emits one
    # placement_search event per generation (path=elastic), and the
    # re-formed N'=2 generation's winner is the searched 4-device
    # 2-process data placement the resumed run trained through (the
    # same mesh the old hand-specified path built, so the resume parity
    # asserted above IS the searched-placement resume)
    searches = [e for e in p0_events
                if e["event"] == "placement_search"]
    assert len(searches) == 2, searches  # one per generation
    assert all(e["path"] == "elastic" for e in searches)
    assert searches[0]["fleet"] == "3x2" \
        and searches[0]["winner"] == "6 (data=data) p3"
    assert searches[1]["fleet"] == "2x2" \
        and searches[1]["winner"] == "4 (data=data) p2"
    assert all(e["candidates_considered"] >= e["candidates_feasible"]
               for e in searches)
    # 8. the supervisor's own re-plan is on the record BEFORE the
    # relaunch: a placement_search (path=reform) for gen 1, the reform
    # fault event names the winner, and the durable coordinator
    # journaled it
    sup_searches = [e for e in sup_events
                    if e["event"] == "placement_search"]
    assert [e["path"] for e in sup_searches] == ["reform"]
    assert sup_searches[0]["gen"] == 1 \
        and sup_searches[0]["winner"] == "4 (data=data) p2"
    assert sup_events.index(sup_searches[0]) < sup_events.index(reform[0])
    assert reform[0]["placement"] == "4 (data=data) p2"
    journaled = sup.coordinator.read_config("elastic/placement/1")
    assert journaled["mesh_axes"] == [["data", 4]]
    assert journaled["process_count"] == 2


def test_checkpoint_under_spanning_mesh_restores_on_one_process(tmp_path):
    """The ROADMAP resharding seed: params saved (host-materialized)
    under a 2-process mesh restore onto ONE process bit-identically."""
    ckpt = tmp_path / "ckpt"
    out = tmp_path / "out"
    ckpt.mkdir()
    out.mkdir()

    sup = elastic.ElasticSupervisor(
        [sys.executable, WORKER, str(ckpt), str(out)],
        n_processes=2, min_processes=2, total_steps=2,
        checkpoint_dir=str(ckpt), max_reforms=0, local_device_count=2,
        gen_timeout=120.0,
        extra_env={"PYTHONPATH": ROOT}, cwd=ROOT)
    try:
        result = sup.run()
    finally:
        sup.close()
    assert len(result.generations) == 1 and result.generations[0].clean

    # restore IN THIS single process (no rendezvous, its own devices)
    from tests.cluster_worker import build_net

    net = build_net()
    assert net.resume_from(str(ckpt)) == 2
    restored = np.asarray(net.params_flat())
    saved = np.load(str(out / "final_params.npy"))
    assert np.array_equal(restored, saved), \
        "2-process host checkpoint did not restore bit-identically on 1"
