"""Distributed tests on the 8-virtual-device CPU mesh (SURVEY.md §4 item 5 —
the reference simulates clusters with Spark local[*] in one JVM)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
    Updater,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import (
    DataParallelTrainer,
    ParameterAveragingTrainer,
    make_mesh,
    ring_attention,
)
from deeplearning4j_tpu.parallel.ring_attention import (
    ring_self_attention,
    sequence_sharded_attention_reference,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def _net():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .learning_rate(0.1)
        .updater(Updater.SGD)
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(OutputLayer(n_in=8, n_out=2, activation="softmax"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int64)
    return DataSet(x, np.eye(2, dtype=np.float32)[y])


def test_allreduce_dp_matches_single_device():
    """Gradient-allreduce DP over the mesh must equal single-device training
    on the full batch (sync SGD semantics)."""
    ds = _data(64)
    net_a = _net()
    net_b = _net()
    # identical init
    net_b.params = jax.tree.map(jnp.copy, net_a.params)
    net_b.opt_state = net_a.tx.init(net_b.params)

    net_a.fit(ListDataSetIterator([ds]), epochs=3)

    mesh = make_mesh({"data": 8})
    trainer = DataParallelTrainer(net_b, mesh)
    trainer.fit(ListDataSetIterator([ds]), epochs=3)

    pa = net_a.params_flat()
    pb = net_b.params_flat()
    np.testing.assert_allclose(pa, pb, atol=2e-5)


def test_parameter_averaging_trainer_runs_and_learns():
    ds = _data(128)
    net = _net()
    mesh = make_mesh({"data": 8})
    trainer = ParameterAveragingTrainer(net, mesh, averaging_frequency=2)
    before = net.score(ds)
    trainer.fit(ListDataSetIterator(ds.batch_by(64)), epochs=20)
    after = net.score(ds)
    assert after < before, f"param-averaging did not reduce loss {before}->{after}"


def test_param_avg_every_step_matches_full_batch_sgd():
    """averaging_frequency=1 with plain SGD and equal shards == full-batch
    SGD on the concatenated batch (average of per-shard gradients)."""
    ds = _data(64)
    net_a = _net()
    net_b = _net()
    net_b.params = jax.tree.map(jnp.copy, net_a.params)
    net_b.opt_state = net_b.tx.init(net_b.params)

    net_a.fit(ds)  # one step on full batch
    mesh = make_mesh({"data": 8})
    tr = ParameterAveragingTrainer(net_b, mesh, averaging_frequency=1)
    tr.fit(ds)
    np.testing.assert_allclose(net_a.params_flat(), net_b.params_flat(), atol=2e-5)


def test_ring_attention_matches_reference():
    B, H, T, D = 2, 2, 16, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, H, T, D)), jnp.float32)
    mesh = make_mesh({"seq": 8})
    for causal in (True, False):
        out_ring = ring_self_attention(q, k, v, mesh, causal=causal)
        out_ref = sequence_sharded_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_ref),
                                   atol=1e-5)


def test_tp_sharded_transformer_params():
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.parallel.tensor_parallel import shard_params

    net = transformer_lm(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_length=16)
    net.init()
    mesh = make_mesh({"data": 2, "model": 4})
    net.params = shard_params(net.params, mesh)
    # column-sharded qkv: last dim split over 4 devices
    qkv = net.params["blk0_attn"]["Wqkv"]
    assert qkv.sharding.spec == (None, "model")
    # forward still correct under sharded params
    toks = np.arange(2 * 8).reshape(2, 8) % 64
    out = np.asarray(net.output(toks))
    assert out.shape == (2, 8, 64)
    assert np.allclose(out.sum(-1), 1.0, atol=1e-4)


@pytest.mark.slow
def test_computation_graph_under_data_parallel_trainer():
    """DP-3: a DAG network trains under the mesh-sharded step and matches
    its own single-device training (gradient allreduce is exact for the
    full batch)."""
    from deeplearning4j_tpu.models.resnet import resnet20
    from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

    rng = np.random.default_rng(0)
    x = rng.random((16, 32, 32, 3), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]
    ds = DataSet(x, y)

    mesh_net = resnet20(seed=5)
    mesh_net.init()
    DataParallelTrainer(mesh_net, make_mesh({"data": 8})).fit(
        ListDataSetIterator([ds] * 2))
    assert np.isfinite(mesh_net.score_value)

    single = resnet20(seed=5)
    single.init()
    single.fit(ListDataSetIterator([ds] * 2))
    np.testing.assert_allclose(mesh_net.score_value, single.score_value,
                               rtol=2e-3)
    # Adam's eps denominator amplifies float-reassociation noise on tiny
    # gradients; the parity bound is loose but still catches wiring bugs
    np.testing.assert_allclose(np.asarray(mesh_net.params_flat()),
                               np.asarray(single.params_flat()), atol=5e-3)


def test_distributed_evaluation_matches_single_device():
    """Mesh-sharded inference/eval == single-device (reference
    EvaluateFlatMapFunction + Evaluation.merge semantics)."""
    from deeplearning4j_tpu.models.resnet import resnet20
    from deeplearning4j_tpu.datasets.api import DataSet

    rng = np.random.default_rng(1)
    x = rng.random((16, 32, 32, 3), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 16)]

    net = resnet20(seed=9)
    net.init()
    ref_out = np.asarray(net.output(x))
    ref_acc = net.evaluate(DataSet(x, y)).accuracy()
    ref_acc10 = net.evaluate(DataSet(x[:10], y[:10])).accuracy()

    net.set_mesh(make_mesh({"data": 8}))
    mesh_out = np.asarray(net.output(x))
    np.testing.assert_allclose(mesh_out, ref_out, atol=2e-5)
    assert net.evaluate(DataSet(x, y)).accuracy() == ref_acc
    # indivisible batches pad-and-slice instead of crashing
    odd = np.asarray(net.output(x[:10]))
    np.testing.assert_allclose(odd, ref_out[:10], atol=2e-5)
    assert net.evaluate(DataSet(x[:10], y[:10])).accuracy() == ref_acc10


def test_zero1_weight_update_sharding_matches_replicated():
    """ZeRO-1 (arXiv:2004.13336): optimizer state sharded over 'data' —
    same trained params as replicated DP, with Adam moments actually
    living sharded on the mesh."""
    mesh = make_mesh({"data": 8})
    ds = _data(64)

    def build():
        conf = (NeuralNetConfiguration.builder().seed(9).learning_rate(0.05)
                .updater(Updater.ADAM).list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=2, activation="softmax"))
                .build())
        return MultiLayerNetwork(conf).init()

    a = build()
    a.set_mesh(mesh)
    a.fit(ListDataSetIterator([ds]), epochs=3)

    b = build()
    b.set_mesh(mesh, zero1=True)
    b.fit(ListDataSetIterator([ds]), epochs=3)

    for n in a.params:
        for k in a.params[n]:
            np.testing.assert_allclose(np.asarray(a.params[n][k]),
                                       np.asarray(b.params[n][k]),
                                       rtol=1e-5, atol=1e-6)
    # inspect the PartitionSpec, not the sharding repr (the repr embeds
    # the mesh, whose axis names appear even for replicated leaves)
    sharded = [x for x in jax.tree.leaves(b.opt_state)
               if hasattr(x, "sharding")
               and "data" in str(getattr(x.sharding, "spec", ""))]
    assert sharded, "no optimizer-state leaf is sharded over 'data'"
