"""NLP periphery (nlp/sentiment.py) and TPU-VM provisioning (provision/)
— reference SWN3.java, UIMA PoStagger, deeplearning4j-aws Ec2BoxCreator."""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.sentiment import (
    PosAwareTokenizerFactory,
    SentiWordNet,
    pos_tag,
)
from deeplearning4j_tpu.provision import (
    TpuPodLauncher,
    TpuVmCreator,
    bootstrap_script,
)


def test_seed_lexicon_classification():
    swn = SentiWordNet()
    assert swn.classify("excellent") == "strong_positive"
    assert swn.classify("terrible") == "strong_negative"
    assert swn.classify("unknownword") == "neutral"
    assert swn.classify_score(0.3) == "positive"
    assert swn.classify_score(-0.3) == "negative"
    assert swn.classify_score(0.1) == "weak_positive"


def test_swn_tsv_parse_rank_weighting(tmp_path):
    # two senses of 'cool': rank 1 strongly positive, rank 2 neutral ->
    # 1/rank weighting pulls the aggregate toward the first sense
    p = tmp_path / "swn.txt"
    p.write_text("# SentiWordNet\n"
                 "a\t1\t0.75\t0.0\tcool#1\n"
                 "a\t2\t0.0\t0.0\tcool#2\n"
                 "v\t3\t0.0\t0.5\tstink#1\n")
    swn = SentiWordNet(str(p))
    expected = (0.75 / 1 + 0.0 / 2) / (1 + 0.5)
    assert abs(swn.extract("cool", "a") - expected) < 1e-9
    assert swn.extract("stink", "v") == -0.5


def test_pos_tagger_rules():
    tagged = dict(pos_tag(["the", "dog", "ran", "quickly", "is", "happiness"]))
    assert tagged["the"] == "d"
    assert tagged["quickly"] == "r"
    assert tagged["is"] == "v"
    assert tagged["happiness"] == "n"
    assert tagged["dog"] == "n"  # default


def test_sentence_scoring_pipeline():
    swn = SentiWordNet()
    good = swn.score_tokens(pos_tag("a wonderful great movie".split()))
    bad = swn.score_tokens(pos_tag("a terrible awful movie".split()))
    assert good > 0 > bad


def test_pos_aware_tokenizer_factory_feeds_word2vec_keys():
    tf = PosAwareTokenizerFactory()
    toks = tf.create("The dog runs happily").get_tokens()
    assert all("#" in t for t in toks)
    assert "happily#r" in toks


# ------------------------------------------------------------- provisioning

def test_tpu_vm_lifecycle_commands():
    c = TpuVmCreator("trainer", zone="us-east5-b",
                     accelerator_type="v5litepod-16", project="proj",
                     preemptible=True, labels={"team": "ml"})
    create = c.create_command()
    assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "--accelerator-type" in create and "v5litepod-16" in create
    assert "--preemptible" in create and "team=ml" in " ".join(create)
    assert "delete" in c.delete_command()
    ssh = c.ssh_command("echo hi", worker="0")
    assert "--worker" in ssh and "echo hi" in ssh
    assert c.num_hosts() == 2  # 16 chips / 8 per v5e host


def test_bootstrap_script_contents():
    script = bootstrap_script(extra_env={"JAX_PLATFORMS": "tpu"})
    assert "pip install" in script
    assert "deeplearning4j_tpu" in script
    assert "JAX_PLATFORMS" in script
    assert script.startswith("#!")


def test_pod_launch_plan():
    import base64

    c = TpuVmCreator("pod", accelerator_type="v5litepod-256")
    launcher = TpuPodLauncher(c)
    plan = launcher.plan("python3 -m deeplearning4j_tpu.cli train --conf c.json")
    assert len(plan) == 3  # create, bootstrap, launch
    assert "create" in plan[0]
    # the bootstrap ships base64 (newline-folding would comment everything
    # out behind the shebang) and decodes to the full script
    assert "base64 -d | bash" in plan[1]
    encoded = plan[1].split("echo ")[1].split(" |")[0]
    decoded = base64.b64decode(encoded).decode()
    assert "pip install" in decoded and decoded.startswith("#!")
    assert "DL4J_TPU_EXPECTED_HOSTS=32" in plan[2]  # 256/8 hosts
    assert "deeplearning4j_tpu.cli" in plan[2]


def test_num_hosts_per_generation():
    assert TpuVmCreator("a", accelerator_type="v3-8").num_hosts() == 1
    assert TpuVmCreator("a", accelerator_type="v3-32").num_hosts() == 4
    assert TpuVmCreator("a", accelerator_type="v4-16").num_hosts() == 2
    assert TpuVmCreator("a", accelerator_type="v4-32").num_hosts() == 4
    assert TpuVmCreator("a", accelerator_type="v5litepod-16").num_hosts() == 2


def test_score_tokens_covers_suffixless_adjectives():
    swn = SentiWordNet()
    assert swn.score_tokens(pos_tag("a good movie".split())) > 0
    assert swn.score_tokens(pos_tag("a bad movie".split())) < 0
