"""Subprocess body for the 4-axis composition test (needs 16 virtual
devices; the suite conftest pins the process to 8)."""
from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

ensure_cpu_devices(16)

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.models.transformer import transformer_moe_lm
from deeplearning4j_tpu.parallel.mesh import make_mesh


def main():
    V, T, B = 64, 8, 8
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, V, (B, T)), np.int32)
    labs = np.eye(V, dtype=np.float32)[np.roll(toks, -1, axis=1)]
    ds = DataSet(toks, labs)

    def net_():
        n = transformer_moe_lm(vocab_size=V, d_model=16, n_heads=2,
                               n_layers=4, n_experts=4, top_k=2,
                               d_expert_hidden=24, max_length=T,
                               capacity_factor=2.0)
        n.init()
        return n

    dense = net_()
    dense.fit(ds)
    four = net_()
    four.set_mesh(make_mesh({"data": 2, "model": 2, "pipe": 2, "expert": 2}),
                  axes={"data": "data", "model": "model", "pipe": "pipe",
                        "expert": "expert"}, n_microbatches=2)
    four.fit(ds)
    diff = abs(float(four.score_value) - float(dense.score_value))
    assert diff < 2e-3, (float(four.score_value), float(dense.score_value))
    print(f"FOUR_AXIS_OK {diff:.2e}")


if __name__ == "__main__":
    main()
