"""Tier-1 gate for graftlint stage 4 (ISSUE 17): the host-concurrency
race & deadlock analyzer. Three layers of teeth:

* the attribute->lock guard INFERENCE is pinned exactly for the real
  runtime classes (PagePool, WeightStore, Channel, the engine workers,
  Recorder, MetricsRegistry) — a refactor that silently drops a guard
  fails here by attribute name, before any race fires under load;
* every rule G025-G028 is proven on an on-disk positive AND negative
  fixture, and the lock-order audit is proven on a deliberately
  inverted two-class fixture (D001, CLI exit 1 regardless of --check)
  and a sink-fan-out fixture (D002);
* the concrete races this PR's first sweep found and fixed (engine
  counters, Recorder sink fan-out under `_lock`, MetricsRegistry
  collectors under `_lock`) are held fixed by behavioral regression
  tests, not just by the linter staying quiet.
"""

import os
import subprocess
import sys
import threading

import pytest

from deeplearning4j_tpu.analysis import (guard_map_for_file, lint_source,
                                         lock_audit)
from deeplearning4j_tpu.analysis.concurrency_rules import (CONC_RULE_DOCS,
                                                           CONC_RULE_IDS)

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "deeplearning4j_tpu")
FIX = os.path.join(ROOT, "tests", "fixtures")
CLI = os.path.join(ROOT, "tools", "graftlint.py")


def _fixture_rules(relpath):
    """Rule ids firing on an on-disk fixture, linted at its repo path
    (the serving/ subdir keeps scoped rules in scope)."""
    path = os.path.join(FIX, relpath)
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    return {f.rule for f in lint_source(src, f"tests/fixtures/{relpath}")}


# ------------------------------------------------- inferred guard maps
#
# guard_map() is the inference G025 runs on: a lock group guards an
# attribute when >= 90% of its non-__init__ mutation sites sit under
# `with self.<lock>:`. These maps are the concurrency CONTRACT of the
# runtime classes; pin them exactly so dropping a guard fails by name.

def _guards(rel):
    return guard_map_for_file(os.path.join(PKG, rel))


def test_guard_map_pagepool():
    assert _guards("serving/kvcache.py")["PagePool"] == {
        "_in_use": "_lock", "peak_in_use": "_lock"}


def test_guard_map_weightstore():
    # _current is lock-free on the READ side (plain reference store is
    # GIL-atomic, the lock-free-reader design) but every swap mutation
    # happens under _lock — which is exactly what the map pins.
    assert _guards("serving/fleet.py")["WeightStore"] == {
        "_current": "_lock", "last_swap_ts": "_lock"}


def test_guard_map_channel():
    # Channel's two Conditions are built over ONE Lock: the inference
    # must unify them into a single lock group, not two.
    assert _guards("data/prefetcher.py")["Channel"] == {
        "_buf": "_not_empty|_not_full",
        "_closed": "_not_empty|_not_full",
        "_error": "_not_empty|_not_full",
        "_stopped": "_not_empty|_not_full",
    }


def test_guard_map_engine_counters():
    """The stat counters this PR put under `_mu` after the first sweep
    flagged them (G025): thread-side `+=` read by describe()."""
    maps = _guards("serving/engine.py")
    assert maps["_Replica"] == {
        "batches_run": "_mu", "failed": "_mu", "served": "_mu",
        "trace_count": "_mu"}
    gw = maps["_GenWorker"]
    assert gw["pending"] == "_cv" and gw["_closed"] == "_cv"
    for counter in ("served", "failed", "trace_count", "tokens_out",
                    "decode_steps_run", "verify_steps_run",
                    "accepted_tokens", "drafted_tokens", "slot_steps",
                    "draft_overhead_s"):
        assert gw[counter] == "_mu", counter


def test_guard_map_telemetry():
    assert _guards("telemetry/recorder.py")["Recorder"] == {
        "_seq": "_lock", "_sinks": "_lock", "_span_seq": "_lock",
        "events": "_lock"}
    assert _guards("telemetry/metrics.py")["MetricsRegistry"] == {
        "_collectors": "_lock", "_metrics": "_lock"}


# ------------------------------------------------- on-disk rule fixtures

FIXTURE_CASES = [
    ("G025", "conc_race_pos.py", "conc_race_neg.py"),
    ("G026", "serving/conc_blocking_pos.py",
     "serving/conc_blocking_neg.py"),
    ("G027", "serving/conc_wait_pos.py", "serving/conc_wait_neg.py"),
    ("G028", "conc_thread_pos.py", "conc_thread_neg.py"),
]


@pytest.mark.parametrize("rule,pos,neg", FIXTURE_CASES,
                         ids=[c[0] for c in FIXTURE_CASES])
def test_rule_fires_on_disk_fixture(rule, pos, neg):
    assert rule in _fixture_rules(pos), f"{rule} missed {pos}"
    assert rule not in _fixture_rules(neg), f"{rule} false-positive {neg}"


def test_every_concurrency_rule_has_a_fixture_pair():
    assert {c[0] for c in FIXTURE_CASES} == set(CONC_RULE_IDS) == \
        set(CONC_RULE_DOCS)


def test_findings_carry_the_concurrency_stage_label():
    path = os.path.join(FIX, "conc_race_pos.py")
    with open(path, encoding="utf-8") as fh:
        findings = [f for f in lint_source(fh.read(), path)
                    if f.rule in CONC_RULE_IDS]
    assert findings and all(f.stage == "concurrency" for f in findings)


# ------------------------------------------------- lock-order audit

def test_lock_inversion_fixture_trips_d001_api():
    findings, edges = lock_audit.audit_paths(
        [os.path.join(FIX, "conc_lock_inversion.py")])
    assert any(f.rule == "D001" for f in findings)
    assert ("conc_lock_inversion.py:PoolSide._lock -> "
            "conc_lock_inversion.py:RegistrySide._lock") in edges
    assert ("conc_lock_inversion.py:RegistrySide._lock -> "
            "conc_lock_inversion.py:PoolSide._lock") in edges


def test_lock_inversion_fixture_exits_one_from_cli():
    """D001 is never reportable-only: the CLI exits 1 on a cycle even
    WITHOUT --check and regardless of any baseline."""
    proc = subprocess.run(
        [sys.executable, CLI, "--stage", "concurrency",
         os.path.join(FIX, "conc_lock_inversion.py")],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "D001" in proc.stdout
    assert "lock-order cycle" in proc.stdout


def test_sink_fanout_fixture_trips_d002_and_g026():
    findings, _ = lock_audit.audit_paths(
        [os.path.join(FIX, "conc_sink_fanout.py")])
    assert [f.rule for f in findings] == ["D002"]
    # the same shape is caught at the AST level when in G026's scope
    with open(os.path.join(FIX, "conc_sink_fanout.py"),
              encoding="utf-8") as fh:
        src = fh.read()
    rules = {f.rule for f in lint_source(
        src, "deeplearning4j_tpu/telemetry/_fixture.py")}
    assert "G026" in rules


def test_package_lock_graph_is_frozen_and_acyclic():
    """The real package audits clean against the frozen edge set, and
    the frozen set is non-trivial: the serving engine really does hold
    `_GenWorker._cv` across PagePool/Recorder acquisitions."""
    findings, edges = lock_audit.audit()
    assert findings == [], [f.format() for f in findings]
    frozen = lock_audit.load_locks()
    assert frozen == sorted(edges)
    assert any(e.startswith("deeplearning4j_tpu/serving/") and
               "->" in e for e in frozen)
    assert any("PagePool._lock" in e for e in frozen)


# ------------------------------------------------- behavioral regressions
#
# The three concrete findings the first stage-4 sweep produced were
# FIXED, not suppressed. These tests hold the fixes in place at the
# behavior level (the linter staying quiet is necessary, not
# sufficient).

def test_recorder_sinks_run_outside_the_lock():
    from deeplearning4j_tpu.telemetry.recorder import Recorder
    rec = Recorder()
    states = []
    rec.add_sink(lambda _e: states.append(rec._lock.locked()))
    rec.event("probe")
    assert states == [False]


def test_recorder_seq_is_unique_across_threads():
    from deeplearning4j_tpu.telemetry.recorder import Recorder
    rec = Recorder(keep=10_000)
    n_threads, per_thread = 8, 200

    def emit():
        for _ in range(per_thread):
            rec.event("tick")

    threads = [threading.Thread(target=emit) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    seqs = [e["seq"] for e in rec.events]
    assert len(seqs) == n_threads * per_thread
    assert len(set(seqs)) == len(seqs)


def test_metrics_collectors_run_without_the_registry_lock():
    """A collector that updates the registry it is registered on (the
    natural scrape-time shape) must not deadlock: render() snapshots
    the collector list under `_lock`, then runs collectors OUTSIDE it."""
    from deeplearning4j_tpu.telemetry.metrics import (MetricsRegistry,
                                                      parse_exposition)
    reg = MetricsRegistry()
    scrapes = reg.counter("scrapes_total", "scrape count")
    reg.add_collector(lambda: reg.inc(scrapes))

    out = {}

    def scrape():
        out["text"] = reg.render()

    t = threading.Thread(target=scrape, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), \
        "render() deadlocked: collector ran under the registry lock"
    assert parse_exposition(out["text"])["scrapes_total"] == 1.0
