"""Loss functions (ops/losses.py) — the sparse integer-label mcxent path
vs one-hot, with masks and through jax.grad (the transformer-LM hot path)."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops.losses import compute_loss


def _softmax_case(shape=(4, 6, 10), seed=0):
    rng = np.random.default_rng(seed)
    logits = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    out = jax.nn.softmax(logits, axis=-1)
    idx = jnp.asarray(rng.integers(0, shape[-1], shape[:-1]), jnp.int32)
    onehot = jnp.asarray(np.eye(shape[-1], dtype=np.float32)[np.asarray(idx)])
    return logits, out, idx, onehot


def test_sparse_labels_match_onehot():
    logits, out, idx, onehot = _softmax_case()
    a = compute_loss("mcxent", onehot, out, logits=logits)
    b = compute_loss("mcxent", idx, out, logits=logits)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-6)


def test_sparse_labels_match_onehot_without_logits():
    _, out, idx, onehot = _softmax_case()
    a = compute_loss("negativeloglikelihood", onehot, out)
    b = compute_loss("negativeloglikelihood", idx, out)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-6)


def test_sparse_labels_respect_mask():
    logits, out, idx, onehot = _softmax_case()
    mask = jnp.asarray(np.random.default_rng(1).integers(0, 2, idx.shape),
                       jnp.float32)
    a = compute_loss("mcxent", onehot, out, mask, logits=logits)
    b = compute_loss("mcxent", idx, out, mask, logits=logits)
    np.testing.assert_allclose(float(a), float(b), rtol=1e-6)


def test_sparse_labels_gradient_matches_onehot():
    logits, _, idx, onehot = _softmax_case(shape=(3, 8))

    def loss_fn(lg, labels):
        return compute_loss("mcxent", labels, jax.nn.softmax(lg, -1),
                            logits=lg)

    g_sparse = jax.grad(loss_fn)(logits, idx)
    g_onehot = jax.grad(loss_fn)(logits, onehot)
    np.testing.assert_allclose(np.asarray(g_sparse), np.asarray(g_onehot),
                               atol=1e-6)
