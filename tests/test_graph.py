"""Graph package tests (reference test model: deeplearning4j-graph's
TestGraph/TestDeepWalk — structural checks + embedding sanity on tiny
graphs)."""

import numpy as np
import pytest

from deeplearning4j_tpu.graph import (
    DeepWalk,
    Edge,
    Graph,
    GraphLoader,
    GraphVectorSerializer,
    NoEdgeHandling,
    PopularityWalker,
    RandomWalkIterator,
    WeightedRandomWalkIterator,
)


def _two_cliques(n=6):
    """Two n-cliques joined by a single bridge edge."""
    g = Graph(2 * n)
    for base in (0, n):
        for i in range(n):
            for j in range(i + 1, n):
                g.add_edge(base + i, base + j)
    g.add_edge(0, n)
    return g


class TestGraph:
    def test_add_edge_undirected(self):
        g = Graph(4)
        g.add_edge(0, 1)
        g.add_edge(Edge(1, 2, weight=2.0))
        assert g.num_edges() == 2
        assert g.get_vertex_degree(1) == 2
        assert set(g.get_connected_vertex_indices(1)) == {0, 2}

    def test_directed_edge(self):
        g = Graph(3)
        g.add_edge(0, 1, directed=True)
        assert g.get_vertex_degree(0) == 1
        assert g.get_vertex_degree(1) == 0

    def test_out_of_range(self):
        g = Graph(2)
        with pytest.raises(ValueError):
            g.add_edge(0, 5)

    def test_loader_roundtrip(self, tmp_path):
        p = tmp_path / "edges.txt"
        p.write_text("# comment\n0 1\n1 2\n2 0\n")
        g = GraphLoader.load_undirected_graph_edge_list_file(str(p), 3)
        assert g.num_edges() == 3
        assert g.get_vertex_degree(0) == 2

        pw = tmp_path / "weighted.txt"
        pw.write_text("0,1,0.5\n1,2,2.5\n")
        gw = GraphLoader.load_weighted_edge_list_file(str(pw), 3, delimiter=",")
        assert gw.get_edge_weights(1).tolist() == [0.5, 2.5]


class TestWalkers:
    def test_walk_length_and_validity(self):
        g = _two_cliques(4)
        it = RandomWalkIterator(g, walk_length=10, seed=1)
        walks = list(it)
        assert len(walks) == g.num_vertices()
        for w in walks:
            assert len(w) == 11
            for a, b in zip(w[:-1], w[1:]):
                assert b in g.get_connected_vertex_indices(a) or a == b

    def test_dead_end_self_loop_and_exception(self):
        g = Graph(2)
        g.add_edge(0, 1, directed=True)
        w = RandomWalkIterator(
            g, 5, seed=0,
            no_edge_handling=NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED,
        )._walk_from(1)
        assert w.tolist() == [1] * 6
        # the default matches the reference: EXCEPTION_ON_DISCONNECTED
        with pytest.raises(RuntimeError):
            RandomWalkIterator(g, 5)._walk_from(1)

    def test_dead_end_cutoff(self):
        g = Graph(3)
        g.add_edge(0, 1, directed=True)
        it = RandomWalkIterator(g, 5, no_edge_handling=NoEdgeHandling.CUTOFF_ON_DISCONNECTED)
        w = it._walk_from(0)
        assert w.tolist() == [0, 1]

    def test_weighted_walker_follows_weights(self):
        g = Graph(3)
        g.add_edge(0, 1, weight=1000.0)
        g.add_edge(0, 2, weight=0.001)
        it = WeightedRandomWalkIterator(g, 1, seed=0)
        hits = [it._walk_from(0)[1] for _ in range(50)]
        assert hits.count(1) >= 48

    def test_popularity_walker_prefers_hubs(self):
        g = Graph(5)
        # vertex 1 is a hub (degree 3), vertex 2 a leaf (degree 1)
        g.add_edge(0, 1)
        g.add_edge(0, 2)
        g.add_edge(1, 3)
        g.add_edge(1, 4)
        it = PopularityWalker(g, 1, seed=0)
        hits = [it._walk_from(0)[1] for _ in range(200)]
        assert hits.count(1) > hits.count(2)


class TestDeepWalk:
    def test_embeddings_cluster_by_clique(self):
        g = _two_cliques(5)
        dw = (DeepWalk.builder().vector_size(16).window_size(3)
              .learning_rate(0.05).seed(7).build())
        dw.fit(g, walk_length=20, walks_per_vertex=8, epochs=3)
        # same-clique similarity should exceed cross-clique similarity
        same = np.mean([dw.similarity(i, j)
                        for i in range(5) for j in range(i + 1, 5)])
        cross = np.mean([dw.similarity(i, 5 + j)
                         for i in range(1, 5) for j in range(1, 5)])
        assert same > cross

    def test_vertex_vector_shape_and_nearest(self):
        g = _two_cliques(4)
        dw = DeepWalk(vector_size=8, window_size=2, seed=1)
        dw.fit(g, walk_length=10, walks_per_vertex=4)
        assert dw.get_vertex_vector(0).shape == (8,)
        assert len(dw.vertices_nearest(0, 3)) == 3

    def test_serializer_roundtrip(self, tmp_path):
        g = _two_cliques(3)
        dw = DeepWalk(vector_size=4, window_size=2, seed=2)
        dw.fit(g, walk_length=8, walks_per_vertex=2)
        path = str(tmp_path / "gv.txt")
        GraphVectorSerializer.write_graph_vectors(dw, path)
        loaded = GraphVectorSerializer.load_txt_vectors(path)
        assert set(loaded) == set(range(6))
        np.testing.assert_allclose(loaded[2], dw.get_vertex_vector(2),
                                   rtol=1e-5)


class TestEdgesOut:
    def test_undirected_edges_reoriented(self):
        g = Graph(3)
        g.add_edge(0, 1)
        g.add_edge(1, 2)
        edges = g.get_edges_out(1)
        assert {e.src for e in edges} == {1}
        assert {e.dst for e in edges} == {0, 2}
