"""Tier-1 gate for graftlint stage 5 (ISSUE 20): the precision-flow
audit (analysis/precision_audit.py). Proves that every stage-5 entry
point's dtype profile matches the shipped analysis/precision_budget.json
with zero P-findings, that the manifest is NON-EMPTY for the int8 decode
/ fused-sampling / fused-neg-softmax entries (the acceptance bar), that
a doctored manifest trips a named PB01 finding with a non-zero CLI exit,
that the checked-in bf16-accumulation fixture trips P001 through the
CLI, that the extras' profiles are rank-independent (and a
rank-branching dtype decision is a P005 DEADLOCK-class finding), and
that each P-rule fires on a minimal positive jaxpr and stays silent on
its disciplined twin."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

from deeplearning4j_tpu.analysis import precision_audit

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(ROOT, "tools", "graftlint.py")
FIXTURE = os.path.join(ROOT, "tests", "fixtures",
                       "precision_bf16_entry.py")


def _cli_main():
    spec = importlib.util.spec_from_file_location("_graftlint_cli", CLI)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main


def _profile(fn, *args):
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    return precision_audit.profile_closed(closed, "unit")


# ------------------------------------------------ the shipped entry set

@pytest.mark.parametrize("entry", precision_audit.entry_names())
def test_entry_matches_frozen_profile_with_zero_findings(entry):
    findings, profiles = precision_audit.audit([entry])
    assert not findings, "\n".join(f.format() for f in findings)
    assert profiles[entry] == precision_audit.load_budget()[entry]


def test_manifest_covers_acceptance_entries_nonempty():
    """The ISSUE 20 acceptance bar: the frozen manifest must cover the
    int8 decode, fused-sampling, and fused-neg-softmax entries with
    NON-EMPTY profiles — the stage actually sees the serving kernels,
    not just the training steps."""
    frozen = precision_audit.load_budget()
    assert set(frozen) == set(precision_audit.entry_names())

    q8 = frozen["decode_attention/q8"]
    assert q8["q8"]["dequantize"] >= 2       # k-codes AND v-codes reads
    assert any(k.startswith("int8->") for k in q8["converts"])
    assert q8["dots"], "q8 decode entry froze no dot_generals"

    upd = frozen["decode_attention/q8_update"]
    assert upd["q8"]["quantize"] >= 1        # the requantize write path
    assert upd["q8"]["dequantize"] >= 1      # the read-modify-write read

    sampling = frozen["fused_sampling/sample"]
    assert sampling["reductions"] and sampling["converts"]

    neg = frozen["fused_neg_softmax/scores"]
    assert neg["dots"], "neg-softmax entry froze no dot_generals"
    assert all(k.endswith("->float32") for k in neg["dots"])


def test_lm_steps_freeze_their_dot_population():
    """Every bench LM mode's train step is in the manifest with a
    non-trivial dot population — the audit walks the real training
    traces, not toy stand-ins."""
    frozen = precision_audit.load_budget()
    lm = {k: v for k, v in frozen.items() if k.startswith("lm_step/")}
    assert len(lm) >= 8
    assert all(sum(p["dots"].values()) > 0 for p in lm.values())


# ------------------------------------------------------ drift tripping

def test_profile_drift_trips_named_finding_and_cli_exit(
        tmp_path, monkeypatch, capsys):
    frozen = precision_audit.load_budget()
    doctored = {k: dict(v) for k, v in frozen.items()}
    doctored["fused_neg_softmax/scores"] = dict(
        doctored["fused_neg_softmax/scores"],
        dots={"bfloat16,bfloat16->bfloat16": 2})
    bad = tmp_path / "precision_budget.json"
    bad.write_text(json.dumps({"entries": doctored}))

    findings, _ = precision_audit.audit(
        ["fused_neg_softmax/scores"], budget_path=str(bad),
        divergence=False)
    assert [f.rule for f in findings] == ["PB01"]
    assert findings[0].path == "fused_neg_softmax/scores"
    assert findings[0].stage == "precision"
    assert "drift" in findings[0].message
    assert "dots" in findings[0].message     # names the divergent key

    # the full CLI gate must refuse the doctored manifest
    monkeypatch.setattr(precision_audit, "BUDGET_PATH", str(bad))
    assert _cli_main()(["--check", "--stage", "precision"]) == 1
    out = capsys.readouterr().out
    assert "PB01" in out and "fused_neg_softmax/scores" in out


def test_missing_profile_is_a_finding(tmp_path):
    empty = tmp_path / "precision_budget.json"
    empty.write_text(json.dumps({"entries": {}}))
    findings, _ = precision_audit.audit(
        ["fused_neg_softmax/scores"], budget_path=str(empty),
        divergence=False)
    assert [f.rule for f in findings] == ["PB01"]
    assert "--update-precision" in findings[0].fixit


# ------------------------------------------------- rank independence

def test_rank_branching_dtype_is_a_deadlock_finding():
    """A dtype decision branching on process_index compiles different
    mixed-precision programs per replica — P005, stage 3's C003 class."""

    def build():
        import jax
        import jax.numpy as jnp

        def fn(x):
            if jax.process_index() == 0:
                return jnp.sum(x.astype(jnp.float32))
            return jnp.sum(x)

        return fn, (jax.ShapeDtypeStruct((4,), "bfloat16"),)

    findings = precision_audit.check_rank_independence("toy/dtype", build)
    assert [f.rule for f in findings] == ["P005"]
    assert "DEADLOCK" in findings[0].message
    assert findings[0].stage == "precision"


def test_rank_invariant_entry_is_clean():
    assert precision_audit.check_rank_independence(
        "decode_attention/q8") == []


# --------------------------------------------- per-rule jaxpr fixtures

def test_p001_fires_on_bf16_chain_not_on_f32_accumulation():
    import jax
    import jax.numpy as jnp

    def chained(x, w):
        # jnp.sum upcasts sub-f32 inputs before reducing, so the raw
        # primitive is the only spelling of a bf16 reduce-over-dot —
        # exactly what a hand-written kernel accumulator lowers to
        return jax.lax.reduce_sum_p.bind(jnp.dot(x, w), axes=(0, 1))

    def disciplined(x, w):
        acc = jnp.dot(x, w, preferred_element_type=jnp.float32)
        return jnp.sum(acc).astype(x.dtype)

    bf = jax.ShapeDtypeStruct((16, 16), jnp.bfloat16)
    _, findings = _profile(chained, bf, bf)
    assert {f.rule for f in findings} == {"P001"}
    assert "chained" in findings[0].message
    _, findings = _profile(disciplined, bf, bf)
    assert not findings, "\n".join(f.format() for f in findings)
    # jnp.sum's own promotion already accumulates sub-f32 inputs in f32;
    # the naive spelling is silent BECAUSE it is safe, not missed
    _, findings = _profile(lambda x, w: jnp.sum(jnp.dot(x, w)), bf, bf)
    assert not findings


def test_p001_fires_on_bf16_scan_carry_not_on_f32_carry():
    import jax
    import jax.numpy as jnp

    def running(dtype):
        def fn(xs):
            def body(c, x):
                c = c + x
                return c, c
            return jax.lax.scan(body, jnp.zeros((4,), dtype), xs)
        return fn

    xs = jax.ShapeDtypeStruct((8, 4), jnp.bfloat16)
    _, findings = _profile(running(jnp.bfloat16), xs)
    assert {f.rule for f in findings} == {"P001"}
    assert "carry" in findings[0].message
    # the kernels' pattern: f32 carry, downcast after — silent (the
    # per-step convert feeds the stacked ys, so it is not P003 churn)
    def f32_carry(xs):
        def body(c, x):
            c = c + x.astype(jnp.float32)
            return c, c.astype(jnp.bfloat16)
        return jax.lax.scan(body, jnp.zeros((4,), jnp.float32), xs)
    _, findings = _profile(f32_carry, xs)
    assert not findings, "\n".join(f.format() for f in findings)


def test_p001_fires_on_bf16_cumsum():
    import jax
    import jax.numpy as jnp

    def fn(x):
        return jnp.cumsum(x)

    _, findings = _profile(fn, jax.ShapeDtypeStruct((64,), jnp.bfloat16))
    assert {f.rule for f in findings} == {"P001"}
    assert "cumulative" in findings[0].message
    _, findings = _profile(fn, jax.ShapeDtypeStruct((64,), jnp.float32))
    assert not findings


def test_p001_backward_scopes_are_exempt():
    """bf16 TRAINING traces are full of autodiff bias-grad reduce_sums
    over dot outputs; add_any (the transpose-rule fan-in) marks those
    scopes and the chain check stands down — the f32 answer there is
    master weights, not rewriting transpose rules. The bias grad below
    IS a bf16 reduce_sum directly over a dot_general; only the add_any
    gate keeps it from flagging."""
    import jax
    import jax.numpy as jnp

    def loss(x, w, b):
        y = jnp.dot(x, w) + b[None, :]   # bias grad -> backward reduce
        z = jnp.dot(y, w)
        return jnp.sum((z * z).astype(jnp.float32))  # z reused -> add_any

    bf = jax.ShapeDtypeStruct((16, 16), jnp.bfloat16)
    bv = jax.ShapeDtypeStruct((16,), jnp.bfloat16)
    closed = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(bf, bf, bv)
    prims = {e.primitive.name
             for s in precision_audit._iter_scopes(closed.jaxpr)
             for e in s.eqns}
    assert "add_any" in prims, "fixture lost its autodiff fan-in"
    assert "reduce_sum" in prims         # the bias grad is really there
    _, findings = precision_audit.profile_closed(closed, "unit")
    assert not findings, "\n".join(f.format() for f in findings)


def test_p002_raw_code_read_fires_scaled_read_does_not():
    import jax
    import jax.numpy as jnp

    def raw_read(codes):
        return jnp.sum(codes.astype(jnp.float32))

    def scaled_read(codes, scale):
        return jnp.sum(codes.astype(jnp.float32) * scale)

    i8 = jax.ShapeDtypeStruct((8, 64), jnp.int8)
    sc = jax.ShapeDtypeStruct((8, 1), jnp.float32)
    _, findings = _profile(raw_read, i8)
    assert {f.rule for f in findings} == {"P002"}
    assert "raw-code read" in findings[0].message
    _, findings = _profile(scaled_read, i8, sc)
    assert not findings, "\n".join(f.format() for f in findings)


def test_p002_unmasked_requantize_fires_masked_does_not():
    import jax
    import jax.numpy as jnp

    def rmw(masked):
        def fn(codes, scale, new, pos):
            vals = codes.astype(jnp.float32) * scale
            if masked:
                vals = jnp.where(pos < 4, new, vals)
            else:
                vals = vals + new
            maxabs = jnp.max(jnp.abs(vals), axis=-1, keepdims=True)
            # deliberately hand-rolled: the P002 requantize-write shape
            return jnp.round(
                vals / (maxabs / 127.0)  # graftlint: disable=G033
            ).astype(jnp.int8)
        return fn

    i8 = jax.ShapeDtypeStruct((8, 64), jnp.int8)
    f32 = jax.ShapeDtypeStruct((8, 64), jnp.float32)
    sc = jax.ShapeDtypeStruct((8, 1), jnp.float32)
    pos = jax.ShapeDtypeStruct((8, 64), jnp.int32)
    _, findings = _profile(rmw(False), i8, sc, f32, pos)
    assert {f.rule for f in findings} == {"P002"}
    assert "write head" in findings[0].message
    _, findings = _profile(rmw(True), i8, sc, f32, pos)
    assert not findings, "\n".join(f.format() for f in findings)


def test_p003_round_trip_churn_fires_consumed_intermediate_does_not():
    import jax
    import jax.numpy as jnp

    def churn(x):
        return x.astype(jnp.float32).astype(jnp.bfloat16) * 2.0

    def real_value(x):
        up = x.astype(jnp.float32)
        return up.astype(jnp.bfloat16) * 2.0, jnp.sum(up)

    bf = jax.ShapeDtypeStruct((16,), jnp.bfloat16)
    profile, findings = _profile(churn, bf)
    assert {f.rule for f in findings} == {"P003"}
    assert profile["convert_round_trips"] == 1
    _, findings = _profile(real_value, bf)
    assert not findings, "\n".join(f.format() for f in findings)


def test_p004_widening_collective_fires_width_preserving_does_not():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util.compat import shard_map

    mesh = make_mesh({"data": 2})

    def sharded(local):
        return lambda x: shard_map(local, mesh=mesh, in_specs=(P("data"),),
                                   out_specs=P("data"),
                                   check_vma=False)(x)

    bf = jax.ShapeDtypeStruct((4, 8), jnp.bfloat16)
    f32 = jax.ShapeDtypeStruct((4, 8), jnp.float32)

    # bf16 entry upcast before the psum: widened bytes on the wire
    widened = sharded(lambda v: jax.lax.psum(v.astype(jnp.float32),
                                             "data"))
    _, findings = _profile(widened, bf)
    assert {f.rule for f in findings} == {"P004"}
    assert "wire" in findings[0].message

    # width-preserving f32 psum over an f32 entry: clean
    plain = sharded(lambda v: jax.lax.psum(v, "data"))
    _, findings = _profile(plain, f32)
    assert not findings, "\n".join(f.format() for f in findings)

    # a bf16 psum is the OTHER failure: a sub-f32 cross-replica sum
    _, findings = _profile(plain, bf)
    assert {f.rule for f in findings} == {"P001"}
    assert "cross-replica" in findings[0].message


# --------------------------------------------------------------- CLI

def test_cli_precision_demo_exits_nonzero_with_p001():
    """The acceptance demo: `--stage precision` on the bf16-accumulation
    fixture must exit non-zero with the P001 chain finding."""
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "--stage", "precision", FIXTURE],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "P001" in proc.stdout
    assert "demo/bf16_carry_over_dot" in proc.stdout


def test_fixture_audit_in_process():
    findings, profiles = precision_audit.audit_paths([FIXTURE])
    assert [f.rule for f in findings] == ["P001"]
    assert "carry" in findings[0].message
    prof = profiles["demo/bf16_carry_over_dot"]
    assert prof["dots"] == {"bfloat16,bfloat16->bfloat16": 1}
    assert prof["scan_carries"] == {"bfloat16": 1}


def test_cli_precision_clean_tree_emits_labeled_json():
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "--stage", "precision", "--json"],
        cwd=ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    profiles = payload["precision_profiles"]
    assert set(profiles) == set(precision_audit.entry_names())
    assert profiles["decode_attention/q8"]["q8"]["dequantize"] >= 2


def test_cli_changed_bad_ref_is_a_usage_error():
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "--changed",
         "0000000000000000000000000000000000000000"],
        cwd=ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 2, proc.stdout + proc.stderr
