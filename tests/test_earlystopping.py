"""Early stopping end-to-end (earlystopping/core.py) — reference
org.deeplearning4j.earlystopping: trainer loop, terminations, savers,
score calculators, and the ComputationGraph variant."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.earlystopping.core import (
    BestScoreEpochTerminationCondition,
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingGraphTrainer,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _data(seed=0, n=64):
    rng = np.random.default_rng(seed)
    x = rng.random((n, 4), dtype=np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(-1) > 2.0).astype(int)]
    return DataSet(x, y)


def _net(seed=3):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.1)
        .updater("adam")
        .list()
        .layer(DenseLayer(n_in=4, n_out=12, activation="tanh"))
        .layer(OutputLayer(n_in=12, n_out=2, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def test_max_epochs_termination_and_best_model():
    train = ListDataSetIterator([_data(0)])
    val = ListDataSetIterator([_data(1)])
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(val),
        epoch_terminations=[MaxEpochsTerminationCondition(5)],
    )
    result = EarlyStoppingTrainer(cfg, _net(), train).fit()
    assert result.termination_reason == "EpochTermination"
    assert result.termination_details == "MaxEpochsTerminationCondition"
    assert result.total_epochs == 5
    assert result.best_model is not None
    assert 0 <= result.best_model_epoch < 5
    # best model really is the argmin of the recorded validation scores
    assert result.best_model_score == min(result.score_vs_epoch.values())
    # restored best model must be usable
    out = result.best_model.output(_data(1).features)
    assert np.isfinite(np.asarray(out)).all()


def test_score_improvement_termination_stops_early():
    train = ListDataSetIterator([_data(0)])
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator([_data(1)])),
        epoch_terminations=[
            ScoreImprovementEpochTerminationCondition(2, min_improvement=10.0),
            MaxEpochsTerminationCondition(50),
        ],
    )
    result = EarlyStoppingTrainer(cfg, _net(), train).fit()
    # an improvement of 10.0/epoch is impossible -> patience fires quickly
    assert result.termination_details == (
        "ScoreImprovementEpochTerminationCondition")
    assert result.total_epochs <= 4


def test_best_score_termination():
    train = ListDataSetIterator([_data(0)])
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator([_data(0)])),
        epoch_terminations=[BestScoreEpochTerminationCondition(1e9),
                            MaxEpochsTerminationCondition(50)],
    )
    result = EarlyStoppingTrainer(cfg, _net(), train).fit()
    assert result.termination_details == "BestScoreEpochTerminationCondition"
    assert result.total_epochs == 1


def test_iteration_termination_on_score_blowup():
    train = ListDataSetIterator([_data(0)])
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator([_data(0)])),
        iteration_terminations=[MaxScoreIterationTerminationCondition(1e-9),
                                InvalidScoreIterationTerminationCondition()],
        epoch_terminations=[MaxEpochsTerminationCondition(50)],
    )
    result = EarlyStoppingTrainer(cfg, _net(), train).fit()
    assert result.termination_reason == "IterationTermination"
    assert result.total_epochs == 0


def test_local_file_saver_round_trip(tmp_path):
    train = ListDataSetIterator([_data(0)])
    saver = LocalFileModelSaver(str(tmp_path))
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator([_data(1)])),
        model_saver=saver,
        save_last_model=True,
        epoch_terminations=[MaxEpochsTerminationCondition(3)],
    )
    result = EarlyStoppingTrainer(cfg, _net(), train).fit()
    assert any(f.endswith(".zip") for f in os.listdir(tmp_path))
    best = saver.get_best_model()
    np.testing.assert_allclose(
        np.asarray(best.params_flat()),
        np.asarray(result.best_model.params_flat()), atol=1e-6)


def test_graph_trainer_runs():
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    g = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .learning_rate(0.05)
        .updater("adam")
        .graph_builder()
        .add_inputs("in")
    )
    g.add_layer("h", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
    g.add_layer("out", OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss_function="mcxent"), "h")
    g.set_outputs("out")
    net = ComputationGraph(g.build())
    net.init()
    cfg = EarlyStoppingConfiguration(
        score_calculator=DataSetLossCalculator(ListDataSetIterator([_data(1)])),
        epoch_terminations=[MaxEpochsTerminationCondition(3)],
    )
    result = EarlyStoppingGraphTrainer(cfg, net,
                                       ListDataSetIterator([_data(0)])).fit()
    assert result.total_epochs == 3
    assert result.best_model is not None
