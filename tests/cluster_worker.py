"""Standalone elastic-worker process used by tests/test_cluster.py.

Run: python tests/cluster_worker.py <address> <worker_id> <shard 0|1>
         <checkpoint_path|-> <crash_after_n_syncs|none> [local_mesh_devices]

With local_mesh_devices > 0 the worker also shards its OWN batches over a
virtual CPU mesh (in-process allreduce DP) — the 2-process x 4-device
hierarchical topology of SURVEY.md §4.5: XLA collectives inside each
process, coordinator averaging across processes.

Also imported by the test for the shared net/data definitions, so the
multi-process run and the single-process reference use identical configs.
"""

import sys

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

N, F, C, STEPS = 32, 6, 3, 6


def build_net(kind: str = "mln"):
    b = (NeuralNetConfiguration.builder()
         .seed(7)
         .learning_rate(0.1)
         .updater("sgd"))
    if kind == "cg":
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        g = b.graph_builder().add_inputs("in")
        g.add_layer("h", DenseLayer(n_in=F, n_out=8, activation="tanh"), "in")
        g.add_layer("out", OutputLayer(n_in=8, n_out=C, activation="softmax",
                                       loss_function="mcxent"), "h")
        g.set_outputs("out")
        return ComputationGraph(g.build())
    conf = (b.list()
            .layer(DenseLayer(n_in=F, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=C, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf)


def full_data():
    rng = np.random.default_rng(0)
    x = rng.random((N, F), dtype=np.float32)
    y = np.eye(C, dtype=np.float32)[rng.integers(0, C, N)]
    return x, y


def shard_batches(shard: str):
    x, y = full_data()
    half = N // 2
    lo, hi = (0, half) if shard == "0" else (half, N)
    return [DataSet(x[lo:hi], y[lo:hi])] * STEPS


def main() -> int:
    address, wid, shard, ckpt, crash_at = sys.argv[1:6]
    local_mesh = int(sys.argv[6]) if len(sys.argv) > 6 else 0
    kind = sys.argv[7] if len(sys.argv) > 7 else "mln"
    ckpt = None if ckpt == "-" else ckpt
    if local_mesh:
        from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

        ensure_cpu_devices(local_mesh)

    from deeplearning4j_tpu.parallel.cluster import (
        ClusterClient,
        run_elastic_worker,
    )

    if crash_at != "none":
        # simulated process failure after N averaging rounds
        n = int(crash_at)
        orig = ClusterClient.average
        calls = [0]

        def avg(self, step, flat):
            calls[0] += 1
            if calls[0] > n:
                import os

                os._exit(1)
            return orig(self, step, flat)

        ClusterClient.average = avg

    net = build_net(kind)
    net.init()
    if local_mesh:
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        net.set_mesh(make_mesh({"data": local_mesh}))
    net = run_elastic_worker(address, wid, net, shard_batches(shard),
                             sync_every=1, checkpoint_path=ckpt)
    out = (ckpt or f"/tmp/{wid}") + ".params.npy"
    np.save(out, np.asarray(net.params_flat()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
