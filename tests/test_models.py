"""Model zoo build-and-train smoke tests (models/): LeNet-5, VGG-16,
ResNet-20, Transformer-LM — the BASELINE.json benchmark configs must
build, run one train step, and produce finite decreasing-capable losses."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.lenet import lenet5
from deeplearning4j_tpu.models.resnet import resnet20
from deeplearning4j_tpu.models.transformer import (
    transformer_flops_per_token,
    transformer_lm,
)
from deeplearning4j_tpu.models.vgg import vgg16


def _img_batch(n, h, w, c, classes, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random((n, h, w, c), dtype=np.float32)
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return x, y


def test_lenet_builds_and_fits():
    net = lenet5()
    net.init()
    x, y = _img_batch(8, 28, 28, 1, 10)
    net.fit(x, y)
    first = net.score_value
    net.fit(x, y)
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    assert net.score_value < first  # learns on a repeated batch
    out = np.asarray(net.output(x))
    assert out.shape == (8, 10)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-4)


@pytest.mark.slow
def test_vgg16_builds_and_steps():
    net = vgg16()
    net.init()
    assert net.num_params() > 1_000_000  # a real VGG-16, not a stub
    x, y = _img_batch(2, 32, 32, 3, 10)
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (2, 10)


@pytest.mark.slow
def test_resnet20_builds_and_steps():
    net = resnet20()
    net.init()
    x, y = _img_batch(4, 32, 32, 3, 10)
    net.fit(x, y)
    assert np.isfinite(net.score_value)
    out = np.asarray(net.output(x))
    assert out.shape == (4, 10)
    # 20 weighted layers: conv0 + 9 blocks x 2 convs + fc
    conv_names = [n for n in net.params if "conv" in n]
    assert len(conv_names) >= 19


def test_transformer_lm_builds_and_fits_sparse_and_onehot():
    net = transformer_lm(vocab_size=50, d_model=32, n_heads=2, n_layers=2,
                         d_ff=64, max_length=12)
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 50, (4, 12)), np.int32)
    shifted = np.roll(toks, -1, 1)
    # sparse integer labels (the bench path)
    net.fit(toks, shifted)
    sparse_score = net.score_value
    # one-hot labels (the reference-parity path) give the same loss scale
    net2 = transformer_lm(vocab_size=50, d_model=32, n_heads=2, n_layers=2,
                          d_ff=64, max_length=12)
    net2.init()
    net2.fit(toks, np.eye(50, dtype=np.float32)[shifted])
    assert np.isfinite(sparse_score) and np.isfinite(net2.score_value)
    np.testing.assert_allclose(sparse_score, net2.score_value, rtol=1e-3)


def test_transformer_flops_accounting():
    fl = transformer_flops_per_token(10000, 256, 6, 1024, 512)
    # 3x(fwd) with fwd = layers*(8d^2 + 4d*dff + 4Td) + 2dV
    fwd = 6 * (8 * 256**2 + 4 * 256 * 1024 + 4 * 512 * 256) + 2 * 256 * 10000
    assert fl == 3 * fwd


def test_transformer_moe_lm_builds_and_fits():
    from deeplearning4j_tpu.models.transformer import transformer_moe_lm

    net = transformer_moe_lm(vocab_size=50, d_model=16, n_heads=2,
                             n_layers=2, n_experts=4, top_k=2,
                             d_expert_hidden=32, max_length=12)
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 50, (4, 12)), np.int32)
    net.fit_scanned(toks, np.roll(toks, -1, 1), epochs=4)
    assert np.isfinite(net.score_value)
    assert float(net._epoch_losses[-1]) < float(net._epoch_losses[0])
    # expert params present per block
    assert net.params["blk0_moe"]["We1"].shape == (4, 16, 32)
