"""Config/serialization tests (reference test strategy §4 item 3: builder →
JSON → fromJson round-trips)."""

import dataclasses

from deeplearning4j_tpu.nn.conf import (
    ComputationGraphConfiguration,
    ConvolutionLayer,
    DenseLayer,
    GravesLSTM,
    InputType,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayer,
    SubsamplingLayer,
    Updater,
    WeightInit,
)


def build_mlp_conf():
    return (
        NeuralNetConfiguration.builder()
        .seed(7)
        .learning_rate(0.05)
        .updater(Updater.ADAM)
        .weight_init(WeightInit.XAVIER)
        .l2(1e-4)
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
        .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )


def test_builder_inheritance():
    conf = build_mlp_conf()
    assert conf.conf.seed == 7
    assert len(conf.layers) == 2
    # global defaults inherited by layers
    assert conf.layers[0].weight_init == "xavier"
    assert conf.layers[0].l2 == 1e-4
    # explicit per-layer values kept
    assert conf.layers[0].activation == "relu"
    assert conf.layers[1].activation == "softmax"


def test_json_round_trip():
    conf = build_mlp_conf()
    s = conf.to_json()
    back = MultiLayerConfiguration.from_json(s)
    assert dataclasses.asdict(back) == dataclasses.asdict(conf)


def test_cnn_shape_inference():
    """ConvolutionLayerSetup analogue: n_in + preprocessors auto-derived."""
    conf = (
        NeuralNetConfiguration.builder()
        .seed(1)
        .list()
        .layer(ConvolutionLayer(n_out=6, kernel_size=(5, 5), stride=(1, 1),
                                activation="relu"))
        .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
        .layer(DenseLayer(n_out=32, activation="relu"))
        .layer(OutputLayer(n_out=10, activation="softmax"))
        .set_input_type(InputType.convolutional(28, 28, 1))
        .build()
    )
    conv = conf.layers[0]
    assert conv.n_in == 1
    dense = conf.layers[2]
    # 28 -5+1 = 24 → pool/2 → 12 → 12*12*6
    assert dense.n_in == 12 * 12 * 6
    assert conf.layers[3].n_in == 32
    # a CnnToFeedForward preprocessor was inserted before the dense layer
    assert conf.get_preprocessor(2) is not None


def test_graph_builder_topo_and_json():
    g = (
        NeuralNetConfiguration.builder()
        .seed(3)
        .graph_builder()
        .add_inputs("in")
        .add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
        .add_layer("d2", DenseLayer(n_in=4, n_out=8, activation="relu"), "in")
        .add_layer("out", OutputLayer(n_in=16, n_out=3, activation="softmax"), "d1", "d2")
        .set_outputs("out")
        .build()
    )
    order = g.topological_order()
    assert order.index("in") < order.index("d1")
    assert order.index("d1") < order.index("out")
    s = g.to_json()
    back = ComputationGraphConfiguration.from_json(s)
    assert dataclasses.asdict(back) == dataclasses.asdict(g)


def test_rnn_shape_inference():
    conf = (
        NeuralNetConfiguration.builder()
        .list()
        .layer(GravesLSTM(n_out=16, activation="tanh"))
        .layer(OutputLayer(n_out=5, activation="softmax"))
        .set_input_type(InputType.recurrent(10))
        .build()
    )
    assert conf.layers[0].n_in == 10
    assert conf.layers[1].n_in == 16
