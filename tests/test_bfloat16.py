"""bf16 training-path tests.

Round-1 postmortem: bench.py selects dtype="bfloat16" exactly when running
on the real TPU chip, but no test exercised a bf16 value_and_grad step, so a
conv-transpose dtype bug lived only on hardware (VERDICT Weak #1). These
tests run the same bf16 path on CPU so the class of bug is caught pre-driver.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.lenet import lenet5
from deeplearning4j_tpu.models.transformer import transformer_lm
import pytest


def _one_step(net, batch):
    step = net._get_train_step()
    key = jax.random.PRNGKey(0)
    # the jitted step donates its buffers — write results back onto the net
    net.params, net.opt_state, net.state, loss, _ = step(
        net.params, net.opt_state, net.state, key, batch)
    jax.block_until_ready(loss)
    return net.params, float(loss)


@pytest.mark.slow
def test_lenet_bf16_train_step():
    """value_and_grad of a bf16 conv net must not die in the conv transpose
    rule (the exact failure mode of BENCH_r01)."""
    net = lenet5(dtype="bfloat16")
    net.init()
    rng = np.random.default_rng(0)
    x = rng.random((8, 28, 28, 1), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 8)]
    batch = {"features": jnp.asarray(x), "labels": jnp.asarray(y)}
    params, loss = _one_step(net, batch)
    assert np.isfinite(loss)
    # master params stay f32 (mixed precision); compute casts to bf16
    assert params["layer_0"]["W"].dtype == jnp.float32
    out = net.output(np.asarray(batch["features"], np.float32))
    assert np.all(np.isfinite(np.asarray(out, np.float32)))


def test_lenet_bf16_multiple_steps_decrease_loss():
    net = lenet5(dtype="bfloat16", learning_rate=1e-2)
    net.init()
    rng = np.random.default_rng(1)
    x = rng.random((32, 28, 28, 1), dtype=np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
    batch = {"features": jnp.asarray(x), "labels": jnp.asarray(y)}
    step = net._get_train_step()
    params, opt_state, state = net.params, net.opt_state, net.state
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(20):
        key, k = jax.random.split(key)
        params, opt_state, state, loss, _ = step(params, opt_state, state, k, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert all(np.isfinite(l) for l in losses)


@pytest.mark.slow
def test_transformer_bf16_train_step():
    """The MFU bench runs the transformer in bf16 — keep that path tested."""
    net = transformer_lm(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                         d_ff=64, max_length=16, dtype="bfloat16")
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 64, (2, 16)), np.int32)
    labels = np.eye(64, dtype=np.float32)[toks]
    net.fit(toks, labels, epochs=2)
    assert np.isfinite(net.score_value)
