"""UI tests: component JSON round-trips (reference ui-components tests),
server endpoints over real HTTP, listeners attached to a training run
(reference ui module tests use embedded Jetty the same way)."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    DecoratorAccordion,
    FlowIterationListener,
    HistogramIterationListener,
    HistoryStorage,
    SessionStorage,
    StaticPageUtil,
    StyleChart,
    UiServer,
)


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10) as r:
        return json.loads(r.read())


class TestComponents:
    def test_chart_line_roundtrip(self):
        c = ChartLine(title="score", style=StyleChart(width=300, height=200))
        c.add_series("train", [0, 1, 2], [1.0, 0.5, 0.25])
        restored = Component.from_json(c.to_json())
        assert isinstance(restored, ChartLine)
        assert restored.title == "score"
        assert restored.y == [[1.0, 0.5, 0.25]]
        assert restored.style.width == 300

    def test_histogram_of(self, rng):
        h = ChartHistogram.of(rng.normal(size=1000), bins=10, title="w")
        assert len(h.y_values) == 10
        assert sum(h.y_values) == 1000
        assert h.lower_bounds[0] < h.upper_bounds[-1]

    def test_nested_div_roundtrip(self):
        div = ComponentDiv(components=[
            ComponentText(text="hello"),
            DecoratorAccordion(title="acc", components=[
                ComponentTable(header=["a"], content=[["1"]])]),
        ])
        restored = Component.from_json(div.to_json())
        assert isinstance(restored.components[0], ComponentText)
        inner = restored.components[1]
        assert isinstance(inner, DecoratorAccordion)
        assert isinstance(inner.components[0], ComponentTable)

    def test_mismatched_series_raises(self):
        with pytest.raises(ValueError):
            ChartScatter().add_series("s", [1, 2], [1.0])


class TestStorage:
    def test_session_storage(self):
        s = SessionStorage()
        s.put("a", "weights", {"x": 1})
        assert s.get("a", "weights") == {"x": 1}
        assert s.get("a", "flow") is None
        assert s.sessions() == ["a"]
        assert s.object_types("a") == ["weights"]

    def test_history_bounded(self):
        h = HistoryStorage(max_history=3)
        for i in range(5):
            h.put("s", "weights", i)
        assert h.history("s", "weights") == [2, 3, 4]
        assert h.get("s", "weights") == 4


class TestServer:
    @pytest.fixture
    def server(self):
        srv = UiServer(port=0).start()
        yield srv
        srv.stop()

    def test_post_and_get_weights(self, server):
        payload = {"iteration": 3, "score": 0.5, "parameters": {}}
        assert _post(f"{server.url}/weights/update?sid=s1", payload) == {"status": "ok"}
        assert _get(f"{server.url}/weights/data?sid=s1") == payload
        assert _get(f"{server.url}/sessions") == ["s1"]
        # history endpoint
        _post(f"{server.url}/weights/update?sid=s1", payload)
        assert len(_get(f"{server.url}/weights/history?sid=s1")) == 2

    def test_nearest_neighbors(self, server, rng):
        vecs = np.eye(4) + 0.01 * rng.normal(size=(4, 4))
        _post(f"{server.url}/nearestneighbors/vectors",
              {"labels": ["a", "b", "c", "d"], "vectors": vecs.tolist()})
        res = _post(f"{server.url}/nearestneighbors/query", {"word": "a", "k": 2})
        assert len(res["words"]) == 2
        assert "a" not in res["words"]

    def test_unknown_word_404(self, server):
        _post(f"{server.url}/nearestneighbors/vectors",
              {"labels": ["a"], "vectors": [[1.0]]})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{server.url}/nearestneighbors/query", {"word": "zzz"})
        assert ei.value.code == 404

    def test_index_page(self, server):
        with urllib.request.urlopen(server.url, timeout=10) as r:
            body = r.read().decode()
        assert "deeplearning4j_tpu" in body
        for view in ("/weights", "/flow", "/activations", "/tsne",
                     "/timeline"):
            assert f'href="{view}"' in body

    def test_timeline_view_renders_merged_shards(self, tmp_path):
        """The fleet-timeline page (ISSUE 15): a UI server pointed at a
        sharded telemetry path renders the merged per-process view —
        span stats, lanes, anomaly table — and /timeline/data serves
        the same as JSON."""
        import json as _json

        base = str(tmp_path / "t.jsonl")
        for p, run in (("p0", "a"), ("p1", "b")):
            with open(f"{base}.{p}", "w") as fh:
                fh.write(_json.dumps(
                    {"event": "span", "name": "compile", "run": run,
                     "seq": 0, "ts": 1.0, "seconds": 0.5}) + "\n")
                fh.write(_json.dumps(
                    {"event": "step", "run": run, "seq": 1,
                     "iteration": 1, "ts": 2.0,
                     "trace_id": "step-1"}) + "\n")
        srv = UiServer(port=0, telemetry_path=base).start()
        try:
            with urllib.request.urlopen(f"{srv.url}/timeline",
                                        timeout=10) as r:
                body = r.read().decode()
            assert "fleet timeline" in body
            assert "p0" in body and "p1" in body
            assert "0 anomalies" in body
            data = _get(f"{srv.url}/timeline/data")
            assert data["processes"] == ["p0", "p1"]
            assert data["span_stats"]["p0::compile"]["p50_ms"] == 500.0
            assert data["anomalies"] == []
        finally:
            srv.stop()

    def test_timeline_view_without_source_renders_hint(self, server,
                                                       monkeypatch):
        monkeypatch.delenv("DL4J_TPU_TELEMETRY", raising=False)
        body = self._get_html(f"{server.url}/timeline")
        assert "no telemetry yet" in body

    def _get_html(self, url):
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/html")
            return r.read().decode()

    def test_weights_view_renders(self, server):
        """VERDICT r2 #6: /weights returns a page that RENDERS the
        session's histograms in-browser (reference
        HistogramIterationListener.java:206 + its weights view)."""
        payload = {"iteration": 3, "score": 0.5, "parameters": {
            "dense_W": {"bins": [0.0, 0.5, 1.0], "counts": [4, 6]}}}
        _post(f"{server.url}/weights/update?sid=s1", payload)
        body = self._get_html(f"{server.url}/weights?sid=s1")
        assert "renderChartSVG" in body        # the SVG renderer shipped
        assert "ChartHistogram" in body        # histogram component data
        assert "dense_W" in body
        assert 'http-equiv="refresh"' in body  # live view

    def test_flow_view_renders(self, server):
        payload = {"iteration": 1, "score": 1.25, "layers": [
            {"name": "dense0", "index": 0, "num_params": 96,
             "param_names": ["W", "b"]}]}
        _post(f"{server.url}/flow/update?sid=s1", payload)
        body = self._get_html(f"{server.url}/flow?sid=s1")
        assert "ComponentTable" in body
        assert "dense0" in body

    def test_activations_view_renders(self, server):
        _post(f"{server.url}/activations/update?sid=s1",
              {"iteration": 1, "activation_means": {"layer_0": 0.3}})
        _post(f"{server.url}/activations/update?sid=s1",
              {"iteration": 2, "activation_means": {"layer_0": 0.4}})
        body = self._get_html(f"{server.url}/activations?sid=s1")
        assert "ChartLine" in body
        assert "layer_0" in body

    def test_tsne_view_renders(self, server):
        _post(f"{server.url}/tsne/coords?sid=s1",
              {"coords": [[0.0, 1.0], [1.0, 0.0]]})
        body = self._get_html(f"{server.url}/tsne?sid=s1")
        assert "ChartScatter" in body

    def test_views_empty_session_still_render(self, server):
        for view in ("weights", "flow", "activations", "tsne"):
            body = self._get_html(f"{server.url}/{view}?sid=nosuch")
            assert "no " in body  # helpful placeholder text, not an error


class TestListeners:
    def _tiny_net(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.1)
                .list()
                .layer(DenseLayer(n_in=4, n_out=8, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss_function="negativeloglikelihood"))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_histogram_listener_embedded(self, rng):
        from deeplearning4j_tpu.datasets.api import DataSet

        net = self._tiny_net()
        storage = HistoryStorage()
        net.set_listeners(HistogramIterationListener(storage=storage))
        x = rng.normal(size=(32, 4)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)]
        net.fit(DataSet(x, y))
        snap = storage.get("default", "weights")
        assert snap is not None
        assert "score" in snap
        assert any(k.endswith("_W") for k in snap["parameters"])
        bins = next(iter(snap["parameters"].values()))
        assert len(bins["bins"]) == len(bins["counts"]) + 1

    def test_flow_listener_http(self, rng):
        from deeplearning4j_tpu.datasets.api import DataSet

        srv = UiServer(port=0).start()
        try:
            net = self._tiny_net()
            net.set_listeners(FlowIterationListener(url=srv.url, session_id="t"))
            x = rng.normal(size=(16, 4)).astype(np.float32)
            y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 16)]
            net.fit(DataSet(x, y))
            snap = _get(f"{srv.url}/flow/data?sid=t")
            assert len(snap["layers"]) == 2
            assert snap["layers"][0]["num_params"] > 0
        finally:
            srv.stop()


class TestStaticPage:
    def test_render_html(self, tmp_path):
        line = ChartLine(title="loss").add_series("t", [0, 1], [1.0, 0.5])
        table = ComponentTable(header=["k", "v"], content=[["acc", "0.9"]])
        html = StaticPageUtil.render_html([line, table], title="report")
        assert "loss" in html and "renderComponent" in html
        p = tmp_path / "r.html"
        StaticPageUtil.save_html([line], str(p))
        assert p.read_text().startswith("<!doctype html>")
