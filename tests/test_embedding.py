"""ISSUE 19 tier-1 gate for the sharded embedding subsystem
(deeplearning4j_tpu/embedding/): ep-row-sharded SGNS/HS training that is
BIT-identical to the legacy dense word2vec path at ep=1, memstat-ledger
table-bytes halving at ep=2, the dp sparse (indices, values) gradient
exchange, ragged DeepWalk walk bucketing with a zero-retrace gate over a
seeded corpus, the fused negative-sampling kernel's parity envelope, the
device ANN index's recall/brute-force contracts, and the /embed +
/search serving round trip (in-process and over HTTP)."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.embedding.ann import (DeviceANNIndex,
                                              brute_force_topk,
                                              recall_at_k)
from deeplearning4j_tpu.embedding.corpus import (prefetched,
                                                 sequence_pair_batches,
                                                 walk_pair_batches,
                                                 with_negatives)
from deeplearning4j_tpu.embedding.engine import (EngineLookupView,
                                                 ShardedEmbeddingEngine)
from deeplearning4j_tpu.embedding.serving import EmbeddingServingEngine
from deeplearning4j_tpu.embedding.walks import (WalkBucketer,
                                                WalkPairExtractor)
from deeplearning4j_tpu.ops.fused_neg_softmax import (_score_body,
                                                      neg_softmax_scores,
                                                      supports)
from deeplearning4j_tpu.serving.buckets import BucketLattice
from deeplearning4j_tpu.telemetry import Recorder

pytestmark = pytest.mark.embedding


def _corpus(rng, vocab=30, n_sentences=40, length=8):
    words = [f"w{i}" for i in range(vocab)]
    return [" ".join(rng.choice(words, size=length))
            for _ in range(n_sentences)]


def _w2v(corpus, use_engine, hs):
    from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    b = (Word2Vec.builder().iterate(corpus)
         .tokenizer_factory(DefaultTokenizerFactory())
         .layer_size(16).window_size(3).min_word_frequency(1)
         .epochs(1).seed(7).use_engine(use_engine))
    b = b.use_hierarchic_softmax(True) if hs else b.negative_sample(3)
    model = b.build()
    model.fit()
    return model


# ------------------------------------------------ ep=1 bit parity (sat. 1)

@pytest.mark.parametrize("hs", [False, True], ids=["sgns", "hs"])
def test_ep1_engine_is_bit_identical_to_legacy_dense_path(hs):
    """The satellite-1 acceptance row: the ep=1 sharded engine through
    the REAL Word2Vec front-end produces np.array_equal tables vs the
    legacy InMemoryLookupTable path — same corpus, same seed, both
    trained end to end. Masked gather + psum and the masked scatter are
    value-preserving identities at ep=1, so this is exact, not
    allclose."""
    rng = np.random.default_rng(0)
    corpus = _corpus(rng)
    engine_model = _w2v(corpus, use_engine=True, hs=hs)
    legacy_model = _w2v(corpus, use_engine=False, hs=hs)
    assert engine_model._engine is not None
    assert legacy_model._engine is None
    assert np.array_equal(np.asarray(engine_model.lookup_table.syn0),
                          np.asarray(legacy_model.lookup_table.syn0))
    other = "syn1" if hs else "syn1neg"
    assert np.array_equal(
        np.asarray(getattr(engine_model.lookup_table, other)),
        np.asarray(getattr(legacy_model.lookup_table, other)))


def test_deepwalk_routes_through_engine():
    from deeplearning4j_tpu.graph.deepwalk import DeepWalk
    from deeplearning4j_tpu.graph.graph import Graph

    g = Graph(8)
    for i in range(8):
        g.add_edge(i, (i + 1) % 8)
        g.add_edge(i, (i + 3) % 8)
    dw = (DeepWalk.builder().vector_size(8).window_size(2)
          .seed(3).build())
    dw.fit(g, walk_length=10)
    assert dw.vectors._engine is not None
    assert dw.get_vertex_vector(0).shape == (8,)


# ------------------------------------------- ep/dp sharding correctness

def _run_steps(eng, steps=4, batch=32, k=3, seed=5):
    rng = np.random.default_rng(seed)
    v = eng.vocab_size
    loss = None
    for _ in range(steps):
        c = rng.integers(0, v, batch)
        x = rng.integers(0, v, batch)
        n = rng.integers(0, v, (batch, k))
        loss = eng.sgns_step(c, x, n, 0.025)
    jax.block_until_ready(loss)
    return eng


def test_ep2_is_bit_identical_to_ep1_and_halves_ledger_bytes():
    """Row sharding is an exact reshard: each table row is owned by one
    ep rank, gathers psum disjoint masked strips, scatters update only
    owned rows — ep=2 training equals ep=1 bit for bit. Per-device
    table bytes (memstat ledger) halve, and the step retraces zero
    times after its first compile."""
    e1 = _run_steps(ShardedEmbeddingEngine(64, 16, ep=1, negative=3,
                                           seed=11))
    e2 = ShardedEmbeddingEngine(64, 16, ep=2, negative=3, seed=11)
    _run_steps(e2, steps=1)
    tc = e2.trace_count
    # re-run the remaining steps with identical inputs: fresh engine so
    # the streams match, but the retrace gate watches the warm engine
    e2b = _run_steps(ShardedEmbeddingEngine(64, 16, ep=2, negative=3,
                                            seed=11))
    _run_steps(e2, steps=3, seed=99)
    assert e2.trace_count == tc, "post-warmup retrace on the ep=2 step"
    v1, v2 = EngineLookupView(e1), EngineLookupView(e2b)
    assert np.array_equal(np.asarray(v1.syn0), np.asarray(v2.syn0))
    assert np.array_equal(np.asarray(v1.syn1neg), np.asarray(v2.syn1neg))
    assert e2.table_bytes_per_device() * 2 == e1.table_bytes_per_device()


def test_dp2_sparse_bucket_gradients_match_dp1():
    """The dp axis ships gradients as (indices, values) pairs through
    the overlap layer's sparse bucket kind; the combined update equals
    the single-rank update up to float reassociation."""
    base = _run_steps(ShardedEmbeddingEngine(64, 16, ep=1, negative=3,
                                             seed=11))
    dp = _run_steps(ShardedEmbeddingEngine(64, 16, ep=1, dp=2,
                                           negative=3, seed=11))
    np.testing.assert_allclose(
        np.asarray(EngineLookupView(base).syn0),
        np.asarray(EngineLookupView(dp).syn0), atol=2e-5, rtol=1e-4)


def test_engine_emits_gather_and_scatter_spans_with_bytes():
    events = []
    rec = Recorder()
    rec.add_sink(events.append)
    eng = ShardedEmbeddingEngine(64, 16, ep=2, negative=3, seed=1,
                                 recorder=rec)
    _run_steps(eng, steps=2)
    np.asarray(eng.embed(np.arange(8)))
    spans = {e["name"]: e for e in events if e.get("event") == "span"}
    assert spans["scatter_add"]["bytes"] > 0
    assert spans["scatter_add"]["ep_gather_bytes"] > 0
    assert spans["gather"]["bytes"] > 0


# --------------------------------------- ragged walks (satellite 4)

def _ragged_walks(rng, n=160, vmax=50):
    return [rng.integers(0, vmax, size=int(length))
            for length in rng.integers(2, 80, size=n)]


def test_ragged_walk_batches_are_fixed_shape_per_bucket():
    rng = np.random.default_rng(2)
    bucketer = WalkBucketer(batch=16)
    shapes = set()
    for block, mask in bucketer.batches(_ragged_walks(rng)):
        assert block.shape == mask.shape
        assert block.shape[0] == 16
        assert block.shape[1] in bucketer.length_buckets
        shapes.add(block.shape)
    # the seeded corpus exercises more than one bucket
    assert len(shapes) > 1


def test_zero_retraces_across_a_seeded_ragged_walk_corpus():
    """The ISSUE 19 satellite-4 gate: after one pass over a seeded
    ragged corpus has compiled each (batch, length-bucket) shape once,
    a second full pass (and a differently-seeded corpus) adds ZERO
    traces — the bucketing really does pin the device shapes."""
    rng = np.random.default_rng(3)
    bucketer = WalkBucketer(batch=16)
    extractor = WalkPairExtractor(window=3)
    walks = _ragged_walks(rng)

    def consume(ws):
        batches = list(walk_pair_batches(
            ws, batch_size=64, bucketer=bucketer, extractor=extractor))
        assert all(c.shape == (64,) and x.shape == (64,)
                   for c, x in batches)
        return batches

    consume(walks)
    warm = extractor.trace_count
    assert warm <= len(bucketer.length_buckets)
    consume(walks)
    consume(_ragged_walks(np.random.default_rng(17)))
    assert extractor.trace_count == warm, "ragged walks retraced"


def test_prefetched_pair_feed_matches_synchronous_feed():
    rng = np.random.default_rng(4)
    seqs = [rng.integers(0, 40, size=12) for _ in range(20)]
    cum = np.arange(1, 41, dtype=np.float64) / 40.0

    def feed():
        return with_negatives(
            sequence_pair_batches(seqs, batch_size=32, window=3, seed=9),
            cum, 3, seed=13)

    sync = list(feed())
    async_ = list(prefetched(feed(), depth=2))
    assert len(sync) == len(async_) > 0
    for (c0, x0, n0), (c1, x1, n1) in zip(sync, async_):
        assert np.array_equal(c0, c1)
        assert np.array_equal(x0, x1)
        assert np.array_equal(n0, n1)
        assert c0.shape == (32,) and n0.shape == (32, 3)


# ----------------------------------- fused kernel parity (tentpole)

def test_fused_neg_softmax_matches_reference_inside_envelope():
    rng = np.random.default_rng(5)
    b, k, d = 16, 5, 128
    assert supports(b, k, d)
    c = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)
    pos = jnp.asarray(rng.normal(size=(b, d)), jnp.float32)
    neg = jnp.asarray(rng.normal(size=(b, k, d)), jnp.float32)
    ps, ns = neg_softmax_scores(c, pos, neg)     # pallas (interpret off-TPU)
    rps, rns = _score_body(c, pos, neg)          # pure-jnp reference
    np.testing.assert_allclose(np.asarray(ps), np.asarray(rps), atol=1e-6)
    np.testing.assert_allclose(np.asarray(ns), np.asarray(rns), atol=1e-6)


def test_fused_neg_softmax_envelope_gates_cleanly():
    # un-tiled dim falls back to the identical-math jnp reference
    assert not supports(16, 5, 64)
    rng = np.random.default_rng(6)
    c = jnp.asarray(rng.normal(size=(16, 64)), jnp.float32)
    neg = jnp.asarray(rng.normal(size=(16, 5, 64)), jnp.float32)
    ps, ns = neg_softmax_scores(c, c, neg)
    rps, rns = _score_body(c, c, neg)
    assert np.array_equal(np.asarray(ps), np.asarray(rps))
    assert np.array_equal(np.asarray(ns), np.asarray(rns))


# --------------------------------------------- ANN index contracts

def _clustered(rng, v=512, d=16, nc=16):
    centers = rng.normal(size=(nc, d)).astype(np.float32)
    return (centers[rng.integers(0, nc, v)]
            + 0.1 * rng.normal(size=(v, d))).astype(np.float32)


def test_ann_calibrates_past_recall_floor_and_full_probe_is_exact():
    rng = np.random.default_rng(7)
    vecs = _clustered(rng)
    idx = DeviceANNIndex.build(vecs, n_partitions=16, seed=0)
    queries = vecs[rng.choice(512, size=32, replace=False)]
    nprobe, recall = idx.calibrate_nprobe(vecs, queries, k=10, floor=0.95)
    assert recall >= 0.95
    assert nprobe <= idx.n_partitions
    # probing every partition recovers the exact brute-force sets
    ids, _ = idx.search(queries, 10, nprobe=idx.n_partitions)
    exact_ids, _ = brute_force_topk(vecs, queries, 10)
    ann, exact = np.asarray(ids), np.asarray(exact_ids)
    assert recall_at_k(ann, exact) == 1.0
    for row in range(ann.shape[0]):
        assert set(ann[row].tolist()) == set(exact[row].tolist())


def test_ann_search_is_fixed_shape_and_trace_stable():
    rng = np.random.default_rng(8)
    vecs = _clustered(rng)
    idx = DeviceANNIndex.build(vecs, n_partitions=16, seed=0)
    q = rng.normal(size=(4, 16)).astype(np.float32)
    ids, scores = idx.search(q, 5, nprobe=4)
    tc = idx.trace_count
    for _ in range(3):
        ids, scores = idx.search(rng.normal(size=(4, 16))
                                 .astype(np.float32), 5, nprobe=4)
    assert idx.trace_count == tc
    assert ids.shape == (4, 5) and scores.shape == (4, 5)
    # nearest-first ordering, the vptree `search` contract
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()


# ------------------------------------------- serving round trips

@pytest.fixture(scope="module")
def embed_stack():
    rng = np.random.default_rng(9)
    vecs = _clustered(rng, v=256, d=16, nc=16)
    rec = Recorder()
    eng = EmbeddingServingEngine(
        vecs, n_partitions=16, lattice=BucketLattice(batch_sizes=(1, 4, 8)),
        k_grid=(5,), recall_floor=0.9, calibration_queries=16, seed=0,
        recorder=rec).start()
    from deeplearning4j_tpu.serving.server import ServingServer

    server = ServingServer(eng, port=0).start()
    yield vecs, eng, server, rec
    server.stop()
    eng.drain(10.0)


def _post(url, route, payload):
    req = urllib.request.Request(
        f"{url}{route}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read())


def test_embed_endpoint_serves_exact_rows(embed_stack):
    vecs, eng, server, _ = embed_stack
    resp = _post(server.url, "/embed", {"ids": [3, 7, 200]})
    np.testing.assert_allclose(np.asarray(resp["vectors"]),
                               vecs[[3, 7, 200]], atol=1e-6)
    assert resp["timing"]["total_s"] >= 0


def test_search_endpoint_finds_self_and_respects_k_grid(embed_stack):
    vecs, eng, server, _ = embed_stack
    resp = _post(server.url, "/search", {"vector": vecs[42].tolist(),
                                         "k": 5})
    assert resp["ids"][0][0] == 42          # a corpus row's NN is itself
    assert len(resp["ids"][0]) == 5
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server.url, "/search", {"vector": vecs[0].tolist(), "k": 7})
    assert e.value.code == 400              # foreign k would retrace


def test_serving_rejects_out_of_envelope_requests(embed_stack):
    vecs, eng, server, _ = embed_stack
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server.url, "/embed", {"ids": [999999]})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server.url, "/embed",
              {"ids": list(range(64))})     # over the lattice max batch
    assert e.value.code == 400


def test_serving_traffic_is_zero_retrace_after_warmup(embed_stack):
    vecs, eng, server, _ = embed_stack
    tc = eng.trace_count
    rng = np.random.default_rng(10)
    for n in (1, 3, 4, 8, 2):               # pad up through the lattice
        _post(server.url, "/search",
              {"vectors": rng.normal(size=(n, 16)).tolist()})
        _post(server.url, "/embed",
              {"ids": rng.integers(0, 256, n).tolist()})
    assert eng.trace_count == tc, "post-warmup retrace in serving path"
    stats = eng.stats()
    assert stats["trace_count"] == tc
    assert stats["ann"]["nprobe"] >= 1
    assert stats["served"] >= 10 and stats["failed"] == 0


def test_metrics_endpoint_exports_embedding_spans(embed_stack):
    """Satellite 6: the gather/ann_probe span stream (bytes attached)
    lands in the Prometheus exposition as latency histograms and a
    bytes-moved counter."""
    from deeplearning4j_tpu.telemetry.metrics import parse_exposition

    _, eng, server, _ = embed_stack
    with urllib.request.urlopen(f"{server.url}/metrics", timeout=10) as r:
        parsed = parse_exposition(r.read().decode())
    assert parsed["serving_embedding_gather_seconds_count"] >= 1
    assert parsed["serving_embedding_ann_probe_seconds_count"] >= 1
    assert parsed['serving_embedding_bytes_total{span="gather"}'] > 0
    assert parsed['serving_embedding_bytes_total{span="ann_probe"}'] > 0


def test_fleet_supervisor_speaks_the_engine_protocol(embed_stack):
    from deeplearning4j_tpu.serving.fleet import FleetSupervisor

    _, eng, server, _ = embed_stack
    sup = FleetSupervisor(eng)
    sup.poll()
    snap = eng.fleet_snapshot()
    assert snap["n_replicas"] == 1 and snap["n_serving"] == 1
    (row,) = (w.describe(__import__("time").monotonic())
              for w in eng.fleet_workers())
    assert row["state"] == "serving" and row["alive"]
