"""Pluggable annotation engine (the UIMA AnalysisEngine slot — reference
text/uima/UimaResource.java, PosUimaTokenizer.java,
UimaSentenceIterator.java)."""

from deeplearning4j_tpu.nlp.annotation import (
    AnnotationEngine,
    AnnotationTokenizerFactory,
    LexiconAnnotationEngine,
    SentenceDetector,
    SpacyAnnotationEngine,
    get_annotation_engine,
    set_annotation_engine,
)
from deeplearning4j_tpu.nlp.sentiment import PosAwareTokenizerFactory


def test_default_engine_is_lexicon():
    assert isinstance(get_annotation_engine(), LexiconAnnotationEngine)


def test_sentence_segmentation():
    eng = LexiconAnnotationEngine()
    text = ("Deep learning works. Does it scale? It does! "
            "Dr. No was here.")
    sents = eng.sentences(text)
    assert sents[0] == "Deep learning works."
    assert sents[1] == "Does it scale?"
    assert sents[2] == "It does!"
    assert len(sents) >= 3


def test_tokenize_and_pos():
    eng = LexiconAnnotationEngine()
    toks = eng.tokenize("The quick dog runs quickly.")
    assert toks[:2] == ["The", "quick"]
    assert "." in toks
    tags = dict(eng.pos_tags(["the", "quickly", "running", "goodness"]))
    assert tags["the"] == "d"
    assert tags["quickly"] == "r"
    assert tags["running"] == "v"
    assert tags["goodness"] == "n"


def test_annotate_document_shape():
    out = LexiconAnnotationEngine().annotate("Cats sleep. Dogs bark.")
    assert len(out) == 2
    assert all(isinstance(t, tuple) and len(t) == 2
               for sent in out for t in sent)


def test_sentence_detector_and_factory_route_through_engine():
    class UpperEngine(LexiconAnnotationEngine):
        def pos_tags(self, tokens):
            return [(t, "x") for t in tokens]

    set_annotation_engine(UpperEngine())
    try:
        toks = PosAwareTokenizerFactory().create("good dog").get_tokens()
        assert toks == ["good#x", "dog#x"]
        toks2 = AnnotationTokenizerFactory().create("good dog").get_tokens()
        assert toks2 == ["good#x", "dog#x"]
        assert SentenceDetector().detect("A b. C d.") == ["A b.", "C d."]
    finally:
        set_annotation_engine(None)
    # restored default
    toks = PosAwareTokenizerFactory().create("good dog").get_tokens()
    assert toks == ["good#a", "dog#n"]


def test_spacy_engine_gated():
    # spaCy is not in this image: available() must say so and construction
    # must raise ImportError (never a crash elsewhere)
    if SpacyAnnotationEngine.available():
        eng = SpacyAnnotationEngine()
        assert eng.sentences("A b. C d.")
    else:
        try:
            SpacyAnnotationEngine()
            raised = False
        except ImportError:
            raised = True
        assert raised


def test_engine_protocol_abstract():
    base = AnnotationEngine()
    for call in (lambda: base.sentences("x"), lambda: base.tokenize("x"),
                 lambda: base.pos_tags(["x"])):
        try:
            call()
            raised = False
        except NotImplementedError:
            raised = True
        assert raised
