"""Run-telemetry subsystem tests (deeplearning4j_tpu/telemetry/):
Recorder JSONL events + span API, the process-global default, the
no-host-sync TelemetryListener, and the truncation-proof summary line —
including the round-trip the acceptance criterion names: build a full
artifact, cut it to the driver's 2000-byte tail, and recover every gate
decision from the surviving summary line."""

import json

import pytest

from deeplearning4j_tpu.telemetry import (
    NullRecorder,
    Recorder,
    TelemetryListener,
    get_default,
    set_default,
)
from deeplearning4j_tpu.telemetry import artifact, recorder as recorder_mod

pytestmark = pytest.mark.telemetry


# ---------------------------------------------------------------- recorder

def _read_jsonl(path):
    with open(path) as fh:
        return [json.loads(line) for line in fh if line.strip()]


def test_recorder_appends_typed_jsonl_events(tmp_path):
    path = str(tmp_path / "run.jsonl")
    rec = Recorder(path)
    rec.meta(role="test")
    rec.step(3, score=0.5, iterations_per_sec=10.0)
    rec.metric({"metric": "m", "value": 1.0})
    rec.close()
    events = _read_jsonl(path)
    assert [e["event"] for e in events] == ["meta", "step", "metric"]
    # envelope: every event carries ts/run/seq; seq is monotonic
    for i, e in enumerate(events):
        assert e["run"] == rec.run_id and e["seq"] == i and e["ts"] > 0
    assert events[1]["iteration"] == 3 and events[1]["score"] == 0.5
    assert events[2]["metric"] == "m"


def test_recorder_appends_across_instances_like_subprocesses(tmp_path):
    """bench children share one log via append — two Recorder instances
    on the same path interleave whole lines, not clobber."""
    path = str(tmp_path / "run.jsonl")
    a, b = Recorder(path), Recorder(path)
    a.event("x")
    b.event("y")
    a.event("z")
    a.close(), b.close()
    assert [e["event"] for e in _read_jsonl(path)] == ["x", "y", "z"]


def test_span_records_wall_clock_and_result_fields(tmp_path):
    rec = Recorder(str(tmp_path / "run.jsonl"))
    with rec.span("compile", mode="lenet") as sp:
        sp["n_ops"] = 7
    rec.close()
    (event,) = _read_jsonl(rec.path)
    assert event["event"] == "span" and event["name"] == "compile"
    assert event["ok"] is True and event["seconds"] >= 0
    assert event["mode"] == "lenet" and event["n_ops"] == 7


def test_span_on_exception_emits_error_with_full_traceback(tmp_path):
    rec = Recorder(str(tmp_path / "run.jsonl"))
    with pytest.raises(ValueError, match="boom"):
        with rec.span("step"):
            raise ValueError("boom")
    rec.close()
    err, span = _read_jsonl(rec.path)
    assert err["event"] == "error" and err["where"] == "span:step"
    # the FULL traceback string — the thing the driver tail destroys
    assert "Traceback (most recent call last)" in err["traceback"]
    assert "ValueError: boom" in err["traceback"]
    assert span["event"] == "span" and span["ok"] is False


def test_error_event_from_exception_object():
    rec = Recorder()
    try:
        raise RuntimeError("kaput")
    except RuntimeError as exc:
        rec.error("mode:vgg16", exc=exc)
    (event,) = rec.events
    assert event["error"] == "RuntimeError('kaput')"
    assert "RuntimeError: kaput" in event["traceback"]


def test_memory_snapshot_counts_live_arrays():
    import jax.numpy as jnp

    keep = jnp.ones((128, 128), jnp.float32)  # noqa: F841 — held live
    rec = Recorder()
    event = rec.memory()
    assert event["live_array_bytes"] >= keep.nbytes
    assert event["live_array_count"] >= 1


def test_metric_event_parses_as_a_bench_line():
    """Telemetry logs and bench stdout share one parser: a `metric`
    event IS the bench line (flattened), and non-metric events are
    invisible to the artifact parser."""
    rec = Recorder()
    rec.meta(role="x")
    rec.metric({"metric": "lenet", "value": 2.0, "vs_baseline": 1.1})
    rec.step(1, score=0.1)
    text = "\n".join(json.dumps(e) for e in rec.events)
    lines, summary = artifact.parse_metric_lines(text)
    assert summary is None
    assert set(lines) == {"lenet"} and lines["lenet"]["value"] == 2.0


def test_default_recorder_is_null_until_configured(monkeypatch):
    monkeypatch.delenv(recorder_mod.ENV_VAR, raising=False)
    prev = set_default(None)
    try:
        rec = get_default()
        assert isinstance(rec, NullRecorder)
        assert rec.event("step") == {} and not rec.events
        with rec.span("s") as sp:  # span still runs the body
            sp["ran"] = True
        assert sp["ran"]
    finally:
        set_default(prev)


def test_default_recorder_from_env_var(tmp_path, monkeypatch):
    path = str(tmp_path / "env.jsonl")
    monkeypatch.setenv(recorder_mod.ENV_VAR, path)
    prev = set_default(None)
    try:
        rec = get_default()
        assert get_default() is rec  # stable across calls
        rec.event("ping")
        rec.close()
        assert _read_jsonl(path)[0]["event"] == "ping"
    finally:
        set_default(prev)


# ---------------------------------------------------------------- listener

class _DeviceScalar:
    """Stand-in for the jitted step's device scalar: float() is the host
    sync the listener must defer to flush time."""

    def __init__(self, value, sync_log):
        self.value, self.sync_log = value, sync_log

    def __float__(self):
        self.sync_log.append(self.value)
        return self.value


class _Model:
    def __init__(self):
        self._score_raw = None


def test_listener_defers_host_sync_to_window_flush():
    syncs = []
    model = _Model()
    rec = Recorder()
    lst = TelemetryListener(recorder=rec, frequency=3)
    for it in range(1, 3):
        model._score_raw = _DeviceScalar(0.1 * it, syncs)
        lst.iteration_done(model, it)
        assert syncs == []  # no host sync on the hot path
    model._score_raw = _DeviceScalar(0.3, syncs)
    lst.iteration_done(model, 3)  # window full -> one batched fetch
    assert len(syncs) == 3
    steps = [e for e in rec.events if e["event"] == "step"]
    assert [e["iteration"] for e in steps] == [1, 2, 3]
    assert steps[0]["score"] == pytest.approx(0.1)
    # throughput over the window rides the LAST event only
    assert "iterations_per_sec" in steps[-1]
    assert all("iterations_per_sec" not in e for e in steps[:-1])


def test_listener_close_flushes_partial_window():
    model = _Model()
    model._score_raw = 0.5
    rec = Recorder()
    lst = TelemetryListener(recorder=rec, frequency=100)
    lst.iteration_done(model, 1)
    assert not rec.events
    lst.close()
    (event,) = rec.events
    assert event["iteration"] == 1 and event["score"] == 0.5
    lst.close()  # idempotent
    assert len(rec.events) == 1


def test_listener_rides_fit(tmp_path):
    """End-to-end through the real fit() loop: scores land as step
    events without touching model.score_value's eager float path."""
    import numpy as np

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1)
            .list()
            .layer(OutputLayer(n_in=4, n_out=3, activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rec = Recorder()
    lst = TelemetryListener(recorder=rec, frequency=4)
    net.set_listeners(lst)
    rng = np.random.default_rng(0)
    x = rng.random((8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    net.fit(x, y, epochs=6)
    lst.close()
    steps = [e for e in rec.events if e["event"] == "step"]
    assert [e["iteration"] for e in steps] == list(range(1, 7))
    assert all(isinstance(e["score"], float) for e in steps)


# ------------------------------------------------- summary / truncation

GATED_LINES = [
    {"metric": "lenet_mnist_images_per_sec_tpu", "value": 2043143.5,
     "unit": "images/sec/chip", "vs_baseline": 1.2,
     "gate_scale": 0.96, "attempts": [{"value": 1.9e6}, {"value": 2.04e6}]},
    {"metric": "vgg16_cifar_images_per_sec_tpu", "value": 56436.5,
     "unit": "images/sec/chip", "vs_baseline": 0.705,
     "gate_scale": 0.93, "regression": True},
    {"metric": "word2vec_sgns_words_per_sec", "value": 850493.5,
     "unit": "words/sec", "vs_baseline": 1.06,
     "quality_ratio_vs_host": 0.977, "quality_gate_min_ratio": 0.95},
    {"metric": "resnet20_dp_allreduce_vs_paramavg_speedup",
     "value": 1.09, "unit": "x", "vs_baseline": 1.09,
     "ratio_median": 1.09, "ratio_spread": [1.02, 1.21],
     "paramavg_averaging_frequency": 1},
    {"metric": "transformer_lm_mfu_tpu", "value": 0.5113,
     "unit": "MFU fraction", "vs_baseline": 1.7042,
     "mfu_vs_achievable": 0.57, "mfu_executed": 0.4489},
    {"metric": "transformer_moe_lm_tokens_per_sec_tpu", "value": 1459666.3,
     "unit": "tokens/sec", "vs_baseline": 1.16,
     "vs_dense_ratio": 0.7894, "ratio_floor": 0.65},
]


def _artifact_text(lines):
    """A bench-stdout-shaped artifact: verbose detail lines (each
    followed by the stderr-echo noise a real run interleaves — what
    pushes early lines past the driver's tail), then the summary line
    LAST (what survives)."""
    rows = []
    for i, l in enumerate(lines):
        rows.append(json.dumps(l))
        rows.append(f"REGRESSION-echo-noise-{i}: " + "x" * 500)
    rows.append(json.dumps(artifact.build_summary(lines)))
    return "\n".join(rows) + "\n"


def test_build_summary_carries_every_gate_field():
    summary = artifact.build_summary(GATED_LINES)
    assert summary["regressions"] == 1
    assert summary["regressed_metrics"] == [
        "vgg16_cifar_images_per_sec_tpu"]
    gates = summary["gates"]
    assert gates["word2vec_sgns_words_per_sec"][
        "quality_ratio_vs_host"] == 0.977
    assert gates["transformer_moe_lm_tokens_per_sec_tpu"][
        "vs_dense_ratio"] == 0.7894
    assert gates["transformer_lm_mfu_tpu"]["mfu_vs_achievable"] == 0.57
    assert gates["vgg16_cifar_images_per_sec_tpu"]["regression"] is True
    assert gates["resnet20_dp_allreduce_vs_paramavg_speedup"][
        "ratio_spread"] == [1.02, 1.21]
    # headline = the north-star MFU metric
    assert summary["value"] == 0.5113 and summary["vs_baseline"] == 1.7042
    # the whole line must FIT in the driver's 2000-byte tail
    assert len(json.dumps(summary)) < 1900


def test_gate_decisions_survive_2000_byte_tail_cut(tmp_path):
    """The acceptance round-trip: full artifact -> keep only the last
    2000 bytes (the driver's truncation) -> every gate field of every
    metric is still recoverable."""
    text = _artifact_text(GATED_LINES)
    tail = text[-2000:]
    # the cut really destroyed the detail lines (not a vacuous test)
    kept_lines, _ = artifact.parse_metric_lines(tail)
    assert len(kept_lines) < len(GATED_LINES)
    path = tmp_path / "BENCH_cut.json"
    path.write_text(tail)
    recovered = artifact.load(str(path))
    for line in GATED_LINES:
        row = recovered[line["metric"]]
        assert row["value"] == line["value"]
        for field in artifact.GATE_FIELDS:
            if field in line:
                assert row[field] == line[field], (line["metric"], field)
        if line.get("regression"):
            assert row["regression"] is True


def test_merge_summary_never_overrides_surviving_rows():
    lines = {"m": {"metric": "m", "value": 1.0, "gate_scale": 0.5}}
    summary = {"metric": "summary", "m": 9.0,
               "gates": {"m": {"gate_scale": 0.9}},
               "regressed_metrics": []}
    merged = artifact.merge_summary(lines, summary)
    assert merged["m"]["value"] == 1.0 and merged["m"]["gate_scale"] == 0.5


def test_ab_ratio_stats_median_and_spread():
    import bench

    stats = bench._ab_ratio_stats([(2.0, 1.0), (1.0, 1.0), (3.0, 1.0)])
    assert stats["ratio_median"] == 2.0
    assert stats["ratio_spread"] == [1.0, 3.0]
    assert stats["repeats"] == 3
    # even count -> midpoint of the two middle ratios
    even = bench._ab_ratio_stats([(1.0, 1.0), (2.0, 1.0)])
    assert even["ratio_median"] == 1.5


def test_bench_mode_crash_leaves_full_traceback_in_telemetry(monkeypatch):
    """Satellite of VERDICT r5 #1: a mode that dies under capture leaves
    an `error` event with the FULL traceback in the telemetry log — the
    r5 transformer_large crash was unrecoverable from the stdout tail."""
    import sys as _sys

    import bench

    rec = Recorder()
    prev = set_default(rec)

    def boom():
        raise RuntimeError("driver-capture crash")

    monkeypatch.setitem(bench.MODES, "boom", boom)
    monkeypatch.setattr(_sys, "argv", ["bench.py", "boom"])
    try:
        with pytest.raises(RuntimeError, match="driver-capture crash"):
            bench.main()
    finally:
        set_default(prev)
    (err,) = [e for e in rec.events if e["event"] == "error"]
    assert "RuntimeError: driver-capture crash" in err["traceback"]
    assert "in boom" in err["traceback"]  # full frames, not just the tail
    spans = [e for e in rec.events if e["event"] == "span"]
    assert spans and spans[-1]["ok"] is False


def test_bench_emit_records_metric_event(capsys):
    import bench

    rec = Recorder()
    prev = set_default(rec)
    try:
        bench._emit("lenet", 2.0e6, "images/sec/chip")
    finally:
        set_default(prev)
    printed = json.loads(capsys.readouterr().out.strip())
    (event,) = [e for e in rec.events if e["event"] == "metric"]
    assert event["metric"] == printed["metric"] == "lenet"
    assert event["value"] == printed["value"]


def test_evaluate_records_eval_event():
    """Both containers' evaluate() feed an `eval` event with the scalar
    summary stats (a NullRecorder no-op when telemetry is off)."""
    import numpy as np

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1)
            .list()
            .layer(OutputLayer(n_in=4, n_out=3, activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.random((8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    rec = Recorder()
    prev = set_default(rec)
    try:
        ev = net.evaluate(DataSet(x, y))
    finally:
        set_default(prev)
    (event,) = [e for e in rec.events if e["event"] == "eval"]
    assert event["stats"]["accuracy"] == pytest.approx(ev.accuracy())
    assert set(event["stats"]) >= {"accuracy", "precision", "recall", "f1"}


def test_fused_fit_emits_compile_then_step_spans():
    """nn/training.py threads a span around the scanned-fit dispatch:
    first call = "compile" (blocks on trace+compile), later = step_scan."""
    import numpy as np

    from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import OutputLayer

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1)
            .list()
            .layer(OutputLayer(n_in=4, n_out=3, activation="softmax"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    x = rng.random((8, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    rec = Recorder()
    prev = set_default(rec)
    try:
        net.fit_scanned(x, y, epochs=2)
        net.fit_scanned(x, y, epochs=2)
    finally:
        set_default(prev)
    spans = [e for e in rec.events if e["event"] == "span"]
    assert [s["name"] for s in spans] == ["compile", "step_scan"]
    assert all(s["what"] == "fit_scanned" and s["ok"] for s in spans)
