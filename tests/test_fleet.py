"""Tier-1 gate for zero-downtime fleet operations (ISSUE 13,
serving/fleet.py).

The acceptance properties are asserted FROM THE TELEMETRY JSONL ALONE:
a mid-traffic hot-swap with zero failed requests and the weight
generation flip visible in `request` events; a replica-kill chaos
replay where only the in-flight batch fails, the respawned replica
serves again, and the trace counter stays frozen (0 retraces). The
swap/supervisor state machines are additionally proven as pure
functions on fake clocks — hysteresis, respawn backoff jitter caps,
double-buffer flip ordering, failed-restore rollback — with no sleeps.

Every test that spawns a supervisor/engine thread runs under a hard
wall-clock deadline: each blocking wait carries an explicit timeout
(DEADLINE_S) and asserts it was not hit, so a wedged fleet fails the
test instead of hanging the suite.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.serving import fleet
from deeplearning4j_tpu.serving.batcher import Batcher, PendingRequest
from deeplearning4j_tpu.serving.buckets import BucketLattice
from deeplearning4j_tpu.serving.engine import InferenceEngine
from deeplearning4j_tpu.serving.fleet import (AutoscalePolicy,
                                              AutoscaleState,
                                              CheckpointWatcher,
                                              FleetSupervisor,
                                              ReplicaFaultInjector,
                                              ReplicaKilled, RespawnBackoff,
                                              WeightStore, WeightSwapError,
                                              autoscale_decision)
from deeplearning4j_tpu.serving.server import ServingServer
from deeplearning4j_tpu.serving import replay
from deeplearning4j_tpu.telemetry import Recorder

pytestmark = [pytest.mark.serving, pytest.mark.fleet]

# the hard deadline every spawned-supervisor wait runs under
DEADLINE_S = 30.0


def _mlp():
    return replay._tiny_mlp()


def _benchdiff():
    """tools/benchdiff.py as a module (the test_benchdiff.py idiom —
    tools/ is not a package)."""
    import importlib.util as ilu
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = ilu.spec_from_file_location(
        "benchdiff_fleet_test", os.path.join(root, "tools",
                                             "benchdiff.py"))
    mod = ilu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _events(path, kind):
    out = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line.startswith("{"):
                continue
            ev = json.loads(line)
            if ev.get("event") == kind:
                out.append(ev)
    return out


def _save_publish_checkpoint(net, step, tmp_path, *, bump=0.5):
    """The 'training fleet publishes a step' half: the net's params
    shifted by `bump`, saved as an Orbax host checkpoint at `step`."""
    import jax

    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    pub = net.clone()
    pub.params = jax.tree.map(lambda a: a + bump, pub.params)
    pub.iteration_count = step
    ckdir = str(tmp_path / f"publish_{step}")
    ShardedCheckpointer(ckdir).save(pub, step, host=True)
    return ckdir


# ------------------------------------------------------ pure: weight store

def test_weight_store_flip_ordering_and_immutability():
    store = WeightStore({"w": 1}, {"s": 1}, step=3)
    before = store.current
    assert (before.generation, before.step) == (0, 3)
    new = store.publish({"w": 2}, {"s": 2}, step=9)
    # the flip is a single reference swap to a FULLY-built set
    assert store.current is new
    assert (new.generation, new.step) == (1, 9)
    # the old set stays intact for in-flight readers
    assert before.params == {"w": 1} and before.generation == 0
    assert store.last_swap_ts is not None
    # frozen: a reader can never mutate a published set
    with pytest.raises(Exception):
        new.params = {}


def test_weight_store_concurrent_readers_see_whole_generations():
    """Readers racing a publisher observe only complete (gen, step)
    pairs — never generation N with generation N+1's step."""
    store = WeightStore({"w": 0}, None, step=0)
    seen = []
    stop = threading.Event()

    def reader():
        while not stop.is_set():
            ws = store.current
            seen.append((ws.generation, ws.step, ws.params["w"]))

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    for g in range(1, 50):
        store.publish({"w": g}, None, step=g * 10)
    stop.set()
    t.join(timeout=DEADLINE_S)
    assert not t.is_alive(), "reader missed its deadline"
    for gen, step, w in seen:
        assert step == gen * 10 and w == gen, "torn read across the flip"


# -------------------------------------------------- pure: respawn backoff

def test_respawn_backoff_growth_cap_and_jitter_cap():
    b = RespawnBackoff(base_s=0.1, factor=2.0, cap_s=0.8, jitter_frac=0.25,
                       seed=7)
    delays = [b.next() for _ in range(8)]
    bases = [0.1, 0.2, 0.4, 0.8, 0.8, 0.8, 0.8, 0.8]
    for d, base in zip(delays, bases):
        assert base <= d <= base * 1.25 + 1e-12, (d, base)
    # the TOTAL is capped: never more than cap * (1 + jitter_frac)
    assert max(delays) <= 0.8 * 1.25 + 1e-12
    # deterministic: same seed, same ladder
    b2 = RespawnBackoff(base_s=0.1, factor=2.0, cap_s=0.8,
                        jitter_frac=0.25, seed=7)
    assert [b2.next() for _ in range(8)] == delays
    b2.reset()
    assert b2.next() <= 0.1 * 1.25


def test_respawn_backoff_rejects_bad_jitter():
    with pytest.raises(ValueError, match="jitter_frac"):
        RespawnBackoff(jitter_frac=1.5)


# ---------------------------------------------- pure: autoscale hysteresis

def test_autoscale_scale_up_on_queue_depth_with_cooldown():
    p = AutoscalePolicy(min_replicas=1, max_replicas=3, up_queue_depth=8,
                        down_queue_depth=1, cooldown_up_s=1.0,
                        cooldown_down_s=5.0)
    s = AutoscaleState()
    assert autoscale_decision(p, s, queue_depth=10, p99_ms=0.0,
                              n_replicas=1, now=0.0) == 1
    # cooldown: an immediate second burst sample does NOT double-grow
    assert autoscale_decision(p, s, queue_depth=50, p99_ms=0.0,
                              n_replicas=2, now=0.5) == 0
    assert autoscale_decision(p, s, queue_depth=50, p99_ms=0.0,
                              n_replicas=2, now=1.1) == 1
    # ceiling: never above max_replicas
    assert autoscale_decision(p, s, queue_depth=50, p99_ms=0.0,
                              n_replicas=3, now=9.0) == 0


def test_autoscale_scale_down_hysteresis_and_floor():
    p = AutoscalePolicy(min_replicas=1, max_replicas=3, up_queue_depth=8,
                        down_queue_depth=1, cooldown_up_s=0.5,
                        cooldown_down_s=4.0)
    s = AutoscaleState()
    assert autoscale_decision(p, s, queue_depth=10, p99_ms=0.0,
                              n_replicas=1, now=0.0) == 1
    # idle right after the burst: the up-flip armed the down cooldown
    assert autoscale_decision(p, s, queue_depth=0, p99_ms=0.0,
                              n_replicas=2, now=1.0) == 0
    # between the low and high water marks: hold (hysteresis band)
    assert autoscale_decision(p, s, queue_depth=4, p99_ms=0.0,
                              n_replicas=2, now=10.0) == 0
    assert autoscale_decision(p, s, queue_depth=0, p99_ms=0.0,
                              n_replicas=2, now=10.0) == -1
    # down cooldown: one drain per window, and never below the floor
    assert autoscale_decision(p, s, queue_depth=0, p99_ms=0.0,
                              n_replicas=2, now=11.0) == 0
    assert autoscale_decision(p, s, queue_depth=0, p99_ms=0.0,
                              n_replicas=1, now=99.0) == 0


def test_autoscale_p99_trigger():
    p = AutoscalePolicy(max_replicas=2, up_queue_depth=10 ** 9,
                        up_p99_ms=50.0, cooldown_up_s=0.0)
    s = AutoscaleState()
    assert autoscale_decision(p, s, queue_depth=0, p99_ms=80.0,
                              n_replicas=1, now=0.0) == 1


# ------------------------------------------------- pure: fault injection

def test_replica_fault_injector_fires_once_and_records():
    rec = Recorder(path=None)
    inj = ReplicaFaultInjector("r1:kill@batch3", recorder=rec)
    inj.check(0, "batch", 3)      # wrong replica: silent
    inj.check(1, "batch", 2)      # wrong count: silent
    inj.check(1, "decode", 3)     # wrong unit: silent
    with pytest.raises(ReplicaKilled):
        inj.check(1, "batch", 3)
    # one-shot: a respawned replica reaching batch 3 again is NOT re-killed
    inj.check(1, "batch", 3)
    faults = [e for e in rec.events if e.get("event") == "fault"]
    assert len(faults) == 1
    assert faults[0]["kind"] == "replica-kill"
    assert faults[0]["spec"] == "r1:kill@batch3"


def test_latest_step_sees_only_committed_steps(tmp_path):
    d = tmp_path / "ck"
    assert fleet.latest_step(str(d)) is None
    (d / "step_3").mkdir(parents=True)
    (d / "step_7").mkdir()
    (d / "step_3" / "meta.json").write_text("{}")
    # step_7 has no meta.json: mid-write, invisible
    assert fleet.latest_step(str(d)) == 3
    (d / "step_7" / "meta.json").write_text("{}")
    assert fleet.latest_step(str(d)) == 7


# --------------------------------------------- batcher requeue (no sleeps)

def test_batcher_requeue_puts_requests_back_at_fifo_head():
    now = {"t": 0.0}
    b = Batcher(BucketLattice(batch_sizes=(1, 2, 4)), max_wait_ms=5.0,
                clock=lambda: now["t"])
    first = b.submit(np.zeros(3, np.float32))
    second = b.submit(np.ones(3, np.float32))
    now["t"] = 0.006
    batch = b.next_batch(timeout=0.5)
    assert batch.n_real == 2 and b.depth == 0
    # a reaped replica hands its batch's requests back: FIFO order kept
    b.requeue(batch.requests)
    assert b.depth == 2
    again = b.next_batch(timeout=0.5)
    assert again.requests[0] is first and again.requests[1] is second
    # requeue works even while draining (they were already admitted)
    b.close()
    b.requeue([first])
    assert b.next_batch(timeout=0.0).requests == [first]


# ----------------------------------------- acceptance: live hot-swap

def test_hot_swap_mid_traffic_zero_failed_from_telemetry(tmp_path):
    """THE swap acceptance, from the JSONL alone: traffic before,
    during, and after a live hot-swap; zero failed requests; the typed
    weight_swap event (step, restore_ms, generation); and the
    generation flip visible in the request events' weight_gen."""
    tpath = str(tmp_path / "telemetry.jsonl")
    rec = Recorder(tpath)
    net = _mlp()
    engine = InferenceEngine(net, BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=1.0, recorder=rec)
    engine.warmup(np.zeros(8, np.float32))
    engine.start()
    ckdir = _save_publish_checkpoint(net, 5, tmp_path)

    x = np.ones(8, np.float32)
    outs = []
    done_half = threading.Event()
    swap_done = threading.Event()
    finished = threading.Event()

    def traffic():
        for i in range(20):
            outs.append(np.asarray(engine.predict(x, timeout=DEADLINE_S)))
            if i == 9:
                done_half.set()
                # the second half of the traffic overlaps and follows
                # the swap — without this gate a fast forward path can
                # finish all 20 requests before the restore completes
                swap_done.wait(DEADLINE_S)
        finished.set()

    t = threading.Thread(target=traffic, daemon=True)
    t.start()
    assert done_half.wait(DEADLINE_S), "traffic missed its deadline"
    swap = fleet.hot_swap(engine, ckdir)   # mid-traffic, off the req path
    swap_done.set()
    assert swap["step"] == 5 and swap["generation"] == 1
    assert finished.wait(DEADLINE_S), "traffic missed its deadline"
    t.join(DEADLINE_S)
    engine.drain(DEADLINE_S)
    rec.close()

    reqs = _events(tpath, "request")
    assert len(reqs) == 20
    assert all(e["ok"] for e in reqs), "a request failed across the swap"
    gens = [e["weight_gen"] for e in reqs]
    assert set(gens) == {0, 1}, "the flip never became visible"
    # generations are monotonic in completion order: old, then new
    assert gens == sorted(gens)
    swaps = _events(tpath, "weight_swap")
    assert len(swaps) == 1 and swaps[0]["ok"]
    assert swaps[0]["step"] == 5 and swaps[0]["generation"] == 1
    assert swaps[0]["restore_ms"] > 0
    # the new weights actually serve: outputs changed across the flip
    assert not np.allclose(outs[0], outs[-1])


def test_hot_swap_rejects_mismatched_and_truncated_checkpoints(tmp_path):
    """Failed-restore rollback: a checkpoint from a different
    architecture and a truncated step directory are both rejected with
    the OLD weights still serving (same outputs, same generation), and
    the rejection is on the telemetry record."""
    tpath = str(tmp_path / "telemetry.jsonl")
    rec = Recorder(tpath)
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=1.0, recorder=rec)
    engine.warmup(np.zeros(8, np.float32))
    engine.start()
    x = np.ones(8, np.float32)
    before = np.asarray(engine.predict(x, timeout=DEADLINE_S))

    # (a) wrong architecture: different OUTPUT width
    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    other = replay._tiny_mlp(n_in=8, n_out=7)
    bad_dir = str(tmp_path / "wrong_arch")
    ShardedCheckpointer(bad_dir).save(other, 3, host=True)
    with pytest.raises(WeightSwapError):
        fleet.hot_swap(engine, bad_dir)

    # (a') wrong HIDDEN width — the insidious case: the reshard-aware
    # restore reads only the slices a target template asks for, so
    # without the PRE-restore metadata gate this partially loads into
    # correctly-shaped garbage that a post-restore check cannot see
    from deeplearning4j_tpu.nn.conf import (DenseLayer,
                                            NeuralNetConfiguration,
                                            OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    narrow_conf = (NeuralNetConfiguration.builder().seed(1).list()
                   .layer(DenseLayer(n_in=8, n_out=5, activation="relu"))
                   .layer(OutputLayer(n_in=5, n_out=4,
                                      activation="softmax",
                                      loss_function="mcxent"))
                   .build())
    narrow = MultiLayerNetwork(narrow_conf).init()
    narrow.iteration_count = 3
    narrow_dir = str(tmp_path / "wrong_hidden")
    ShardedCheckpointer(narrow_dir).save(narrow, 3, host=True)
    with pytest.raises(WeightSwapError, match="mismatch"):
        fleet.hot_swap(engine, narrow_dir)

    # (b) truncated checkpoint: a committed-looking step with its
    # array data gutted
    import os
    import shutil

    ckdir = _save_publish_checkpoint(engine.net, 4, tmp_path)
    step_dir = os.path.join(ckdir, "step_4")
    shutil.rmtree(os.path.join(step_dir, "model"))
    with pytest.raises(WeightSwapError):
        fleet.hot_swap(engine, ckdir)

    # old weights still serving, generation unmoved
    after = np.asarray(engine.predict(x, timeout=DEADLINE_S))
    np.testing.assert_array_equal(before, after)
    assert engine.weights.generation == 0
    engine.drain(DEADLINE_S)
    rec.close()
    swaps = _events(tpath, "weight_swap")
    assert len(swaps) == 3 and not any(s["ok"] for s in swaps)
    assert all(s["generation"] == 0 for s in swaps)
    assert all(e["ok"] for e in _events(tpath, "request"))


def test_checkpoint_watcher_follows_publishes_and_skips_rejects(tmp_path):
    """The train-fleet-publishes loop: poll_once swaps each newly
    committed step exactly once, ignores already-seen steps, and never
    hot-loops on a rejected one."""
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1,)),
                             max_wait_ms=1.0, recorder=Recorder(path=None))
    engine.warmup(np.zeros(8, np.float32))
    ckdir = _save_publish_checkpoint(engine.net, 2, tmp_path)
    watcher = CheckpointWatcher(engine, ckdir, interval_s=0.01)
    out = watcher.poll_once()
    assert out["ok"] and out["step"] == 2
    assert engine.weights.generation == 1
    assert watcher.poll_once() is None  # nothing new
    # publish step 6 with GUTTED data -> rejected once, then quiet
    import os
    import shutil

    from deeplearning4j_tpu.util.orbax_checkpoint import ShardedCheckpointer

    pub = engine.net.clone()
    pub.iteration_count = 6
    ShardedCheckpointer(ckdir).save(pub, 6, host=True)
    shutil.rmtree(os.path.join(ckdir, "step_6", "model"))
    out = watcher.poll_once()
    assert out is not None and not out["ok"] and out["step"] == 6
    assert engine.weights.generation == 1  # old weights still serving
    assert watcher.poll_once() is None     # rejected step not retried


def test_hot_swap_refuses_generation_engines():
    from deeplearning4j_tpu.serving.engine import GenerationEngine

    net = replay._tiny_lm(16)
    engine = GenerationEngine(
        net, BucketLattice(batch_sizes=(1,), seq_lens=(8, 16)),
        slots=2, max_new_tokens=4, recorder=Recorder(path=None))
    with pytest.raises(WeightSwapError, match="KV cache"):
        fleet.hot_swap(engine, "/nonexistent")


# ------------------------------------- acceptance: replica chaos healing

def test_replica_kill_chaos_only_inflight_batch_fails_zero_retraces(
        tmp_path):
    """THE self-healing acceptance, from the JSONL alone: an injected
    replica kill fails ONLY the in-flight batch, the supervisor reaps
    and respawns (respawn_ms on the record), the respawned replica
    serves again, and the trace counter stays frozen — 0 non-warmup
    compiles."""
    tpath = str(tmp_path / "telemetry.jsonl")
    rec = Recorder(tpath)
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=1.0, recorder=rec,
                             faults="r0:kill@batch2")
    engine.warmup(np.zeros(8, np.float32))
    trace_frozen_at = engine.trace_count
    engine.start()
    supervisor = FleetSupervisor(
        engine, death_after_s=1.0,
        backoff=RespawnBackoff(base_s=0.0, jitter_frac=0.0), recorder=rec)
    x = np.ones(8, np.float32)
    ok_before = np.asarray(engine.predict(x, timeout=DEADLINE_S))  # batch 1
    with pytest.raises(RuntimeError, match="ReplicaKilled"):
        engine.predict(x, timeout=DEADLINE_S)                      # batch 2
    actions = supervisor.poll()
    assert actions["reaped"] == [0] and actions["respawned"] == [0]
    ok_after = np.asarray(engine.predict(x, timeout=DEADLINE_S))
    np.testing.assert_array_equal(ok_before, ok_after)
    assert engine.trace_count == trace_frozen_at, "respawn retraced"
    engine.drain(DEADLINE_S)
    rec.close()

    reqs = _events(tpath, "request")
    failed = [e for e in reqs if not e["ok"]]
    assert len(failed) == 1, "more than the in-flight batch failed"
    assert "ReplicaKilled" in failed[0]["error"]
    assert [e["ok"] for e in reqs].count(True) == 2
    kinds = [e["kind"] for e in _events(tpath, "fault")]
    assert kinds == ["replica-kill", "replica-dead", "replica-respawn"]
    respawn = _events(tpath, "fault")[-1]
    assert respawn["respawn_ms"] >= 0
    compiles = [e for e in _events(tpath, "span")
                if e.get("name") == "compile"]
    assert compiles and all(e.get("warmup") for e in compiles), \
        "a non-warmup compile leaked into the chaos replay"


def test_replica_hang_reaped_by_heartbeat_and_queue_drains_back(tmp_path):
    """The hang half: a wedged replica is detected by heartbeat
    staleness (fake `now`), its in-flight batch fails loudly, its
    QUEUED batch drains back to the batcher and completes on the
    respawned replica."""
    rec = Recorder(path=None)
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1,)),
                             max_wait_ms=0.5, recorder=rec,
                             faults="r0:hang@batch1")
    engine.warmup(np.zeros(8, np.float32))
    engine.start()
    supervisor = FleetSupervisor(
        engine, death_after_s=2.0,
        backoff=RespawnBackoff(base_s=0.0, jitter_frac=0.0), recorder=rec)
    x = np.ones(8, np.float32)
    hung = engine.submit(x)      # batch 1: the replica wedges on it
    queued = engine.submit(x)    # lands in the wedged replica's queue
    replica = engine.fleet_workers()[0]
    deadline = threading.Event()
    for _ in range(int(DEADLINE_S / 0.01)):
        if replica.current_batch is not None:
            break
        deadline.wait(0.01)
    assert replica.current_batch is not None, "hang never engaged"
    # heartbeat staleness via a FAKE now — no real waiting; the zero
    # backoff lets the same poll reap AND respawn
    actions = supervisor.poll(now=engine._clock() + 10.0)
    assert actions["reaped"] == [0] and actions["respawned"] == [0]
    assert hung.wait(DEADLINE_S) and hung.error is not None
    assert "reaped" in hung.error
    assert queued.wait(DEADLINE_S), "requeued batch missed its deadline"
    assert queued.error is None and queued.result is not None
    engine.drain(2.0)


def test_gen_worker_kill_mid_decode_releases_pages_and_respawns(tmp_path):
    """The generation twin: a mid-decode kill fails the active slots
    (pages released — the pool returns to empty), the supervisor
    respawns the worker with ZERO new compiles, and queued work
    completes."""
    tpath = str(tmp_path / "telemetry.jsonl")
    rec = Recorder(tpath)
    from deeplearning4j_tpu.serving.engine import GenerationEngine

    net = replay._tiny_lm(24)
    engine = GenerationEngine(
        net, BucketLattice(batch_sizes=(1,), seq_lens=(8,)),
        slots=2, max_new_tokens=8, page_size=4, recorder=rec,
        faults="r0:kill@decode2")
    engine.warmup()
    trace_frozen_at = engine.trace_count
    engine.start()
    supervisor = FleetSupervisor(
        engine, death_after_s=1.0,
        backoff=RespawnBackoff(base_s=0.0, jitter_frac=0.0), recorder=rec)
    prompt = np.arange(8, dtype=np.int32)
    req = engine.submit_generate(prompt, max_new_tokens=6)
    assert req.wait(DEADLINE_S), "killed generation missed its deadline"
    assert req.error is not None and "ReplicaKilled" in req.error
    worker = engine.fleet_workers()[0]
    assert worker.lifecycle == "dead"
    assert worker.pool.describe()["pages_in_use"] == 0, \
        "a dead slot leaked its pages"
    actions = supervisor.poll()
    assert actions["respawned"] == [0]
    toks = engine.generate(prompt, max_new_tokens=6, timeout=DEADLINE_S)
    assert len(toks) == 6
    assert engine.trace_count == trace_frozen_at, "respawn retraced"
    engine.drain(DEADLINE_S)
    rec.close()
    kinds = [e["kind"] for e in _events(tpath, "fault")]
    assert kinds == ["replica-kill", "replica-dead", "replica-respawn"]


# --------------------------------------------- scale up / drain down

def test_add_replica_serves_and_keeps_retrace_accounting(tmp_path):
    tpath = str(tmp_path / "telemetry.jsonl")
    rec = Recorder(tpath)
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=0.5, recorder=rec)
    engine.warmup(np.zeros(8, np.float32))
    engine.start()
    assert engine.fleet_snapshot()["n_serving"] == 1
    engine.add_replica()
    assert engine.fleet_snapshot()["n_serving"] == 2
    x = np.ones(8, np.float32)
    for _ in range(6):
        engine.predict(x, timeout=DEADLINE_S)
    engine.drain(DEADLINE_S)
    rec.close()
    # the new replica's compiles are warmup-flagged: the zero-retrace
    # accounting survives scale-up
    compiles = [e for e in _events(tpath, "span")
                if e.get("name") == "compile"]
    assert len(compiles) == 4 and all(e.get("warmup") for e in compiles)
    assert all(e["ok"] for e in _events(tpath, "request"))


def test_retire_replica_drains_queued_work_and_keeps_last():
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=0.5,
                             recorder=Recorder(path=None))
    engine.warmup(np.zeros(8, np.float32))
    engine.start()
    second = engine.add_replica()
    # park a batch directly on the replica being retired: scale-down
    # with queued work must finish it, not drop it
    from deeplearning4j_tpu.serving.batcher import assemble

    req = PendingRequest(features=np.ones(8, np.float32),
                         t_enqueue=engine._clock())
    batch = assemble([req], engine.lattice)
    batch.t_cut = engine._clock()
    req.t_assembled = batch.t_cut
    second.queue.put(batch)
    retired = engine.retire_replica()
    assert retired is second
    assert req.wait(DEADLINE_S), "queued work dropped on scale-down"
    assert req.error is None
    # the drained replica left dispatch; the survivor still serves
    assert engine.fleet_snapshot()["n_serving"] == 1
    out = engine.predict(np.ones(8, np.float32), timeout=DEADLINE_S)
    assert np.asarray(out).shape == (4,)
    # the LAST live replica is never retired
    assert engine.retire_replica() is None
    engine.drain(DEADLINE_S)


def test_supervisor_autoscales_live_engine_up_and_down():
    """The supervisor's live loop against a real engine, with manual
    polls and fake clocks: deep queue grows the fleet, sustained idle
    drains it back to the floor."""
    rec = Recorder(path=None)
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1, 2)),
                             max_wait_ms=0.5, recorder=rec)
    engine.warmup(np.zeros(8, np.float32))
    supervisor = FleetSupervisor(
        engine, policy=AutoscalePolicy(min_replicas=1, max_replicas=2,
                                       up_queue_depth=4,
                                       down_queue_depth=0,
                                       cooldown_up_s=0.0,
                                       cooldown_down_s=1.0),
        recorder=rec)
    # park a deep queue BEFORE the dispatcher starts (requeue admits
    # without the submit() drain race), sample it, then serve
    reqs = [PendingRequest(features=np.ones(8, np.float32),
                           t_enqueue=engine._clock()) for _ in range(8)]
    engine.batcher.requeue(reqs)
    actions = supervisor.poll(now=100.0)
    assert actions["scale"] == 1
    assert engine.fleet_snapshot()["n_replicas"] == 2
    # start serving: the grown fleet flushes the queue
    engine.start()
    for r in reqs:
        assert r.wait(DEADLINE_S), "parked request missed its deadline"
    assert engine.batcher.depth == 0
    actions = supervisor.poll(now=200.0)
    assert actions["scale"] == -1
    assert engine.fleet_snapshot()["n_serving"] == 1
    auto = [e for e in rec.events if e.get("event") == "autoscale"]
    assert len(auto) == 2
    assert auto[0]["action"] == 1 and auto[1]["action"] == -1
    assert all(e["max_replicas"] == 2 for e in auto)
    engine.drain(DEADLINE_S)


# --------------------------------------------------- server fleet state

def test_healthz_reports_fleet_state_and_drain_retry_after(tmp_path):
    engine = InferenceEngine(_mlp(), BucketLattice(batch_sizes=(1,)),
                             max_wait_ms=1.0, recorder=Recorder(path=None))
    engine.warmup(np.zeros(8, np.float32))
    ckdir = _save_publish_checkpoint(engine.net, 11, tmp_path)
    server = ServingServer(engine, port=0).start()
    try:
        fleet.hot_swap(engine, ckdir)
        with urllib.request.urlopen(f"{server.url}/healthz",
                                    timeout=10) as r:
            health = json.loads(r.read())
        assert health["status"] == "serving"
        assert health["weights"]["generation"] == 1
        assert health["weights"]["step"] == 11
        assert health["weights"]["last_swap_ts"] is not None
        rows = health["fleet"]
        assert rows[0]["state"] == "serving" and rows[0]["alive"]
        assert "last_beat_age_s" in rows[0]
        # drain: /predict 503s WITH a Retry-After header
        urllib.request.urlopen(
            urllib.request.Request(f"{server.url}/drain", data=b""),
            timeout=10).read()
        req = urllib.request.Request(
            f"{server.url}/predict",
            data=json.dumps({"features": [0.0] * 8}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 503
        assert e.value.headers["Retry-After"] == "5"
    finally:
        server.stop()


# ------------------------------------------------ bench + artifact gates

def test_fleet_replay_artifact_and_benchdiff_gate(tmp_path):
    """A small end-to-end fleet replay: both arms complete, the chaos
    kill's failures stay bounded, zero retraces, the swap and respawn
    are on the record — and the artifact self-diffs clean while a
    doctored regression (failed_requests growing) trips benchdiff."""
    tpath = str(tmp_path / "t.jsonl")
    apath = str(tmp_path / "SERVE_fleet.json")
    out = replay.run_fleet_replay(
        seed=3, n_requests=24, burst=4, mean_gap_s=0.01,
        autoscale_max=2, chaos="r0:kill@batch3", hot_swap_after=6,
        telemetry_path=tpath, artifact_path=apath)
    fixed, auto = out["fixed"], out["autoscale"]
    assert fixed["n_failed"] == 0 and fixed["n_ok"] == 24
    assert auto["n_ok"] >= 20
    assert 1 <= auto["n_failed"] <= 4, "chaos failures not bounded"
    assert auto["n_respawns"] >= 1 and auto["respawn_ms"] >= 0
    assert auto["n_swaps"] == 1 and auto["swap_ms"] > 0
    # the flip's deterministic visibility proof lives in
    # test_hot_swap_mid_traffic...; here the replay just must not
    # invent generations or lose the starting one
    assert auto["weight_generations"][0] == 0
    assert set(auto["weight_generations"]) <= {0, 1}
    assert auto["recompiles_after_warmup"] == 0
    assert fixed["recompiles_after_warmup"] == 0
    assert 0 < auto["autoscale_occupancy"] <= 1.0

    bd = _benchdiff()
    assert bd.main([apath, apath]) == 0
    # doctor failed_requests upward: lower-is-better must trip
    doctored = str(tmp_path / "doctored.json")
    with open(apath) as fh, open(doctored, "w") as out_fh:
        for line in fh:
            row = json.loads(line)
            if row.get("metric") == "fleet_failed_requests":
                row["value"] = row["value"] + 50
            if row.get("metric") == "summary" and \
                    "fleet_failed_requests" in row:
                row["fleet_failed_requests"] += 50
            out_fh.write(json.dumps(row) + "\n")
    assert bd.main([apath, doctored]) == 1


def test_committed_serve_r03_artifact_parses_and_gates():
    """The committed SERVE_r03.json: every fleet row present with the
    right direction flags, zero retraces on the record, and a self-diff
    through benchdiff is clean."""
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    apath = os.path.join(root, "SERVE_r03.json")
    assert os.path.exists(apath), "SERVE_r03.json missing"
    from deeplearning4j_tpu.telemetry import artifact as art

    lines = art.load(apath)
    for metric in ("fleet_fixed_qps", "fleet_autoscale_qps",
                   "fleet_autoscale_occupancy", "fleet_swap_ms",
                   "fleet_respawn_ms", "fleet_failed_requests",
                   "fleet_recompiles_after_warmup"):
        assert metric in lines, f"{metric} missing from SERVE_r03"
    assert lines["fleet_recompiles_after_warmup"]["value"] == 0
    assert lines["fleet_swap_ms"]["lower_is_better"]
    assert lines["fleet_failed_requests"]["lower_is_better"]
    assert lines["fleet_fixed_qps"]["value"] > 0
    assert lines["fleet_autoscale_qps"]["value"] > 0