"""Tier-1 gate for graftlint (ISSUE 2 + the ISSUE 5 SPMD rules + the
ISSUE 17 concurrency stage + the ISSUE 18 memory-introspection rule +
the ISSUE 19 sparse-embedding rule): every AST rule G001-G030 proven on a
positive AND a negative fixture, the suppression + baseline machinery,
the stage-2 jaxpr audit over every public entry point, and the package
itself held lint-clean (zero non-baselined findings). The stage-3
collective audit has its own gate in tests/test_spmd_lint.py; the
stage-4 lock-order audit and guard-map inference have theirs in
tests/test_concurrency_lint.py.

PR 1 burned its budget reactively fixing exactly these bug classes
(silent RNG divergence, jax API drift, modes that crashed only at real
dims); this file is what makes them build-breaking instead."""

import json
import os
import subprocess
import sys

import pytest

from deeplearning4j_tpu.analysis import (RULE_DOCS, lint_report,
                                         lint_source, load_baseline,
                                         split_baselined)
from deeplearning4j_tpu.analysis.core import Finding

pytestmark = pytest.mark.lint

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "deeplearning4j_tpu")
BASELINE = os.path.join(ROOT, "tools", "graftlint_baseline.json")
CLI = os.path.join(ROOT, "tools", "graftlint.py")

# fixtures land in a location that is BOTH a G002 hot path and inside
# the G011 SPMD scope (parallel/ is in HOT_PATH_FRAGMENTS and _G011_SCOPE)
FIXTURE_PATH = "deeplearning4j_tpu/parallel/_graftlint_fixture.py"

_PRELUDE = """\
import functools
import os
import random
import time
import numpy as np
import jax
import jax.numpy as jnp
from functools import partial
from jax.sharding import PartitionSpec as P
from deeplearning4j_tpu.util.compat import shard_map
"""


def rules_in(src, path=FIXTURE_PATH):
    return {f.rule for f in lint_source(_PRELUDE + src, path)}


# ----------------------------------------------- per-rule fixtures
# (rule, positive source, negative source) — the negative exercises the
# precision carve-outs, not just an empty file.

FIXTURES = [
    ("G001", """\
@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
""", """\
@jax.jit
def f(x, flag):
    if x is None:
        return flag
    if x.shape[0] > 2:
        return jnp.where(x > 0, x, -x)
    return x


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def g(x, causal):
    if causal:
        return x
    return -x
"""),
    ("G001", """\
@jax.jit
def f(x):
    s = x.sum()
    return float(s)
""", """\
def host(x):
    return float(x.sum())
"""),
    ("G002", """\
def step(x):
    y = np.asarray(x)
    return y.item()
""", """\
def step(x):
    y = jnp.asarray(x)
    return y
"""),
    ("G003", """\
def f(x):
    w = np.arange(5)
    return jnp.dot(x, w)
""", """\
def f(x):
    w = np.arange(5, dtype=np.float32)
    return jnp.dot(x, w)


def host_only():
    return np.arange(5)
"""),
    ("G004", """\
@jax.jit
def f(x):
    noise = np.random.randn(4)
    return x + noise
""", """\
@jax.jit
def f(x, key):
    return x + jax.random.normal(key, x.shape)
"""),
    ("G004", """\
def sample():
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (2,))
    b = jax.random.uniform(key, (2,))
    return a + b
""", """\
def sample():
    key = jax.random.PRNGKey(0)
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, (2,))
    key, sub2 = jax.random.split(key)
    b = jax.random.uniform(sub2, (2,))
    k1 = jax.random.fold_in(key, 1)
    k2 = jax.random.fold_in(key, 2)
    return a + b, k1, k2
"""),
    ("G004", """\
def consume_twice(key):
    a = jax.random.split(key)
    b = jax.random.split(key)
    return a, b
""", """\
def init_ladder(rng, scheme, shape):
    if scheme == "normal":
        return jax.random.normal(rng, shape)
    if scheme == "uniform":
        return jax.random.uniform(rng, shape)
    raise ValueError(scheme)


def arms(rng, flag):
    if flag:
        return jax.random.normal(rng, (2,))
    else:
        return jax.random.uniform(rng, (2,))
"""),
    ("G005", """\
def g(x):
    return x


def f(x):
    return jax.jit(g)(x)
""", """\
def g(x):
    return x


fast_g = jax.jit(g)


def f(x):
    return fast_g(x)
"""),
    ("G005", """\
def g(x):
    return x


def f(xs):
    out = []
    for x in xs:
        h = jax.jit(g)
        out.append(h(x))
    return out
""", """\
def g(x):
    return x


def f(xs):
    h = jax.jit(g, static_argnums=(0,))
    return [h(x) for x in xs]
"""),
    ("G006", """\
def local(a, b):
    return a + b


def run(mesh, P):
    return shard_map(local, mesh=mesh,
                     in_specs=(P, P, P), out_specs=P)
""", """\
def local(a, b):
    return a + b


def run(mesh, P):
    one = shard_map(local, mesh=mesh, in_specs=(P, P), out_specs=P)
    pre = shard_map(local, mesh=mesh, in_specs=P, out_specs=P)
    return one, pre
"""),
    ("G006", """\
def local(a):
    return a, a + 1


def run(mesh, P):
    return shard_map(local, mesh=mesh, in_specs=(P,),
                     out_specs=(P, P, P))
""", """\
def local(a):
    return a, a + 1


def run(mesh, P):
    return shard_map(local, mesh=mesh, in_specs=(P,),
                     out_specs=(P, P))
"""),
    ("G007", """\
from jax.experimental.shard_map import shard_map as raw_shard_map
from jax.experimental.pallas import tpu as pltpu


def params():
    return pltpu.TPUCompilerParams(dimension_semantics=("parallel",))
""", """\
from deeplearning4j_tpu.util.compat import (pcast_varying, shard_map,
                                            tpu_compiler_params)


def params():
    return tpu_compiler_params(dimension_semantics=("parallel",))
"""),
    ("G008", """\
K = jnp.zeros((4,))


def f(x, acc=[]):
    acc.append(x)
    return K + x
""", """\
K = np.zeros((4,), dtype=np.float32)


def f(x, acc=None):
    if acc is None:
        acc = []
    acc.append(x)
    return jnp.zeros((4,)) + x
"""),
    ("G009", """\
def up(addr, n, i):
    jax.distributed.initialize(coordinator_address=addr,
                               num_processes=n, process_id=i)
""", """\
def up(addr, n, i):
    from deeplearning4j_tpu.distributed import bootstrap

    bootstrap.initialize(coordinator_address=addr, num_processes=n,
                         process_id=i)
"""),
    ("G009", """\
import os


def wire(env):
    env["DL4J_TPU_PROCESS_ID"] = "0"
    return os.environ.get("DL4J_TPU_COORDINATOR")
""", """\
import os

from deeplearning4j_tpu.distributed.bootstrap import (ENV_COORDINATOR,
                                                      ENV_PROCESS_ID)


def wire(env):
    env[ENV_PROCESS_ID] = "0"
    return os.environ.get(ENV_COORDINATOR)
"""),
    ("G010", """\
def up(x):
    if jax.process_index() == 0:
        return jax.lax.psum(x, "data")
    return x
""", """\
def up(x, process_id, axis_name):
    if process_id == 0:
        print("rank 0: host-side logging/checkpoint IO is fine")
    return jax.lax.psum(x, axis_name)
"""),
    ("G010", """\
from deeplearning4j_tpu.distributed.bootstrap import ENV_PROCESS_ID
from deeplearning4j_tpu.parallel.mesh import make_mesh


def up(f, x):
    if os.environ[ENV_PROCESS_ID] == "0":
        mesh = make_mesh({"data": 8})
    return f(x)
""", """\
from deeplearning4j_tpu.parallel.mesh import make_mesh


def up(f, x, process_index):
    mesh = make_mesh({"data": 8})
    if process_index == 0:
        path = "checkpoint.zip"
    return f(x)
"""),
    ("G011", """\
def f(x):
    t = time.time()
    return jnp.full((2,), t)
""", """\
def f(x, rec):
    rec.event(time.time())
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.random(3))
"""),
    ("G012", """\
def f(x):
    return jax.lax.pmean(x, "data")
""", """\
def g(x, axis_name):
    return jax.lax.psum(x, axis_name)


def run(mesh, x):
    local = lambda a: jax.lax.pmean(a, "data")
    return shard_map(local, mesh=mesh, in_specs=(P("data"),),
                     out_specs=P())(x)


def wrapped(a):
    return jax.lax.psum(a, "seq")


def outer(mesh, x):
    return shard_map(wrapped, mesh=mesh, in_specs=(P("seq"),),
                     out_specs=P())(x)
"""),
    ("G013", """\
def sync(x, loss):
    if jax.process_index() == 0:
        return loss.item()
    return x
""", """\
def sync(x, loss, process_id):
    if process_id == 0:
        path = "ck.zip"
    jax.block_until_ready(x)
    return x
"""),
    ("G014", """\
def sync(x, axis_name):
    try:
        return jax.lax.psum(x, axis_name)
    except Exception:
        return x
""", """\
def sync(x, axis_name):
    try:
        return jax.lax.psum(x, axis_name)
    except ConnectionError:
        raise RuntimeError("fleet lost")


def sync_cleanup(x, axis_name):
    try:
        return jax.lax.psum(x, axis_name)
    except Exception:
        raise


def teardown():
    try:
        jax.distributed.shutdown()
    except Exception:
        pass


def retry_outside_distributed():
    while True:
        try:
            connect()
            break
        except OSError:
            time.sleep(0.1)
"""),
    ("G015", """\
def reduce_step(grads, axis_name):
    return jax.lax.pmean(grads, axis_name)
""", """\
def reduce_params(params, axis_name):
    return jax.lax.pmean(params, axis_name)


def reduce_loss(loss, acts, axis_name):
    return jax.lax.psum(loss, axis_name), jax.lax.pmean(acts, axis_name)
"""),
    ("G017", """\
fwd = jax.jit(lambda p, s, x: x)


def handle(request, params, state):
    y = fwd(params, state, request.features)
    outs = []
    for req in request.siblings:
        outs.append(req.result.item())
    return y, outs
""", """\
fwd = jax.jit(lambda p, s, x, m: x)


def run_batch(batch, params, state):
    y = fwd(params, state, batch.features, batch.mask)
    rows = np.asarray(y)
    for req, row in zip(batch.requests, rows):
        req.set_result(row)
    return rows


def warmup_bucket(params, state, zeros, mask):
    return fwd(params, state, zeros, mask)
"""),
    ("G016", """\
from jax.experimental import pallas as pl


def build(kern, x):
    return pl.pallas_call(
        kern,
        grid=(8, 512),
        in_specs=[pl.BlockSpec((512, 128), lambda i, j: (i, 0))],
        out_specs=pl.BlockSpec(block_shape=(256, 128),
                               index_map=lambda i, j: (i, 0)),
    )(x)
""", """\
from jax.experimental import pallas as pl
from deeplearning4j_tpu.ops import autotune


def build(kern, x, T, D):
    bq, bk = autotune.flash_blocks(T, D, causal=True, dropout=False,
                                   masked=False)
    return pl.pallas_call(
        kern,
        grid=(T // bq, 8),
        in_specs=[pl.BlockSpec((bq, 128), lambda i, j: (i, 0)),
                  pl.BlockSpec((1, 3), lambda i, j: (0, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0)),
    )(x)
"""),
    ("G019", """\
def stream_decoded(emitted_tokens, sink):
    for tok in emitted_tokens:
        sink.write(tok.item())
""", """\
def decode_step_fetch(step_out, slots):
    toks = np.asarray(step_out)  # ONE batch-boundary fetch per step
    for slot, value in zip(slots, toks.tolist()):
        slot.emit(value)
"""),
    ("G020", """\
def fit(net, it, step):
    while it.has_next():
        ds = it.next()
        batch = net._batch_dict(ds)
        placed = jax.device_put(batch)
        step(placed)
""", """\
from deeplearning4j_tpu.data.pipeline import iter_prefetched


def fit(net, it, step):
    for ds, batch in iter_prefetched(it, net._batch_dict):
        step(batch)


def stage_epoch(net, data):
    # whole-epoch staging (fit_scanned), not a step loop
    return [net._batch_dict(ds) for ds in data]


def fit_tbptt(net, ds, step, L):
    for t0 in range(0, ds.features.shape[1], L):
        step(net._batch_dict(ds.slice_time(t0, L)))
"""),
    ("G018", """\
from deeplearning4j_tpu.util.orbax_checkpoint import host_materialize


def snapshot(net):
    tree = host_materialize(net.params)
    flat = jax.device_get(net.opt_state)
    moments = jax.tree.map(np.asarray, net.opt_state)
    return tree, flat, moments
""", """\
def read_one(net, params):
    w = np.asarray(params["W"])        # single leaf, not the tree
    s = np.asarray(net.score_value)    # a derived scalar
    placed = jax.tree.map(jax.device_put, net.params, net._param_sh)
    return w, s, placed
"""),
    ("G021", """\
def adopt_new_weights(worker, new_params, ckpt_dir):
    worker.net.params = new_params     # direct live-param write
    worker.net.resume_from(ckpt_dir)   # restore outside the swap path
""", """\
def serve_one(self, batch):
    ws = self.weights.current          # the ONE read per batch
    return self._jit(ws.params, ws.state, batch.features)


def swap(engine, ckpt_dir):
    from deeplearning4j_tpu.serving import fleet
    return fleet.hot_swap(engine, ckpt_dir)  # the blessed path


def init_if_needed(net):
    if net.params is None:             # reading params never flags
        net.init()
"""),
    ("G022", """\
def run(net, devices):
    mesh = jax.sharding.Mesh(devices, ("data",))     # raw ctor
    net.set_mesh(mesh, axes={"data": "data"})        # role-dict literal


def train(net):
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    return make_mesh({"data": 2, "model": 4})        # role-dict literal
""", """\
from deeplearning4j_tpu.reshard.planner import Placement
from deeplearning4j_tpu.reshard.search import FleetShape, search_placement


def run(net, fleet_spec):
    result = search_placement(net, FleetShape.parse(fleet_spec))
    net.set_mesh(result.winner)            # the searched winner


def declare(net):
    # the validated declarative spelling: Placement.of IS the blessed
    # home of the role-dict literal
    placement = Placement.of({"data": 2, "expert": 4},
                             {"data": "data", "expert": "expert"})
    net.set_mesh(placement)


def parsed(net, make_mesh, axes):
    # parsed/derived dicts (CLI --mesh) and comprehensions never flag
    mesh = make_mesh(axes)
    net.set_mesh(mesh, axes={r: r for r in axes})
    opts = {"data": "d.csv"}               # a non-mesh dict is silent
    return opts
"""),
    ("G023", """\
def run(rec):
    with rec.span("my_invented_phase"):       # unregistered span name
        pass
    rec.event("telemetry_blob", x=1)          # unregistered event kind
    rec.event("span", name="custom_region",   # unregistered via name=
              ok=True, seconds=0.0)
""", """\
def run(rec, m, mode):
    with rec.span("compile", what="fit_scanned"):   # registered name
        pass
    rec.event("fault", kind="reform")               # registered kind
    rec.event("span", name="bucket_reduce",         # registered name=
              ok=True, seconds=0.0)
    rec.event("anomaly", kind="straggler")          # the detector kind
    a, b = m.span(0)              # non-string first arg (re.Match.span)
    name = "dynamic"
    rec.span(name)                # variable names are uncheckable
    with rec.span(f"mode:{mode}"):  # f-strings parse as opaque spans
        pass
"""),
    ("G024", """\
def sample_tokens(slots, logits_batch):
    for slot, decode_row in zip(slots, logits_batch):
        if np.random.random() < 0.5:              # host RNG per token
            order = np.argsort(decode_row_logits)  # host top-k rebuild
            mass = np.cumsum(probs[order])         # host top-p rebuild
""", """\
from deeplearning4j_tpu.ops.fused_sampling import fused_sample


def sample_step(slots, logits, noise):
    ids = fused_sample(logits, noise, temperature=0.8,
                       top_k=32, top_p=0.9)        # the blessed kernel
    for slot, tok in zip(slots, np.asarray(ids).tolist()):
        slot.emit(tok)


def order_slots(slots):
    # argsort over non-logits values in a token loop stays silent
    for tok_batch in slots:
        ranks = np.argsort(tok_batch.arrival_times)


def seed_proposer(seed):
    # host RNG OUTSIDE decode loops (setup, jitter) is not sampling
    return np.random.default_rng(seed)
"""),
    # ------------------------------------------- stage 4 (ISSUE 17)
    ("G025", """\
import threading


class RacyWorker:
    def __init__(self):
        self.served = 0
        self._thread = None

    def start(self):
        def loop():
            for _ in range(1000):
                self.served += 1

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def describe(self):
        return {"served": self.served}
""", """\
import threading


class GuardedWorker:
    def __init__(self):
        self._mu = threading.Lock()
        self.served = 0
        self._thread = None

    def start(self):
        def loop():
            for _ in range(1000):
                with self._mu:
                    self.served += 1

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def describe(self):
        with self._mu:
            return {"served": self.served}
"""),
    ("G026", """\
import queue
import threading


class BlockingDispatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = queue.Queue(maxsize=4)

    def dispatch(self, item):
        with self._lock:
            self.q.put(item)      # blocks every lock contender

    def backoff(self):
        with self._lock:
            time.sleep(0.05)
""", """\
import queue
import threading


class PoliteDispatcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._buf = []
        self.q = queue.Queue(maxsize=4)

    def try_drain(self):
        with self._cv:
            return self.q.get(block=False)   # non-blocking: exempt

    def wait_item(self):
        with self._cv:
            while not self._buf:
                self._cv.wait(0.1)           # waits on the HELD cond
            return self._buf.pop()

    def dispatch(self, item):
        with self._cv:
            target = self.q                  # snapshot under the lock
        target.put(item)                     # block outside it
"""),
    ("G027", """\
import threading


class SloppyWaiter:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False

    def await_once(self):
        with self._cv:
            self._cv.wait(0.5)    # no while-predicate re-check

    def poke(self):
        self._cv.notify_all()     # owning lock not held

    def spin(self):
        while not self.ready:
            time.sleep(0.01)      # sleep-poll loop
""", """\
import threading


class PatientWaiter:
    def __init__(self):
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self.ready = False

    def await_ready(self):
        with self._cv:
            while not self.ready:
                self._cv.wait(0.5)

    def set_ready(self):
        with self._cv:
            self.ready = True
            self._cv.notify_all()

    def idle(self):
        while not self._stop.is_set():
            self._stop.wait(0.05)   # Event stop-flag, not a sleep poll
"""),
    ("G028", """\
import threading


class FireAndForget:
    def launch(self):
        t = threading.Thread(target=self._loop)
        t.start()                 # non-daemon, never joined

    def _loop(self):
        pass


class BareDaemon:
    def launch(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        pass
""", """\
import threading


class SupervisedWorker:
    def __init__(self):
        self._thread = None

    def launch(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        pass

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=1.0)
"""),
    # ------------------------------------------- ISSUE 18 (memory)
    ("G029", """\
import jax


@jax.jit
def forward(params, batch):
    hbm = jax.devices()[0].memory_stats()     # frozen at trace time
    return params


def decode_all(slots):
    for tok in slots:
        live = sum(a.nbytes for a in jax.live_arrays())  # per-token walk


def serve(requests, compiled):
    for req in requests:
        peak = compiled.memory_analysis()     # per-request re-summary
""", """\
import jax


def snapshot():
    # batch-boundary sampling OUTSIDE traced/hot contexts is the
    # sampler contract, not a violation
    return sum(a.nbytes for a in jax.live_arrays())


def harvest(compiled):
    # warmup-time harvest in a plain function
    return compiled.memory_analysis()


def decode_all(slots, cached_memory_event):
    for tok in slots:
        read = cached_memory_event["live_array_bytes"]  # cached, no walk
"""),
    # ---------------------------------------- ISSUE 19 (embeddings)
    ("G030", """\
import jax.numpy as jnp


def lookup_rows(syn0, idx):
    return jnp.take(syn0, idx, axis=0)        # dense full-table gather


def lookup_direct(syn1neg, idx):
    return syn1neg[idx]                       # same, spelled as subscript


def densify_grad(embedding_table, idx, values):
    # table-shaped zeros + scatter: the densified sparse gradient
    return jnp.zeros_like(embedding_table).at[idx].add(values)
""", """\
import jax.numpy as jnp


def lookup_weight(params, idx):
    return jnp.take(params["W"], idx, axis=0)  # a weight, not a table


def gather_cum(cum_table, draws):
    return cum_table[draws]                    # sampling table, exempt


def accumulate(W, i, g):
    return W.at[i].add(g)                      # in-place, not zeros_like


def engine_step(table, idx, values):
    from deeplearning4j_tpu.parallel.overlap import sparse_bucket_reduce
    return sparse_bucket_reduce(idx, values, "data")
"""),
    # ---------------------------------------- ISSUE 20 (precision)
    ("G031", """\
def scores(q, k):
    s = jnp.einsum("qd,kd->qk", q, k)      # accumulator dtype implicit
    return s + q @ k.T                     # `@` cannot declare one
""", """\
def scores(q, k):
    return jnp.einsum("qd,kd->qk", q, k,
                      preferred_element_type=jnp.float32)
"""),
    ("G032", """\
def f(x):
    y = x.astype(jnp.float64)
    z = jnp.zeros((2,), dtype="float64")
    w = np.float64(3.0)
    return y, z, w
""", """\
def f(x):
    y = x.astype(jnp.float32)
    z = jnp.zeros((2,), dtype="float32")
    return y, z


_DTYPES = {"float64": jnp.float64, "float32": jnp.float32}
"""),
    ("G033", """\
def quantize(vals, maxabs):
    scale = maxabs / 127.0
    return jnp.clip(jnp.round(vals / scale), -127, 127), scale
""", """\
from deeplearning4j_tpu.ops.decode_attention import quantize_pages


def quantize(vals):
    return quantize_pages(vals)


def round_up(n):
    return (n + 127) // 128 * 128          # lane-tile round-up, exempt


BLOCK = 128
"""),
    ("G034", """\
def downcast(net):
    half = net.params.astype(jnp.bfloat16)
    opt = jax.tree.map(lambda x: x.astype(jnp.bfloat16), net.opt_state)
    return half, opt
""", """\
def place(params):
    w = params["W"].astype(jnp.bfloat16)   # single leaf, not the tree
    moved = jax.tree.map(jnp.asarray, params)  # no cast in the mapped fn
    return w, moved
"""),
]


# rules whose scope excludes the default fixture path lint their
# fixtures at a path inside their scope (G017: serving/ hot paths)
RULE_FIXTURE_PATHS = {
    "G017": "deeplearning4j_tpu/serving/_graftlint_fixture.py",
    "G019": "deeplearning4j_tpu/serving/_graftlint_fixture.py",
    "G021": "deeplearning4j_tpu/serving/_graftlint_fixture.py",
    "G024": "deeplearning4j_tpu/serving/_graftlint_fixture.py",
    "G022": "deeplearning4j_tpu/cli/_graftlint_fixture.py",
    # stage-4 scoped rules: G026 (serving//data//telemetry/) and G027
    # (serving//data/) lint their fixtures on a serving/ path
    "G026": "deeplearning4j_tpu/serving/_graftlint_fixture.py",
    "G027": "deeplearning4j_tpu/serving/_graftlint_fixture.py",
    # G031 (accumulator discipline) is scoped to the kernel dirs
    "G031": "deeplearning4j_tpu/ops/_graftlint_fixture.py",
}


@pytest.mark.parametrize(
    "rule,pos,neg", FIXTURES,
    ids=[f"{r}-{i}" for i, (r, _, _) in enumerate(FIXTURES)])
def test_rule_fires_on_positive_not_negative(rule, pos, neg):
    path = RULE_FIXTURE_PATHS.get(rule, FIXTURE_PATH)
    assert rule in rules_in(pos, path), f"{rule} missed its positive fixture"
    assert rule not in rules_in(neg, path), f"{rule} false-positive"


def test_every_rule_has_fixture_coverage():
    assert {r for r, _, _ in FIXTURES} == set(RULE_DOCS) == {
        f"G{i:03d}" for i in range(1, 35)}


def test_g015_blessed_sites_are_exempt():
    """The bucket planner and the train-step assembly are the two
    blessed gradient-collective sites; the same source flags anywhere
    else in the package."""
    src = ("def reduce_step(grads, axis_name):\n"
           "    return jax.lax.psum(grads, axis_name)\n")
    assert "G015" not in rules_in(
        src, "deeplearning4j_tpu/parallel/overlap.py")
    assert "G015" not in rules_in(
        src, "deeplearning4j_tpu/nn/training.py")
    assert "G015" in rules_in(
        src, "deeplearning4j_tpu/parallel/sequence_parallel.py")
    assert "G015" in rules_in(src)  # the default fixture path


def test_g017_scope_and_carveouts():
    """G017 is serving/-only (the same source is silent elsewhere), and
    both named carve-outs hold: bucket-ish argument names and
    warmup/bucket-named enclosing functions don't flag the jit-entry
    half; a batch-boundary sync outside a request loop doesn't flag the
    host-sync half."""
    rule_id, pos, neg = next(f for f in FIXTURES if f[0] == "G017")
    serving = RULE_FIXTURE_PATHS["G017"]
    assert "G017" in rules_in(pos, serving)
    assert "G017" not in rules_in(pos)  # parallel/ default path: out of scope
    assert "G017" not in rules_in(pos, "deeplearning4j_tpu/nn/x.py")
    # batch-boundary fetch: one sync per batch, outside a request loop
    boundary = ("fwd = jax.jit(lambda p, s, x: x)\n"
                "def run(batch, p, s):\n"
                "    y = fwd(p, s, batch.features)\n"
                "    return np.asarray(y).item()\n")
    assert "G017" not in rules_in(boundary, serving)


def test_g019_scope_and_batch_boundary_carveout():
    """G019 is serving/-only, and the decode loop's blessed pattern —
    ONE np.asarray of the step's whole next-token vector, host-side
    distribution after — never flags; the per-token `.item()` does."""
    _, pos, neg = next(f for f in FIXTURES if f[0] == "G019")
    serving = RULE_FIXTURE_PATHS["G019"]
    assert "G019" in rules_in(pos, serving)
    assert "G019" not in rules_in(pos)  # parallel/ default path: out of scope
    assert "G019" not in rules_in(pos, "deeplearning4j_tpu/nn/decode.py")
    # a sync on a non-token loop stays G019-silent (G017 owns requests)
    other = ("def collect(results):\n"
             "    for r in results:\n"
             "        r.block_until_ready()\n")
    assert "G019" not in rules_in(other, serving)


def test_g024_scope_and_carveouts():
    """G024 is serving/-only: the same host-sampling source is silent
    in ops/ (where the kernel's own reference path legitimately sorts
    logits) and on the default path; argsort over non-logits values and
    host RNG outside decode loops never flag."""
    _, pos, neg = next(f for f in FIXTURES if f[0] == "G024")
    serving = RULE_FIXTURE_PATHS["G024"]
    assert "G024" in rules_in(pos, serving)
    assert "G024" not in rules_in(pos)  # parallel/ default path
    assert "G024" not in rules_in(
        pos, "deeplearning4j_tpu/ops/fused_sampling.py")
    # an RNG draw in a non-token loop stays G024-silent
    other = ("def jitter(requests):\n"
             "    for r in requests:\n"
             "        r.delay = np.random.random()\n")
    assert "G024" not in rules_in(other, serving)


def test_g020_blessed_paths_and_loop_shape():
    """The pipeline's own synchronous fallback (data/) and the
    AsyncDataSetIterator adapter are the blessed conversion sites; the
    same step-loop source flags anywhere else, and a non-has_next while
    loop never engages the rule."""
    _, pos, _ = next(f for f in FIXTURES if f[0] == "G020")
    assert "G020" not in rules_in(
        pos, "deeplearning4j_tpu/data/pipeline.py")
    assert "G020" not in rules_in(
        pos, "deeplearning4j_tpu/datasets/async_iterator.py")
    assert "G020" in rules_in(pos)  # the default parallel/ fixture path
    assert "G020" in rules_in(pos, "deeplearning4j_tpu/nn/multilayer.py")
    other = ("def drain(q, net):\n"
             "    while q:\n"
             "        net._batch_dict(q.pop())\n")
    assert "G020" not in rules_in(other)


def test_g018_blessed_paths_are_exempt():
    """The resharding engine and the two checkpoint formats ARE the
    places full-tree host materialization is allowed; the same source
    flags anywhere else in the package."""
    src = ("def snap(net):\n"
           "    return jax.device_get(net.params)\n")
    assert "G018" not in rules_in(
        src, "deeplearning4j_tpu/reshard/executor.py")
    assert "G018" not in rules_in(
        src, "deeplearning4j_tpu/util/orbax_checkpoint.py")
    assert "G018" not in rules_in(
        src, "deeplearning4j_tpu/util/model_serializer.py")
    assert "G018" in rules_in(src)  # the default parallel/ fixture path
    assert "G018" in rules_in(src, "deeplearning4j_tpu/serving/engine.py")


def test_g021_scope_and_blessed_swap_path():
    """G021 is serving/-only (a training loop assigning net.params is
    legitimate elsewhere), serving/fleet.py is THE blessed publish/flip
    site, and both halves fire independently: the `.params` assignment
    without resume_from, and resume_from without an assignment."""
    _, pos, _ = next(f for f in FIXTURES if f[0] == "G021")
    serving = RULE_FIXTURE_PATHS["G021"]
    assert "G021" in rules_in(pos, serving)
    assert "G021" in rules_in(pos, "deeplearning4j_tpu/serving/engine.py")
    assert "G021" not in rules_in(pos)  # parallel/ default: out of scope
    assert "G021" not in rules_in(
        pos, "deeplearning4j_tpu/nn/multilayer.py")
    assert "G021" not in rules_in(
        pos, "deeplearning4j_tpu/serving/fleet.py")  # the blessed path
    assign_only = "def f(w, p):\n    w.net.params = p\n"
    resume_only = "def f(net, d):\n    return net.resume_from(d)\n"
    assert "G021" in rules_in(assign_only, serving)
    assert "G021" in rules_in(resume_only, serving)


def test_g022_scope_and_blessed_paths():
    """G022 covers the user-facing layers only — examples/, cli/, and
    distributed/elastic.py (library internals IMPLEMENT the blessed
    paths and stay silent) — and both halves fire independently: the
    raw Mesh ctor without a role dict, and a role-dict literal without
    a raw ctor. Placement.of keeps its role-dict literals."""
    _, pos, neg = next(f for f in FIXTURES if f[0] == "G022")
    cli = RULE_FIXTURE_PATHS["G022"]
    assert "G022" in rules_in(pos, cli)
    assert "G022" in rules_in(pos, "examples/data_parallel_training.py")
    assert "G022" in rules_in(
        pos, "deeplearning4j_tpu/distributed/elastic.py")
    # out of scope: the library layers that implement the blessed paths
    assert "G022" not in rules_in(pos)  # parallel/ default fixture path
    assert "G022" not in rules_in(
        pos, "deeplearning4j_tpu/parallel/mesh.py")
    assert "G022" not in rules_in(
        pos, "deeplearning4j_tpu/distributed/global_mesh.py")
    raw_only = ("def f(devices):\n"
                "    return jax.sharding.Mesh(devices, ('data',))\n")
    dict_only = ("def f(net, mesh):\n"
                 "    net.set_mesh(mesh, axes={'data': 'data'})\n")
    blessed = ("from deeplearning4j_tpu.reshard.planner import Placement\n"
               "def f(net):\n"
               "    net.set_mesh(Placement.of({'data': 8},\n"
               "                              {'data': 'data'}))\n")
    assert "G022" in rules_in(raw_only, cli)
    assert "G022" in rules_in(dict_only, cli)
    assert "G022" not in rules_in(blessed, cli)


def test_g022_user_facing_layers_sweep_clean():
    """The rule's whole scope — examples/ (outside the package sweep)
    plus cli/ and distributed/elastic.py — holds zero G022 findings:
    every mesh the user-facing layers build now routes through
    Placement / the search."""
    targets = [os.path.join(ROOT, "examples"),
               os.path.join(PKG, "cli"),
               os.path.join(PKG, "distributed", "elastic.py")]
    new, _old = lint_report(targets, load_baseline(BASELINE), root=ROOT)
    hits = [f for f in new if f.rule == "G022"]
    assert not hits, "G022 findings in user-facing layers:\n" + "\n".join(
        f.format() for f in hits)


def test_g023_scope_and_registry():
    """G023 holds everywhere EXCEPT telemetry/ (the registry is the
    blessed home of new kinds/names), checks the `event("span",
    name=...)` spelling, and the whole package + bench.py + tools sweep
    clean — every literal the code emits is registered."""
    _, pos, neg = next(f for f in FIXTURES if f[0] == "G023")
    hits = [f for f in lint_source(_PRELUDE + pos, FIXTURE_PATH)
            if f.rule == "G023"]
    assert len(hits) == 3  # span literal + event kind + name= kwarg
    # the registry itself is exempt: the same source is silent there
    assert "G023" not in rules_in(
        pos, "deeplearning4j_tpu/telemetry/recorder.py")
    assert "G023" not in rules_in(
        pos, "deeplearning4j_tpu/telemetry/trace.py")
    # in scope across the package AND outside it (bench.py, tools/)
    assert "G023" in rules_in(pos, "deeplearning4j_tpu/serving/engine.py")
    assert "G023" in rules_in(pos, "bench.py")
    # the registered sets ARE the recorder's: a name added to the
    # registry immediately stops flagging
    from deeplearning4j_tpu.telemetry.recorder import (EVENT_KINDS,
                                                       SPAN_NAMES)
    assert "compile" in SPAN_NAMES and "anomaly" in EVENT_KINDS
    assert "my_invented_phase" not in SPAN_NAMES


def test_g029_scope_and_blessed_producers():
    """G029 is contextual: introspection flags inside jit-traced fns
    and token/request loops anywhere, EXCEPT the two blessed producer
    modules (memstat.py batch-boundary sampler, costbook.py warmup
    harvest); the same walks outside those contexts — the sampler
    contract itself — never flag."""
    _, pos, neg = next(f for f in FIXTURES if f[0] == "G029")
    hits = [f for f in lint_source(pos, FIXTURE_PATH)
            if f.rule == "G029"]
    assert len(hits) == 3  # traced fn + token loop + request loop
    assert "G029" not in rules_in(
        pos, "deeplearning4j_tpu/telemetry/memstat.py")
    assert "G029" not in rules_in(
        pos, "deeplearning4j_tpu/telemetry/costbook.py")
    # non-blessed telemetry files are NOT exempt (unlike G023's scope)
    assert "G029" in rules_in(
        pos, "deeplearning4j_tpu/telemetry/trace.py")
    # a walk at a batch boundary (plain function, no hot loop) is the
    # design, not a finding
    boundary = ("import jax\n\n"
                "def sample_now():\n"
                "    return [a.nbytes for a in jax.live_arrays()]\n")
    assert "G029" not in rules_in(boundary)
    # a loop over non-token/non-request names stays silent even with
    # introspection inside (precision over recall)
    cold = ("import jax\n\n"
            "def audit(checkpoints):\n"
            "    for ckpt in checkpoints:\n"
            "        print(sum(a.nbytes for a in jax.live_arrays()))\n")
    assert "G029" not in rules_in(cold)


def test_g029_package_sweeps_clean():
    """No hot-path memory introspection anywhere in the package, the
    bench, or the tools — the only producers are the blessed modules."""
    targets = [PKG, os.path.join(ROOT, "bench.py"),
               os.path.join(ROOT, "tools")]
    new, _old = lint_report(targets, load_baseline(BASELINE), root=ROOT)
    hits = [f for f in new if f.rule == "G029"]
    assert not hits, "hot-path memory introspection:\n" + "\n".join(
        f.format() for f in hits)


def test_g023_whole_surface_sweeps_clean():
    """Every telemetry literal the repo emits — package, bench.py,
    examples/, and the tools — is in the registered schema."""
    targets = [PKG, os.path.join(ROOT, "bench.py"),
               os.path.join(ROOT, "examples"),
               os.path.join(ROOT, "tools")]
    new, _old = lint_report(targets, load_baseline(BASELINE), root=ROOT)
    hits = [f for f in new if f.rule == "G023"]
    assert not hits, "unregistered telemetry names:\n" + "\n".join(
        f.format() for f in hits)


def test_g016_tuning_layer_and_scope():
    """The tuning layer itself is exempt (it IS where block literals
    live); the module-constant half applies to ops/ kernel files only,
    and 128 (the hardware lane tile) never flags."""
    spec = ("from jax.experimental import pallas as pl\n"
            "def f():\n"
            "    return pl.BlockSpec((512, 128), lambda i: (i, 0))\n")
    assert "G016" in rules_in(spec, "deeplearning4j_tpu/ops/x.py")
    assert "G016" in rules_in(spec)  # BlockSpec half is package-wide
    assert "G016" not in rules_in(spec,
                                  "deeplearning4j_tpu/ops/autotune.py")
    const = "BLOCK_Q_MAX = 512\nCHUNK_TILES = (8192, 4096)\n"
    assert "G016" in rules_in(const, "deeplearning4j_tpu/ops/x.py")
    assert "G016" not in rules_in(const,
                                  "deeplearning4j_tpu/ops/autotune.py")
    # constants half is scoped to kernel files; non-ops code with a
    # TILE-named constant (e.g. a plotting grid) stays clean
    assert "G016" not in rules_in(const,
                                  "deeplearning4j_tpu/plot/x.py")
    lane = ("from jax.experimental import pallas as pl\n"
            "BLOCK = 128\n"
            "def f(bn):\n"
            "    return pl.BlockSpec((bn, 128), lambda i: (i, 0))\n")
    assert "G016" not in rules_in(lane, "deeplearning4j_tpu/ops/x.py")


def test_g031_scope_and_embedding_dir():
    """G031 covers the kernel dirs only (ops/ + embedding/): a
    contraction elsewhere legitimately inherits the backend default."""
    _, pos, _ = next(f for f in FIXTURES if f[0] == "G031")
    assert "G031" in rules_in(pos, RULE_FIXTURE_PATHS["G031"])
    assert "G031" in rules_in(
        pos, "deeplearning4j_tpu/embedding/_graftlint_fixture.py")
    assert "G031" not in rules_in(pos)  # parallel/ default: out of scope
    assert "G031" not in rules_in(pos, "deeplearning4j_tpu/nn/x.py")


def test_g032_blessed_dirs_and_registry_carveout():
    """gradientcheck/'s finite differences deliberately run f64 (tests
    enable x64) and stay silent; the np.float64-constructor half is
    device-dirs only (host analytics keep their f64); a name->dtype
    registry dict is declarative, not drift."""
    _, pos, neg = next(f for f in FIXTURES if f[0] == "G032")
    assert "G032" in rules_in(pos)  # parallel/ is a device dir
    assert "G032" not in rules_in(
        pos, "deeplearning4j_tpu/gradientcheck/finite_diff.py")
    np_ctor = "def f():\n    return np.float64(3.0)\n"
    assert "G032" in rules_in(np_ctor, "deeplearning4j_tpu/ops/x.py")
    assert "G032" not in rules_in(
        np_ctor, "deeplearning4j_tpu/clustering/kmeans.py")
    registry = '_DTYPES = {"float64": jnp.float64}\n'
    assert "G032" not in rules_in(registry)


def test_g033_blessed_quantize_helpers_are_exempt():
    """ops/decode_attention.py IS where maxabs/127 lives — the rule
    exists so there is exactly ONE spelling of the scale math."""
    _, pos, _ = next(f for f in FIXTURES if f[0] == "G033")
    assert "G033" in rules_in(pos)
    assert "G033" in rules_in(pos, "deeplearning4j_tpu/serving/engine.py")
    assert "G033" not in rules_in(
        pos, "deeplearning4j_tpu/ops/decode_attention.py")
    # integer 128 is the lane tile (G016's constant), never quant math
    lane = "def f(x):\n    return x * 128\n"
    assert "G033" not in rules_in(lane)


def test_g034_blessed_dtype_policy_paths_are_exempt():
    """reshard/ and the two checkpoint formats OWN the dtype policy;
    the same wholesale tree cast flags anywhere else."""
    _, pos, _ = next(f for f in FIXTURES if f[0] == "G034")
    assert "G034" in rules_in(pos)
    assert "G034" in rules_in(pos, "deeplearning4j_tpu/nn/multilayer.py")
    assert "G034" not in rules_in(
        pos, "deeplearning4j_tpu/reshard/executor.py")
    assert "G034" not in rules_in(
        pos, "deeplearning4j_tpu/util/orbax_checkpoint.py")
    assert "G034" not in rules_in(
        pos, "deeplearning4j_tpu/util/model_serializer.py")


def test_g014_retry_loop_scoped_to_distributed():
    """The uncapped-retry half of G014 applies to distributed/ only
    (the elastic rejoin path); a bounded Backoff loop stays clean."""
    uncapped = ("def retry():\n"
                "    while True:\n"
                "        try:\n"
                "            connect()\n"
                "            break\n"
                "        except OSError:\n"
                "            time.sleep(0.1)\n")
    capped = ("def retry(backoff):\n"
              "    while True:\n"
              "        try:\n"
              "            connect()\n"
              "            break\n"
              "        except OSError:\n"
              "            if not backoff.pause():\n"
              "                raise\n")
    dist = "deeplearning4j_tpu/distributed/x.py"
    assert "G014" in rules_in(uncapped, dist)
    assert "G014" not in rules_in(capped, dist)
    assert "G014" not in rules_in(uncapped,
                                  "deeplearning4j_tpu/parallel/x.py")


def test_g002_scoped_to_hot_paths():
    src = "def step(x):\n    return np.asarray(x)\n"
    assert "G002" in rules_in(src, "deeplearning4j_tpu/ops/x.py")
    assert "G002" in rules_in(src, "deeplearning4j_tpu/nn/layers/x.py")
    assert "G002" not in rules_in(src, "deeplearning4j_tpu/datasets/x.py")


def test_g011_scoped_to_spmd_dirs():
    src = "def f():\n    t = time.time()\n    return jnp.full((2,), t)\n"
    assert "G011" in rules_in(src, "deeplearning4j_tpu/distributed/x.py")
    assert "G011" in rules_in(src, "deeplearning4j_tpu/nn/layers/x.py")
    assert "G011" not in rules_in(src, "deeplearning4j_tpu/ops/x.py")


def test_g007_exempts_compat_itself():
    src = "from jax.experimental.shard_map import shard_map\n"
    assert "G007" in rules_in(src, "deeplearning4j_tpu/parallel/x.py")
    assert "G007" not in rules_in(src, "deeplearning4j_tpu/util/compat.py")


def test_g009_exempts_bootstrap_itself():
    src = ("def up():\n"
           "    jax.distributed.initialize()\n"
           'ENV = "DL4J_TPU_NUM_PROCESSES"\n')
    assert "G009" in rules_in(src, "deeplearning4j_tpu/parallel/x.py")
    assert "G009" not in rules_in(
        src, "deeplearning4j_tpu/distributed/bootstrap.py")


def test_inline_suppression_and_fixit():
    src = """\
def g(x):
    return x


def f(x):
    return jax.jit(g)(x)
"""
    findings = lint_source(_PRELUDE + src, FIXTURE_PATH)
    assert [f.rule for f in findings] == ["G005"]
    assert findings[0].fixit  # every rule ships a fix-it message
    suppressed = src.replace("jax.jit(g)(x)",
                             "jax.jit(g)(x)  # graftlint: disable=G005")
    assert not lint_source(_PRELUDE + suppressed, FIXTURE_PATH)


def test_baseline_roundtrip(tmp_path):
    f1 = Finding("G005", "a.py", 3, 0, "m", "f", "jax.jit(g)(x)")
    f2 = Finding("G002", "b.py", 9, 0, "m", "f", "np.asarray(x)")
    from deeplearning4j_tpu.analysis import write_baseline
    path = tmp_path / "base.json"
    write_baseline(str(path), [f1])
    base = load_baseline(str(path))
    new, old = split_baselined([f1, f2], base)
    assert old == [f1] and new == [f2]
    # the key survives line-number drift
    assert Finding("G005", "a.py", 77, 4, "m", "f",
                   "jax.jit(g)(x)").key in base


def test_syntax_error_is_a_finding():
    assert rules_in("def f(:\n") == {"G000"}


# ----------------------------------------------- the package gate

def test_package_is_lint_clean():
    baseline = load_baseline(BASELINE)
    assert len(baseline) <= 5, "baseline must shrink, never grow"
    new, _old = lint_report([PKG], baseline, root=ROOT)
    assert not new, "new graftlint findings:\n" + "\n".join(
        f.format() for f in new)


# ----------------------------------------------- stage 2: jaxpr audit

from deeplearning4j_tpu.analysis import jaxpr_audit  # noqa: E402


@pytest.mark.parametrize("entry", jaxpr_audit.entry_names())
def test_jaxpr_audit_entry(entry):
    findings, counts = jaxpr_audit.audit([entry])
    assert not findings, "\n".join(f.format() for f in findings)
    assert counts[entry] > 0


def test_budget_catches_bloat(tmp_path):
    bad = tmp_path / "budget.json"
    bad.write_text(json.dumps({"ops": {"fused_layer_norm": 1}}))
    findings, _ = jaxpr_audit.audit(["fused_layer_norm"],
                                    budget_path=str(bad))
    assert [f.rule for f in findings] == ["J002"]


def test_every_finding_carries_its_stage_label(tmp_path):
    """--json consumers (benchdiff-style tooling) filter on the `stage`
    field, so AST findings AND budget trips must both carry it."""
    src = "def g(x):\n    return x\n\n\ndef f(x):\n    return jax.jit(g)(x)\n"
    findings = lint_source(_PRELUDE + src, FIXTURE_PATH)
    assert findings and all(f.stage == "ast" for f in findings)
    assert findings[0].to_json()["stage"] == "ast"
    bad = tmp_path / "budget.json"
    bad.write_text(json.dumps({"ops": {"fused_layer_norm": 1}}))
    jfindings, _ = jaxpr_audit.audit(["fused_layer_norm"],
                                     budget_path=str(bad))
    assert [f.stage for f in jfindings] == ["jaxpr"]
    # the stage is display metadata, not identity: baseline keys ignore it
    assert Finding("G005", "a.py", 3, 0, "m", "f", "s").key == \
        Finding("G005", "a.py", 3, 0, "m", "f", "s", stage="ast").key


def test_missing_budget_is_a_finding(tmp_path):
    empty = tmp_path / "budget.json"
    empty.write_text(json.dumps({"ops": {}}))
    findings, _ = jaxpr_audit.audit(["fused_layer_norm"],
                                    budget_path=str(empty))
    assert [f.rule for f in findings] == ["J004"]


def test_forbidden_primitive_detection():
    import jax

    def leaky(x):
        return jax.device_put(x)

    closed = jax.make_jaxpr(leaky)(jax.ShapeDtypeStruct((2,), "float32"))
    prims = {e.primitive.name for e in jaxpr_audit._iter_eqns(closed.jaxpr)}
    assert prims & jaxpr_audit.FORBIDDEN_PRIMITIVES


# ----------------------------------------------- CLI

def _run_cli(*argv):
    return subprocess.run([sys.executable, CLI, *argv], cwd=ROOT,
                          capture_output=True, text=True, timeout=120)


def test_cli_check_clean_tree_exits_zero():
    proc = _run_cli("--check", "deeplearning4j_tpu")
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_check_fails_on_findings_and_emits_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n\ndef f(x):\n    return jax.jit(x)(1)\n")
    proc = _run_cli("--check", str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "G005" in proc.stdout
    proc = _run_cli("--check", "--json", str(bad))
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["findings"][0]["rule"] == "G005"
    assert payload["findings"][0]["fixit"]
    assert payload["findings"][0]["stage"] == "ast"


def _poisoned_jax_env(tmp_path):
    shim = tmp_path / "shim"
    shim.mkdir()
    (shim / "jax.py").write_text(
        "raise ImportError('graftlint host-only stage imported jax')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = f"{shim}{os.pathsep}{ROOT}"
    return env


def test_ast_stage_completes_without_importing_jax(tmp_path):
    """The pre-commit fast path: --stage ast (G001-G014 included) must
    never import jax. A poisoned `jax` module on PYTHONPATH turns any
    violation into a hard failure."""
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "deeplearning4j_tpu"],
        cwd=ROOT, env=_poisoned_jax_env(tmp_path),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ----------------------------------------------- stage 4 (ISSUE 17)

def test_cli_concurrency_stage_gate():
    """The tier-1 concurrency gate: the package sweeps clean under
    --stage concurrency (G025-G028 + the lock-order audit against the
    frozen edge set) with a non-empty lock graph."""
    proc = _run_cli("--check", "--stage", "concurrency", "--json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["lock_order_edges"], "frozen lock graph is empty"


def test_cli_concurrency_findings_carry_stage_label(tmp_path):
    bad = tmp_path / "racy.py"
    bad.write_text(
        "import threading\n\n\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self.n = 0\n"
        "        self._t = None\n\n"
        "    def start(self):\n"
        "        def loop():\n"
        "            self.n += 1\n\n"
        "        self._t = threading.Thread(target=loop, daemon=True)\n"
        "        self._t.start()\n\n"
        "    def stop(self):\n"
        "        self._t.join()\n\n"
        "    def describe(self):\n"
        "        return self.n\n")
    proc = _run_cli("--check", "--stage", "concurrency", "--json",
                    str(bad))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    rules = {f["rule"] for f in payload["findings"]}
    assert "G025" in rules
    assert all(f["stage"] == "concurrency"
               for f in payload["findings"])


def test_concurrency_stage_completes_without_importing_jax(tmp_path):
    """Stage 4 is host-only analysis (AST rules + lock graph): it must
    run with jax poisoned, exactly like stage 1."""
    proc = subprocess.run(
        [sys.executable, CLI, "--check", "--stage", "concurrency",
         "deeplearning4j_tpu"],
        cwd=ROOT, env=_poisoned_jax_env(tmp_path),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_rules_prints_per_stage_inventory(tmp_path):
    """--rules is the one-stop rule inventory: every id every stage can
    emit, grouped by stage — and it runs jax-free (doc lookups only)."""
    proc = subprocess.run(
        [sys.executable, CLI, "--rules"],
        cwd=ROOT, env=_poisoned_jax_env(tmp_path),
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for stage in ("ast", "jaxpr", "spmd", "concurrency", "precision"):
        assert f"stage {stage}:" in proc.stdout
    for rid in ("G001", "G024", "G025", "G028", "G031", "G034",
                "J001", "J004", "C001", "C003", "D001", "D003",
                "P001", "P005", "PB01"):
        assert rid in proc.stdout, f"--rules missing {rid}"
