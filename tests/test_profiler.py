"""util/profiler.py tests — previously untested: the steady-state
window start/stop arithmetic, and above all the close()/unstopped-trace
path (an unstopped jax.profiler trace is lost AND leaves the
process-global profiler started, so every later trace in the process
fails). jax.profiler is faked so no real trace runs."""

import pytest

from deeplearning4j_tpu.telemetry import Recorder
from deeplearning4j_tpu.util.profiler import ProfilerIterationListener, trace

pytestmark = pytest.mark.telemetry


class _FakeProfiler:
    def __init__(self):
        self.calls = []

    def start_trace(self, logdir):
        self.calls.append(("start", logdir))

    def stop_trace(self):
        self.calls.append(("stop",))


@pytest.fixture
def fake_profiler(monkeypatch):
    import jax

    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    return fake


def test_listener_traces_exactly_the_window(fake_profiler):
    rec = Recorder()
    lst = ProfilerIterationListener("/tmp/prof", start_iteration=2,
                                    n_iterations=3, recorder=rec)
    for it in range(8):
        lst.iteration_done(None, it)
    assert fake_profiler.calls == [("start", "/tmp/prof"), ("stop",)]
    assert lst.done and not lst._active
    (span,) = rec.events
    assert span["event"] == "span" and span["name"] == "profiler_trace"
    assert span["start_iteration"] == 2 and span["seconds"] >= 0
    # done: the window never restarts
    for it in range(8, 16):
        lst.iteration_done(None, it)
    assert len(fake_profiler.calls) == 2


def test_close_flushes_an_unstopped_trace(fake_profiler):
    """fit() ends INSIDE the window: without close() the process-global
    profiler stays started — the exact leak the docstring warns about."""
    rec = Recorder()
    lst = ProfilerIterationListener("/tmp/prof", start_iteration=1,
                                    n_iterations=100, recorder=rec)
    for it in range(3):
        lst.iteration_done(None, it)
    assert fake_profiler.calls == [("start", "/tmp/prof")]
    assert lst._active and not lst.done
    lst.close()
    assert fake_profiler.calls[-1] == ("stop",)
    assert lst.done and not lst._active
    assert rec.events[-1]["name"] == "profiler_trace"
    # idempotent: a second close must NOT stop an already-stopped trace
    lst.close()
    assert fake_profiler.calls.count(("stop",)) == 1


def test_close_is_a_noop_before_the_window_opens(fake_profiler):
    lst = ProfilerIterationListener("/tmp/prof", start_iteration=10)
    lst.iteration_done(None, 1)
    lst.close()
    assert fake_profiler.calls == []
    assert not lst.done  # close() before start leaves the window armed


def test_del_flushes_best_effort(fake_profiler):
    lst = ProfilerIterationListener("/tmp/prof", start_iteration=0,
                                    n_iterations=100, recorder=Recorder())
    lst.iteration_done(None, 0)
    assert fake_profiler.calls == [("start", "/tmp/prof")]
    lst.__del__()
    assert fake_profiler.calls[-1] == ("stop",)


def test_trace_context_manager_stops_on_exception(fake_profiler):
    from deeplearning4j_tpu.telemetry import set_default

    rec = Recorder()
    prev = set_default(rec)
    try:
        with pytest.raises(RuntimeError):
            with trace("/tmp/prof"):
                assert fake_profiler.calls == [("start", "/tmp/prof")]
                raise RuntimeError("mid-trace")
    finally:
        set_default(prev)
    assert fake_profiler.calls[-1] == ("stop",)
    (span,) = rec.events
    assert span["name"] == "profiler_trace" and span["logdir"] == "/tmp/prof"
