# graftlint D002 fixture: registered sink callbacks invoked while the
# emitter's lock is held — the sink-reentrancy shape (a sink that
# acquires a lock runs under whatever the emitter holds). The same
# source trips G026 when linted at a telemetry/ path.
import threading


class Emitter:
    def __init__(self):
        self._lock = threading.Lock()
        self._sinks = []

    def add_sink(self, fn):
        with self._lock:
            self._sinks.append(fn)

    def emit(self, record):
        with self._lock:
            for sink in self._sinks:
                sink(record)
