# graftlint D001 fixture: two classes acquiring each other's locks in
# opposite order through uniquely-named helpers — a lock-order cycle
# the audit must report and the CLI must exit 1 on, baseline or not.
import threading


class PoolSide:
    def __init__(self, registry):
        self._lock = threading.Lock()
        self.registry = registry

    def reserve_pages(self):
        with self._lock:
            self.registry.bump_usage_counter()

    def note_pool_state(self):
        with self._lock:
            return True


class RegistrySide:
    def __init__(self):
        self._lock = threading.Lock()
        self.pool = None

    def bump_usage_counter(self):
        with self._lock:
            if self.pool is not None:
                self.pool.note_pool_state()
