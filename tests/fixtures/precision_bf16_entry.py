"""Demo stage-5 fixture: a hand-written FORWARD bf16 accumulation — the
silent-precision shape graftlint's precision stage exists to catch.

`python tools/graftlint.py --check --stage precision tests/fixtures/\
precision_bf16_entry.py` must exit non-zero with a P001 finding: the
scan below add-accumulates its carry in bfloat16 over rows of a bf16
dot_general, so the running sum drops low bits on every iteration — the
loss-curve-flattens-late bug class the f32-accumulation policy (f32
carries + preferred_element_type, the flash/decode kernels' pattern)
prevents. No `add_any` appears (this is not an autodiff backward
region), so the accumulation checks apply in full. Note jnp.sum would
NOT reproduce this: it upcasts sub-f32 inputs before reducing — the bug
needs a hand-rolled accumulator, which is exactly where it occurs.

The GRAFTLINT_PRECISION_ENTRIES hook is the external-entry contract of
analysis/precision_audit.py: {name: builder}, builder() -> (fn, args).
"""


def build_bf16_carry_over_dot():
    import jax
    import jax.numpy as jnp

    def fn(x, w):
        y = jnp.dot(x, w)  # bf16 dot (no preferred_element_type)

        def body(carry, row):
            return carry + row, ()  # bf16 running sum: drops low bits

        acc, _ = jax.lax.scan(body, jnp.zeros((64,), jnp.bfloat16), y)
        return acc

    bf16 = jnp.bfloat16
    return fn, (jax.ShapeDtypeStruct((64, 64), bf16),
                jax.ShapeDtypeStruct((64, 64), bf16))


GRAFTLINT_PRECISION_ENTRIES = {
    "demo/bf16_carry_over_dot": build_bf16_carry_over_dot,
}
