# graftlint G028 positive fixture: a non-daemon thread the class never
# joins, and a daemon thread with no stop/close/drain handle.
import threading


class FireAndForget:
    def launch(self):
        t = threading.Thread(target=self._loop)
        t.start()

    def _loop(self):
        pass


class BareDaemon:
    def launch(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        pass
