# graftlint G025 negative fixture: the same worker with every counter
# access (thread-side += AND the public read) under one lock.
import threading


class GuardedWorker:
    def __init__(self):
        self._mu = threading.Lock()
        self.served = 0
        self._thread = None

    def start(self):
        def loop():
            for _ in range(1000):
                with self._mu:
                    self.served += 1

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def describe(self):
        with self._mu:
            return {"served": self.served}
