"""Demo stage-3 fixture: a rank-conditional collective — the deadlock
shape graftlint's SPMD stage exists to catch.

`python tools/graftlint.py --check --stage spmd tests/fixtures/\
spmd_divergent_entry.py` must exit non-zero with BOTH a G010 AST finding
(the rank-guarded psum below is statically visible) and a C003 deadlock
finding from the collective audit naming the two divergent sequences
(process 0 issues the psum, process 1 never joins it — on a live fleet
every process then aborts with the SIGABRT "Deadline Exceeded" mode
documented in ARCHITECTURE.md §Distributed runtime).

The GRAFTLINT_SPMD_ENTRIES hook is the external-entry contract of
analysis/collective_audit.py: {name: builder}, builder() -> (fn, args).
"""


def build_divergent():
    import jax

    from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

    ensure_cpu_devices(2)
    from jax.sharding import PartitionSpec as P

    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.util.compat import shard_map

    mesh = make_mesh({"data": 2})

    def local(x):
        if jax.process_index() == 0:  # rank-conditional collective
            return jax.lax.psum(x, "data")
        return x * 2.0  # process 1 never reaches the allreduce: deadlock

    fn = shard_map(local, mesh=mesh, in_specs=(P("data"),),
                   out_specs=P("data"), check_vma=False)
    return fn, (jax.ShapeDtypeStruct((4,), "float32"),)


GRAFTLINT_SPMD_ENTRIES = {"demo/rank_conditional_psum": build_divergent}
