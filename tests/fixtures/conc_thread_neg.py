# graftlint G028 negative fixture: a daemon worker with a stop()
# handle that joins the thread on shutdown.
import threading


class SupervisedWorker:
    def __init__(self):
        self._thread = None

    def launch(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        pass

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=1.0)
