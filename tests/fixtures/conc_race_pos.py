# graftlint G025 positive fixture: `served` is += mutated on the
# worker thread and read from the public describe() with no lock.
import threading


class RacyWorker:
    def __init__(self):
        self.served = 0
        self._thread = None

    def start(self):
        def loop():
            for _ in range(1000):
                self.served += 1

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._thread.join(timeout=1.0)

    def describe(self):
        return {"served": self.served}
