# graftlint G026 positive fixture (lives under a serving/ path, the
# rule's scope): a blocking queue.put and a time.sleep inside a
# held-lock body.
import queue
import threading
import time


class BlockingDispatcher:
    def __init__(self):
        self._lock = threading.Lock()
        self.q = queue.Queue(maxsize=4)

    def dispatch(self, item):
        with self._lock:
            self.q.put(item)

    def backoff(self):
        with self._lock:
            time.sleep(0.05)
