# graftlint G026 negative fixture: the carve-outs — non-blocking
# queue ops under the lock, waiting on the condition you HOLD, and
# snapshot-under-lock / block-outside-it.
import queue
import threading
import time


class PoliteDispatcher:
    def __init__(self):
        self._cv = threading.Condition()
        self._buf = []
        self.q = queue.Queue(maxsize=4)

    def try_drain(self):
        with self._cv:
            return self.q.get(block=False)

    def wait_item(self):
        with self._cv:
            while not self._buf:
                self._cv.wait(0.1)
            return self._buf.pop()

    def put_item(self, item):
        with self._cv:
            self._buf.append(item)
            self._cv.notify()

    def dispatch(self, item):
        with self._cv:
            target = self.q
        target.put(item)
        time.sleep(0.0)
