# graftlint G027 negative fixture: wait in a while-predicate loop,
# notify under the owning lock, and an Event.wait stop-flag loop
# instead of a sleep poll.
import threading


class PatientWaiter:
    def __init__(self):
        self._cv = threading.Condition()
        self._stop = threading.Event()
        self.ready = False

    def await_ready(self):
        with self._cv:
            while not self.ready:
                self._cv.wait(0.5)

    def set_ready(self):
        with self._cv:
            self.ready = True
            self._cv.notify_all()

    def idle(self):
        while not self._stop.is_set():
            self._stop.wait(0.05)
