# graftlint G027 positive fixture (serving/ scope): a Condition.wait
# outside a while-predicate loop, a notify without the owning lock,
# and a bare time.sleep polling loop.
import threading
import time


class SloppyWaiter:
    def __init__(self):
        self._cv = threading.Condition()
        self.ready = False

    def await_once(self):
        with self._cv:
            self._cv.wait(0.5)

    def poke(self):
        self._cv.notify_all()

    def spin(self):
        while not self.ready:
            time.sleep(0.01)
