"""Fused Pallas flash attention (ops/flash_attention.py) vs the dense
reference — forward and custom-VJP backward, causal and full, plus the
dispatch gate. Runs in interpret mode on CPU (same kernel code as TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.ops.flash_attention import (
    MIN_FLASH_SEQ,
    flash_attention,
    supports,
)


def _qkv(B=2, H=2, T=256, D=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    o_flash = flash_attention(q, k, v, causal=causal)
    o_dense = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o_flash), np.asarray(o_dense),
                               atol=2e-5)


def test_backward_matches_dense():
    q, k, v = _qkv(T=128)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_backward_matches_dense_long_sequence():
    """T > BLOCK_K_MAX exercises the two-kernel (dq + dkv) backward; the
    shorter tests hit the fused single-pass backward (block_k == T)."""
    q, k, v = _qkv(B=1, H=1, T=1024)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_bf16_forward():
    q, k, v = _qkv(T=128)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    o = flash_attention(q, k, v, causal=True)
    assert o.dtype == jnp.bfloat16
    o_dense = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_dense, np.float32), atol=3e-2)


def test_supports_gate():
    # long causal unmasked sequences -> fused kernel
    assert supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.0,
                    mask=None)
    # short sequences use XLA's fused dense path (faster below the cutoff)
    assert not supports((2, 2, MIN_FLASH_SEQ // 2, 64), causal=True,
                        dropout=0.0, mask=None)
    # dropout and padding masks are dense-only cases
    assert not supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.1,
                        mask=None)
    assert not supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.0,
                        mask=np.ones((2, MIN_FLASH_SEQ)))
    # non-divisible lengths fall back
    assert not supports((2, 2, MIN_FLASH_SEQ + 40, 64), causal=True,
                        dropout=0.0, mask=None)
