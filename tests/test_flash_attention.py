"""Fused Pallas flash attention (ops/flash_attention.py) vs the dense
reference — forward and custom-VJP backward, causal and full, plus the
dispatch gate. Runs in interpret mode on CPU (same kernel code as TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.ops.flash_attention import (
    MIN_FLASH_SEQ,
    flash_attention,
    supports,
)


def _qkv(B=2, H=2, T=256, D=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    o_flash = flash_attention(q, k, v, causal=causal)
    o_dense = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o_flash), np.asarray(o_dense),
                               atol=2e-5)


def test_backward_matches_dense():
    q, k, v = _qkv(T=128)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_backward_matches_dense_long_sequence():
    """T > BLOCK_K_MAX exercises the two-kernel (dq + dkv) backward; the
    shorter tests hit the fused single-pass backward (block_k == T)."""
    q, k, v = _qkv(B=1, H=1, T=1024)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_bf16_forward():
    q, k, v = _qkv(T=128)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    o = flash_attention(q, k, v, causal=True)
    assert o.dtype == jnp.bfloat16
    o_dense = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_dense, np.float32), atol=3e-2)


def test_supports_gate():
    # long causal unmasked sequences -> fused kernel
    assert supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.0,
                    mask=None)
    # short sequences use XLA's fused dense path (faster below the cutoff)
    assert not supports((2, 2, MIN_FLASH_SEQ // 2, 64), causal=True,
                        dropout=0.0, mask=None)
    # attention dropout is a dense-only case
    assert not supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.1,
                        mask=None)
    # padding masks keep the fused path (VERDICT r2 #3)
    assert supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.0,
                    mask=np.ones((2, MIN_FLASH_SEQ)))
    # non-divisible lengths fall back
    assert not supports((2, 2, MIN_FLASH_SEQ + 40, 64), causal=True,
                        dropout=0.0, mask=None)


def _varlen_mask(B, T, lengths):
    m = np.zeros((B, T), np.float32)
    for b, L in enumerate(lengths):
        m[b, :L] = 1.0
    return jnp.asarray(m)


@pytest.mark.parametrize("causal", [True, False])
def test_masked_forward_matches_dense(causal):
    """Variable-length batches: the [B, T] key padding mask folds into the
    kernel's block predicate and matches the dense masked path on every
    VALID query row (padded rows are downstream-masked by the loss)."""
    B, T = 3, 256
    q, k, v = _qkv(B=B, T=T)
    lengths = [256, 200, 64]
    mask = _varlen_mask(B, T, lengths)
    o_flash = flash_attention(q, k, v, causal=causal, mask=mask)
    o_dense = dot_product_attention(q, k, v, causal=causal, mask=mask)
    valid = np.asarray(mask, bool)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(o_flash)[b, :, valid[b]],
            np.asarray(o_dense)[b, :, valid[b]], atol=2e-5)


@pytest.mark.parametrize("T", [128, 1024])
def test_masked_backward_matches_dense(T):
    """Masked fwd+grad parity on both backward paths (fused single-pass at
    T=128; two-kernel dq+dkv at T=1024). The loss only reads valid rows —
    the realistic setting where padded-query outputs never matter."""
    B = 2
    q, k, v = _qkv(B=B, T=T)
    lengths = [T, T - T // 4]
    mask = _varlen_mask(B, T, lengths)
    w = mask[:, None, :, None]  # zero out padded query rows like the loss

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(
            flash_attention(q, k, v, causal=True, mask=mask)) * w)

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(
            q, k, v, causal=True, mask=mask)) * w)

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_masked_fully_padded_row_is_finite():
    """A fully padded sequence (all keys masked) must yield zeros, not NaN
    (the all-masked softmax row is the classic flash-attention bug)."""
    B, T = 2, 128
    q, k, v = _qkv(B=B, T=T)
    mask = _varlen_mask(B, T, [T, 0])
    o = flash_attention(q, k, v, causal=False, mask=mask)
    assert np.isfinite(np.asarray(o)).all()
    np.testing.assert_allclose(np.asarray(o)[1], 0.0, atol=1e-6)
