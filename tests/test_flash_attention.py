"""Fused Pallas flash attention (ops/flash_attention.py) vs the dense
reference — forward and custom-VJP backward, causal and full, plus the
dispatch gate. Runs in interpret mode on CPU (same kernel code as TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn.layers.attention import dot_product_attention
from deeplearning4j_tpu.ops.flash_attention import (
    MIN_FLASH_SEQ,
    flash_attention,
    supports,
)


def _qkv(B=2, H=2, T=256, D=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_dense(causal):
    q, k, v = _qkv()
    o_flash = flash_attention(q, k, v, causal=causal)
    o_dense = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o_flash), np.asarray(o_dense),
                               atol=2e-5)


def test_backward_matches_dense():
    q, k, v = _qkv(T=128)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_backward_matches_dense_long_sequence():
    """T > BLOCK_K_MAX exercises the two-kernel (dq + dkv) backward; the
    shorter tests hit the fused single-pass backward (block_k == T)."""
    q, k, v = _qkv(B=1, H=1, T=1024)

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(q, k, v, causal=True)))

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_bf16_forward():
    q, k, v = _qkv(T=128)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    o = flash_attention(q, k, v, causal=True)
    assert o.dtype == jnp.bfloat16
    o_dense = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(o, np.float32),
                               np.asarray(o_dense, np.float32), atol=3e-2)


def test_supports_gate():
    # long causal unmasked sequences -> fused kernel
    assert supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.0,
                    mask=None)
    # short sequences use XLA's fused dense path (faster below the cutoff)
    assert not supports((2, 2, MIN_FLASH_SEQ // 2, 64), causal=True,
                        dropout=0.0, mask=None)
    # attention dropout keeps the fused path (r4: in-kernel counter-hash)
    assert supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.1,
                    mask=None)
    # padding masks keep the fused path (VERDICT r2 #3)
    assert supports((2, 2, MIN_FLASH_SEQ, 64), causal=True, dropout=0.0,
                    mask=np.ones((2, MIN_FLASH_SEQ)))
    # non-divisible lengths fall back
    assert not supports((2, 2, MIN_FLASH_SEQ + 40, 64), causal=True,
                        dropout=0.0, mask=None)


def _varlen_mask(B, T, lengths):
    m = np.zeros((B, T), np.float32)
    for b, L in enumerate(lengths):
        m[b, :L] = 1.0
    return jnp.asarray(m)


@pytest.mark.parametrize("causal", [True, False])
def test_masked_forward_matches_dense(causal):
    """Variable-length batches: the [B, T] key padding mask folds into the
    kernel's block predicate and matches the dense masked path on every
    VALID query row (padded rows are downstream-masked by the loss)."""
    B, T = 3, 256
    q, k, v = _qkv(B=B, T=T)
    lengths = [256, 200, 64]
    mask = _varlen_mask(B, T, lengths)
    o_flash = flash_attention(q, k, v, causal=causal, mask=mask)
    o_dense = dot_product_attention(q, k, v, causal=causal, mask=mask)
    valid = np.asarray(mask, bool)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(o_flash)[b, :, valid[b]],
            np.asarray(o_dense)[b, :, valid[b]], atol=2e-5)


@pytest.mark.parametrize("T", [128, 1024])
def test_masked_backward_matches_dense(T):
    """Masked fwd+grad parity on both backward paths (fused single-pass at
    T=128; two-kernel dq+dkv at T=1024). The loss only reads valid rows —
    the realistic setting where padded-query outputs never matter."""
    B = 2
    q, k, v = _qkv(B=B, T=T)
    lengths = [T, T - T // 4]
    mask = _varlen_mask(B, T, lengths)
    w = mask[:, None, :, None]  # zero out padded query rows like the loss

    def f_flash(q, k, v):
        return jnp.sum(jnp.sin(
            flash_attention(q, k, v, causal=True, mask=mask)) * w)

    def f_dense(q, k, v):
        return jnp.sum(jnp.sin(dot_product_attention(
            q, k, v, causal=True, mask=mask)) * w)

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_masked_fully_padded_row_is_finite():
    """A fully padded sequence (all keys masked) must yield zeros, not NaN
    (the all-masked softmax row is the classic flash-attention bug)."""
    B, T = 2, 128
    q, k, v = _qkv(B=B, T=T)
    mask = _varlen_mask(B, T, [T, 0])
    o = flash_attention(q, k, v, causal=False, mask=mask)
    assert np.isfinite(np.asarray(o)).all()
    np.testing.assert_allclose(np.asarray(o)[1], 0.0, atol=1e-6)


# ------------------------------------------------- packed-qkv (no relayout)

def _packed_ref(qkv, B, T, H, D, mask=None):
    """Dense reference for the packed path: split + head transpose +
    dot-product attention + inverse transpose."""
    n = H * D
    q, k, v = jnp.split(qkv, 3, -1)
    heads = lambda t: t.reshape(B, T, H, D).transpose(0, 2, 1, 3)
    o = dot_product_attention(heads(q), heads(k), heads(v), causal=True,
                              mask=mask)
    return o.transpose(0, 2, 1, 3).reshape(B, T, n)


@pytest.mark.parametrize("masked", [False, True])
def test_packed_qkv_matches_dense(masked):
    from deeplearning4j_tpu.ops.flash_attention import (
        flash_attention_qkv,
        supports_qkv,
    )

    B, T, H, D = 2, 512, 2, 128
    n = H * D
    rng = np.random.default_rng(0)
    qkv = jnp.asarray(rng.standard_normal((B, T, 3 * n)), jnp.float32)
    mask = (jnp.asarray((rng.random((B, T)) < 0.8), jnp.float32)
            if masked else None)
    assert supports_qkv(B, T, n, H, dropout=0.0)
    ref = _packed_ref(qkv, B, T, H, D, mask)
    out = flash_attention_qkv(qkv, H, causal=True, mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    gref = jax.grad(lambda x: jnp.sum(_packed_ref(x, B, T, H, D, mask) ** 2))(qkv)
    gout = jax.grad(lambda x: jnp.sum(
        flash_attention_qkv(x, H, causal=True, mask=mask) ** 2))(qkv)
    np.testing.assert_allclose(np.asarray(gout), np.asarray(gref), atol=5e-4)


def test_packed_qkv_supports_envelope():
    from deeplearning4j_tpu.ops.flash_attention import supports_qkv

    assert supports_qkv(2, 512, 256, 2, dropout=0.0)       # D=128
    assert supports_qkv(2, 512, 256, 2, dropout=0.1)       # dropout (r5)
    assert supports_qkv(2, 512, 256, 4, dropout=0.0)       # D=64 pair (r5)
    assert supports_qkv(2, 512, 256, 4, dropout=0.1)
    assert not supports_qkv(2, 512, 96, 3, dropout=0.0)    # D=32
    assert not supports_qkv(2, 1024, 256, 2, dropout=0.0)  # multi-block T
    assert not supports_qkv(2, 256, 256, 2, dropout=0.0)   # below MIN_FLASH


@pytest.mark.parametrize("masked,dropout", [(False, 0.0), (True, 0.0),
                                            (False, 0.2), (True, 0.2)])
def test_packed_qkv_head_pair_d64_matches_flat(masked, dropout):
    """D=64 head-pair packed kernels (r5 — VERDICT r4 #5): two adjacent
    heads per 128-lane column slice must equal the flat [B*H, T, 64]
    layout — values and gradients, with masks and in-kernel dropout."""
    from deeplearning4j_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_qkv,
        supports_qkv,
    )

    B, T, H, D = 2, 512, 4, 64
    n = H * D
    assert supports_qkv(B, T, n, H, dropout=dropout)
    rng = np.random.default_rng(5)
    qkv = jnp.asarray(rng.standard_normal((B, T, 3 * n)), jnp.float32)
    key = jax.random.PRNGKey(13)
    mask = (jnp.asarray((rng.random((B, T)) < 0.8), jnp.float32)
            if masked else None)

    def flat(x):
        q, k, v = jnp.split(x, 3, axis=-1)
        heads = lambda t: t.reshape(B, T, H, D).transpose(0, 2, 1, 3)
        o = flash_attention(heads(q), heads(k), heads(v), causal=True,
                            mask=mask, dropout=dropout, dropout_rng=key)
        return o.transpose(0, 2, 1, 3).reshape(B, T, n)

    def packed(x):
        return flash_attention_qkv(x, H, causal=True, mask=mask,
                                   dropout=dropout, dropout_rng=key)

    np.testing.assert_allclose(np.asarray(packed(qkv)),
                               np.asarray(flat(qkv)), atol=2e-5)
    gf = jax.grad(lambda x: jnp.sum(flat(x) ** 2))(qkv)
    gp = jax.grad(lambda x: jnp.sum(packed(x) ** 2))(qkv)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gf), atol=5e-4)


@pytest.mark.parametrize("masked", [False, True])
def test_packed_qkv_dropout_matches_flat(masked):
    """The packed path's in-kernel dropout (r5 — VERDICT r4 #2) uses the
    same (b*H + h) counter-hash stream as the flat layout: identical rng
    must produce identical outputs AND gradients across the two layouts."""
    from deeplearning4j_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_qkv,
    )

    B, T, H, D = 2, 512, 2, 128
    n = H * D
    rate = 0.2
    rng = np.random.default_rng(3)
    qkv = jnp.asarray(rng.standard_normal((B, T, 3 * n)), jnp.float32)
    key = jax.random.PRNGKey(11)
    mask = (jnp.asarray((rng.random((B, T)) < 0.8), jnp.float32)
            if masked else None)

    def flat(x):
        q, k, v = jnp.split(x, 3, axis=-1)
        heads = lambda t: t.reshape(B, T, H, D).transpose(0, 2, 1, 3)
        o = flash_attention(heads(q), heads(k), heads(v), causal=True,
                            mask=mask, dropout=rate, dropout_rng=key)
        return o.transpose(0, 2, 1, 3).reshape(B, T, n)

    def packed(x):
        return flash_attention_qkv(x, H, causal=True, mask=mask,
                                   dropout=rate, dropout_rng=key)

    np.testing.assert_allclose(np.asarray(packed(qkv)),
                               np.asarray(flat(qkv)), atol=2e-5)
    gf = jax.grad(lambda x: jnp.sum(flat(x) ** 2))(qkv)
    gp = jax.grad(lambda x: jnp.sum(packed(x) ** 2))(qkv)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gf), atol=5e-4)


# --------------------------------------------------- in-kernel dropout

def _dense_dropout_ref(q, k, v, seed, rate, T, H, mask=None):
    """Dense attention applying the EXACT in-kernel counter-hash keep
    mask (dropout_keep_mask_host) — a bitwise oracle, not a statistical
    one."""
    from deeplearning4j_tpu.ops.flash_attention import dropout_keep_mask_host

    B, D = q.shape[0], q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(float(D))
    s = jnp.where(jnp.tril(jnp.ones((T, T), bool)), s, -1e30)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :].astype(bool), s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    keeps = np.stack([dropout_keep_mask_host(seed, b * H + h, T, rate)
                      for b in range(B) for h in range(H)]).reshape(
                          B, H, T, T)
    w = w * jnp.asarray(keeps, jnp.float32) / (1.0 - rate)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


@pytest.mark.parametrize("masked", [False, True])
def test_dropout_matches_dense_with_same_mask(masked):
    """VERDICT r3 #6: attention dropout runs inside the kernels. The
    counter-hash mask is reproducible on the host, so fwd AND bwd are
    checked exactly against a dense reference using the identical mask."""
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    B, H, T, D = 2, 2, 512, 32
    rate = 0.2
    rng = np.random.default_rng(0)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
               for _ in range(3))
    key = jax.random.PRNGKey(7)
    seed = int(jax.random.randint(key, (1, 1), 0, 2**31 - 1,
                                  dtype=jnp.int32)[0, 0])
    if masked:
        m = (rng.random((B, T)) < 0.8)
        m[:, 0] = True  # causal row 0 must keep a valid key (the kernel
        # zeroes fully-masked rows; the dense softmax saturates instead)
        mask = jnp.asarray(m, jnp.float32)
    else:
        mask = None

    ref_fn = lambda q, k, v: _dense_dropout_ref(q, k, v, seed, rate, T, H,
                                                mask)
    out_fn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, mask=mask, dropout=rate, dropout_rng=key)
    np.testing.assert_allclose(np.asarray(out_fn(q, k, v)),
                               np.asarray(ref_fn(q, k, v)), atol=2e-5)
    gref = jax.grad(lambda q, k, v: jnp.sum(ref_fn(q, k, v) ** 2),
                    (0, 1, 2))(q, k, v)
    gout = jax.grad(lambda q, k, v: jnp.sum(out_fn(q, k, v) ** 2),
                    (0, 1, 2))(q, k, v)
    for a, b in zip(gout, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_dropout_statistics_and_determinism():
    from deeplearning4j_tpu.ops.flash_attention import dropout_keep_mask_host

    m1 = dropout_keep_mask_host(12345, 3, 512, 0.25)
    m2 = dropout_keep_mask_host(12345, 3, 512, 0.25)
    assert (m1 == m2).all()                      # deterministic
    assert abs(m1.mean() - 0.75) < 0.01          # keep fraction
    m3 = dropout_keep_mask_host(12346, 3, 512, 0.25)
    assert (m1 != m3).any()                      # seed-sensitive


def test_dropout_keeps_fused_path_in_supports():
    from deeplearning4j_tpu.ops.flash_attention import supports

    assert supports((2, 4, 512, 64), causal=True, dropout=0.1, mask=None)
    assert not supports((2, 4, 256, 64), causal=True, dropout=0.1,
                        mask=None)


def test_bf16_backward_matches_f32_reference():
    """ADVICE r3: the fused backward computes softmax exp and ds in the
    operand dtype (bf16 for bf16 models, ~0.4% p error) but CI only ran
    f32 parity — this pins the bf16 numeric path against an f32 dense
    reference of the SAME bf16 inputs, with tolerance sized to the bf16
    softmax approximation."""
    B, H, T, D = 2, 2, 512, 64
    rng = np.random.default_rng(5)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.bfloat16)
               for _ in range(3))

    def f_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32) ** 2)

    def f_dense(q, k, v):
        # f32 reference evaluated on the same bf16 inputs
        return jnp.sum(dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=True) ** 2)

    g_flash = jax.grad(f_flash, (0, 1, 2))(q, k, v)
    g_dense = jax.grad(f_dense, (0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        assert a32.dtype == np.float32 and np.isfinite(a32).all()
        scale = max(np.abs(b32).max(), 1e-3)
        assert np.abs(a32 - b32).max() / scale < 0.05, (
            np.abs(a32 - b32).max(), scale)


def test_dropout_streaming_kernels_match_dense():
    """T > BLOCK_K_MAX routes the backward through the streaming dq+dkv
    kernels — the dropout keep-mask must regenerate identically there
    (absolute-coordinate hash), not just in the fused single-block path
    the other dropout tests cover."""
    from deeplearning4j_tpu.ops.flash_attention import flash_attention

    B, H, T, D = 1, 2, 1024, 32
    rate = 0.15
    rng = np.random.default_rng(9)
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)), jnp.float32)
               for _ in range(3))
    key = jax.random.PRNGKey(11)
    seed = int(jax.random.randint(key, (1, 1), 0, 2**31 - 1,
                                  dtype=jnp.int32)[0, 0])
    ref_fn = lambda q, k, v: _dense_dropout_ref(q, k, v, seed, rate, T, H)
    out_fn = lambda q, k, v: flash_attention(
        q, k, v, causal=True, dropout=rate, dropout_rng=key)
    np.testing.assert_allclose(np.asarray(out_fn(q, k, v)),
                               np.asarray(ref_fn(q, k, v)), atol=2e-5)
    gref = jax.grad(lambda q, k, v: jnp.sum(ref_fn(q, k, v) ** 2),
                    (0, 1, 2))(q, k, v)
    gout = jax.grad(lambda q, k, v: jnp.sum(out_fn(q, k, v) ** 2),
                    (0, 1, 2))(q, k, v)
    for a, b in zip(gout, gref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)


class TestChunkedFlash:
    """Blockwise long-context attention (chunked_flash_attention): the
    ring-attention hop primitive + lse merge serialized on one chip, for
    T beyond the monolithic kernels' VMEM envelope (MAX_FLASH_T). Tested
    at small T with an explicit chunk so CPU interpret mode stays fast."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_dense(self, causal):
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        q, k, v = _qkv(T=512)
        o_c = chunked_flash_attention(q, k, v, causal=causal, chunk=128)
        o_d = dot_product_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(o_c), np.asarray(o_d),
                                   atol=2e-5)

    def test_backward_matches_monolithic(self, rng):
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        q, k, v = _qkv(T=512, seed=3)

        def f_chunked(q, k, v):
            return jnp.sum(jnp.sin(
                chunked_flash_attention(q, k, v, causal=True, chunk=128)))

        def f_mono(q, k, v):
            return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

        g_c = jax.grad(f_chunked, argnums=(0, 1, 2))(q, k, v)
        g_m = jax.grad(f_mono, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_c, g_m):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_supports_envelope(self):
        from deeplearning4j_tpu.ops.flash_attention import (
            MAX_CHUNKS,
            MAX_FLASH_T,
            pick_chunk,
            supports_chunked,
        )

        big = (2, 2, 2 * MAX_FLASH_T, 64)
        assert supports_chunked(big, causal=True, dropout=0.0, mask=None)
        # monolithic envelope excludes what chunked picks up
        assert not supports(big, causal=True, dropout=0.0, mask=None)
        # dropout rides the chunk loop since r6 (global-coordinate keep
        # mask); masks since r5 (each kv tile sees its mask slice)
        assert supports_chunked(big, causal=True, dropout=0.1, mask=None)
        assert supports_chunked(big, causal=True, dropout=0.0,
                                mask=np.ones((2, big[2])))
        # T inside the monolithic envelope stays monolithic
        small = (2, 2, MAX_FLASH_T, 64)
        assert not supports_chunked(small, causal=True, dropout=0.0,
                                    mask=None)
        assert pick_chunk(2 * MAX_FLASH_T) == MAX_FLASH_T
        assert pick_chunk(8192 + 128) == 0  # not tile-divisible
        # the unroll guard: an awkward T whose only tiles would exceed
        # the pair budget (49 x 512) is rejected, not compiled for minutes
        assert pick_chunk(25088) == 0
        # the measured ceiling: MAX_CHUNKS tiles of MAX_FLASH_T
        assert pick_chunk(MAX_CHUNKS * MAX_FLASH_T) == MAX_FLASH_T

    def test_trace_budget_non_causal(self):
        """ADVICE r5 #1 closed structurally in r8: non-causal kv tiles
        run under a lax.scan, so the trace budget is the CHUNK count
        (one traced kernel per q chunk) — not n*n unrolled calls — and
        non-causal T reaches the same 16-chunk ceiling as causal."""
        from deeplearning4j_tpu.ops.flash_attention import (
            MAX_CHUNK_PAIRS,
            MAX_CHUNKS,
            MAX_FLASH_T,
            chunk_pairs,
            max_chunks,
            pick_chunk,
            supports_chunked,
            traced_tile_calls,
        )

        assert max_chunks(True) == MAX_CHUNKS == 16
        assert max_chunks(False) == MAX_CHUNKS  # scanned kv loop (r8)
        # dispatch still prefers FEWER, larger tiles: 16384 = 2 x 8192
        c = pick_chunk(16384, False)
        assert c == MAX_FLASH_T
        # the causal 16-chunk ceiling T now has a non-causal twin — the
        # r7 rejection (n*n = 256 unrolled pairs) is gone
        T_max = MAX_CHUNKS * MAX_FLASH_T
        assert pick_chunk(T_max, True) == MAX_FLASH_T
        assert pick_chunk(T_max, False) == MAX_FLASH_T
        for causal in (True, False):
            assert supports_chunked((1, 1, T_max, 64), causal=causal,
                                    dropout=0.0, mask=None)
        # every pick keeps the TRACE size inside the budget: causal
        # unrolls pairs, non-causal traces one kernel per q chunk
        for T in range(16384, 131072 + 1, 4096):
            for causal in (True, False):
                c = pick_chunk(T, causal)
                if c:
                    assert traced_tile_calls(T // c, causal) <= \
                        MAX_CHUNK_PAIRS
                    if causal:
                        assert chunk_pairs(T // c, True) <= MAX_CHUNK_PAIRS
                    else:
                        assert T // c <= MAX_CHUNKS

    def test_non_causal_scan_trace_count(self):
        """The non-causal jaxpr contains one forward kernel per q chunk
        (scan body traced once), not n^2: at n = 8 chunks the unrolled
        loop would trace 64 forward pallas calls."""
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention_lse,
        )

        n = 8
        q = jax.ShapeDtypeStruct((2, n * 128, 32), jnp.float32)
        jaxpr = jax.make_jaxpr(lambda q: chunked_flash_attention_lse(
            q, q, q, 1.0, False, chunk=128))(q)
        calls = str(jaxpr).count("pallas_call")
        assert calls <= 2 * n, f"{calls} traced pallas calls at n={n}"

    def test_explicit_non_causal_chunk_budget(self):
        from deeplearning4j_tpu.ops.flash_attention import (
            MAX_CHUNKS,
            chunked_flash_attention_lse,
        )

        q = jnp.zeros((1, 16384, 64), jnp.float32)
        # 16 non-causal chunks now fit (scanned kv loop, r8)...
        jax.eval_shape(lambda q: chunked_flash_attention_lse(
            q, q, q, 1.0, False, chunk=1024), q)
        # ...but the chunk-count ceiling still binds: 32 chunks raise
        assert 16384 // 512 > MAX_CHUNKS
        with pytest.raises(ValueError, match="kernel tiles"):
            jax.eval_shape(lambda q: chunked_flash_attention_lse(
                q, q, q, 1.0, False, chunk=512), q)
        # the same chunk count is INSIDE the causal budget (136 pairs)
        jax.eval_shape(lambda q: chunked_flash_attention_lse(
            q, q, q, 1.0, True, chunk=1024), q)

    def test_d_aware_tile_bound(self):
        """ADVICE r5 #2 closed in r8: D > 128 long-T has a supported
        chunked tier whose tile length shrinks with head_dim (the
        backward streams full-tile [T, D] K/V pairs, so the proven
        envelope is tile * D <= 8192 * 128 elements)."""
        from deeplearning4j_tpu.ops import autotune
        from deeplearning4j_tpu.ops.flash_attention import (
            MAX_FLASH_T,
            chunked_unsupported_reason,
            pick_chunk,
            supports_chunked,
            supports_monolithic_fallback,
        )

        assert autotune.max_tile_for_dim(None) == MAX_FLASH_T
        assert autotune.max_tile_for_dim(64) == MAX_FLASH_T
        assert autotune.max_tile_for_dim(128) == MAX_FLASH_T
        assert autotune.max_tile_for_dim(256) == 4096
        assert autotune.max_tile_for_dim(512) == 2048
        # D=256 long-T: tiles cap at 4096, so 16384 = 4 x 4096
        assert pick_chunk(16384, True, head_dim=256) == 4096
        big_d = (1, 2, 16384, 256)
        assert supports_chunked(big_d, causal=True, dropout=0.0, mask=None)
        assert supports_chunked(big_d, causal=False, dropout=0.0,
                                mask=None)
        # the monolithic fallback tier stays D <= 128 (measured there)
        assert not supports_monolithic_fallback(
            (1, 2, 12288, 256), causal=True, dropout=0.0, mask=None)
        # ...but the same shape is now CHUNK-supported at D-aware tiles
        assert supports_chunked((1, 2, 12288, 256), causal=True,
                                dropout=0.0, mask=None)
        # what remains unsupported says so with the D-aware bound named
        msg = chunked_unsupported_reason(25088, dropout=0.0, mask=None,
                                         causal=True, head_dim=256)
        assert "caps tiles at 4096" in msg

    def test_d_aware_chunked_executes(self):
        """A D > 128 config runs the chunked path end to end (values +
        grad vs the dense reference) — the shape class that had NO
        supported path before r8, exercised at a scaled-down T."""
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        B, H, T, D = 1, 1, 256, 160  # D > 128, T = 2 tiles of 128
        rng = np.random.default_rng(11)
        q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)) * 0.3,
                               jnp.float32) for _ in range(3))
        o_c = chunked_flash_attention(q, k, v, causal=True, chunk=128)
        o_d = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(o_c), np.asarray(o_d),
                                   atol=2e-5)
        g_c = jax.grad(lambda q: jnp.sum(jnp.sin(chunked_flash_attention(
            q, k, v, causal=True, chunk=128))))(q)
        g_d = jax.grad(lambda q: jnp.sum(jnp.sin(dot_product_attention(
            q, k, v, causal=True))))(q)
        np.testing.assert_allclose(np.asarray(g_c), np.asarray(g_d),
                                   atol=2e-4)

    @pytest.mark.parametrize("causal", [True, False])
    def test_masked_forward_matches_dense(self, causal):
        """Variable-length batches through the chunk loop: each kv tile
        sees its slice of the [B, T] key mask; valid rows match the
        dense masked path, fully-padded kv tiles are weighted away by
        the lse merge."""
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        B, T = 3, 512
        q, k, v = _qkv(B=B, T=T)
        # lengths straddle tile boundaries: full, mid-tile, one tile
        mask = _varlen_mask(B, T, [512, 300, 128])
        o_c = chunked_flash_attention(q, k, v, causal=causal, mask=mask,
                                      chunk=128)
        o_d = dot_product_attention(q, k, v, causal=causal, mask=mask)
        valid = np.asarray(mask, bool)
        for b in range(B):
            np.testing.assert_allclose(
                np.asarray(o_c)[b, :, valid[b]],
                np.asarray(o_d)[b, :, valid[b]], atol=2e-5)

    def test_masked_backward_matches_monolithic(self):
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        B, T = 2, 512
        q, k, v = _qkv(B=B, T=T, seed=7)
        mask = _varlen_mask(B, T, [512, 384])
        w = mask[:, None, :, None]  # loss reads valid rows only

        def f_chunked(q, k, v):
            return jnp.sum(jnp.sin(chunked_flash_attention(
                q, k, v, causal=True, mask=mask, chunk=128)) * w)

        def f_mono(q, k, v):
            return jnp.sum(jnp.sin(flash_attention(
                q, k, v, causal=True, mask=mask)) * w)

        g_c = jax.grad(f_chunked, (0, 1, 2))(q, k, v)
        g_m = jax.grad(f_mono, (0, 1, 2))(q, k, v)
        for a, b in zip(g_c, g_m):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_monolithic_fallback_tier(self):
        """T in (MAX_FLASH_T, MONOLITHIC_COMPILE_MAX] that the tile loop
        cannot take (mask/dropout, non-tileable length) keeps the
        monolithic kernels — the pre-r5 dispatch for those shapes must
        not regress to an error (measured: the backward compiles to
        14336 with 512-blocks; 15360 busts VMEM)."""
        from deeplearning4j_tpu.ops.flash_attention import (
            MONOLITHIC_COMPILE_MAX,
            pick_chunk,
            supports_chunked,
            supports_monolithic_fallback,
        )

        awkward = (2, 2, 8320, 64)  # 128-divisible, no 512+ tile divisor
        assert pick_chunk(8320) == 0
        assert not supports_chunked(awkward, causal=True, dropout=0.0,
                                    mask=None)
        assert supports_monolithic_fallback(awkward, causal=True,
                                            dropout=0.0, mask=None)
        # masked/dropout tileable T inside the ceiling also falls back
        masked = (2, 2, 12288, 64)
        assert supports_monolithic_fallback(masked, causal=True, dropout=0.1,
                                            mask=None)
        # beyond the ceiling nothing monolithic is claimed
        over = (2, 2, MONOLITHIC_COMPILE_MAX + 1024, 64)
        assert not supports_monolithic_fallback(over, causal=True,
                                                dropout=0.0, mask=None)

    def test_explicit_chunk_obeys_guards(self):
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        q, k, v = _qkv(T=512)
        # an explicit chunk that would unroll past MAX_CHUNKS is rejected
        with pytest.raises(ValueError, match="kernel tiles"):
            chunked_flash_attention(q, k, v, causal=True, chunk=16)
        # non-lane-multiple tiles are rejected even when count-legal
        with pytest.raises(ValueError, match="kernel tiles"):
            chunked_flash_attention(q, k, v, causal=True, chunk=64)

    def test_long_t_misconfig_raises_not_ooms(self):
        """An untileable long T must raise with instructions — the dense
        fallback would be a device OOM. Dropout is NOT a misconfig
        anymore (r6): the same layer config that raised in r5 now
        dispatches to the chunked path."""
        from deeplearning4j_tpu.nn.conf.layers import SelfAttentionLayer
        from deeplearning4j_tpu.nn.layers.attention import (
            SelfAttentionImpl,
        )
        from deeplearning4j_tpu.ops.flash_attention import MAX_FLASH_T

        T = 2 * MAX_FLASH_T
        conf = SelfAttentionLayer(n_in=16, n_out=16, n_heads=2, causal=True,
                                  weight_init="xavier",
                                  attention_dropout=0.5)
        impl = SelfAttentionImpl()
        params, state = impl.init(conf, jax.random.PRNGKey(0), jnp.float32)
        x = jnp.zeros((1, T, 16), jnp.float32)
        # dropout + long T traces through the chunked path end-to-end
        out, _ = jax.eval_shape(lambda p, s, x: impl.apply(
            conf, p, s, x, train=True, rng=jax.random.PRNGKey(1)),
            params, state, x)
        assert out.shape == x.shape
        conf2 = SelfAttentionLayer(n_in=16, n_out=16, n_heads=2, causal=True,
                                   weight_init="xavier")
        with pytest.raises(ValueError, match="cannot be tiled"):
            jax.eval_shape(lambda p, s, x: impl.apply(
                conf2, p, s, x, train=False, rng=None),
                params, state, jnp.zeros((1, 25088, 16), jnp.float32))
        # the untileable message names the monolithic fallback's head-dim
        # gate when T is inside its ceiling (ADVICE r5 #2): head_dim 256
        # at T=12288 is rejected by BOTH tiers and must say why
        conf3 = SelfAttentionLayer(n_in=512, n_out=512, n_heads=2,
                                   causal=True, weight_init="xavier")
        params3, state3 = impl.init(conf3, jax.random.PRNGKey(0),
                                    jnp.float32)
        with pytest.raises(ValueError, match="head_dim"):
            jax.eval_shape(lambda p, s, x: impl.apply(
                conf3, p, s, x, train=False, rng=None),
                params3, state3, jnp.zeros((1, 8320, 512), jnp.float32))


# ------------------------------------- chunk-invariant in-kernel dropout (r6)

class TestChunkInvariantDropout:
    """The r6 tentpole: the in-kernel keep mask hashes GLOBAL (q, k)
    coordinates, so the keep decision for logical element (bh, i, j) is
    identical whether attention runs monolithically, per-chunk, or
    per-ring-hop — dropout composes with the chunked long-context path
    at full rate instead of raising."""

    def test_keep_mask_bitwise_invariant_to_windowing(self):
        """Bit-for-bit acceptance at the tile-straddling length
        14336+BLOCK: _keep_mask evaluated over ANY window (origin, size)
        equals the corresponding slice of the dropout_keep_mask_host
        oracle at the full T — including windows that straddle the
        512-block grid and an odd tail. (_keep_mask is plain jnp outside
        pallas, so this runs the exact kernel hash at long T cheaply.)"""
        from deeplearning4j_tpu.ops.flash_attention import (
            BLOCK,
            MONOLITHIC_COMPILE_MAX,
            _keep_mask,
            dropout_keep_mask_host,
        )

        T = MONOLITHIC_COMPILE_MAX + BLOCK  # 14464
        seed, bh, rate = 987654321, 5, 0.3
        ref = dropout_keep_mask_host(seed, bh, T, rate)
        windows = [
            (0, 0, 512, 512),            # block-aligned head
            (13952, 640, 512, 512),      # tail x early-key straddle
            (14336, 14336, BLOCK, BLOCK),  # the odd 128 tail, diagonal
            (640, 13952, 256, 512),      # rectangular, unequal blocks
        ]
        for q0, k0, bq, bk in windows:
            got = np.asarray(_keep_mask(
                jnp.asarray(seed, jnp.int32), bh, 1, 1, q0, k0, bq, bk,
                T, rate))[0]
            np.testing.assert_array_equal(got, ref[q0:q0 + bq, k0:k0 + bk])

    def test_chunked_dropout_matches_monolithic(self):
        """Values AND gradients: chunked-with-dropout equals the
        monolithic dropout kernel at the same T/seed (identical keep
        mask; only lse-merge float reassociation differs). T=640
        straddles the 512 block cap, chunk=128 gives 5 tiles."""
        B, H, T, D = 1, 2, 640, 32
        rate = 0.2
        rng = np.random.default_rng(11)
        q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)),
                               jnp.float32) for _ in range(3))
        key = jax.random.PRNGKey(7)
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        def mono(q, k, v):
            return flash_attention(q, k, v, causal=True, dropout=rate,
                                   dropout_rng=key)

        def chunked(q, k, v):
            return chunked_flash_attention(q, k, v, causal=True, chunk=128,
                                           dropout=rate, dropout_rng=key)

        np.testing.assert_allclose(np.asarray(chunked(q, k, v)),
                                   np.asarray(mono(q, k, v)), atol=2e-5)
        gm = jax.grad(lambda q, k, v: jnp.sum(mono(q, k, v) ** 2),
                      (0, 1, 2))(q, k, v)
        gc = jax.grad(lambda q, k, v: jnp.sum(chunked(q, k, v) ** 2),
                      (0, 1, 2))(q, k, v)
        for a, b in zip(gc, gm):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4)

    def test_chunked_dropout_invariant_to_chunk_count(self):
        """The same (seed, bh, i, j) keeps/drops identically at chunk=128
        and chunk=256 — the mask depends on global coordinates only."""
        B, H, T, D = 1, 2, 512, 32
        rate = 0.25
        rng = np.random.default_rng(3)
        q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)),
                               jnp.float32) for _ in range(3))
        key = jax.random.PRNGKey(13)
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        outs = [chunked_flash_attention(q, k, v, causal=True, chunk=c,
                                        dropout=rate, dropout_rng=key)
                for c in (128, 256)]
        np.testing.assert_allclose(np.asarray(outs[0]), np.asarray(outs[1]),
                                   atol=2e-5)

    def test_chunked_dropout_matches_host_oracle_dense(self):
        """End-to-end mask identity: the chunked kernel path reproduces a
        dense reference applying the EXACT dropout_keep_mask_host oracle
        (the same oracle the monolithic dropout tests pin against)."""
        B, H, T, D = 2, 2, 512, 32
        rate = 0.2
        rng = np.random.default_rng(0)
        q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)),
                               jnp.float32) for _ in range(3))
        key = jax.random.PRNGKey(7)
        seed = int(jax.random.randint(key, (1, 1), 0, 2**31 - 1,
                                      dtype=jnp.int32)[0, 0])
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        ref = _dense_dropout_ref(q, k, v, seed, rate, T, H)
        out = chunked_flash_attention(q, k, v, causal=True, chunk=128,
                                      dropout=rate, dropout_rng=key)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_masked_chunked_dropout_matches_monolithic(self):
        """Padding masks AND dropout together through the chunk loop —
        the full long-context training feature set on one dispatch."""
        B, H, T, D = 2, 2, 512, 32
        rate = 0.15
        rng = np.random.default_rng(9)
        q, k, v = (jnp.asarray(rng.standard_normal((B, H, T, D)),
                               jnp.float32) for _ in range(3))
        mask = _varlen_mask(B, T, [T, 300])
        w = mask[:, None, :, None]
        key = jax.random.PRNGKey(21)
        from deeplearning4j_tpu.ops.flash_attention import (
            chunked_flash_attention,
        )

        def mono(q, k, v):
            return flash_attention(q, k, v, causal=True, mask=mask,
                                   dropout=rate, dropout_rng=key)

        def chunked(q, k, v):
            return chunked_flash_attention(q, k, v, causal=True, mask=mask,
                                           chunk=128, dropout=rate,
                                           dropout_rng=key)

        np.testing.assert_allclose(np.asarray(chunked(q, k, v) * w),
                                   np.asarray(mono(q, k, v) * w), atol=2e-5)
        gm = jax.grad(lambda q: jnp.sum((mono(q, k, v) * w) ** 2))(q)
        gc = jax.grad(lambda q: jnp.sum((chunked(q, k, v) * w) ** 2))(q)
        np.testing.assert_allclose(np.asarray(gc), np.asarray(gm),
                                   atol=2e-4)

    def test_layer_dispatches_dropout_to_chunked_path(self):
        """The r5 hard exclusion is gone at the LAYER level: a dropout
        config at T beyond the monolithic ceiling traces through the
        chunked dispatch (shape-level end-to-end; the seq-32768 value
        run is the transformer_lm_seq32768_dropout bench mode)."""
        from deeplearning4j_tpu.nn.conf.layers import SelfAttentionLayer
        from deeplearning4j_tpu.nn.layers.attention import SelfAttentionImpl
        from deeplearning4j_tpu.ops.flash_attention import (
            supports_chunked,
        )

        T = 32768
        assert supports_chunked((1, 2, T, 64), causal=True, dropout=0.1,
                                mask=None)
        conf = SelfAttentionLayer(n_in=128, n_out=128, n_heads=2,
                                  causal=True, weight_init="xavier",
                                  attention_dropout=0.1)
        impl = SelfAttentionImpl()
        params, state = impl.init(conf, jax.random.PRNGKey(0), jnp.float32)
        x = jnp.zeros((1, T, 128), jnp.float32)
        out, _ = jax.eval_shape(lambda p, s, x: impl.apply(
            conf, p, s, x, train=True, rng=jax.random.PRNGKey(1)),
            params, state, x)
        assert out.shape == x.shape
