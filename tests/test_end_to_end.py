"""The SURVEY.md §7 step-4 'minimum end-to-end slice': LeNet-5 on MNIST
through the builder API — fit, >=97% accuracy, checkpoint/resume, score
listener — plus cloud dataset IO (datasets/cloud.py) and the profiler
listener window (util/profiler.py)."""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.datasets.cloud import (
    GcsDataSetIterator,
    GcsDownloader,
    GcsUploader,
    load_dataset,
    save_dataset,
)
from deeplearning4j_tpu.datasets.mnist import MnistDataSetIterator
from deeplearning4j_tpu.models.lenet import lenet5
from deeplearning4j_tpu.optimize.listeners import CollectScoresIterationListener
from deeplearning4j_tpu.util.model_serializer import ModelSerializer


@pytest.mark.slow
def test_lenet_mnist_end_to_end_slice(tmp_path):
    train_it = MnistDataSetIterator(batch_size=128, num_examples=2048,
                                    train=True, reshape_images=True,
                                    shuffle=True, seed=7)
    test_it = MnistDataSetIterator(batch_size=256, num_examples=512,
                                   train=False, reshape_images=True)
    net = lenet5(learning_rate=2e-3)
    net.init()
    collector = CollectScoresIterationListener(frequency=1)
    net.set_listeners(collector)
    net.fit(train_it, epochs=4)
    assert collector.scores[-1][1] < collector.scores[0][1]
    ev = net.evaluate(test_it)
    acc = ev.accuracy()
    assert acc >= 0.97, f"end-to-end slice accuracy {acc} < 0.97"

    # checkpoint / resume
    path = str(tmp_path / "lenet.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_multi_layer_network(path)
    test_it.reset()
    ev2 = restored.evaluate(test_it)
    assert abs(ev2.accuracy() - acc) < 1e-9


def test_cloud_dataset_round_trip(tmp_path):
    rng = np.random.default_rng(0)
    bucket = tmp_path / "bucket"
    os.makedirs(bucket)
    up = GcsUploader()
    for i in range(3):
        ds = DataSet(rng.random((8, 4)).astype(np.float32),
                     np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
        local = str(tmp_path / f"part{i}.npz")
        save_dataset(ds, local)
        up.upload(local, str(bucket / f"part{i}.npz"))

    it = GcsDataSetIterator(str(bucket))
    n, batches = 0, 0
    it.reset()
    while it.has_next():
        b = it.next()
        assert b.features.shape == (8, 4)
        n += b.num_examples()
        batches += 1
    assert (n, batches) == (24, 3)
    # local passthrough download + masks round trip
    ds = DataSet(rng.random((4, 3)).astype(np.float32),
                 rng.random((4, 2)).astype(np.float32),
                 features_mask=np.ones((4,), np.float32))
    p = str(tmp_path / "masked.npz")
    save_dataset(ds, p)
    back = load_dataset(GcsDownloader().download(p))
    np.testing.assert_allclose(back.features, ds.features)
    assert back.features_mask is not None


def test_cloud_iterator_empty_prefix_raises(tmp_path):
    with pytest.raises(IOError):
        GcsDataSetIterator(str(tmp_path))


@pytest.mark.slow
def test_profiler_listener_window(tmp_path):
    from deeplearning4j_tpu.util.profiler import ProfilerIterationListener

    lst = ProfilerIterationListener(str(tmp_path), start_iteration=2,
                                   n_iterations=2)

    class M:
        score_value = 0.0

    for i in range(1, 7):
        lst.iteration_done(M(), i)
    assert lst.done
    # a trace directory was produced
    assert any(os.scandir(str(tmp_path)))
