"""Fused Pallas LayerNorm (ops/fused_layernorm.py) vs the jnp reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.fused_layernorm import (
    fused_layer_norm,
    supports,
)


def _ref(x, g, b, eps=1e-5):
    mu = jnp.mean(x, -1, keepdims=True)
    var = jnp.var(x, -1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


@pytest.mark.parametrize("shape", [(4, 64, 256), (32, 128), (2, 8, 384)])
def test_forward_and_grads_match_reference(shape):
    rng = np.random.default_rng(0)
    C = shape[-1]
    x = jnp.asarray(rng.standard_normal(shape) * 2 + 1, jnp.float32)
    g = jnp.asarray(rng.standard_normal(C) * 0.5 + 1, jnp.float32)
    b = jnp.asarray(rng.standard_normal(C) * 0.1, jnp.float32)
    assert supports(shape)
    np.testing.assert_allclose(
        np.asarray(fused_layer_norm(x, g, b, 1e-5)),
        np.asarray(_ref(x, g, b)), atol=2e-5)
    gr = jax.grad(lambda *a: jnp.sum(jnp.sin(_ref(*a))), (0, 1, 2))(x, g, b)
    gf = jax.grad(lambda *a: jnp.sum(jnp.sin(fused_layer_norm(*a, 1e-5))),
                  (0, 1, 2))(x, g, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r), atol=2e-4)


def test_bf16_path():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 256)), jnp.bfloat16)
    g = jnp.ones(256, jnp.bfloat16)
    b = jnp.zeros(256, jnp.bfloat16)
    y = fused_layer_norm(x, g, b, 1e-5)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(y, np.float32),
        np.asarray(_ref(x.astype(jnp.float32), 1.0, 0.0), np.float32),
        atol=2e-2)


def test_supports_envelope():
    assert supports((32, 512, 256))
    assert not supports((32, 512, 200))   # C not lane-tile
    assert not supports((3, 256))         # N % 8
    assert not supports((256,))           # needs a batch dim
    # bn must be lane-tile or full-N for the stat rows
    assert supports((8, 256))             # bn == N == 8
    assert not supports((24, 256))        # bn=8, N=24: illegal stat block
