"""Fused softmax cross-entropy head (ops/fused_softmax_xent.py) vs the
dense log_softmax reference — forward, all three gradients, vocab padding,
3D (rnn) shapes, and the OutputImpl dispatch gate. Runs the same Pallas
kernels in interpret mode on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deeplearning4j_tpu.ops.fused_softmax_xent as fsx
from deeplearning4j_tpu.ops.fused_softmax_xent import softmax_xent_head


def _ref(x, w, b, lab):
    z = x @ w + b
    logp = jax.nn.log_softmax(z, axis=-1)
    return -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]


@pytest.fixture
def head():
    rng = np.random.default_rng(7)
    N, d, V = 256, 128, 2500  # V % BLOCK_V != 0 -> exercises padding
    x = jnp.asarray(rng.standard_normal((N, d)), jnp.float32)
    w = jnp.asarray(0.05 * rng.standard_normal((d, V)), jnp.float32)
    b = jnp.asarray(0.01 * rng.standard_normal((V,)), jnp.float32)
    lab = jnp.asarray(rng.integers(0, V, (N,)), jnp.int32)
    return x, w, b, lab


def test_forward_matches_dense(head):
    x, w, b, lab = head
    np.testing.assert_allclose(
        softmax_xent_head(x, w, b, lab), _ref(x, w, b, lab),
        rtol=1e-5, atol=1e-5)


def test_gradients_match_dense(head):
    x, w, b, lab = head
    gf = jax.grad(lambda x, w, b: softmax_xent_head(x, w, b, lab).mean(),
                  argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(lambda x, w, b: _ref(x, w, b, lab).mean(),
                  argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(a, r, rtol=1e-4, atol=1e-6)


def test_3d_shape_matches_flat(head):
    x, w, b, lab = head
    p2 = softmax_xent_head(x, w, b, lab)
    p3 = softmax_xent_head(x.reshape(8, 32, -1), w, b, lab.reshape(8, 32))
    np.testing.assert_allclose(p3.ravel(), p2, rtol=1e-6)


def test_output_layer_dispatch_parity():
    """A small LM scores identically through the fused head and the stock
    mcxent path (same params, f32, CPU interpret)."""
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.datasets.api import DataSet

    rng = np.random.default_rng(3)
    vocab, seq, batch = 2048, 128, 2
    toks = np.asarray(rng.integers(0, vocab, (batch, seq)), np.int32)
    ds = DataSet(toks, np.roll(toks, -1, axis=1))

    def build_and_score(force):
        fsx.FORCE_FUSED = force
        try:
            net = transformer_lm(vocab_size=vocab, d_model=128, n_heads=2,
                                 n_layers=1, d_ff=256, max_length=seq)
            net.init()
            return net.score(ds)
        finally:
            fsx.FORCE_FUSED = None

    s_fused = build_and_score(True)
    s_dense = build_and_score(False)
    assert np.isclose(s_fused, s_dense, rtol=1e-5), (s_fused, s_dense)


def test_ragged_row_count_padded(head):
    """N not a multiple of 128 (e.g. a final partial batch): rows are
    padded to the grid internally and padded entries never leak into the
    loss or the gradients."""
    x, w, b, lab = head
    n = 200
    xs, ls = x[:n], lab[:n]
    np.testing.assert_allclose(
        softmax_xent_head(xs, w, b, ls), _ref(xs, w, b, ls),
        rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda w: softmax_xent_head(xs, w, b, ls).mean())(w)
    gr = jax.grad(lambda w: _ref(xs, w, b, ls).mean())(w)
    np.testing.assert_allclose(gf, gr, rtol=1e-4, atol=1e-6)


def test_supports_gate():
    assert fsx.supports(256, 128, 4096)
    assert not fsx.supports(256, 128, 512)      # small vocab: dense fuses fine
    assert fsx.supports(250, 128, 4096)         # ragged N pads internally
    assert not fsx.supports(256, 130, 4096)     # ragged d
    assert not fsx.supports(256, 2048, 4096)    # d too big for VMEM scratch
