"""Bucketed async gradient allreduce (ISSUE 7 tentpole): the
`parallel/overlap.py` bucket planner and the
`make_train_step(..., overlap=BucketPlan)` path.

Coverage contract (the ISSUE's bucket-planning satellite):
- partition DETERMINISM across ranks (the plan is pure structure — the
  same under simulated process_index 0 vs 1, so every rank issues the
  identical per-bucket collective sequence);
- EXACT COVER of the grads pytree (no leaf dropped or duplicated, sizes
  add up, reverse layer order);
- NUMERICAL EQUIVALENCE of bucketed vs monolithic reduction (both
  reduce modes), and of the full overlap train step vs the unbucketed
  GSPMD step at tight atol — including composed with ZeRO-1.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.analysis import collective_audit
from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.parallel.overlap import (
    bucketed_reduce,
    plan_buckets,
    reduce_gradients,
)
from deeplearning4j_tpu.util.compat import shard_map
from tests.cluster_worker import build_net, full_data

N_DEV = 8


def _tree(seed=0):
    """A layered grads-shaped pytree with mixed dtypes and sizes."""
    rng = np.random.default_rng(seed)
    return {
        "layer_0": {"W": rng.standard_normal((6, 8)).astype(np.float32),
                    "b": rng.standard_normal(8).astype(np.float32)},
        "layer_1": {"W": rng.standard_normal((8, 16)).astype(np.float32),
                    "b": rng.standard_normal(16).astype(np.float32)},
        "layer_2": {"W": rng.standard_normal((16, 3)).astype(np.float32),
                    "b": rng.standard_normal(3).astype(np.float32)},
    }


LAYERS = ["layer_0", "layer_1", "layer_2"]


# ---------------------------------------------------------------- planning

def test_plan_exactly_covers_the_tree():
    tree = _tree()
    plan = plan_buckets(tree, bucket_bytes=128, layer_order=LAYERS)
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    all_paths = sorted(jax.tree_util.keystr(p) for p, _ in flat)
    # no leaf dropped or duplicated
    assert sorted(plan.leaf_paths()) == all_paths
    assert plan.n_leaves == len(flat)
    assert plan.n_elements == sum(l.size for _, l in flat)
    # per-bucket byte accounting at the f32 reduction dtype
    for b in plan.buckets:
        assert b.n_bytes == b.n_elements * 4


def test_plan_is_reverse_layer_ordered_and_size_targeted():
    tree = _tree()
    plan = plan_buckets(tree, bucket_bytes=128, layer_order=LAYERS)
    # the FIRST bucket holds the LAST layer's gradients (they finish
    # backward first, so they reduce first)
    assert all("layer_2" in p for p in plan.buckets[0].paths)
    last = [p for p in plan.buckets[-1].paths]
    assert all("layer_0" in p for p in last)
    # size target respected except single oversized leaves
    for b in plan.buckets:
        assert b.n_bytes <= 128 or len(b.paths) == 1
    # one giant bucket when the target exceeds the model
    assert len(plan_buckets(tree, bucket_bytes=1 << 30,
                            layer_order=LAYERS).buckets) == 1


def test_plan_is_deterministic_across_simulated_ranks():
    tree = _tree()
    plans = []
    for pid in (0, 1):
        with collective_audit.simulated_process_index(pid):
            plans.append(plan_buckets(tree, bucket_bytes=96,
                                      layer_order=LAYERS))
    assert plans[0] == plans[1]
    assert plans[0] == plan_buckets(tree, bucket_bytes=96,
                                    layer_order=LAYERS)


def test_plan_rejects_bad_inputs():
    with pytest.raises(ValueError, match="mode"):
        plan_buckets(_tree(), mode="allreduce")
    with pytest.raises(ValueError, match="positive"):
        plan_buckets(_tree(), bucket_bytes=0)
    with pytest.raises(ValueError, match="empty"):
        plan_buckets({})


def test_plan_summary_is_telemetry_ready():
    plan = plan_buckets(_tree(), bucket_bytes=128, layer_order=LAYERS)
    s = plan.summary()
    assert s["n_buckets"] == len(plan.buckets) and s["mode"] == "psum"
    assert [b["index"] for b in s["buckets"]] == list(range(s["n_buckets"]))
    assert sum(b["bytes"] for b in s["buckets"]) == plan.n_elements * 4


# --------------------------------------------------------------- reduction

def _reduce_on_mesh(tree, plan, mesh):
    """Run bucketed_reduce under shard_map with each replica holding
    `tree * (rank+1)` — the expected mean is tree * mean(1..n)."""
    def body(t):
        r = jax.lax.axis_index("data").astype(jnp.float32) + 1.0
        scaled = jax.tree.map(lambda l: l * r.astype(l.dtype), t)
        return bucketed_reduce(scaled, plan, axis_name="data")

    fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                   check_vma=False, axis_names={"data"})
    return jax.jit(fn)(tree)


@pytest.mark.parametrize("mode", ["psum", "psum_scatter"])
@pytest.mark.parametrize("bucket_bytes", [64, 96, 1 << 30])
def test_bucketed_reduce_matches_monolithic_mean(mode, bucket_bytes):
    mesh = make_mesh({"data": N_DEV})
    tree = _tree()
    plan = plan_buckets(tree, bucket_bytes=bucket_bytes,
                        layer_order=LAYERS, mode=mode)
    got = _reduce_on_mesh(tree, plan, mesh)
    scale = np.mean(np.arange(1, N_DEV + 1))
    want = jax.tree.map(lambda l: l * scale, tree)
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(g), w, atol=1e-5, rtol=1e-5)


def test_bucketed_reduce_rejects_mismatched_plan():
    mesh = make_mesh({"data": N_DEV})
    tree = _tree()
    plan = plan_buckets({"other": {"W": np.zeros((4, 4), np.float32)}})
    with pytest.raises(ValueError, match="does not cover"):
        _reduce_on_mesh(tree, plan, mesh)


def test_reduce_gradients_is_a_whole_tree_pmean():
    """The unbucketed blessed helper (sequence_parallel's routing) keeps
    the single multi-operand psum eqn per axis — the frozen SP collective
    signature depends on it."""
    mesh = make_mesh({"data": N_DEV})
    tree = _tree()

    def body(t):
        return reduce_gradients(t, "data")

    fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                   check_vma=False, axis_names={"data"})
    closed = jax.make_jaxpr(fn)(tree)
    sig = collective_audit.jaxpr_collectives(closed)
    assert len([s for s in sig if s.startswith("psum@data")]) == 1
    got = jax.jit(fn)(tree)
    for g, w in zip(jax.tree.leaves(got), jax.tree.leaves(tree)):
        np.testing.assert_allclose(np.asarray(g), w, atol=1e-6)


def test_bucket_collective_sequence_is_one_psum_per_bucket():
    """The jaxpr-visible contract behind the stage-3 entry: the overlap
    reduction issues exactly len(buckets) gradient psums, in plan
    order, each over the bucket's flat f32 vector."""
    mesh = make_mesh({"data": N_DEV})
    tree = _tree()
    plan = plan_buckets(tree, bucket_bytes=128, layer_order=LAYERS)

    def body(t):
        return bucketed_reduce(t, plan, axis_name="data")

    fn = shard_map(body, mesh=mesh, in_specs=(P(),), out_specs=P(),
                   check_vma=False, axis_names={"data"})
    sig = collective_audit.jaxpr_collectives(jax.make_jaxpr(fn)(tree))
    psums = [s for s in sig if s.startswith("psum@data")]
    assert len(psums) == len(plan.buckets)
    sizes = [int(s.split("[")[1].rstrip("]")) for s in psums]
    assert sizes == [b.n_elements for b in plan.buckets]


# ------------------------------------------------------- train-step parity

def _one_step(net, overlap=None, zero1=False):
    mesh = make_mesh({"data": N_DEV})
    net.set_mesh(mesh, zero1=zero1, overlap=overlap)
    x, y = full_data()
    net.fit(DataSet(x, y))
    return np.asarray(net.params_flat())


@pytest.mark.parametrize("bucket_bytes", [128, 1 << 30])
def test_overlap_step_matches_monolithic_step(bucket_bytes):
    """Bucketed-vs-unbucketed numerical equivalence through the REAL
    set_mesh/fit path: same seed, same batch, one step each — params
    agree at tight atol (f32 reduction-order freedom only)."""
    ref = _one_step(build_net().init())
    got = _one_step(build_net().init(), overlap=bucket_bytes)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_overlap_step_is_deterministic():
    a = _one_step(build_net().init(), overlap=128)
    b = _one_step(build_net().init(), overlap=128)
    assert np.array_equal(a, b)


def test_overlap_composes_with_zero1():
    """overlap + zero1: the bucketed reduction runs in shard_map, the
    sharded weight update stays with GSPMD — same params as the
    monolithic zero1 step."""
    ref = _one_step(build_net().init(), zero1=True)
    got = _one_step(build_net().init(), overlap=128, zero1=True)
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_overlap_rides_the_scanned_fit_path():
    """fit_scanned reuses _get_train_step, so the overlap step must
    scan: one fused epoch over two batches."""
    net = build_net().init()
    net.set_mesh(make_mesh({"data": N_DEV}), overlap=128)
    x, y = full_data()
    net.fit_scanned([DataSet(x[:16], y[:16]), DataSet(x[16:], y[16:])],
                    epochs=2)
    assert net.iteration_count == 4
    assert np.isfinite(net.score_value)


def test_overlap_rejects_non_data_roles_and_tbptt():
    net = build_net().init()
    mesh = make_mesh({"data": 4, "model": 2})
    with pytest.raises(ValueError, match="'data' role only"):
        net.set_mesh(mesh, axes={"data": "data", "model": "model"},
                     overlap=True)
    with pytest.raises(ValueError, match="requires a mesh"):
        net.set_mesh(None, overlap=True)

    from deeplearning4j_tpu.nn.conf import (
        NeuralNetConfiguration,
        RnnOutputLayer,
    )
    from deeplearning4j_tpu.nn.conf.layers import LSTM
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(LSTM(n_in=3, n_out=4))
            .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .backprop_type("truncated_bptt")
            .t_bptt_forward_length(2).t_bptt_backward_length(2)
            .build())
    tb = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="TRUNCATED_BPTT"):
        tb.set_mesh(make_mesh({"data": N_DEV}), overlap=True)


def test_trainer_overlap_arm_matches_reference():
    """The bench's overlap arm end-to-end: DataParallelTrainer(...,
    overlap=...) over sharded batches equals the single-device
    full-batch step (gradient linearity, same seed)."""
    from deeplearning4j_tpu.parallel.data_parallel import DataParallelTrainer

    x, y = full_data()
    net = build_net().init()
    DataParallelTrainer(net, make_mesh({"data": N_DEV}),
                        overlap=128).fit(DataSet(x, y))
    ref = build_net().init()
    ref.fit(DataSet(x, y))
    np.testing.assert_allclose(np.asarray(net.params_flat()),
                               np.asarray(ref.params_flat()), atol=1e-5)
