"""NLP stack tests — modeled on the reference's test strategy (SURVEY.md §4
item 6): Word2Vec end-to-end nearest-neighbor sanity, serializer
round-trips, vocab construction, tokenizer/iterator unit tests.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.text import (
    BasicLineIterator,
    CollectionSentenceIterator,
    CommonPreprocessor,
    DefaultTokenizerFactory,
    EndingPreProcessor,
    LabelAwareListSentenceIterator,
    NGramTokenizer,
    PrefetchingSentenceIterator,
    SentenceTransformer,
    get_stop_words,
    input_homogenization,
    windows,
)
from deeplearning4j_tpu.nlp.vocab import (
    Huffman,
    VocabConstructor,
    VocabWord,
    unigram_table,
    sample_negatives,
)


# --------------------------------------------------------------- fixtures
def synthetic_corpus(rng, n_sentences=300):
    """Two word 'clusters' that co-occur within, never across — embeddings
    must place same-cluster words closer (Word2VecTestsSmall analogue)."""
    animals = ["cat", "dog", "mouse", "horse", "cow", "sheep"]
    tech = ["cpu", "gpu", "ram", "disk", "cache", "bus"]
    sents = []
    for _ in range(n_sentences):
        pool = animals if rng.random() < 0.5 else tech
        sents.append(" ".join(rng.choice(pool, size=8)))
    return sents, animals, tech


# ------------------------------------------------------------- tokenizers
def test_default_tokenizer_and_preprocessor():
    f = DefaultTokenizerFactory()
    f.set_token_pre_processor(CommonPreprocessor())
    toks = f.create("Hello, World! 42 times").get_tokens()
    assert toks == ["hello", "world", "times"]


def test_ngram_tokenizer():
    toks = NGramTokenizer("a b c", 1, 2).get_tokens()
    assert "a b" in toks and "b c" in toks and "a" in toks


def test_ending_preprocessor():
    p = EndingPreProcessor()
    assert p.pre_process("running") == "runn"
    assert p.pre_process("cats") == "cat"


def test_input_homogenization():
    assert input_homogenization("Héllo, Wörld!") == "hello world"


def test_windows():
    ws = windows(["a", "b", "c", "d", "e"], window_size=4)
    assert len(ws) == 5
    assert ws[0].focus_word() == "a"
    assert ws[2].words == ["a", "b", "c", "d", "e"]


# -------------------------------------------------------------- iterators
def test_basic_line_iterator(tmp_path):
    p = tmp_path / "corpus.txt"
    p.write_text("line one\nline two\nline three\n")
    it = BasicLineIterator(str(p))
    assert list(it) == ["line one", "line two", "line three"]
    it.reset()
    assert it.next_sentence() == "line one"


def test_prefetching_iterator():
    base = CollectionSentenceIterator([f"s{i}" for i in range(100)])
    it = PrefetchingSentenceIterator(base, buffer_size=8)
    assert sorted(list(it)) == sorted(f"s{i}" for i in range(100))


def test_label_aware_iterator():
    it = LabelAwareListSentenceIterator(["doc a", "doc b"], ["pos", "neg"])
    docs = list(it)
    assert [d.labels[0] for d in docs] == ["pos", "neg"]
    assert it.get_labels_source().get_labels() == ["pos", "neg"]


# ------------------------------------------------------------------ vocab
def test_vocab_constructor_counts_and_filter():
    seqs = [["a", "b", "a"], ["a", "c"], ["b", "a"]]
    cache = (VocabConstructor(min_word_frequency=2)
             .add_source(seqs).build_joint_vocabulary())
    assert cache.index_of("a") == 0  # most frequent first
    assert cache.word_frequency("a") == 4
    assert not cache.contains_word("c")  # filtered at min freq 2


def test_huffman_codes_prefix_free():
    words = [VocabWord(w, c) for w, c in
             [("a", 100), ("b", 50), ("c", 20), ("d", 10), ("e", 5)]]
    Huffman(words).build()
    codes = {w.word: "".join(map(str, w.code)) for w in words}
    # frequent words get shorter codes
    assert len(codes["a"]) <= len(codes["e"])
    # prefix-free property
    vals = list(codes.values())
    for i, a in enumerate(vals):
        for j, b in enumerate(vals):
            if i != j:
                assert not b.startswith(a)


def test_unigram_table_sampling_distribution():
    seqs = [["common"] * 90 + ["rare"] * 10]
    cache = VocabConstructor().add_source(seqs).build_joint_vocabulary()
    cum = unigram_table(cache)
    rng = np.random.default_rng(0)
    draws = sample_negatives(cum, (10000,), rng)
    frac_common = (draws == cache.index_of("common")).mean()
    # 90^.75 : 10^.75 ≈ 0.84 : 0.16
    assert 0.75 < frac_common < 0.92


# --------------------------------------------------------------- word2vec
def test_word2vec_cluster_similarity(rng):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents, animals, tech = synthetic_corpus(rng)
    w2v = (Word2Vec.builder()
           .iterate(sents)
           .layer_size(24).window_size(3).min_word_frequency(1)
           .epochs(4).seed(7).negative_sample(5).batch_size(512)
           .build())
    w2v.fit()
    assert w2v.vocab_size == 12
    within = w2v.similarity("cat", "dog")
    across = w2v.similarity("cat", "gpu")
    assert within > across, (within, across)
    nearest = w2v.words_nearest("cpu", 3)
    assert all(w in ("gpu", "ram", "disk", "cache", "bus") for w in nearest)


def test_word2vec_hierarchical_softmax(rng):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents, animals, tech = synthetic_corpus(rng, 200)
    w2v = (Word2Vec.builder().iterate(sents).layer_size(16).window_size(3)
           .epochs(3).seed(3).negative_sample(0).use_hierarchic_softmax()
           .batch_size(256).build())
    w2v.fit()
    assert w2v.similarity("cat", "horse") > w2v.similarity("cat", "disk")


def test_word2vec_cbow(rng):
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents, _, _ = synthetic_corpus(rng, 200)
    w2v = (Word2Vec.builder().iterate(sents).layer_size(16).window_size(3)
           .epochs(3).seed(3).elements_learning_algorithm("CBOW")
           .batch_size(256).build())
    w2v.fit()
    assert w2v.similarity("cow", "sheep") > w2v.similarity("cow", "cache")


# ------------------------------------------------------------- serializer
def test_serializer_roundtrips(tmp_path, rng):
    from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents, _, _ = synthetic_corpus(rng, 60)
    w2v = (Word2Vec.builder().iterate(sents).layer_size(8).epochs(1)
           .batch_size(128).build())
    w2v.fit()

    txt = str(tmp_path / "vecs.txt")
    WordVectorSerializer.write_word_vectors(w2v, txt)
    loaded = WordVectorSerializer.load_txt_vectors(txt)
    np.testing.assert_allclose(loaded.get_word_vector("cat"),
                               w2v.get_word_vector("cat"), atol=1e-5)

    binp = str(tmp_path / "vecs.bin")
    WordVectorSerializer.write_binary(w2v, binp)
    loaded2 = WordVectorSerializer.load_google_model(binp)
    np.testing.assert_allclose(loaded2.get_word_vector("dog"),
                               w2v.get_word_vector("dog"), atol=1e-6)

    full = str(tmp_path / "model.zip")
    WordVectorSerializer.write_full_model(w2v, full)
    loaded3 = WordVectorSerializer.read_full_model(full)
    assert loaded3.vocab.num_words() == w2v.vocab.num_words()
    np.testing.assert_allclose(np.asarray(loaded3.lookup_table.syn0),
                               np.asarray(w2v.lookup_table.syn0), atol=1e-6)
    assert loaded3.similarity("cat", "dog") == pytest.approx(
        w2v.similarity("cat", "dog"), abs=1e-5)


# ----------------------------------------------------- paragraph vectors
def test_paragraph_vectors_dbow(rng):
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

    sents, animals, tech = synthetic_corpus(rng, 200)
    labels = ["animal" if any(w in s.split() for w in animals) else "tech"
              for s in sents]
    pv = ParagraphVectors(layer_size=24, window_size=3, epochs=4, seed=5,
                          negative=5, batch_size=512)
    pv.fit(sents, labels)
    assert set(pv.labels) == {"animal", "tech"}
    assert (pv.similarity_to_label(["cat", "dog"], "animal")
            > pv.similarity_to_label(["cat", "dog"], "tech"))
    vec = pv.infer_vector("cat dog mouse")
    assert vec.shape == (24,) and np.isfinite(vec).all()
    assert pv.nearest_labels("cat dog horse cow", 1)[0] == "animal"


# ------------------------------------------------------------------ glove
def test_glove_cluster_similarity(rng):
    from deeplearning4j_tpu.nlp.glove import Glove

    sents, _, _ = synthetic_corpus(rng, 300)
    glove = Glove(layer_size=16, window_size=5, epochs=15, seed=11,
                  batch_size=1024)
    glove.fit([s.split() for s in sents])
    assert glove.similarity("cat", "dog") > glove.similarity("cat", "gpu")


def test_glove_mesh_matches_single_device(rng):
    """Distributed GloVe (triples sharded over the mesh 'data' axis) is an
    exact redistribution of the same scan — same seeds, same updates up to
    float reassociation."""
    from deeplearning4j_tpu.nlp.glove import Glove
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    sents, _, _ = synthetic_corpus(rng, 200)
    corpus = [s.split() for s in sents]

    single = Glove(layer_size=8, window_size=4, epochs=4, seed=3,
                   batch_size=256)
    single.fit(corpus)
    meshed = Glove(layer_size=8, window_size=4, epochs=4, seed=3,
                   batch_size=256, device_mesh=make_mesh({"data": 4}))
    meshed.fit(corpus)

    for a, b in [("cat", "dog"), ("cat", "gpu"), ("dog", "mouse")]:
        np.testing.assert_allclose(single.similarity(a, b),
                                   meshed.similarity(a, b),
                                   rtol=1e-3, atol=1e-3)


# ------------------------------------------------------------------ tfidf
def test_tfidf_and_bow_vectorizers():
    from deeplearning4j_tpu.nlp.bagofwords import (
        BagOfWordsVectorizer,
        TfidfVectorizer,
    )

    docs = ["the cat sat", "the dog ran", "the cat ran home"]
    bow = BagOfWordsVectorizer().fit(docs)
    v = bow.transform("cat cat dog")
    assert v[bow.vocab.index_of("cat")] == 2
    assert v[bow.vocab.index_of("dog")] == 1

    tfidf = TfidfVectorizer().fit(docs)
    v2 = tfidf.transform("the cat")
    # 'the' appears in every doc → idf 0; 'cat' in 2 of 3 → positive
    assert v2[tfidf.vocab.index_of("the")] == 0.0
    assert v2[tfidf.vocab.index_of("cat")] > 0.0

    ds = tfidf.vectorize(docs, ["a", "b", "a"])
    assert ds.features.shape[0] == 3 and ds.labels.shape == (3, 2)


def test_stop_words():
    assert "the" in get_stop_words()


def test_sentence_transformer_filters_stops():
    st = SentenceTransformer(
        CollectionSentenceIterator(["the cat sat on the mat"]),
        stop_words=get_stop_words())
    assert list(st) == [["cat", "sat", "mat"]]


# ------------------------------------------------- review-fix regressions
def test_alpha_decays_across_epochs(rng):
    """Learning rate must decay over the WHOLE run, not reset per epoch."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents, _, _ = synthetic_corpus(rng, 50)
    w2v = (Word2Vec.builder().iterate(sents).layer_size(8).epochs(5)
           .batch_size(64).learning_rate(0.025).build())
    seqs = [list(s) for s in w2v._sequences()]
    w2v.build_vocab(seqs)
    alphas = []
    orig = w2v._alpha
    w2v._alpha = lambda d, t: alphas.append(orig(d, t)) or orig(d, t)
    w2v.fit(seqs)
    assert alphas[-1] < 0.3 * alphas[0]  # decays well past 1/epochs


def test_paragraph_vectors_hs_infer(rng):
    """infer_vector must work on hierarchical-softmax models."""
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

    sents, animals, _ = synthetic_corpus(rng, 100)
    labels = ["animal" if any(w in s.split() for w in animals) else "tech"
              for s in sents]
    pv = ParagraphVectors(layer_size=16, window_size=3, epochs=3, seed=5,
                          negative=0, batch_size=256)  # HS mode
    pv.fit(sents, labels)
    vec = pv.infer_vector("cat dog mouse")
    assert vec.shape == (16,) and np.isfinite(vec).all()
    assert np.abs(vec).sum() > 0


def test_paragraph_vectors_train_words_kwarg(rng):
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

    pv = ParagraphVectors(layer_size=8, train_words=False)
    assert pv.train_words is False


def test_words_nearest_with_many_labels(rng):
    """Label rows must not crowd words out of words_nearest results."""
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

    sents, _, _ = synthetic_corpus(rng, 80)
    pv = ParagraphVectors(layer_size=8, window_size=3, epochs=2, seed=5,
                          batch_size=128)
    pv.fit(sents)  # auto DOC_i label per sentence → 80 label rows vs 12 words
    out = pv.words_nearest("cat", 5)
    assert len(out) == 5
    assert all(pv.vocab.contains_word(w) for w in out)


def test_hs_model_resumes_after_reload(tmp_path, rng):
    from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    sents, _, _ = synthetic_corpus(rng, 40)
    w2v = (Word2Vec.builder().iterate(sents).layer_size(8).epochs(1)
           .negative_sample(0).use_hierarchic_softmax().batch_size(64)
           .build())
    w2v.fit()
    p = str(tmp_path / "hs.zip")
    WordVectorSerializer.write_full_model(w2v, p)
    loaded = WordVectorSerializer.read_full_model(p)
    loaded.fit([s.split() for s in sents[:10]])  # continue training
    assert np.isfinite(np.asarray(loaded.lookup_table.syn0)).all()


def test_prefetching_reset_no_race():
    from deeplearning4j_tpu.nlp.text import (CollectionSentenceIterator,
                                             PrefetchingSentenceIterator)

    base = CollectionSentenceIterator([f"s{i}" for i in range(50)])
    it = PrefetchingSentenceIterator(base, buffer_size=4)
    for _ in range(5):
        it.next_sentence()
    it.reset()  # mid-stream reset while producer is active
    out = list(it)
    assert sorted(out) == sorted(f"s{i}" for i in range(50))
