"""Networks as layers + stored-state activation (VERDICT r2 #8).

Reference anchors: MultiLayerNetwork `implements ... Layer`
(nn/multilayer/MultiLayerNetwork.java:78) so networks nest;
rnnActivateUsingStoredState (MultiLayerNetwork.java:2203) activates a full
sequence from the streaming state map.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.gradientcheck import GradientCheckUtil
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers.nested import NetworkLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _inner_mln_conf(n_in=4, n_out=6, seed=11, dtype="float32"):
    """A small MLN used AS A LAYER (no output/loss layer — pure stack).
    The inner conf controls its own compute/param dtype."""
    return (NeuralNetConfiguration.builder().seed(seed).learning_rate(0.1)
            .updater("sgd").dtype(dtype).param_dtype(dtype).list()
            .layer(DenseLayer(n_in=n_in, n_out=8, activation="tanh"))
            .layer(DenseLayer(n_in=8, n_out=n_out, activation="relu"))
            .build())


def _blob(rng, n=64):
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[
        np.argmax(x @ rng.normal(size=(4, 3)), axis=1)]
    return DataSet(x, y)


def test_mln_nested_in_mln_trains(rng):
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("adam").list()
            .layer(NetworkLayer(conf=_inner_mln_conf()))
            .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = _blob(rng)
    net.fit(ds, epochs=5)
    s1 = net.score_value
    net.fit(ds, epochs=25)
    assert net.score_value < s1
    assert net.evaluate(ds).accuracy() > 0.8
    # inner params live as this layer's subtree and were trained
    assert "layer_0" in net.params
    assert "layer_0" in net.params["layer_0"]  # nested inner layer subtree


@pytest.fixture
def f64():
    import jax

    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", False)


def test_cg_with_mln_vertex_trains_and_gradient_checks(rng, f64):
    """The VERDICT 'done' criterion: a CG containing an MLN vertex trains
    and passes the finite-difference gradient check."""
    g = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.1)
         .updater("sgd").dtype("float64").param_dtype("float64")
         .graph_builder().add_inputs("in"))
    g.add_layer("sub", NetworkLayer(conf=_inner_mln_conf(dtype="float64")),
                "in")
    g.add_layer("out", OutputLayer(n_in=6, n_out=3, activation="softmax",
                                   loss_function="mcxent"), "sub")
    g.set_outputs("out")
    net = ComputationGraph(g.build())
    net.init()
    ds = _blob(rng, n=8)
    assert GradientCheckUtil.check_gradients_graph(net, ds)
    net.fit(_blob(rng), epochs=20)
    assert np.isfinite(net.score_value)


def test_nested_graph_in_mln(rng):
    """A ComputationGraph nested as a layer of an MLN."""
    g = (NeuralNetConfiguration.builder().seed(9).learning_rate(0.1)
         .graph_builder().add_inputs("x"))
    g.add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "x")
    g.add_layer("d2", DenseLayer(n_in=8, n_out=6, activation="identity"),
                "d1")
    g.set_outputs("d2")
    inner = g.build()
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("adam").list()
            .layer(NetworkLayer(conf=inner))
            .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    ds = _blob(rng)
    net.fit(ds, epochs=30)
    assert net.evaluate(ds).accuracy() > 0.8


def test_nested_graph_output_type_inference(rng):
    """Outer shape inference must see the nested graph's TRUE output size
    (a 4->6 nested graph followed by an n_in-inferred output layer)."""
    from deeplearning4j_tpu.nn.conf.inputs import InputType

    g = (NeuralNetConfiguration.builder().seed(9).graph_builder()
         .add_inputs("x"))
    g.add_layer("d1", DenseLayer(n_in=4, n_out=8, activation="tanh"), "x")
    g.add_layer("d2", DenseLayer(n_in=8, n_out=6, activation="identity"),
                "d1")
    g.set_outputs("d2")
    inner = g.build()
    nl = NetworkLayer(conf=inner)
    out_t = nl.get_output_type(InputType.feed_forward(4))
    assert out_t.flat_size() == 6
    # end-to-end: outer OutputLayer's n_in inferred from the nested output
    conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.1)
            .updater("adam").list()
            .layer(NetworkLayer(conf=inner))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .input_type(InputType.feed_forward(4))
            .build())
    net = MultiLayerNetwork(conf).init()
    assert net.params["layer_1"]["W"].shape == (6, 3)
    net.fit(_blob(rng), epochs=5)
    assert np.isfinite(net.score_value)


def test_seq_axis_rejects_mln():
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    conf = (NeuralNetConfiguration.builder().seed(3).list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                               loss_function="mcxent")).build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="ComputationGraph"):
        net.set_mesh(make_mesh({"seq": 8}), axes={"seq": "seq"})


def test_network_layer_conf_roundtrip():
    conf = (NeuralNetConfiguration.builder().seed(5).list()
            .layer(NetworkLayer(conf=_inner_mln_conf()))
            .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    from deeplearning4j_tpu.nn.conf.neural_net_configuration import (
        MultiLayerConfiguration,
    )

    restored = MultiLayerConfiguration.from_json(conf.to_json())
    inner = restored.layers[0].conf
    assert len(inner.layers) == 2
    net = MultiLayerNetwork(restored).init()
    y = net.output(np.zeros((2, 4), np.float32))
    assert y.shape == (2, 3)


# ------------------------------------------------------- stored-state path

def _rnn_net():
    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.05)
            .updater("sgd").list()
            .layer(GravesLSTM(n_in=2, n_out=5, activation="tanh"))
            .layer(RnnOutputLayer(n_in=5, n_out=2, activation="softmax",
                                  loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def test_rnn_activate_using_stored_state_matches_full_forward(rng):
    """Splitting a sequence: rnn_time_step over the first half, then
    rnn_activate_using_stored_state on the second half must reproduce the
    full-sequence activations (the reference API's TBPTT-style eval use)."""
    net = _rnn_net()
    x = rng.normal(size=(3, 12, 2)).astype(np.float32)
    full = net.feed_forward(x)
    net.rnn_clear_previous_state()
    net.rnn_time_step(x[:, :6])               # advance stored state
    acts = net.rnn_activate_using_stored_state(x[:, 6:])
    np.testing.assert_allclose(np.asarray(acts[-1]),
                               np.asarray(full[-1])[:, 6:], atol=1e-5)
    # without store_last_for_tbptt the stored state did NOT advance:
    # calling again gives identical activations
    acts2 = net.rnn_activate_using_stored_state(x[:, 6:])
    np.testing.assert_allclose(np.asarray(acts2[-1]), np.asarray(acts[-1]),
                               atol=0)


def test_rnn_activate_stored_state_store_flag(rng):
    net = _rnn_net()
    x = rng.normal(size=(2, 8, 2)).astype(np.float32)
    net.rnn_clear_previous_state()
    net.rnn_activate_using_stored_state(x[:, :4], store_last_for_tbptt=True)
    acts = net.rnn_activate_using_stored_state(x[:, 4:])
    full = net.feed_forward(x)
    np.testing.assert_allclose(np.asarray(acts[-1]),
                               np.asarray(full[-1])[:, 4:], atol=1e-5)


def test_rnn_activate_stored_state_graph(rng):
    g = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.05)
         .graph_builder().add_inputs("in"))
    g.add_layer("lstm", GravesLSTM(n_in=2, n_out=5, activation="tanh"), "in")
    g.add_layer("out", RnnOutputLayer(n_in=5, n_out=2, activation="softmax",
                                      loss_function="mcxent"), "lstm")
    g.set_outputs("out")
    net = ComputationGraph(g.build())
    net.init()
    x = rng.normal(size=(2, 10, 2)).astype(np.float32)
    full, _, _ = net._forward(net.params, net.state, {"in": jnp.asarray(x)},
                              train=False, rng=None)
    net.rnn_clear_previous_state()
    net.rnn_time_step(x[:, :5])
    acts = net.rnn_activate_using_stored_state(x[:, 5:])
    np.testing.assert_allclose(np.asarray(acts["out"]),
                               np.asarray(full[0])[:, 5:], atol=1e-5)
