"""Determinism by construction (SURVEY.md §5): the reference embraces
HogWild data races (SequenceVectors threads on shared syn0); this build
replaces shared-memory racing with keyed PRNG + order-free collective
sums, so identical seeds must give bitwise-identical results — across
runs, across fit/fit_scanned restarts, and across device counts."""

import numpy as np

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    NeuralNetConfiguration,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def _net(seed=11):
    conf = (
        NeuralNetConfiguration.builder()
        .seed(seed)
        .learning_rate(0.1)
        .updater("adam")
        .dropout(0.2)  # rng-consuming path included
        .list()
        .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
        .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _data():
    rng = np.random.default_rng(0)
    return DataSet(rng.random((32, 4), dtype=np.float32),
                   np.eye(2, dtype=np.float32)[rng.integers(0, 2, 32)])


def test_training_bitwise_reproducible_across_runs():
    ds = _data()
    runs = []
    for _ in range(2):
        net = _net()
        for _ in range(5):
            net.fit(ds)
        runs.append(np.asarray(net.params_flat()))
    np.testing.assert_array_equal(runs[0], runs[1])


def test_init_reproducible_across_runs():
    np.testing.assert_array_equal(np.asarray(_net().params_flat()),
                                  np.asarray(_net().params_flat()))


def test_word2vec_device_pipeline_reproducible():
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    sents = [[f"w{rng.integers(0, 20)}", f"v{rng.integers(0, 20)}"] * 3
             for _ in range(200)]

    def run():
        w = (Word2Vec.builder().layer_size(16).window_size(2)
             .min_word_frequency(1).negative_sample(3).epochs(2).seed(9)
             .use_device_pipeline(True).build())
        w.fit(sents)
        return np.asarray(w.lookup_table.syn0)

    np.testing.assert_array_equal(run(), run())


def test_device_count_invariance_of_mesh_word2vec():
    """2-device and 4-device meshes give identical embeddings (order-free
    psum'd gradients — the anti-HogWild design property)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(1)
    sents = [[f"w{rng.integers(0, 15)}", f"v{rng.integers(0, 15)}"] * 2
             for _ in range(200)]

    def run(n_dev):
        w = (Word2Vec.builder().layer_size(16).window_size(2)
             .min_word_frequency(1).negative_sample(3).epochs(1).seed(4)
             .use_device_pipeline(True)
             .device_mesh(make_mesh({"data": n_dev}), chunk=64, group=4)
             .build())
        w.fit(sents)
        return np.asarray(w.lookup_table.syn0)

    np.testing.assert_allclose(run(2), run(4), atol=1e-6)
