"""Multi-process runtime (deeplearning4j_tpu/distributed/): the tier-1
proof that a mesh spanning 2 OS processes x 4 virtual CPU devices runs
ONE jitted allreduce train step through the ordinary `set_mesh` path
with bit-identical resulting params on every process (VERDICT r5
Missing #1 — until this test, the L8 "distributed" column was a claim),
plus the rendezvous env contract, the launcher's straggler reaping and
log streaming, per-process telemetry logs, the bootstrap failure mode,
and the CLI / pod dry-run plans.

Every spawned-process test carries a hard subprocess timeout (the
launcher enforces its own wall-clock deadline on top)."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from deeplearning4j_tpu.distributed import bootstrap
from deeplearning4j_tpu.distributed.launcher import (
    free_port,
    launch_local,
    launch_plan,
)

pytestmark = pytest.mark.distributed

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env(extra=None):
    """Child env additions: import path + no inherited rendezvous or
    telemetry state leaking from the test process."""
    env = {"PYTHONPATH": ROOT}
    env.update(extra or {})
    return env


# ------------------------------------------------------------ the proof

def test_two_process_pjit_mesh_runs_one_allreduce_step(tmp_path):
    """2 processes x 4 virtual CPU devices rendezvous via
    jax.distributed, build the 8-device global mesh, and run one jitted
    allreduce train step via set_mesh/fit on per-process batch shards —
    with BOTH DP formulations in one fleet launch: the monolithic GSPMD
    step and the ISSUE 7 bucketed-overlap step (per-bucket psums under
    shard_map, the frozen `distributed/overlap_step_2x4` sequence).
    Params must come out BIT-identical on both processes for both
    formulations, match the single-process full-batch reference
    (gradient linearity), and the overlap step must match the unbucketed
    one at tight atol (f32 reduction-order freedom only)."""
    results = launch_local(
        [sys.executable, "tests/distributed_worker.py", str(tmp_path)],
        n_processes=2, local_device_count=4, timeout=240.0,
        extra_env=_clean_env(), cwd=ROOT)
    for r in results:
        assert not r.timed_out, f"p{r.process_id} timed out:\n{r.output}"
        assert r.returncode == 0, f"p{r.process_id} failed:\n{r.output}"

    p0 = np.load(str(tmp_path / "params_p0.npy"))
    p1 = np.load(str(tmp_path / "params_p1.npy"))
    assert np.array_equal(p0, p1), "replicas diverged across processes"
    ov0 = np.load(str(tmp_path / "params_overlap_p0.npy"))
    ov1 = np.load(str(tmp_path / "params_overlap_p1.npy"))
    assert np.array_equal(ov0, ov1), \
        "overlap-step replicas diverged across processes"

    # single-process full-batch reference: same config, same seed, one
    # step — DP averaging over equal shards must equal the full batch
    from deeplearning4j_tpu.datasets.api import DataSet
    from tests.cluster_worker import build_net, full_data

    x, y = full_data()
    ref = build_net().init()
    ref.fit(DataSet(x, y))
    np.testing.assert_allclose(p0, np.asarray(ref.params_flat()),
                               atol=1e-5)
    # bucketed-vs-monolithic parity on the LIVE fleet (the tight-atol
    # half of the ISSUE 7 acceptance; test_overlap.py proves the same
    # bound single-process)
    np.testing.assert_allclose(ov0, p0, atol=1e-5)


# ------------------------------------------------------ N x K fleet matrix

# the 2-process x 4-device proof above, parameterized into a small
# process-count x device-count matrix through the ELASTIC launcher path
# (ElasticSupervisor -> launch_local with death_grace; the elastic worker
# already regenerates rank-portable global batches for any N). The
# cheapest combo stays tier-1; the rest ride the slow tier so the gate
# keeps its budget.
FLEET_MATRIX = [
    (2, 2),
    pytest.param(3, 2, marks=pytest.mark.slow),
    pytest.param(2, 4, marks=pytest.mark.slow),
]


@pytest.mark.parametrize("n_processes,local_devices", FLEET_MATRIX)
def test_fleet_matrix_trains_to_reference(n_processes, local_devices,
                                          tmp_path):
    """N processes x K virtual devices train 2 deterministic global
    steps through the elastic supervisor (no faults: one clean
    generation) and land on the single-process full-batch reference
    params — the mesh/batch plumbing holds at every N x K, not just the
    proven 2x4."""
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.distributed import elastic
    from tests.cluster_worker import build_net
    from tests.elastic_worker import batch_for_step

    ckpt = tmp_path / "ckpt"
    out = tmp_path / "out"
    ckpt.mkdir()
    out.mkdir()
    total_steps = 2
    sup = elastic.ElasticSupervisor(
        [sys.executable, os.path.join("tests", "elastic_worker.py"),
         str(ckpt), str(out)],
        n_processes=n_processes, min_processes=n_processes,
        total_steps=total_steps, checkpoint_dir=str(ckpt), max_reforms=0,
        local_device_count=local_devices, gen_timeout=150.0,
        extra_env=_clean_env(), cwd=ROOT)
    try:
        result = sup.run()
    finally:
        sup.close()
    assert len(result.generations) == 1
    gen = result.generations[0]
    assert gen.n_processes == n_processes and gen.clean, gen.exit_classes

    done = (out / "done.txt").read_text()
    assert f"n_processes={n_processes}" in done
    final = np.load(str(out / "final_params.npy"))
    ref = build_net().init()
    for step in range(1, total_steps + 1):
        ref.fit(DataSet(*batch_for_step(step)))
    np.testing.assert_allclose(final, np.asarray(ref.params_flat()),
                               atol=1e-5)


def test_bootstrap_failure_mode_is_bounded(tmp_path):
    """The documented failure mode of a fleet member whose coordinator
    never appears: on this jax generation the XLA distributed client
    ABORTS the process (SIGABRT, "Deadline Exceeded") once init_timeout
    expires — no Python exception ever surfaces, which is exactly why
    the launcher must reap and capture logs (ARCHITECTURE.md
    §Distributed runtime failure matrix). Assert the death is bounded
    and attributable, not hung."""
    script = (
        "from deeplearning4j_tpu.distributed import bootstrap\n"
        "try:\n"
        "    bootstrap.initialize(coordinator_address='127.0.0.1:9',\n"
        "                         num_processes=2, process_id=1,\n"
        "                         connect_timeout=6.0, init_timeout=2)\n"
        "except Exception as exc:\n"
        "    print('RAISED', type(exc).__name__)\n"
        "    raise SystemExit(0)\n"
        "raise SystemExit(1)\n")
    env = dict(os.environ)
    env.update(_clean_env({"JAX_PLATFORMS": "cpu"}))
    proc = subprocess.run([sys.executable, "-c", script], env=env, cwd=ROOT,
                          capture_output=True, text=True, timeout=120)
    # either outcome is the documented contract: a clean Python raise
    # (newer jax) or the hard C++ abort with the deadline marker (this
    # jax) — a silent hang or a bogus success is the only failure
    if proc.returncode == 0:
        assert "RAISED" in proc.stdout
    else:
        blob = proc.stdout + proc.stderr
        assert ("Deadline Exceeded" in blob
                or "DEADLINE_EXCEEDED" in blob), blob


# ------------------------------------------------------------- launcher

def test_launcher_reaps_stragglers():
    """A fleet member that never exits is terminated (then killed) at the
    wall-clock deadline — no spawned-process test can hang the suite."""
    results = launch_local(
        [sys.executable, "-c", "import time; print('up', flush=True); "
                               "time.sleep(600)"],
        n_processes=2, local_device_count=None, timeout=3.0, grace=2.0)
    assert all(r.timed_out for r in results)
    # the reaper observed their death (terminate or kill), so no zombies
    assert all(r.returncode is None or r.returncode != 0 for r in results)


def test_launcher_streams_prefixed_logs_and_env_contract():
    """Each process's lines are captured per-process and echoed with a
    [pN] prefix; the rendezvous env contract reaches every child."""
    echoed = []
    script = ("import os; "
              "print(os.environ['DL4J_TPU_PROCESS_ID'], "
              "os.environ['DL4J_TPU_NUM_PROCESSES'], "
              "os.environ['DL4J_TPU_COORDINATOR'], flush=True)")
    results = launch_local([sys.executable, "-c", script], n_processes=3,
                           local_device_count=None, timeout=60.0,
                           echo=echoed.append)
    assert [r.returncode for r in results] == [0, 0, 0]
    for i, r in enumerate(results):
        pid, n, coord = r.lines[0].split()
        assert (pid, n) == (str(i), "3")
        assert coord.startswith("127.0.0.1:")
    assert any(line.startswith("[p2] ") for line in echoed)


def test_launch_plan_lines_are_complete():
    lines = launch_plan(["python", "train.py"], n_processes=2,
                        local_device_count=4,
                        coordinator="127.0.0.1:5555")
    assert len(lines) == 3 and lines[-1] == "wait"
    for i, line in enumerate(lines[:2]):
        assert f"{bootstrap.ENV_PROCESS_ID}={i}" in line
        assert f"{bootstrap.ENV_COORDINATOR}=127.0.0.1:5555" in line
        assert f"{bootstrap.ENV_NUM_PROCESSES}=2" in line
        assert "xla_force_host_platform_device_count=4" in line
        assert line.endswith("python train.py &")


# ------------------------------------------------------------- contract

def test_rendezvous_env_roundtrip():
    env = bootstrap.rendezvous_env("10.0.0.1:8476", 3, 8,
                                   local_device_count=4)
    assert bootstrap.env_contract_present(env)
    parsed = bootstrap.contract_from_env(env)
    assert parsed == {"coordinator_address": "10.0.0.1:8476",
                      "process_id": 3, "num_processes": 8,
                      "local_device_count": 4}
    assert not bootstrap.env_contract_present({})
    assert bootstrap.contract_from_env({})["process_id"] is None


def test_free_port_is_bindable():
    import socket

    port = free_port()
    with socket.socket() as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", port))


# ----------------------------------------------------------- global mesh

def test_single_process_mesh_passes_batches_through():
    """Off the multi-process path nothing changes: a local mesh does not
    span processes and globalize_batch is the identity."""
    from deeplearning4j_tpu.distributed.global_mesh import (
        globalize_batch,
        local_shard,
        make_global_mesh,
        spans_processes,
    )

    mesh = make_global_mesh({"data": -1})
    assert not spans_processes(mesh)
    batch = {"features": np.ones((4, 2), np.float32)}
    assert globalize_batch(batch, mesh) is batch
    # one process: the local shard IS the full array
    x = np.arange(8.0).reshape(4, 2)
    np.testing.assert_array_equal(local_shard(x), x)


def test_multiprocess_rejects_param_placement_roles(monkeypatch):
    """Process-spanning meshes support the data role only — the error
    must name the restriction and point at the design note."""
    import deeplearning4j_tpu.parallel.mesh as mesh_mod
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from tests.cluster_worker import build_net

    net = build_net().init()
    mesh = make_mesh({"data": 1})
    # configure_mesh recomputes _multiprocess from the mesh, so patch the
    # detector it calls rather than the attribute
    monkeypatch.setattr(mesh_mod, "spans_processes", lambda m: True)
    with pytest.raises(ValueError, match="Distributed runtime"):
        net.set_mesh(mesh, axes={"data": "data", "model": "data"})


# ------------------------------------------------- per-process telemetry

def test_two_telemetry_writers_two_parseable_logs(tmp_path):
    """N fleet processes sharing one DL4J_TPU_TELEMETRY value must not
    interleave a single JSONL: with the env contract active each writes
    `<path>.p<id>`, and both logs parse line-by-line."""
    base = str(tmp_path / "run.jsonl")
    script = ("from deeplearning4j_tpu.telemetry.recorder import "
              "get_default\n"
              "rec = get_default()\n"
              "rec.meta(role='writer')\n"
              "rec.event('span', name='work', seconds=0.1)\n")
    for pid in ("0", "1"):
        env = dict(os.environ)
        env.update(_clean_env({"DL4J_TPU_TELEMETRY": base,
                               bootstrap.ENV_PROCESS_ID: pid}))
        proc = subprocess.run([sys.executable, "-c", script], env=env,
                              cwd=ROOT, capture_output=True, timeout=60)
        assert proc.returncode == 0, proc.stderr.decode()
    assert not os.path.exists(base), "writers clobbered the shared path"
    for pid in ("0", "1"):
        events = [json.loads(l) for l in open(f"{base}.p{pid}")]
        assert [e["event"] for e in events] == ["meta", "span"]
        assert all(e["run"] for e in events)


# ------------------------------------------------------------- dry runs

def test_cli_multiprocess_prints_launch_plan(tmp_path, capsys):
    from deeplearning4j_tpu.cli import main

    conf = tmp_path / "conf.json"
    conf.write_text("{}")  # never parsed: the plan prints before loading
    argv = ["train", "--conf", str(conf), "--input", "d.csv",
            "--model", "m.zip", "--num-classes", "2",
            "--mesh", "data=8", "--multiprocess", "2",
            "--local-devices", "4"]
    assert main(argv) == 0
    out = capsys.readouterr().out
    lines = [l for l in out.splitlines() if l.endswith("&")]
    assert len(lines) == 2
    for i, line in enumerate(lines):
        assert f"{bootstrap.ENV_PROCESS_ID}={i}" in line
        assert "--mesh data=8" in line
        # the plan flags themselves are scrubbed from the child command
        assert "--multiprocess" not in line
        assert "--local-devices" not in line
    assert out.splitlines()[-1] == "wait"


def test_pod_launch_script_drives_bootstrap_contract():
    from deeplearning4j_tpu.provision.tpu_vm import (
        TpuPodLauncher,
        TpuVmCreator,
        pod_launch_script,
    )

    script = pod_launch_script("python3 -m deeplearning4j_tpu.cli train "
                               "--conf c.json", num_hosts=4,
                               coordinator_port=8476)
    assert f'export {bootstrap.ENV_PROCESS_ID}="$WORKER_ID"' in script
    assert f"export {bootstrap.ENV_NUM_PROCESSES}=4" in script
    assert f'export {bootstrap.ENV_COORDINATOR}="$COORD_HOST:8476"' \
        in script
    assert "TPU_WORKER_HOSTNAMES" in script and script.startswith("#!")

    creator = TpuVmCreator(name="pod", accelerator_type="v5litepod-32")
    plan = TpuPodLauncher(creator).plan("python3 train.py",
                                       explicit_rendezvous=True)
    assert len(plan) == 3  # create, bootstrap, rendezvous launch
    assert "base64 -d | bash" in plan[-1]
