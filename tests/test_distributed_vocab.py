"""Parallel + cluster-distributed vocabulary construction
(nlp/distributed_vocab.py; reference TextPipeline.buildVocabCache and the
multi-threaded VocabConstructor)."""

import threading

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.distributed_vocab import (
    build_vocab_distributed,
    parallel_count,
)
from deeplearning4j_tpu.nlp.vocab import VocabConstructor


def _corpus(n=5000, vocab=200, seed=0):
    rng = np.random.default_rng(seed)
    return [[f"w{int(i)}" for i in rng.integers(0, vocab, 12)]
            for _ in range(n)]


def test_parallel_count_matches_serial():
    sents = _corpus()
    serial, n1 = parallel_count(sents, n_workers=1)
    par, n2 = parallel_count(sents, n_workers=4, chunk_size=500)
    assert serial == par and n1 == n2 == len(sents)


def test_parallel_constructor_identical_vocab():
    """n_workers>1 must produce a bit-identical VocabCache (same counts,
    same index order, same Huffman codes) — the device pipeline depends
    on deterministic word indexing."""
    sents = _corpus()
    a = (VocabConstructor(min_word_frequency=2, n_workers=1,
                          build_huffman=True)
         .add_source(sents).build_joint_vocabulary())
    b = (VocabConstructor(min_word_frequency=2, n_workers=4,
                          build_huffman=True)
         .add_source(sents).build_joint_vocabulary())
    assert a.words() == b.words()
    for w in a.words():
        va, vb = a.word_for(w), b.word_for(w)
        assert va.count == vb.count
        assert getattr(va, "codes", None) == getattr(vb, "codes", None)


def test_parallel_count_with_tokenizer():
    from deeplearning4j_tpu.nlp.text import DefaultTokenizerFactory

    raw = ["the quick brown fox", "the lazy dog", "the fox"] * 100
    counts, n = parallel_count(raw, tokenizer_factory=DefaultTokenizerFactory(),
                               n_workers=2, chunk_size=50)
    assert n == 300
    assert counts["the"] == 300 and counts["fox"] == 200


def test_build_vocab_distributed_identical_across_workers():
    """Every cluster worker ends with the same cache from disjoint
    corpus shards, equal to a single-host build over the full corpus."""
    from deeplearning4j_tpu.parallel.cluster import (
        ClusterClient,
        ClusterCoordinator,
    )

    sents = _corpus(2000)
    shards = [sents[0::2], sents[1::2]]
    coord = ClusterCoordinator(heartbeat_timeout=10.0).start()
    results = {}

    def worker(wid, shard):
        c = ClusterClient(coord.address, wid)
        try:
            results[wid] = build_vocab_distributed(
                c, shard, min_word_frequency=2, build_huffman=True)
        finally:
            c.close()

    try:
        a = ClusterClient(coord.address, "wA")
        b = ClusterClient(coord.address, "wB")
        a.close(deregister=False)
        b.close(deregister=False)  # pre-register so workers() sees both
        ts = [threading.Thread(target=worker, args=(w, s))
              for w, s in zip(("wA", "wB"), shards)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
    finally:
        coord.shutdown()

    ref = (VocabConstructor(min_word_frequency=2, build_huffman=True)
           .add_source(sents).build_joint_vocabulary())
    assert set(results) == {"wA", "wB"}
    for cache in results.values():
        assert cache.words() == ref.words()
        assert cache.n_sequences == len(sents)
        for w in ref.words():
            assert cache.word_frequency(w) == ref.word_frequency(w)
