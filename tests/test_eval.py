"""Known-value tests for the eval package (reference test strategy §4:
eval/EvaluationTest-style assertions against hand-computed matrices;
Evaluation.java:111 eval, :294 stats, merge; RegressionEvaluation.java)."""

import numpy as np

from deeplearning4j_tpu.eval.confusion import ConfusionMatrix
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.eval.regression import RegressionEvaluation


def one_hot(idx, n):
    out = np.zeros((len(idx), n), dtype=np.float32)
    out[np.arange(len(idx)), idx] = 1.0
    return out


class TestEvaluation:
    def test_known_values(self):
        # 3-class problem with a hand-checkable confusion matrix
        actual = np.array([0, 0, 0, 1, 1, 2, 2, 2, 2, 2])
        pred = np.array([0, 0, 1, 1, 1, 2, 2, 2, 0, 1])
        ev = Evaluation()
        ev.eval(one_hot(actual, 3), one_hot(pred, 3))
        assert ev.examples == 10
        assert ev.accuracy() == 7 / 10
        # class 0: tp=2, predicted total=3, actual total=3
        assert ev.precision(0) == 2 / 3
        assert ev.recall(0) == 2 / 3
        # class 2: tp=3, predicted=3, actual=5
        assert ev.precision(2) == 1.0
        assert ev.recall(2) == 3 / 5
        p, r = ev.precision(1), ev.recall(1)
        assert ev.f1(1) == 2 * p * r / (p + r)
        assert ev.confusion.get_count(2, 0) == 1
        assert "Accuracy" in ev.stats()

    def test_never_predicted_class_warning_and_macro_exclusion(self):
        actual = np.array([0, 1, 2, 2])
        pred = np.array([0, 0, 0, 0])
        ev = Evaluation()
        ev.eval(one_hot(actual, 3), one_hot(pred, 3))
        # macro precision only over predicted classes (class 0)
        assert ev.precision() == 1 / 4
        assert "never predicted" in ev.stats()

    def test_time_series_mask(self):
        # [batch=1, time=4, C=2]; mask drops the 2 wrong timesteps
        labels = one_hot(np.array([0, 1, 0, 1]), 2)[None]
        preds = one_hot(np.array([0, 1, 1, 0]), 2)[None]
        mask = np.array([[1, 1, 0, 0]])
        ev = Evaluation()
        ev.eval(labels, preds, mask=mask)
        assert ev.examples == 2
        assert ev.accuracy() == 1.0

    def test_merge(self):
        a, b = Evaluation(), Evaluation()
        a.eval(one_hot(np.array([0, 1]), 2), one_hot(np.array([0, 0]), 2))
        b.eval(one_hot(np.array([1, 1]), 2), one_hot(np.array([1, 0]), 2))
        a.merge(b)
        assert a.examples == 4
        assert a.accuracy() == 2 / 4
        assert a.confusion.get_count(1, 0) == 2

    def test_top_n_accuracy(self):
        # probs: true class is rank-2 for examples 1 and 2, rank-1 for 0,
        # rank-3 (out of top-2) for 3
        probs = np.array([
            [0.7, 0.2, 0.1],   # true 0 → top-1 hit
            [0.5, 0.4, 0.1],   # true 1 → top-2 hit
            [0.4, 0.5, 0.1],   # true 0 → top-2 hit
            [0.5, 0.3, 0.2],   # true 2 → miss even at top-2
        ])
        truth = one_hot(np.array([0, 1, 0, 2]), 3)
        ev = Evaluation(top_n=2)
        ev.eval(truth, probs)
        assert ev.accuracy() == 1 / 4
        assert ev.top_n_accuracy() == 3 / 4
        assert "Top-2" in ev.stats()

    def test_top_n_merge(self):
        a = Evaluation(top_n=2)
        b = Evaluation(top_n=2)
        probs = np.array([[0.5, 0.4, 0.1]])
        a.eval(one_hot(np.array([1]), 3), probs)
        b.eval(one_hot(np.array([2]), 3), probs)
        a.merge(b)
        assert a.top_n_correct == 1
        assert a.top_n_accuracy() == 1 / 2


class TestRegressionEvaluation:
    def test_known_values(self):
        labels = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        preds = np.array([[1.5, 2.0], [2.5, 5.0], [5.0, 5.0]])
        re = RegressionEvaluation()
        re.eval(labels, preds)
        assert np.isclose(re.mean_squared_error(0), (0.25 + 0.25 + 0) / 3)
        assert np.isclose(re.mean_absolute_error(1), (0 + 1 + 1) / 3)
        assert np.isclose(re.root_mean_squared_error(0),
                          np.sqrt((0.25 + 0.25 + 0) / 3))
        # R^2 column 0: ss_res=0.5, ss_tot=8 (mean 3)
        assert np.isclose(re.r_squared(0), 1 - 0.5 / 8)
        assert "MSE" in re.stats()

    def test_perfect_fit_r2(self):
        labels = np.random.default_rng(0).normal(size=(10, 3))
        re = RegressionEvaluation()
        re.eval(labels, labels.copy())
        for c in range(3):
            assert re.mean_squared_error(c) == 0.0
            assert re.r_squared(c) >= 1.0 - 1e-9

    def test_time_series_with_mask(self):
        labels = np.ones((2, 3, 1))
        preds = np.zeros((2, 3, 1))
        mask = np.array([[1, 1, 0], [1, 0, 0]])
        re = RegressionEvaluation()
        re.eval(labels, preds, mask=mask)
        assert re._count == 3
        assert np.isclose(re.mean_squared_error(0), 1.0)


class TestConfusionMatrix:
    def test_add_and_totals(self):
        cm = ConfusionMatrix(range(3))
        cm.add(0, 1)
        cm.add(0, 1)
        cm.add(2, 2, count=3)
        assert cm.get_count(0, 1) == 2
        assert cm.get_actual_total(0) == 2
        assert cm.get_predicted_total(2) == 3
        assert "0,2,0" in cm.to_csv()
