"""ModelSerializer round-trips (util/model_serializer.py) — reference
org.deeplearning4j.util.ModelSerializer: both network kinds, updater
state, iteration counter, and retrain-after-restore."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    GravesLSTM,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.model_serializer import ModelSerializer


def _mln():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .learning_rate(0.05)
        .updater("adam")
        .list()
        .layer(DenseLayer(n_in=5, n_out=9, activation="relu"))
        .layer(OutputLayer(n_in=9, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _cg():
    g = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .learning_rate(0.05)
        .updater("rmsprop")
        .graph_builder()
        .add_inputs("in")
    )
    g.add_layer("lstm", GravesLSTM(n_in=4, n_out=6, activation="tanh"), "in")
    g.add_layer("out", RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                      loss_function="mcxent"), "lstm")
    g.set_outputs("out")
    return ComputationGraph(g.build())


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random((16, 5), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    return DataSet(x, y)


def test_mln_round_trip_params_updater_and_step(tmp_path):
    net = _mln()
    net.fit(_data())
    net.fit(_data(1))
    path = str(tmp_path / "mln.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore(path)
    assert isinstance(restored, MultiLayerNetwork)
    assert restored.iteration_count == net.iteration_count
    np.testing.assert_allclose(np.asarray(restored.params_flat()),
                               np.asarray(net.params_flat()), atol=0)
    x = _data(2).features
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)


def test_mln_restore_continues_training_identically(tmp_path):
    """Updater state round-trips: training after restore == training the
    original (the optimizer moments must survive serialization)."""
    net = _mln()
    net.fit(_data())
    path = str(tmp_path / "mln.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore(path)
    net.fit(_data(1))
    restored.fit(_data(1))
    np.testing.assert_allclose(np.asarray(restored.params_flat()),
                               np.asarray(net.params_flat()), atol=1e-6)


def test_mln_restore_without_updater(tmp_path):
    net = _mln()
    net.fit(_data())
    path = str(tmp_path / "mln.zip")
    ModelSerializer.write_model(net, path, save_updater=False)
    restored = ModelSerializer.restore(path)
    x = _data(2).features
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)


def test_cg_round_trip_with_rnn_state(tmp_path):
    net = _cg()
    net.init()
    rng = np.random.default_rng(0)
    x = rng.random((4, 7, 4), dtype=np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 7))]
    net.fit(x, y)
    path = str(tmp_path / "cg.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_computation_graph(path)
    assert isinstance(restored, ComputationGraph)
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)
    # streaming inference works on the restored graph
    step = restored.rnn_time_step(x[:, 0])
    assert np.asarray(step).shape == (4, 2)


def test_kind_specific_restores_reject_wrong_kind(tmp_path):
    net = _mln()
    path = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, path)
    with pytest.raises(ValueError):
        ModelSerializer.restore_computation_graph(path)
    assert isinstance(ModelSerializer.restore_multi_layer_network(path),
                      MultiLayerNetwork)
