"""ModelSerializer round-trips (util/model_serializer.py) — reference
org.deeplearning4j.util.ModelSerializer: both network kinds, updater
state, iteration counter, and retrain-after-restore."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.api import DataSet
from deeplearning4j_tpu.nn.conf import (
    DenseLayer,
    GravesLSTM,
    NeuralNetConfiguration,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.model_serializer import ModelSerializer


def _mln():
    conf = (
        NeuralNetConfiguration.builder()
        .seed(11)
        .learning_rate(0.05)
        .updater("adam")
        .list()
        .layer(DenseLayer(n_in=5, n_out=9, activation="relu"))
        .layer(OutputLayer(n_in=9, n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .build()
    )
    return MultiLayerNetwork(conf).init()


def _cg():
    g = (
        NeuralNetConfiguration.builder()
        .seed(5)
        .learning_rate(0.05)
        .updater("rmsprop")
        .graph_builder()
        .add_inputs("in")
    )
    g.add_layer("lstm", GravesLSTM(n_in=4, n_out=6, activation="tanh"), "in")
    g.add_layer("out", RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                      loss_function="mcxent"), "lstm")
    g.set_outputs("out")
    return ComputationGraph(g.build())


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random((16, 5), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    return DataSet(x, y)


def test_mln_round_trip_params_updater_and_step(tmp_path):
    net = _mln()
    net.fit(_data())
    net.fit(_data(1))
    path = str(tmp_path / "mln.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore(path)
    assert isinstance(restored, MultiLayerNetwork)
    assert restored.iteration_count == net.iteration_count
    np.testing.assert_allclose(np.asarray(restored.params_flat()),
                               np.asarray(net.params_flat()), atol=0)
    x = _data(2).features
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)


def test_mln_restore_continues_training_identically(tmp_path):
    """Updater state round-trips: training after restore == training the
    original (the optimizer moments must survive serialization)."""
    net = _mln()
    net.fit(_data())
    path = str(tmp_path / "mln.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore(path)
    net.fit(_data(1))
    restored.fit(_data(1))
    np.testing.assert_allclose(np.asarray(restored.params_flat()),
                               np.asarray(net.params_flat()), atol=1e-6)


def test_mln_restore_without_updater(tmp_path):
    net = _mln()
    net.fit(_data())
    path = str(tmp_path / "mln.zip")
    ModelSerializer.write_model(net, path, save_updater=False)
    restored = ModelSerializer.restore(path)
    x = _data(2).features
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)


def test_cg_round_trip_with_rnn_state(tmp_path):
    net = _cg()
    net.init()
    rng = np.random.default_rng(0)
    x = rng.random((4, 7, 4), dtype=np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 7))]
    net.fit(x, y)
    path = str(tmp_path / "cg.zip")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_computation_graph(path)
    assert isinstance(restored, ComputationGraph)
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(net.output(x)), atol=1e-6)
    # streaming inference works on the restored graph
    step = restored.rnn_time_step(x[:, 0])
    assert np.asarray(step).shape == (4, 2)


def test_kind_specific_restores_reject_wrong_kind(tmp_path):
    net = _mln()
    path = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, path)
    with pytest.raises(ValueError):
        ModelSerializer.restore_computation_graph(path)
    assert isinstance(ModelSerializer.restore_multi_layer_network(path),
                      MultiLayerNetwork)


@pytest.mark.slow
def test_flat_layout_v1_checkpoint_upgrades(tmp_path):
    """Pre-r5 (flat_layout v1) checkpoints stored every leaf row-major in
    the flat optimizer vector; v2 axis-rotates lane-hostile leaves (2D+
    with minor dim < 128). Restoring a v1 zip must reorder the moments so
    resumed training matches — not silently scramble them."""
    import io
    import json
    import zipfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import transformer_moe_lm
    from deeplearning4j_tpu.nn import updater as upd
    from deeplearning4j_tpu.nn.updater import (
        FlatViewTransform,
        _lane_hostile,
    )

    # a model with lane-hostile leaves ([d_model, n_experts] routers) and
    # enough params that the flat view is active
    def _net():
        net = transformer_moe_lm(vocab_size=512, d_model=64, n_heads=2,
                                 n_layers=1, n_experts=4, top_k=2,
                                 d_expert_hidden=2048, max_length=8)
        net.init()
        return net

    net = _net()
    assert isinstance(net.tx, FlatViewTransform)
    assert any(_lane_hostile(l) for l in jax.tree.leaves(net.params))
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, 512, (4, 8)), np.int32)
    ds = DataSet(toks, np.roll(toks, -1, axis=1).astype(np.int32))
    net.fit(ds)
    path = str(tmp_path / "v2.zip")
    ModelSerializer.write_model(net, path)

    # rewrite the zip as a v1 checkpoint: flat vectors de-rotated to the
    # old all-row-major order + flat_layout stripped from meta
    def _derotate(vec):
        outs, off = [], 0
        for l in jax.tree.leaves(net.params):
            seg = vec[off:off + l.size]
            if _lane_hostile(l):
                rot = (l.shape[-1],) + l.shape[:-1]
                seg = np.moveaxis(seg.reshape(rot), 0, -1).ravel()
            outs.append(seg)
            off += l.size
        return np.concatenate(outs)

    total = upd.flat_state_size(net.params)
    v1path = str(tmp_path / "v1.zip")
    with zipfile.ZipFile(path) as zin, \
            zipfile.ZipFile(v1path, "w") as zout:
        for item in zin.namelist():
            data = zin.read(item)
            if item == "meta.json":
                meta = json.loads(data)
                meta.pop("flat_layout")
                data = json.dumps(meta).encode()
            elif item == "updater.npz":
                npz = np.load(io.BytesIO(data), allow_pickle=False)
                leaves = [npz[k] for k in npz.files]
                leaves = [_derotate(l) if l.ndim == 1 and l.size == total
                          else l for l in leaves]
                buf = io.BytesIO()
                np.savez(buf, *leaves)
                data = buf.getvalue()
            zout.writestr(item, data)

    for p in (path, v1path):
        restored = ModelSerializer.restore(p)
        for a, b in zip(jax.tree.leaves(restored.opt_state),
                        jax.tree.leaves(net.opt_state)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=0)
