"""Tier-1 gate for the fleet trace timeline (ISSUE 15): shard merge
ordering, correlation-field stamping, span-tree reconstruction, the
anomaly detectors (straggler/hang from an injected `pN:hang@stepK`
timeline, post-warmup retrace from a doctored late-compile shard,
input_wait/queue spikes), Perfetto export schema validity, the
tracetool CLI contract, the rolling-histogram /metrics registry, and
the artifact loader's sharded-input fallback.

Everything here is pure-host (no jax): the detectors must be provable
from the JSONL alone — that is the point of the subsystem."""

import json
import os
import subprocess
import sys
import threading

import pytest

from deeplearning4j_tpu.telemetry import Recorder
from deeplearning4j_tpu.telemetry import trace as trace_mod
from deeplearning4j_tpu.telemetry.metrics import (CONTENT_TYPE,
                                                  MetricsRegistry,
                                                  parse_exposition)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRACETOOL = os.path.join(ROOT, "tools", "tracetool.py")


# ------------------------------------------------------------ fixtures

def _write_shard(path, events):
    with open(path, "w") as fh:
        for ev in events:
            fh.write(json.dumps(ev) + "\n")


def _step(run, seq, it, ts, **extra):
    return {"event": "step", "run": run, "seq": seq, "iteration": it,
            "ts": ts, "trace_id": f"step-{it}", **extra}


def _fleet_shards(tmp_path, *, hang_at=None, skew_s=0.0, steps=8):
    """Two per-process shards of a training fleet: p0 runs to `steps`;
    p1 optionally hangs at step `hang_at` (its events just STOP — the
    SIGKILL signature) or completes each step `skew_s` late."""
    base = str(tmp_path / "telemetry.jsonl")
    p0, p1 = [], []
    t0 = 1000.0
    for s in range(1, steps + 1):
        ts = t0 + s * 0.1
        p0.append(_step("runA", s, s, ts))
        if hang_at is not None and s >= hang_at:
            continue
        p1.append(_step("runB", s, s, ts + skew_s))
    _write_shard(base + ".p0", p0)
    _write_shard(base + ".p1", p1)
    return base


# ------------------------------------------------------- merge ordering

def test_two_shard_merge_is_causal_and_process_tagged(tmp_path):
    base = str(tmp_path / "t.jsonl")
    _write_shard(base + ".p0", [
        {"event": "meta", "run": "a", "seq": 0, "ts": 10.0},
        {"event": "step", "run": "a", "seq": 1, "iteration": 1,
         "ts": 12.0},
        # same ts as p1's second event: per-process seq breaks the tie
        {"event": "step", "run": "a", "seq": 2, "iteration": 2,
         "ts": 13.0},
    ])
    _write_shard(base + ".p1", [
        {"event": "meta", "run": "b", "seq": 0, "ts": 11.0},
        {"event": "step", "run": "b", "seq": 1, "iteration": 1,
         "ts": 13.0},
    ])
    tl = trace_mod.load_timeline(base)
    assert tl.processes == ["p0", "p1"]
    assert [(e["process"], e["ts"]) for e in tl.events] == [
        ("p0", 10.0), ("p1", 11.0), ("p0", 12.0), ("p0", 13.0),
        ("p1", 13.0)]
    # one process's stream never reorders, whatever the clock says
    p0_seqs = [e["seq"] for e in tl.events if e["process"] == "p0"]
    assert p0_seqs == sorted(p0_seqs)


def test_discover_shards_prefers_unsuffixed_plus_shards(tmp_path):
    base = str(tmp_path / "t.jsonl")
    _write_shard(base, [{"event": "meta", "seq": 0, "ts": 1.0}])
    _write_shard(base + ".p0", [{"event": "meta", "seq": 0, "ts": 2.0}])
    labels = [l for l, _ in trace_mod.discover_shards(base)]
    assert labels == ["main", "p0"]
    with pytest.raises(FileNotFoundError):
        trace_mod.discover_shards(str(tmp_path / "absent.jsonl"))


def test_merge_skips_garbage_and_partial_lines(tmp_path):
    base = str(tmp_path / "t.jsonl")
    with open(base, "w") as fh:
        fh.write("not json\n")
        fh.write('{"event": "meta", "seq": 0, "ts": 1.0}\n')
        fh.write('{"event": "step", "seq": 1, "ts": 2.0, "iterat')  # cut
    tl = trace_mod.load_timeline(base)
    assert len(tl.events) == 1


# ------------------------------------------- correlation + span trees

def test_recorder_stamps_span_ids_and_nesting():
    rec = Recorder(path=None)
    with rec.span("forward", bucket=[2, 8]):
        with rec.span("compile"):
            pass
        rec.event("page_pool", pages_in_use=1)
    spans = [e for e in rec.events if e["event"] == "span"]
    fwd = next(e for e in spans if e["name"] == "forward")
    comp = next(e for e in spans if e["name"] == "compile")
    pool = next(e for e in rec.events if e["event"] == "page_pool")
    assert comp["parent_id"] == fwd["span_id"]
    assert pool["parent_id"] == fwd["span_id"]
    assert "parent_id" not in fwd


def test_trace_context_crosses_threads():
    """The batch handoff idiom: a trace rooted on one thread, continued
    on another through the explicit trace() context."""
    rec = Recorder(path=None)
    root = rec.new_span_id()
    rec.event("span", name="batch_assemble", ok=True, seconds=0.001,
              trace_id="b1", span_id=root)

    def worker():
        with rec.trace("b1", parent_id=root):
            with rec.span("forward"):
                pass
            rec.request("r1", ok=True, total_s=0.01)

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    fwd = next(e for e in rec.events
               if e["event"] == "span" and e["name"] == "forward")
    req = next(e for e in rec.events if e["event"] == "request")
    assert fwd["trace_id"] == req["trace_id"] == "b1"
    assert fwd["parent_id"] == root
    tl = trace_mod.timeline_from_events(rec.events)
    roots = trace_mod.span_tree(tl, "b1")
    assert len(roots) == 1
    names = {c["event"].get("name") or c["event"]["event"]
             for c in roots[0]["children"]}
    assert names == {"forward", "request"}
    rendered = trace_mod.render_tree(roots)
    assert "batch_assemble" in rendered and "request" in rendered


def test_step_events_carry_cross_process_trace_id():
    rec = Recorder(path=None)
    rec.step(7)
    assert rec.events[-1]["trace_id"] == "step-7"


# --------------------------------------------------- straggler detection

def test_straggler_hang_detected_from_jsonl_alone(tmp_path):
    """The injected `p1:hang@step5` fault timeline: p1's events stop at
    step 4 while p0 runs to 8 — the detector names the process and the
    step it never completed, from the shards alone."""
    base = _fleet_shards(tmp_path, hang_at=5, steps=8)
    findings = trace_mod.detect_anomalies(
        trace_mod.load_timeline(base),
        trace_mod.AnomalyConfig(straggler_skew_ms=100.0))
    stalls = [f for f in findings if f["anomaly"] == "straggler"
              and f["mode"] == "stall"]
    assert len(stalls) == 1
    f = stalls[0]
    assert f["process"] == "p1" and f["step"] == 5
    assert f["last_step"] == 4 and f["fleet_step"] == 8
    assert f["skew_ms"] > 100.0


def test_straggler_skew_detected_and_thresholded(tmp_path):
    base = _fleet_shards(tmp_path, skew_s=0.5, steps=4)
    tl = trace_mod.load_timeline(base)
    tight = trace_mod.detect_stragglers(
        tl, trace_mod.AnomalyConfig(straggler_skew_ms=100.0))
    assert len(tight) == 4
    assert all(f["process"] == "p1" and f["mode"] == "skew"
               and f["skew_ms"] == pytest.approx(500.0)
               for f in tight)
    loose = trace_mod.detect_stragglers(
        tl, trace_mod.AnomalyConfig(straggler_skew_ms=2000.0))
    assert loose == []


def test_clean_fleet_timeline_yields_zero_anomalies(tmp_path):
    base = _fleet_shards(tmp_path, steps=8)
    assert trace_mod.detect_anomalies(trace_mod.load_timeline(base)) == []


def test_single_process_never_flags_stragglers(tmp_path):
    base = str(tmp_path / "t.jsonl")
    _write_shard(base, [_step("a", i, i, 100.0 + i * 60)
                        for i in range(1, 5)])
    assert trace_mod.detect_stragglers(
        trace_mod.load_timeline(base)) == []


# ----------------------------------------------------- retrace detection

def _serving_events(*, late_compile):
    evs = [
        {"event": "span", "name": "compile", "warmup": True, "run": "s",
         "seq": 0, "ts": 1.0, "seconds": 0.5, "bucket": [1, 8]},
        {"event": "span", "name": "compile", "warmup": True, "run": "s",
         "seq": 1, "ts": 2.0, "seconds": 0.4, "bucket": [2, 8]},
        {"event": "request", "id": "r0", "ok": True, "run": "s",
         "seq": 2, "ts": 3.0, "total_s": 0.01},
    ]
    if late_compile:
        evs.append({"event": "span", "name": "compile", "run": "s",
                    "seq": 3, "ts": 4.0, "seconds": 0.6,
                    "bucket": [4, 8]})
    return evs


def _mem(run, seq, ts, live, *, devices=None, **extra):
    """One ledger-annotated memory event (telemetry/memstat.py shape)."""
    return {"event": "memory", "run": run, "seq": seq, "ts": ts,
            "live_array_bytes": int(live),
            "ledger": {"params": int(live) // 2,
                       "activations": int(live) - int(live) // 2},
            "ledger_total_bytes": int(live), "source": "fit",
            "devices": devices or {}, **extra}


def test_retrace_detected_from_doctored_late_compile_shard(tmp_path):
    base = str(tmp_path / "t.jsonl")
    _write_shard(base, _serving_events(late_compile=True))
    findings = trace_mod.detect_retraces(trace_mod.load_timeline(base))
    assert len(findings) == 1
    assert findings[0]["bucket"] == [4, 8]


def test_warmup_compiles_and_training_compiles_never_flag(tmp_path):
    base = str(tmp_path / "t.jsonl")
    # a training run: compile WITHOUT warmup flags, steps after — the
    # expected first-dispatch cost, not a retrace
    _write_shard(base, [
        {"event": "span", "name": "compile", "run": "t", "seq": 0,
         "ts": 1.0, "seconds": 2.0},
        _step("t", 1, 1, 2.0),
        {"event": "span", "name": "step_scan", "run": "t", "seq": 2,
         "ts": 3.0, "seconds": 0.1},
    ] + _serving_events(late_compile=False))
    assert trace_mod.detect_retraces(trace_mod.load_timeline(base)) == []


def test_retrace_scoped_per_run_in_shared_sweep_log(tmp_path):
    """The bench sweep's shared log interleaves many runs: a warmed
    serving run must not poison a LATER training run's first compile."""
    base = str(tmp_path / "t.jsonl")
    _write_shard(base, _serving_events(late_compile=False) + [
        {"event": "span", "name": "compile", "run": "t2", "seq": 0,
         "ts": 10.0, "seconds": 2.0}])
    assert trace_mod.detect_retraces(trace_mod.load_timeline(base)) == []


# ----------------------------------------------------- spike detection

def test_input_wait_spike_detection_and_warmup_carveout(tmp_path):
    base = str(tmp_path / "t.jsonl")
    waits = [0.4, 0.3, 0.001, 0.002, 0.5, 0.001]  # first two = cold start
    _write_shard(base, [
        {"event": "span", "name": "input_wait", "pipelined": True,
         "run": "a", "seq": i, "ts": 1.0 + i, "seconds": w}
        for i, w in enumerate(waits)
    ] + [  # the synchronous fallback measures conversion, exempt
        {"event": "span", "name": "input_wait", "pipelined": False,
         "run": "a", "seq": 10, "ts": 20.0, "seconds": 5.0}])
    findings = trace_mod.detect_input_wait_spikes(
        trace_mod.load_timeline(base))
    assert len(findings) == 1
    assert findings[0]["wait_ms"] == pytest.approx(500.0)


def test_queue_spike_detection(tmp_path):
    base = str(tmp_path / "t.jsonl")
    _write_shard(base, [
        {"event": "span", "name": "queue", "run": "a", "seq": 0,
         "ts": 1.0, "seconds": 2.0},
        {"event": "span", "name": "queue", "run": "a", "seq": 1,
         "ts": 2.0, "seconds": 0.002},
        {"event": "autoscale", "run": "a", "seq": 2, "ts": 3.0,
         "queue_depth": 100, "action": 1},
        {"event": "autoscale", "run": "a", "seq": 3, "ts": 4.0,
         "queue_depth": 2, "action": 0},
    ])
    findings = trace_mod.detect_queue_spikes(trace_mod.load_timeline(base))
    assert [f["kind"] for f in findings] == ["wait", "depth"]


# ------------------------------------------------------ straggler watch

def test_straggler_watch_emits_each_anomaly_once(tmp_path):
    base = _fleet_shards(tmp_path, hang_at=5, steps=8)
    rec = Recorder(path=None)
    watch = trace_mod.StragglerWatch(
        base, recorder=rec,
        config=trace_mod.AnomalyConfig(straggler_skew_ms=100.0),
        min_interval_s=0.0)
    first = watch.poll(force=True)
    again = watch.poll(force=True)
    assert len(first) == 1 and again == []
    anomalies = [e for e in rec.events if e["event"] == "anomaly"]
    assert len(anomalies) == 1
    assert anomalies[0]["kind"] == "straggler"
    assert anomalies[0]["process"] == "p1"


def test_straggler_watch_tolerates_missing_shards(tmp_path):
    rec = Recorder(path=None)
    watch = trace_mod.StragglerWatch(str(tmp_path / "nope.jsonl"),
                                     recorder=rec, min_interval_s=0.0)
    assert watch.poll(force=True) == []


# --------------------------------------------------- memory detectors

def _leak_shard(tmp_path, *, growth_per_step=1 << 20, steps=8,
                warm_spike=True):
    """A seeded synthetic leak: live bytes climb monotonically every
    sample past the warmup window. JSONL alone — no live process."""
    base = str(tmp_path / "t.jsonl")
    evs = []
    live = 10 << 20
    for s in range(steps):
        if warm_spike and s == 0:
            # warmup allocations dwarf the leak; the warmup slice
            # must hide them
            evs.append(_mem("runL", s, 1000.0 + s, live * 3))
            continue
        evs.append(_mem("runL", s, 1000.0 + s, live))
        live += growth_per_step
    _write_shard(base, evs)
    return base


def test_seeded_leak_detected_from_jsonl_alone(tmp_path):
    base = _leak_shard(tmp_path)
    findings = trace_mod.detect_leaks(trace_mod.load_timeline(base))
    assert len(findings) == 1
    f = findings[0]
    assert f["anomaly"] == "leak"
    assert f["growth_bytes"] >= 4 << 20
    assert f["last_bytes"] > f["first_bytes"]


def test_leak_needs_monotonic_steady_state_growth(tmp_path):
    # a sawtooth (allocations that free) is NOT a leak
    base = str(tmp_path / "t.jsonl")
    vals = [10, 14, 11, 15, 12, 16, 13]
    _write_shard(base, [_mem("runS", i, 1000.0 + i, v << 20)
                        for i, v in enumerate(vals)])
    assert trace_mod.detect_leaks(trace_mod.load_timeline(base)) == []
    # flat steady state is clean too
    base2 = str(tmp_path / "t2.jsonl")
    _write_shard(base2, [_mem("runF", i, 1000.0 + i, 10 << 20)
                         for i in range(8)])
    assert trace_mod.detect_leaks(trace_mod.load_timeline(base2)) == []
    # growth under the floor (a few stray KBs) stays silent
    base3 = str(tmp_path / "t3.jsonl")
    _write_shard(base3, [_mem("runK", i, 1000.0 + i, (10 << 20) + i * 512)
                         for i in range(8)])
    assert trace_mod.detect_leaks(trace_mod.load_timeline(base3)) == []


def test_headroom_breach_detected_and_off_tpu_silent(tmp_path):
    base = str(tmp_path / "t.jsonl")
    hot = {"0": {"bytes_in_use": 95, "bytes_limit": 100,
                 "peak_bytes_in_use": 96}}
    cold = {"0": {"bytes_in_use": 10, "bytes_limit": 100,
                  "peak_bytes_in_use": 12}}
    _write_shard(base, [
        _mem("runH", 0, 1000.0, 1 << 20, devices=cold),
        _mem("runH", 1, 1001.0, 1 << 20, devices=hot),
        _mem("runH", 2, 1002.0, 1 << 20, devices=hot),  # dedup: one finding
    ])
    findings = trace_mod.detect_headroom(trace_mod.load_timeline(base))
    assert len(findings) == 1
    assert findings[0]["anomaly"] == "headroom"
    assert findings[0]["ratio"] == pytest.approx(0.95)
    # off-TPU shards carry no bytes_limit: never a breach
    base2 = str(tmp_path / "t2.jsonl")
    _write_shard(base2, [_mem("runC", 0, 1000.0, 1 << 30)])
    assert trace_mod.detect_headroom(trace_mod.load_timeline(base2)) == []


def test_cost_drift_detected_from_typed_event(tmp_path):
    base = str(tmp_path / "t.jsonl")
    _write_shard(base, [
        {"event": "cost_drift", "run": "runD", "seq": 0, "ts": 1000.0,
         "predicted_bytes": 1000, "measured_bytes": 32000,
         "ratio": 32.0, "factor": 8.0, "source": "placement"},
        {"event": "cost_drift", "run": "runD", "seq": 1, "ts": 1001.0,
         "predicted_bytes": 1000, "measured_bytes": 2000,
         "ratio": 2.0, "factor": 8.0, "source": "placement"},
    ])
    findings = trace_mod.detect_cost_drift(trace_mod.load_timeline(base))
    assert len(findings) == 1  # in-band reconciliation stays silent
    assert findings[0]["anomaly"] == "cost_drift"
    assert findings[0]["ratio"] == pytest.approx(32.0)
    # the acceptance path: the doctored drift gates the CLI from the
    # JSONL alone, and gating on other kinds leaves it informational
    out = _tracetool("check", base, "--fail-on", "cost_drift")
    assert out.returncode == 1, out.stdout
    assert _tracetool("check", base, "--fail-on",
                      "leak,headroom").returncode == 0


def test_cost_drift_join_fallback_from_placement_search(tmp_path):
    """A doctored cost-model drift with NO typed reconciliation: the
    detector joins the placement_search winner's predicted bytes
    against later measured memory events in the same (process, run)."""
    base = str(tmp_path / "t.jsonl")
    search = {"event": "placement_search", "run": "runJ", "seq": 0,
              "ts": 1000.0, "winner": "tp4", "winner_score": 1.0,
              "winner_memory_bytes": 1000.0}
    _write_shard(base, [search,
                        _mem("runJ", 1, 1001.0, 64000)])
    findings = trace_mod.detect_cost_drift(trace_mod.load_timeline(base))
    assert len(findings) == 1
    assert findings[0]["source"] == "join"
    assert findings[0]["ratio"] == pytest.approx(64.0)
    # within-band measurement: clean
    base2 = str(tmp_path / "t2.jsonl")
    _write_shard(base2, [dict(search, run="runK"),
                         _mem("runK", 1, 1001.0, 4000)])
    assert trace_mod.detect_cost_drift(
        trace_mod.load_timeline(base2)) == []


def test_clean_memory_timeline_yields_zero_anomalies(tmp_path):
    """The happy path: warmup spike settling into flat steady state,
    healthy device headroom, in-band reconciliation — zero findings
    across ALL detectors."""
    base = str(tmp_path / "t.jsonl")
    dev = {"0": {"bytes_in_use": 40, "bytes_limit": 100,
                 "peak_bytes_in_use": 45}}
    evs = [_mem("runOK", 0, 1000.0, 30 << 20, devices=dev)]
    evs += [_mem("runOK", i, 1000.0 + i, 10 << 20, devices=dev)
            for i in range(1, 7)]
    evs.append({"event": "cost_drift", "run": "runOK", "seq": 7,
                "ts": 1007.0, "predicted_bytes": 8 << 20,
                "measured_bytes": 12 << 20, "ratio": 1.5,
                "factor": 8.0, "source": "placement"})
    _write_shard(base, evs)
    assert trace_mod.detect_anomalies(trace_mod.load_timeline(base)) == []


def test_memory_watch_emits_each_finding_once(tmp_path):
    base = _leak_shard(tmp_path)
    rec = Recorder(path=None)
    watch = trace_mod.MemoryWatch(base, recorder=rec, min_interval_s=0.0)
    first = watch.poll(force=True)
    again = watch.poll(force=True)
    assert len(first) == 1 and again == []
    anomalies = [e for e in rec.events if e["event"] == "anomaly"]
    assert len(anomalies) == 1 and anomalies[0]["kind"] == "leak"


def test_memory_report_and_metric_rows(tmp_path):
    base = _leak_shard(tmp_path)
    with open(base, "a") as fh:
        fh.write(json.dumps(
            {"event": "cost", "run": "runL", "seq": 99, "ts": 2000.0,
             "entry": "forward", "shape": [4, 16], "flops": 1e9,
             "bytes_accessed": 2e6, "peak_temp_bytes": 4096}) + "\n")
    tl = trace_mod.load_timeline(base)
    report = trace_mod.memory_report(tl)
    proc = report["processes"]["main"]
    assert proc["samples"] == 8
    assert proc["peak_bytes"] == 30 << 20  # the warmup spike
    assert proc["ledger"]["params"] > 0
    assert report["cost_book"]["forward::[4, 16]"]["flops"] == 1e9
    findings = trace_mod.detect_anomalies(tl)
    lines = trace_mod.metric_lines(tl, findings)
    by_name = {l["metric"]: l for l in lines}
    assert by_name["trace_leak_count"]["value"] == 1
    assert by_name["trace_leak_count"]["lower_is_better"]
    assert by_name["trace_cost_drift_ratio"]["value"] == 0.0
    assert by_name["trace_hbm_peak_bytes"]["value"] == 30 << 20


def test_tracetool_check_fails_on_seeded_leak(tmp_path):
    """The acceptance criterion: a seeded synthetic leak is flagged
    `leak` by `tracetool check --fail-on leak` from JSONL alone."""
    base = _leak_shard(tmp_path)
    out = _tracetool("check", base, "--fail-on", "leak", "--json")
    assert out.returncode == 1, out.stdout
    payload = json.loads(out.stdout)
    assert payload["gating"] == 1
    assert payload["findings"][0]["anomaly"] == "leak"
    # threshold flag: a floor above the seeded growth silences it
    out = _tracetool("check", base, "--fail-on", "leak",
                     "--leak-min-bytes", str(1 << 30))
    assert out.returncode == 0
    # and scoping: the same record gated on other kinds stays 0
    out = _tracetool("check", base, "--fail-on", "retrace,straggler")
    assert out.returncode == 0


def test_tracetool_mem_report_cli(tmp_path):
    base = _leak_shard(tmp_path)
    out = _tracetool("mem", base, "--json")
    assert out.returncode == 0
    report = json.loads(out.stdout)
    assert report["processes"]["main"]["samples"] == 8
    out = _tracetool("mem", base)
    assert out.returncode == 0 and "ledger" in out.stdout


def test_committed_bench_shards_memory_happy_path():
    """Clean committed fixtures stay clean through the new detectors:
    zero leak/headroom/cost_drift findings on the happy path."""
    tl = trace_mod.load_timeline(
        os.path.join(ROOT, "telemetry_bench.jsonl"))
    findings = (trace_mod.detect_leaks(tl)
                + trace_mod.detect_headroom(tl)
                + trace_mod.detect_cost_drift(tl))
    assert findings == []


# ------------------------------------------------------ perfetto export

def test_perfetto_export_schema_validity(tmp_path):
    base = _fleet_shards(tmp_path, steps=3)
    rec_events = _serving_events(late_compile=False)
    rec_events.append(_mem("s", 3, 5.0, 1 << 20))
    _write_shard(base, rec_events)  # unsuffixed joins as "main"
    doc = trace_mod.to_perfetto(trace_mod.load_timeline(base))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    assert evs, "export must not be empty"
    # round-trips through json
    evs = json.loads(json.dumps(doc))["traceEvents"]
    pids = set()
    counters = []
    for ev in evs:
        assert {"name", "ph", "pid", "tid"} <= set(ev)
        pids.add(ev["pid"])
        if ev["ph"] == "X":
            assert ev["dur"] >= 0 and ev["ts"] >= 0
        elif ev["ph"] == "M":
            assert ev["name"] == "process_name"
        elif ev["ph"] == "C":
            # memory events render as counter tracks: live bytes +
            # the per-subsystem ledger series
            assert ev["name"] == "device_memory"
            assert ev["args"]["live_array_bytes"] == 1 << 20
            assert "ledger_params" in ev["args"]
            counters.append(ev)
        else:
            assert ev["ph"] == "i"
    assert len(counters) == 1
    assert len(pids) == 3  # main + p0 + p1
    # spans are placed at START time: a compile at ts=1.0 lasting 0.5s
    # begins 0.5s before its completion stamp
    comp = next(e for e in evs if e["name"] == "compile")
    assert comp["dur"] == pytest.approx(0.5e6)


# ------------------------------------------------------- TRACE artifacts

def test_metric_lines_and_benchdiff_directions(tmp_path):
    base = _fleet_shards(tmp_path, skew_s=0.5, steps=4)
    tl = trace_mod.load_timeline(base)
    findings = trace_mod.detect_anomalies(
        tl, trace_mod.AnomalyConfig(straggler_skew_ms=100.0))
    lines = trace_mod.metric_lines(tl, findings)
    by_name = {l["metric"]: l for l in lines}
    assert by_name["trace_anomaly_count"]["value"] == 4
    assert by_name["trace_anomaly_count"]["lower_is_better"]
    assert by_name["trace_straggler_skew_ms"]["value"] == \
        pytest.approx(500.0)


# ------------------------------------------------------------- the CLI

def _tracetool(*args):
    return subprocess.run([sys.executable, TRACETOOL, *args],
                          capture_output=True, text=True, timeout=120)


def test_tracetool_stats_merge_tree_and_check(tmp_path):
    base = _fleet_shards(tmp_path, steps=4)
    _write_shard(base, _serving_events(late_compile=False))
    out = _tracetool("stats", base)
    assert out.returncode == 0
    assert "p0" in out.stdout and "p1" in out.stdout
    merged = str(tmp_path / "merged.jsonl")
    out = _tracetool("merge", base, "-o", merged)
    assert out.returncode == 0
    with open(merged) as fh:
        lines = [json.loads(l) for l in fh]
    assert len(lines) == 11 and all("process" in l for l in lines)
    out = _tracetool("check", base)
    assert out.returncode == 0, out.stdout
    out = _tracetool("tree", base)
    assert out.returncode == 0
    out = _tracetool("check", str(tmp_path / "absent.jsonl"))
    assert out.returncode == 2


def test_tracetool_check_fails_on_injected_hang(tmp_path):
    base = _fleet_shards(tmp_path, hang_at=3, steps=6)
    out = _tracetool("check", base, "--skew-ms", "100", "--json")
    assert out.returncode == 1
    payload = json.loads(out.stdout)
    assert payload["gating"] == 1
    assert payload["findings"][0]["anomaly"] == "straggler"
    # --fail-on scoping: the same finding demoted to informational
    out = _tracetool("check", base, "--skew-ms", "100",
                     "--fail-on", "retrace")
    assert out.returncode == 0


def test_tracetool_export_perfetto(tmp_path):
    base = _fleet_shards(tmp_path, steps=3)
    out_path = str(tmp_path / "t.perfetto.json")
    out = _tracetool("export", base, "--perfetto", "-o", out_path)
    assert out.returncode == 0
    with open(out_path) as fh:
        doc = json.load(fh)
    assert doc["traceEvents"]


def test_tracetool_stats_on_committed_shards():
    """The acceptance fixture: the committed telemetry_bench.jsonl.p0/
    .p1 pair merges into per-span p50/p99 for >= 2 processes."""
    out = _tracetool("stats", os.path.join(ROOT, "telemetry_bench.jsonl"),
                     "--json")
    assert out.returncode == 0
    stats = json.loads(out.stdout)
    procs = {k.split("::")[0] for k in stats}
    assert {"p0", "p1"} <= procs
    for row in stats.values():
        assert row["p99_ms"] >= row["p50_ms"] >= 0
        assert row["count"] >= 1


# ------------------------------------------------------ metrics registry

def test_rolling_histogram_exposition_and_quantiles():
    reg = MetricsRegistry()
    h = reg.histogram("req_latency_seconds", "test", window=64)
    g = reg.gauge("queue_depth", "test")
    c = reg.counter("requests_total", "test")
    for v in (0.001, 0.002, 0.004, 0.2, 0.4):
        reg.observe(h, v)
    g.set(3)
    reg.inc(c, 1.0, outcome="ok")
    reg.inc(c, 1.0, outcome="ok")
    reg.inc(c, 1.0, outcome="error")
    text = reg.render()
    parsed = parse_exposition(text)
    assert parsed["req_latency_seconds_count"] == 5
    assert parsed["req_latency_seconds_sum"] == pytest.approx(0.607)
    assert parsed['req_latency_seconds_bucket{le="+Inf"}'] == 5
    assert parsed['req_latency_seconds_bucket{le="0.005"}'] == 3
    assert parsed['requests_total{outcome="ok"}'] == 2
    assert parsed["queue_depth"] == 3
    assert parsed["req_latency_seconds_p50"] == pytest.approx(0.004)
    assert parsed["req_latency_seconds_p99"] == pytest.approx(0.4)
    assert "# TYPE req_latency_seconds histogram" in text
    assert CONTENT_TYPE.startswith("text/plain; version=0.0.4")
    # bucket counts are cumulative-monotone
    cum = [v for k, v in parsed.items() if "_bucket{" in k]
    assert cum == sorted(cum)


def test_registry_render_is_thread_safe_under_writes():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "test")
    stop = threading.Event()
    errors = []

    def writer():
        i = 0
        while not stop.is_set():
            reg.observe(h, 0.001 * (i % 7))
            i += 1

    def reader():
        try:
            for _ in range(50):
                parse_exposition(reg.render())
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    w = threading.Thread(target=writer)
    w.start()
    readers = [threading.Thread(target=reader) for _ in range(3)]
    for t in readers:
        t.start()
    for t in readers:
        t.join()
    stop.set()
    w.join()
    assert not errors


# ----------------------------------------- artifact sharded-input fallback

def test_artifact_load_falls_back_to_shards(tmp_path):
    from deeplearning4j_tpu.telemetry import artifact as art

    base = str(tmp_path / "t.jsonl")
    _write_shard(base + ".p0", [
        {"event": "metric", "metric": "m0", "value": 1.0, "seq": 0,
         "ts": 1.0}])
    _write_shard(base + ".p1", [
        {"event": "metric", "metric": "m1", "value": 2.0, "seq": 0,
         "ts": 2.0}])
    lines = art.load(base)  # the unsuffixed file does not exist
    assert lines["m0"]["value"] == 1.0 and lines["m1"]["value"] == 2.0
    with pytest.raises(FileNotFoundError):
        art.load(str(tmp_path / "absent.jsonl"))


def test_artifact_committed_shard_pair_parses():
    from deeplearning4j_tpu.telemetry import artifact as art

    text = art.read_artifact_text(
        os.path.join(ROOT, "telemetry_bench.jsonl") + "")
    assert text  # unsuffixed exists; now force the shard path
    shard_text = art._read_shards(
        os.path.join(ROOT, "telemetry_bench.jsonl"))
    assert shard_text.count("\n") >= 2
