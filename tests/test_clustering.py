"""Clustering package tests (SURVEY.md §4 pattern: real math on tiny data;
reference tests KDTreeTest/QuadTreeTest/SPTreeTest/VpTreeNodeTest)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import (
    HyperRect,
    KDTree,
    KMeansClustering,
    Point,
    QuadTree,
    SpTree,
    VPTree,
)


def _blobs(rng, k=3, per=40, dim=4, spread=0.15):
    centers = rng.normal(size=(k, dim)) * 5.0
    pts = np.concatenate(
        [c + rng.normal(scale=spread, size=(per, dim)) for c in centers])
    labels = np.repeat(np.arange(k), per)
    return pts.astype(np.float32), labels, centers


class TestKMeans:
    def test_recovers_well_separated_blobs(self, rng):
        pts, labels, _ = _blobs(rng)
        cs = KMeansClustering(3, max_iterations=50, seed=1).apply_to(pts)
        assert cs.cluster_count() == 3
        # every true blob maps to exactly one predicted cluster
        pred = np.empty(len(pts), dtype=int)
        for ci, cluster in enumerate(cs.clusters):
            for p in cluster.points:
                pred[int(p.id)] = ci
        for b in range(3):
            assert len(set(pred[labels == b])) == 1
        assert len({pred[labels == b][0] for b in range(3)}) == 3

    def test_distortion_monotone_nonincreasing(self, rng):
        pts, _, _ = _blobs(rng, k=2, per=30)
        km = KMeansClustering(2, max_iterations=30, seed=3)
        km.apply_to(pts)
        h = km.distortion_history
        assert all(h[i + 1] <= h[i] + 1e-3 for i in range(len(h) - 1))

    def test_classify_point(self, rng):
        pts, _, _ = _blobs(rng, k=2, per=20, dim=3)
        cs = KMeansClustering(2, seed=0).apply_to(pts)
        pc = cs.classify_point(Point(pts[0]), move=False)
        assert pc.distance == pytest.approx(
            float(np.linalg.norm(pts[0] - pc.cluster.center)))

    def test_point_objects_roundtrip(self, rng):
        pts = rng.normal(size=(10, 2)).astype(np.float32)
        objs = Point.to_points(pts)
        cs = KMeansClustering(2, seed=0).apply_to(objs)
        total = sum(len(c.points) for c in cs.clusters)
        assert total == 10

    def test_setup_rejects_unknown_metric(self):
        with pytest.raises(ValueError):
            KMeansClustering.setup(2, distance_function="manhattan")


class TestKDTree:
    def test_knn_matches_bruteforce(self, rng):
        pts = rng.normal(size=(200, 3))
        tree = KDTree(3)
        for p in pts:
            tree.insert(p)
        q = rng.normal(size=3)
        got = [tuple(p) for _, p in tree.knn(q, 5)]
        want_idx = np.argsort(np.linalg.norm(pts - q, axis=1))[:5]
        want = [tuple(pts[i]) for i in want_idx]
        assert got == want

    def test_nn(self, rng):
        pts = rng.normal(size=(50, 2))
        tree = KDTree(2)
        for p in pts:
            tree.insert(p)
        d, p = tree.nn(pts[7])
        assert d == pytest.approx(0.0)
        assert np.allclose(p, pts[7])

    def test_range_query(self, rng):
        pts = rng.uniform(-1, 1, size=(100, 2))
        tree = KDTree(2)
        for p in pts:
            tree.insert(p)
        rect = HyperRect(np.array([-0.5, -0.5]), np.array([0.5, 0.5]))
        got = {tuple(p) for p in tree.range(rect)}
        want = {tuple(p) for p in pts if rect.contains(p)}
        assert got == want


class TestVPTree:
    def test_search_matches_bruteforce_euclidean(self, rng):
        pts = rng.normal(size=(150, 5))
        tree = VPTree(pts, seed=0)
        q = rng.normal(size=5)
        got = [i for _, i in tree.search(q, 7)]
        want = list(np.argsort(np.linalg.norm(pts - q, axis=1))[:7])
        assert got == want

    def test_words_nearest_cosine(self, rng):
        vecs = rng.normal(size=(20, 8))
        labels = [f"w{i}" for i in range(20)]
        tree = VPTree(vecs, labels=labels, metric="cosine", seed=0)
        near = tree.words_nearest(vecs[3], 1)
        assert near == ["w3"]


class TestSpTree:
    def test_build_and_com(self, rng):
        pts = rng.normal(size=(64, 2))
        tree = SpTree(pts)
        assert tree.cum_size == 64
        assert np.allclose(tree.center_of_mass, pts.mean(axis=0))
        assert tree.depth() > 1

    def test_non_edge_forces_match_exact_at_theta0(self, rng):
        """theta=0 disables approximation → matches the exact O(N²) sums."""
        pts = rng.normal(size=(40, 2))
        tree = SpTree(pts)
        i = 5
        neg_f = np.zeros(2)
        sum_q = tree.compute_non_edge_forces(i, theta=0.0, neg_f=neg_f)
        diff = pts[i][None, :] - pts
        d2 = np.sum(diff * diff, axis=1)
        q = 1.0 / (1.0 + d2)
        q[i] = 0.0
        exact_sum_q = q.sum()
        exact_neg = (q[:, None] ** 2 * diff).sum(axis=0)
        assert sum_q == pytest.approx(exact_sum_q, rel=1e-9)
        assert np.allclose(neg_f, exact_neg)

    def test_non_edge_forces_duplicate_rows(self, rng):
        """Duplicate rows collapse into one leaf; every copy must still count
        toward every other point's sum_Q, and each duplicate must see the
        same sums (self excluded) — covers both the absorbed-then-subdivided
        insertion order and direct duplicate leaves."""
        base = rng.normal(size=(6, 2))
        # [dup, dup, far, ...]: index 1 absorbed into 0's leaf, later points
        # force subdivision of that leaf
        pts = np.vstack([base[0], base[0], base[1:], base[0]])
        n = len(pts)
        for i in range(n):
            neg_f = np.zeros(2)
            sum_q = SpTree(pts).compute_non_edge_forces(i, theta=0.0,
                                                        neg_f=neg_f)
            diff = pts[i][None, :] - pts
            d2 = np.sum(diff * diff, axis=1)
            q = 1.0 / (1.0 + d2)
            q[i] = 0.0
            exact_neg = (q[:, None] ** 2 * diff).sum(axis=0)
            assert sum_q == pytest.approx(q.sum(), rel=1e-9), f"point {i}"
            assert np.allclose(neg_f, exact_neg)

    def test_theta_pruning_approximates(self, rng):
        pts = rng.normal(size=(128, 2))
        tree = SpTree(pts)
        exact, approx = np.zeros(2), np.zeros(2)
        sq_exact = tree.compute_non_edge_forces(0, 0.0, exact)
        sq_approx = tree.compute_non_edge_forces(0, 0.5, approx)
        assert sq_approx == pytest.approx(sq_exact, rel=0.1)
        assert np.linalg.norm(approx - exact) < 0.1 * (np.linalg.norm(exact) + 1e-9)

    def test_edge_forces(self, rng):
        pts = rng.normal(size=(6, 2))
        tree = SpTree(pts)
        # one edge 0→1 with weight 2.0
        rows = np.array([0, 1, 1, 1, 1, 1, 1])
        cols = np.array([1])
        vals = np.array([2.0])
        pos_f = tree.compute_edge_forces(rows, cols, vals)
        diff = pts[0] - pts[1]
        want = 2.0 * diff / (1.0 + diff @ diff)
        assert np.allclose(pos_f[0], want)
        assert np.allclose(pos_f[2:], 0.0)


class TestQuadTree:
    def test_quadrants(self, rng):
        pts = np.array([[-1.0, -1.0], [1.0, -1.0], [-1.0, 1.0], [1.0, 1.0],
                        [0.5, 0.5]])
        tree = QuadTree(pts)
        assert tree.cum_size == 5
        assert not tree.is_leaf
        assert tree.north_east is not None and tree.north_east.cum_size >= 1

    def test_requires_2d(self, rng):
        with pytest.raises(ValueError):
            QuadTree(rng.normal(size=(10, 3)))


class TestRobustness:
    def test_vptree_duplicate_rows(self):
        """Regression: duplicate rows once stalled the median split."""
        pts = np.zeros((1500, 3))
        tree = VPTree(pts, seed=0)
        assert len(tree.search(np.zeros(3), 3)) == 3

    def test_kdtree_sorted_insertion(self):
        """Regression: sorted input builds a deep chain; traversal must not
        recurse."""
        tree = KDTree(2)
        for i in range(5000):
            tree.insert(np.array([float(i), 0.0]))
        got = [p[0] for _, p in tree.knn(np.array([4999.0, 0.0]), 3)]
        assert sorted(got) == [4997.0, 4998.0, 4999.0]
        rect = HyperRect(np.array([10.0, -1.0]), np.array([12.0, 1.0]))
        assert len(tree.range(rect)) == 3

    def test_kdtree_delete(self, ):
        tree = KDTree(2)
        pts = [np.array([float(i), float(i % 3)]) for i in range(30)]
        for p in pts:
            tree.insert(p)
        assert tree.delete(pts[10])
        assert tree.size == 29
        d, _ = tree.nn(pts[10])
        assert d > 0.0
        assert not tree.delete(np.array([99.0, 99.0]))

    def test_cluster_move_semantics(self):
        from deeplearning4j_tpu.clustering import KMeansClustering
        rng = np.random.default_rng(5)
        pts = np.concatenate([rng.normal(size=(10, 2)) + 5,
                              rng.normal(size=(10, 2)) - 5]).astype(np.float32)
        cs = KMeansClustering(2, seed=0).apply_to(pts)
        # re-classify every point: membership count stays exactly N
        for c in cs.clusters:
            for p in list(c.points):
                cs.classify_point(p)
        assert sum(len(c.points) for c in cs.clusters) == 20
        results = cs.classify_points([c.points[0] for c in cs.clusters])
        assert len(results) == 2
        assert sum(len(c.points) for c in cs.clusters) == 20

    def test_kdtree_delete_with_duplicate_split_values(self):
        """Regression: rebuild after delete must keep equal-valued points
        findable (strict-< goes left invariant)."""
        tree = KDTree(2)
        pts = [np.array(v, dtype=float) for v in
               [(5, 0), (2, 9), (2, 1), (3, 4), (2, 5), (1, 7)]]
        for p in pts:
            tree.insert(p)
        assert tree.delete(pts[0])
        assert tree.delete(pts[1])
        assert tree.delete(pts[4])
        rect = HyperRect(np.array([2.0, -10.0]), np.array([2.0, 10.0]))
        assert len(tree.range(rect)) == 1  # only (2,1) remains
