"""Off-TPU compile smoke for the bench's Transformer-LM modes (VERDICT
r5 #1): the r5 `transformer_large` mode crashed ONLY under driver capture
because no CI path ever built the d1024 model — its CPU branch printed a
skip line and returned. Here every mode in bench.LM_MODE_DIMS is built at
its REAL (TPU) dims and its training step is traced end-to-end with
jax.eval_shape (fwd + bwd + optimizer, no FLOPs executed), so a mode that
cannot even trace fails tier-1, not the round artifact.

This is also where the r6 tentpole's end-to-end acceptance lives off-TPU:
`longcontext_chunked_dropout` (masked + attention dropout at seq 32768)
must trace through the chunked flash dispatch — in r5 that config raised
chunked_unsupported_reason.
"""

import numpy as np

import jax
import pytest

import bench
from bench import LM_MODE_DIMS, lm_mode_net_ds


def _trace_step(mode):
    net, ds, cfg = lm_mode_net_ds(mode, force_tpu_dims=True)
    batch = net._batch_dict(net._to_mds(ds))
    step = net._get_train_step()
    out = jax.eval_shape(step, net.params, net.opt_state, net.state,
                         jax.random.PRNGKey(0), batch)
    return out, cfg


@pytest.mark.parametrize("mode", sorted(LM_MODE_DIMS))
def test_lm_mode_builds_and_traces_at_real_dims(mode):
    (params, opt_state, state, loss, _), cfg = _trace_step(mode)
    assert loss.shape == ()
    # the traced model really is the TPU config, not a CPU shrink
    emb = params["embed"]["W"] if "embed" in params else None
    if emb is not None:
        assert emb.shape[-1] == cfg["d_model"]


@pytest.mark.parametrize("mode", ["transformer", "transformer_large"])
def test_lm_mode_scanned_fit_path_traces_at_real_dims(mode):
    """The bench times `_time_net_steps` -> fit_scanned (the whole-epoch
    lax.scan over the jitted step), a path the bare-step smoke above
    does not reach — the r5 transformer_large crash class lived exactly
    in "works when the author tried a step, dies in the sweep's stock
    fit path". Trace the scan end-to-end at REAL dims."""
    from deeplearning4j_tpu.nn.training import make_scanned_fit, stack_batches

    net, ds, cfg = lm_mode_net_ds(mode, force_tpu_dims=True)
    batch = net._batch_dict(net._to_mds(ds))
    stacked = stack_batches([batch])
    run = make_scanned_fit(net._get_train_step())
    params, _, _, losses = jax.eval_shape(
        lambda *a: run(*a, n_epochs=2),
        net.params, net.opt_state, net.state, jax.random.PRNGKey(0),
        stacked)
    assert losses.shape == (2, 1)
    assert params["embed"]["W"].shape[-1] == cfg["d_model"]


@pytest.mark.slow
def test_transformer_large_real_dims_executes_one_step():
    """Execute (not just trace) the d1024/8-head/d_ff-4096 config at the
    REAL model dims through the same fit_scanned path the bench times —
    interpret-mode kernels off-TPU, batch shrunk to 2 to keep the run in
    the slow-tier budget. A d1024 path that only breaks at execution
    time fails here, not in the round artifact."""
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
    from deeplearning4j_tpu.models.transformer import transformer_lm

    cfg = LM_MODE_DIMS["transformer_large"]
    batch = 2
    net = transformer_lm(
        vocab_size=bench.VOCAB_LM, d_model=cfg["d_model"],
        n_heads=cfg["n_heads"], n_layers=cfg.get("n_layers", 6),
        d_ff=cfg["d_ff"], max_length=cfg["seq"], dtype="bfloat16")
    net.init()
    rng = np.random.default_rng(0)
    toks = np.asarray(rng.integers(0, bench.VOCAB_LM, (batch, cfg["seq"])),
                      np.int32)
    ds = DataSet(toks, np.roll(toks, -1, axis=1))
    net.fit_scanned(ListDataSetIterator([ds]), epochs=1)
    assert np.isfinite(net.score_value)


# ------------------------------------------------- causal FLOP accounting

def test_causal_flop_formula_pinned_at_two_sequence_lengths():
    """VERDICT r5 #4 / ISSUE 7 satellite: the executed-FLOPs accounting
    must count exactly T(T+1)/2 causal (query, key) pairs — not the
    dense T^2 and not the 0.5 approximation. Pinned against the closed
    form at both the flagship and the chunked-path sequence lengths."""
    from deeplearning4j_tpu.models.transformer import (
        causal_attention_factor,
        transformer_flops_per_token,
        transformer_flops_per_token_executed,
    )

    V, d, L, dff = 10000, 256, 6, 1024
    for T in (512, 32768):
        factor = causal_attention_factor(T)
        assert factor == (T + 1) / (2.0 * T)
        # exact closed form of the executed count
        per_layer = (4 * 2 * d * d + 2 * 2 * d * dff
                     + factor * 2 * 2 * T * d)
        want = int(3 * (L * per_layer + 2 * d * V))
        got = transformer_flops_per_token_executed(V, d, L, dff, T)
        assert got == want
        dense = transformer_flops_per_token(V, d, L, dff, T)
        # causal executes T(T+1)/2 of the dense T^2 attention pairs
        attn_dense = 3 * L * 2 * 2 * T * d
        assert dense - got == int(round(attn_dense * (1 - factor)))
        assert got < dense
        # non-causal executes the full dense matrix
        assert transformer_flops_per_token_executed(
            V, d, L, dff, T, causal=False) == dense
    # the inflation the dense convention buys grows with T: ~12% of the
    # attention-dominated total at 32k vs ~4% at 512
    r512 = (transformer_flops_per_token(V, d, L, dff, 512)
            / transformer_flops_per_token_executed(V, d, L, dff, 512))
    r32k = (transformer_flops_per_token(V, d, L, dff, 32768)
            / transformer_flops_per_token_executed(V, d, L, dff, 32768))
    assert r32k > 1.8 > 1.2 > r512 > 1.0


def test_every_lm_mode_is_runnable_from_the_cli():
    """Each registry entry is wired to a MODES command (and vice versa
    for the LM family), so the smoke can't drift from what the driver
    actually runs."""
    for mode in LM_MODE_DIMS:
        assert mode in bench.MODES, mode


def test_dropout_seq32768_cfg_is_the_tentpole_config():
    cfg = LM_MODE_DIMS["longcontext_chunked_dropout"]
    assert cfg["seq"] == 32768 and cfg["attention_dropout"] > 0
    assert cfg["masked"]


_REAL_RUN = bench.subprocess.run


def _fake_mode_run(argv, env=None, capture_output=True, text=True,
                   timeout=None):
    """Fake subprocess.run for the sweep loop: one clean mode, one
    deterministic crasher, one wall-clock timeout. The sweep's OWN
    tracetool self-audit passes through to the real CLI — the check
    over the fake sweep's telemetry is part of the contract."""
    import subprocess as sp
    import json as _json
    if any("tracetool" in str(a) for a in argv):
        return _REAL_RUN(argv, env=env, capture_output=capture_output,
                         text=text, timeout=timeout)
    mode = argv[-1]

    class Out:
        def __init__(self, rc, stdout="", stderr=""):
            self.returncode, self.stdout, self.stderr = rc, stdout, stderr

    if mode == "ok":
        return Out(0, stdout=_json.dumps(
            {"metric": "ok", "value": 1.0, "unit": "x"}) + "\n")
    if mode == "crashy":
        return Out(1, stderr="Traceback (most recent call last):\n"
                             "ValueError: boom at real dims\n")
    raise sp.TimeoutExpired(argv, timeout, stderr=b"partial child stderr")


def test_sweep_classifies_env_failures_off_tpu(monkeypatch, tmp_path):
    """ROADMAP "get the sweep to rc=0": OFF-TPU, a mode lost to the
    environment (the vgg16 CPU-contention timeout class, or any per-mode
    crash) becomes a skipped-env metric line with the FULL stderr in
    telemetry — the sweep exits 0 and the summary names what was
    skipped."""
    import json as _json
    from deeplearning4j_tpu.telemetry import set_default

    monkeypatch.setattr(bench.subprocess, "run", _fake_mode_run)
    monkeypatch.setattr(bench, "_probe_backend", lambda: "cpu")
    monkeypatch.setattr(bench, "MODES", {"ok": None, "crashy": None,
                                         "slow": None})
    tpath = tmp_path / "tel.jsonl"
    monkeypatch.setenv("DL4J_TPU_TELEMETRY", str(tpath))
    monkeypatch.setenv("DL4J_TPU_TRACE_ARTIFACT",
                       str(tmp_path / "TRACE_test.json"))
    try:
        rc = bench._run_all()
    finally:
        set_default(None)
    assert rc == 0
    # the self-audit rows rode the sweep record (clean run: 0 findings)
    assert (tmp_path / "TRACE_test.json").exists()
    events = [_json.loads(line) for line in open(tpath)]
    errors = [e for e in events if e["event"] == "error"]
    # full stderr survives in telemetry even though the sweep passed
    assert any("skipped-env" in e["error"]
               and "boom at real dims" in e["traceback"] for e in errors)
    assert any("skipped-env" in e["error"]
               and "partial child stderr" in e["traceback"]
               for e in errors)
    metrics = [e for e in events if e["event"] == "metric"]
    skip_lines = {e["metric"]: e["skipped"] for e in metrics
                  if "skipped" in e}
    assert set(skip_lines) == {"crashy", "slow"}
    assert all(s.startswith("env: off-TPU") for s in skip_lines.values())
    summary = [e for e in metrics if e.get("metric") == "summary"][-1]
    assert sorted(summary["skipped_env"]) == ["crashy", "slow"]
    assert summary.get("ok") == 1.0


def test_sweep_still_fails_on_tpu(monkeypatch, tmp_path):
    """ON the real chip the same failures keep rc=1 — skipped-env is an
    off-TPU smoke classification, not a blanket amnesty."""
    from deeplearning4j_tpu.telemetry import set_default

    monkeypatch.setattr(bench.subprocess, "run", _fake_mode_run)
    monkeypatch.setattr(bench, "_probe_backend", lambda: "tpu")
    monkeypatch.setattr(bench, "MODES", {"ok": None, "crashy": None})
    monkeypatch.setenv("DL4J_TPU_TELEMETRY", str(tmp_path / "tel.jsonl"))
    monkeypatch.setenv("DL4J_TPU_TRACE_ARTIFACT",
                       str(tmp_path / "TRACE_test.json"))
    try:
        rc = bench._run_all()
    finally:
        set_default(None)
    assert rc == 1


def test_sweep_trace_check_gates_on_fleet_rank_skew(monkeypatch, tmp_path):
    """ISSUE 15 CI satellite: the sweep audits its own telemetry — a
    rank-skew (or hang) left in the fleet modes' .pN shards fails the
    sweep with rc=1 even when every mode exited 0."""
    import json as _json
    from deeplearning4j_tpu.telemetry import set_default

    monkeypatch.setattr(bench.subprocess, "run", _fake_mode_run)
    monkeypatch.setattr(bench, "_probe_backend", lambda: "cpu")
    monkeypatch.setattr(bench, "MODES", {"ok": None})
    tpath = tmp_path / "tel.jsonl"
    monkeypatch.setenv("DL4J_TPU_TELEMETRY", str(tpath))
    monkeypatch.setenv("DL4J_TPU_TRACE_ARTIFACT",
                       str(tmp_path / "TRACE_test.json"))
    # a fleet mode's shard pair with the pN:hang@stepK signature: p1
    # stops at step 2 while p0 runs on (minutes of silence)
    for proc, last in (("p0", 6), ("p1", 2)):
        with open(f"{tpath}.{proc}", "w") as fh:
            for s in range(1, last + 1):
                fh.write(_json.dumps(
                    {"event": "step", "run": proc, "seq": s,
                     "iteration": s, "ts": 1000.0 + s * 60.0,
                     "trace_id": f"step-{s}"}) + "\n")
    try:
        rc = bench._run_all()
    finally:
        set_default(None)
    assert rc == 1
    events = [_json.loads(line) for line in open(tpath)]
    anomalies = [e for e in events if e["event"] == "anomaly"]
    assert anomalies and anomalies[0]["kind"] == "straggler"
    skew_rows = [e for e in events if e.get("metric")
                 == "straggler_skew_ms"]
    assert skew_rows and skew_rows[-1]["value"] > 0


def test_embed_mode_registered_and_smoke_runs():
    """ISSUE 19: the embed bench mode is in the sweep and a toy-sized
    `_embed_run` passes its structural gates — zero post-warmup
    retraces on both the train and /search paths, the ep=2 memstat
    table-bytes ratio at exactly 0.5, exact /embed rows, and every row
    family the benchdiff baseline tracks present in the output. The
    5x ANN speedup floor is a full-size (`python bench.py embed`)
    gate; at toy sizes brute force wins and that is expected."""
    assert "embed" in bench.MODES
    cfg = dict(bench.EMBED_DIMS, vocab=2048, dim=32, n_partitions=64,
               n_clusters=64, batch=256, train_steps=3, query_batch=16,
               qps_reps=3)
    out = bench._embed_run(cfg)
    g = out["gates"]
    assert g["train_retraces"] == 0 and g["search_retraces"] == 0
    assert g["sharding_ratio"] == 0.5
    assert g["embed_exact"]
    assert g["recall"] >= cfg["recall_floor"]
    names = {row["metric"] for row in out["lines"]}
    for family in ("embed_queries_per_sec", "embed_recall_at_k",
                   "embed_scatter_add_us", "embed_ep2_ep_gather_bytes",
                   "embed_mem_table_bytes_ep1", "embed_mem_table_bytes_ep2",
                   "embed_brute_force_queries_per_sec",
                   "embed_ann_speedup_vs_brute"):
        assert family in names, family
