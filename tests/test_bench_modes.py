"""Off-TPU compile smoke for the bench's Transformer-LM modes (VERDICT
r5 #1): the r5 `transformer_large` mode crashed ONLY under driver capture
because no CI path ever built the d1024 model — its CPU branch printed a
skip line and returned. Here every mode in bench.LM_MODE_DIMS is built at
its REAL (TPU) dims and its training step is traced end-to-end with
jax.eval_shape (fwd + bwd + optimizer, no FLOPs executed), so a mode that
cannot even trace fails tier-1, not the round artifact.

This is also where the r6 tentpole's end-to-end acceptance lives off-TPU:
`longcontext_chunked_dropout` (masked + attention dropout at seq 32768)
must trace through the chunked flash dispatch — in r5 that config raised
chunked_unsupported_reason.
"""

import jax
import pytest

import bench
from bench import LM_MODE_DIMS, lm_mode_net_ds


def _trace_step(mode):
    net, ds, cfg = lm_mode_net_ds(mode, force_tpu_dims=True)
    batch = net._batch_dict(net._to_mds(ds))
    step = net._get_train_step()
    out = jax.eval_shape(step, net.params, net.opt_state, net.state,
                         jax.random.PRNGKey(0), batch)
    return out, cfg


@pytest.mark.parametrize("mode", sorted(LM_MODE_DIMS))
def test_lm_mode_builds_and_traces_at_real_dims(mode):
    (params, opt_state, state, loss, _), cfg = _trace_step(mode)
    assert loss.shape == ()
    # the traced model really is the TPU config, not a CPU shrink
    emb = params["embed"]["W"] if "embed" in params else None
    if emb is not None:
        assert emb.shape[-1] == cfg["d_model"]


def test_every_lm_mode_is_runnable_from_the_cli():
    """Each registry entry is wired to a MODES command (and vice versa
    for the LM family), so the smoke can't drift from what the driver
    actually runs."""
    for mode in LM_MODE_DIMS:
        assert mode in bench.MODES, mode


def test_dropout_seq32768_cfg_is_the_tentpole_config():
    cfg = LM_MODE_DIMS["longcontext_chunked_dropout"]
    assert cfg["seq"] == 32768 and cfg["attention_dropout"] > 0
    assert cfg["masked"]
