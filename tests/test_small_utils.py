"""Small host-side utilities filling out the SURVEY §2 inventory:
StringGrid/StringCluster dedupe, DiskBasedQueue, SloppyMath, the
unstructured-data train/test formatter, ImageVectorizer, PlotFilters, and
the moving-window converters."""

import math
import os

import numpy as np
import pytest

from deeplearning4j_tpu.util.string_grid import (
    StringCluster,
    StringGrid,
    fingerprint,
)


def test_fingerprint_clusters_reorderings():
    c = StringCluster(["Two words", "TWO words", "words two", "other"])
    assert fingerprint("Two words") == fingerprint("words TWO!")
    clusters = c.get_clusters()
    assert len(c) == 2
    assert sum(clusters[0].values()) == 3  # biggest cluster first


def test_string_grid_cleanup_and_dedupe(tmp_path):
    lines = ["a,1,x", "b,2,y", "a,3,z", "A,4,w", ",5,v"]
    g = StringGrid.from_lines(lines, ",")
    assert len(g) == 5 and g.num_columns == 3
    g.remove_rows_with_empty_column(0)
    assert len(g) == 4
    dup = g.get_rows_with_duplicate_values_in_column(0)
    assert len(dup) == 2  # the two literal "a" rows
    g.dedupe_by_cluster(0)  # "a", "a", "A" share a fingerprint
    assert len(g) == 2
    p = tmp_path / "grid.csv"
    g.write_lines_to(str(p))
    g2 = StringGrid.from_file(str(p), ",")
    assert g2.rows == g.rows


def test_string_grid_similarity_filter():
    g = StringGrid(",", rows=[["color", "colour"], ["color", "zebra"]])
    assert len(g.get_all_with_similarity(0.8, 0, 1)) == 1


def test_disk_based_queue(tmp_path):
    from deeplearning4j_tpu.util.disk_queue import DiskBasedQueue

    q = DiskBasedQueue(str(tmp_path / "q"))
    assert q.is_empty() and q.poll() is None
    q.add({"a": 1})
    q.add_all([[1, 2], "three"])
    assert len(q) == 3
    assert q.peek() == {"a": 1}
    assert q.poll() == {"a": 1}
    assert q.poll() == [1, 2]
    assert q.poll() == "three"
    assert q.poll() is None
    q.close()


def test_disk_queue_refuses_foreign_directory(tmp_path):
    from deeplearning4j_tpu.util.disk_queue import DiskBasedQueue

    d = tmp_path / "data"
    d.mkdir()
    (d / "precious.txt").write_text("keep me")
    with pytest.raises(ValueError):
        DiskBasedQueue(str(d))
    assert (d / "precious.txt").exists()
    # but it does reclaim its own stale directory
    q1 = DiskBasedQueue(str(tmp_path / "q"))
    q1.add(1)
    q2 = DiskBasedQueue(str(tmp_path / "q"))
    assert q2.is_empty()


def test_sloppy_math():
    from deeplearning4j_tpu.util import sloppy_math as sm

    assert np.isclose(sm.log_add(math.log(2), math.log(3)), math.log(5))
    assert np.isclose(sm.log_add([math.log(1), math.log(2), math.log(3)]),
                      math.log(6))
    # truncation: a summand 40 nats down is treated as zero
    assert sm.log_add(0.0, -40.0) == 0.0
    assert np.isclose(sm.log_subtract(math.log(5), math.log(2)), math.log(3))
    p = np.exp(sm.log_normalize([0.0, 0.0]))
    np.testing.assert_allclose(p, [0.5, 0.5])
    assert sm.n_choose_k(5, 2) == 10
    assert sm.int_pow(3, 5) == 243
    assert sm.is_dangerous(float("nan")) and sm.is_dangerous(0.0)
    assert not sm.is_dangerous(1.0)


def test_unstructured_formatter_directory_labels(tmp_path):
    from deeplearning4j_tpu.datasets.rearrange import (
        LabelingType,
        LocalUnstructuredDataFormatter,
    )

    src = tmp_path / "raw"
    for label in ("cat", "dog"):
        (src / label).mkdir(parents=True)
        for i in range(10):
            (src / label / f"img{i}.txt").write_text(f"{label}{i}")
    fmt = LocalUnstructuredDataFormatter(
        str(tmp_path / "out"), str(src), LabelingType.DIRECTORY,
        percent_train=0.8, seed=0)
    fmt.rearrange()
    assert fmt.num_examples_total == 20
    assert fmt.num_examples_to_train_on == 16
    n_train = sum(len(files) for _, _, files in os.walk(fmt.get_train()))
    n_test = sum(len(files) for _, _, files in os.walk(fmt.get_test()))
    assert (n_train, n_test) == (16, 4)
    # labels preserved as subdirectories
    assert set(os.listdir(fmt.get_train())) <= {"cat", "dog"}
    # refuses to overwrite an existing split
    with pytest.raises(FileExistsError):
        LocalUnstructuredDataFormatter(
            str(tmp_path / "out"), str(src), LabelingType.DIRECTORY, 0.8)


def test_formatter_disambiguates_duplicate_basenames(tmp_path):
    from deeplearning4j_tpu.datasets.rearrange import (
        LabelingType,
        LocalUnstructuredDataFormatter,
    )

    src = tmp_path / "raw"
    for sub in ("part_a", "part_b"):
        (src / sub / "cat").mkdir(parents=True)
        (src / sub / "cat" / "img0.txt").write_text(sub)
    fmt = LocalUnstructuredDataFormatter(
        str(tmp_path / "out"), str(src), LabelingType.DIRECTORY,
        percent_train=1.0, seed=0)
    fmt.rearrange()
    n = sum(len(files) for _, _, files in os.walk(fmt.get_train()))
    assert n == 2  # both survive despite the shared basename


def test_name_label_parsing():
    from deeplearning4j_tpu.datasets.rearrange import (
        LocalUnstructuredDataFormatter as F,
    )

    assert F.get_name_label("/data/img1-cat.png") == "cat"
    with pytest.raises(ValueError):
        F.get_name_label("/data/nolabel.png")
    with pytest.raises(ValueError):
        F.get_name_label("/data/noext")


def test_image_vectorizer(tmp_path):
    from deeplearning4j_tpu.datasets.vectorizer import ImageVectorizer
    from deeplearning4j_tpu.util.image_loader import ImageLoader

    img = (np.arange(64, dtype=np.float32).reshape(8, 8) / 63.0)
    p = str(tmp_path / "img.pgm")
    ImageLoader.save(img[..., None], p)
    ds = ImageVectorizer(p, num_labels=3, label=1).normalize().vectorize()
    assert ds.features.shape[0] == 1
    assert ds.features.max() <= 1.0
    np.testing.assert_array_equal(ds.labels, [[0, 1, 0]])
    ds_bin = ImageVectorizer(p, num_labels=3, label=0).binarize(30).vectorize()
    assert set(np.unique(ds_bin.features)) <= {0.0, 1.0}


def test_plot_filters_grid():
    from deeplearning4j_tpu.plot.filters import PlotFilters

    filters = np.random.default_rng(0).random((6, 16))  # 6 4x4 filters
    pf = PlotFilters(filters, tile_shape=(2, 3), tile_spacing=(1, 1),
                     image_shape=(4, 4))
    grid = pf.plot()
    assert grid.shape == ((4 + 1) * 2 - 1, (4 + 1) * 3 - 1)
    assert grid.max() <= 255.0 and grid.min() >= 0.0
    assert pf.get_plot() is grid


def test_plot_filters_listener():
    from deeplearning4j_tpu.nn.conf import (
        DenseLayer,
        NeuralNetConfiguration,
        OutputLayer,
    )
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.plot.filters import PlotFiltersIterationListener

    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_in=16, n_out=4, activation="relu"))
            .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    name = next(iter(net.params))
    lst = PlotFiltersIterationListener(name, tile_shape=(2, 2),
                                       image_shape=(4, 4), frequency=1)
    lst.iteration_done(net, 0)
    assert lst.last_plot is not None and lst.invoked == 1


def test_context_label_retriever():
    from deeplearning4j_tpu.nlp.movingwindow import string_with_labels

    clean, spans = string_with_labels(
        "the <LOC> new york </LOC> subway is <ADJ> loud </ADJ> today")
    assert clean == "the new york subway is loud today"
    assert spans == {(1, 3): "LOC", (5, 6): "ADJ"}
    with pytest.raises(ValueError):
        string_with_labels("<A> oops </B>")
    with pytest.raises(ValueError):
        string_with_labels("stray </A> end")
    with pytest.raises(ValueError):
        string_with_labels("<A> unclosed")
    # NONE spans are stripped from the markup but omitted from the map
    clean2, spans2 = string_with_labels("<NONE> the </NONE> <LOC1> lhr </LOC1>")
    assert clean2 == "the lhr" and spans2 == {(1, 2): "LOC1"}


def test_window_converter():
    from deeplearning4j_tpu.nlp.movingwindow import WindowConverter
    from deeplearning4j_tpu.nlp.text import windows

    class FakeVec:
        layer_size = 4

        def word_vector(self, w):
            if w == "<none>":
                return None
            return np.full((4,), float(len(w)), np.float32)

    ws = windows(["a", "bb", "ccc"], window_size=3)
    ex = WindowConverter.as_example_array(ws[1], FakeVec())
    assert ex.shape == (12,)
    np.testing.assert_allclose(ex[:4], 1.0)   # "a"
    np.testing.assert_allclose(ex[4:8], 2.0)  # focus "bb"
    mat = WindowConverter.as_example_matrix(ws, FakeVec(), normalize=True)
    assert mat.shape == (3, 12)
    # normalized vectors have unit norm per word slot
    np.testing.assert_allclose(np.linalg.norm(mat[1, 4:8]), 1.0, rtol=1e-6)
