"""Native IO core (deeplearning4j_tpu/native): build, parse correctness vs
the Python paths, fallbacks, and the record-iterator fast path."""

import csv

import numpy as np
import pytest

from deeplearning4j_tpu import native
from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    RecordReaderDataSetIterator,
    SVMLightRecordReader,
)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


@pytest.fixture()
def csv_file(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.random((64, 5)).astype(np.float32)
    data[:, -1] = rng.integers(0, 3, 64)
    p = tmp_path / "data.csv"
    with open(p, "w") as f:
        for row in data:
            f.write(",".join(f"{v:.6f}" for v in row) + "\n")
    return str(p), data


def test_load_csv_matches_python(csv_file):
    path, data = csv_file
    arr = native.load_csv(path)
    ref = np.asarray([[float(v) for v in row]
                      for row in csv.reader(open(path))], np.float32)
    np.testing.assert_allclose(arr, ref, rtol=1e-6)


def test_load_csv_nonnumeric_returns_none(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("1.0,hello,3\n")
    assert native.load_csv(str(p)) is None


def test_load_csv_skip_lines(tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("colA,colB\n1,2\n3,4\n")
    arr = native.load_csv(str(p), skip_lines=1)
    np.testing.assert_allclose(arr, [[1, 2], [3, 4]])


def test_load_svmlight(tmp_path):
    p = tmp_path / "s.txt"
    p.write_text("1 1:0.5 3:2.0\n# comment\n\n0 2:1.5\n")
    labels, feats = native.load_svmlight(str(p), 4)
    np.testing.assert_allclose(labels, [1, 0])
    np.testing.assert_allclose(feats, [[0.5, 0, 2.0, 0], [0, 1.5, 0, 0]])


def test_encode_tokens_matches_vocab_indices():
    vocab = [f"w{i}" for i in range(5000)]
    text = "w10 w4999 nope w0\n w17"
    ids = native.encode_tokens(text, vocab)
    assert ids.tolist() == [10, 4999, -1, 0, 17]


def test_record_iterator_native_path_matches_python(csv_file):
    path, data = csv_file
    it = RecordReaderDataSetIterator(CSVRecordReader(path), batch_size=16,
                                     num_classes=3)
    assert it._matrix is not None  # fast path engaged
    batches = []
    it.reset()
    while it.has_next():
        batches.append(it.next())
    x = np.concatenate([b.features for b in batches])
    y = np.concatenate([b.labels for b in batches])
    np.testing.assert_allclose(x, data[:, :-1], atol=1e-6)
    np.testing.assert_allclose(y.argmax(-1), data[:, -1])


def test_record_iterator_python_fallback_same_result(tmp_path, csv_file):
    path, data = csv_file

    class NoNative(CSVRecordReader):
        def to_matrix(self):
            return None

    fast = RecordReaderDataSetIterator(CSVRecordReader(path), 16,
                                       num_classes=3)
    slow = RecordReaderDataSetIterator(NoNative(path), 16, num_classes=3)
    assert slow._matrix is None
    for _ in range(2):
        a, b = fast.next(), slow.next()
        np.testing.assert_allclose(a.features, b.features, atol=1e-6)
        np.testing.assert_allclose(a.labels, b.labels)


def test_svmlight_iterator_native_path(tmp_path):
    p = tmp_path / "s.txt"
    p.write_text("".join(f"{i % 2} 1:{i}.0 4:{i * 2}.5\n" for i in range(10)))
    it = RecordReaderDataSetIterator(SVMLightRecordReader(str(p), 4),
                                     batch_size=4, num_classes=2)
    assert it._matrix is not None
    ds = it.next()
    assert ds.features.shape == (4, 4)
    np.testing.assert_allclose(ds.features[1, 0], 1.0)


def test_parse_csv_empty_cell_falls_back(tmp_path):
    """An empty trailing cell must NOT steal the next line's value."""
    p = tmp_path / "empty.csv"
    p.write_text("1,2,\n4,5,6\n")
    assert native.load_csv(str(p)) is None  # Python path handles/raises


def test_parse_csv_ragged_lines_skipped_consistently(tmp_path):
    p = tmp_path / "ragged.csv"
    p.write_text("1,2,3\n9,9,9,9\n4,5,6\n")
    arr = native.load_csv(str(p))
    np.testing.assert_allclose(arr, [[1, 2, 3], [4, 5, 6]])


def test_raw_string_corpus_uses_native_encoder():
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    lines = ["a b a b a b"] * 30
    w = (Word2Vec.builder().layer_size(8).window_size(2).min_word_frequency(1)
         .negative_sample(2).epochs(1).seed(1).use_device_pipeline(True)
         .build())
    w.fit(lines)
    assert w.vocab_size == 2
    assert np.isfinite(w.loss_history).all()


def test_svmlight_empty_value_falls_back(tmp_path):
    """An empty 'idx:' value must NOT consume the next line's label."""
    p = tmp_path / "bad.txt"
    p.write_text("1 2: \n5 1:7\n")
    assert native.load_svmlight(str(p), 4) is None


def test_encode_corpus_single_pass():
    ids, sent = native.encode_corpus(["a b oov", "b a"], ["a", "b"])
    assert ids.tolist() == [0, 1, -1, 1, 0]
    assert sent.tolist() == [0, 0, 0, 1, 1]


def test_host_path_raw_string_corpus_trains():
    """Raw-string corpora must train on the HOST path too (words, not
    characters)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    lines = ["alpha beta alpha beta"] * 20
    w = (Word2Vec.builder().layer_size(8).window_size(2).min_word_frequency(1)
         .negative_sample(2).epochs(1).seed(1).build())  # host path
    w.fit(lines)
    assert w.vocab_size == 2
    assert len(w.loss_history) > 0  # pairs actually trained
