"""Live in-browser training views (VERDICT r2 #6).

The reference UI *renders* — histogram/weights pages fed by
HistogramIterationListener (ui/weights/HistogramIterationListener.java:206),
the flow topology view (ui/flow/FlowIterationListener.java +
beans/ModelInfo.java), activation and tsne pages served by UiServer.java
with bundled JS assets. Here the same listener payloads are turned into
the declarative chart components (ui/components.py) and rendered by the
self-contained SVG renderer (ui/standalone.py) — a browser pointed at
/weights, /flow, /activations or /tsne sees live charts (auto-refresh),
with zero external JS dependencies.
"""

from __future__ import annotations

import json
from typing import Optional

from .components import (
    ChartHistogram,
    ChartLine,
    ChartScatter,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    DecoratorAccordion,
)
from .standalone import StaticPageUtil

REFRESH_SECONDS = 3


def _fmt_score(payload: dict) -> str:
    s = payload.get("score")
    return f"{s:.6g}" if isinstance(s, (int, float)) else "n/a"


def _score_chart(history) -> Optional[ChartLine]:
    pts = [(h.get("iteration", i), h.get("score"))
           for i, h in enumerate(history) if h.get("score") is not None]
    if not pts:
        return None
    c = ChartLine(title="score")
    c.add_series("score", [p[0] for p in pts], [p[1] for p in pts])
    return c


def weights_page(payload: Optional[dict], history, sid: str) -> str:
    """Param/gradient histogram view (HistogramIterationListener data)."""
    comps = []
    score = _score_chart(history)
    if score is not None:
        comps.append(score)
    if not payload:
        comps.append(ComponentText(
            text="no weights data yet — attach a HistogramIterationListener"))
    else:
        comps.append(ComponentText(
            text=f"iteration {payload.get('iteration')}, "
                 f"score {_fmt_score(payload)}"))
        for pname in sorted(payload.get("parameters", {})):
            h = payload["parameters"][pname]
            chart = ChartHistogram(title=pname)
            bins, counts = h.get("bins", []), h.get("counts", [])
            for i, cnt in enumerate(counts):
                chart.add_bin(bins[i], bins[i + 1], cnt)
            comps.append(DecoratorAccordion(
                title=pname, default_collapsed=True, components=[chart]))
    return StaticPageUtil.render_html(
        comps, title=f"weights — session {sid}",
        refresh_seconds=REFRESH_SECONDS)


def flow_page(payload: Optional[dict], history, sid: str) -> str:
    """Network topology view (FlowIterationListener's ModelInfo beans)."""
    comps = []
    if not payload:
        comps.append(ComponentText(
            text="no flow data yet — attach a FlowIterationListener"))
    else:
        comps.append(ComponentText(
            text=f"iteration {payload.get('iteration')}, "
                 f"score {_fmt_score(payload)}"))
        rows = [[str(l.get("index")), l.get("name"),
                 str(l.get("num_params")),
                 ", ".join(l.get("param_names", []))]
                for l in payload.get("layers", [])]
        comps.append(ComponentTable(
            header=["#", "layer", "params", "param names"], content=rows))
        sizes = [l.get("num_params", 0) for l in payload.get("layers", [])]
        if sizes:
            bar = ChartLine(title="parameters per layer")
            bar.add_series("num_params", list(range(len(sizes))),
                           [float(s) for s in sizes])
            comps.append(bar)
    score = _score_chart(history)
    if score is not None:
        comps.append(score)
    return StaticPageUtil.render_html(
        comps, title=f"flow — session {sid}", refresh_seconds=REFRESH_SECONDS)


def activations_page(history, sid: str) -> str:
    """Mean |activation| per layer over iterations
    (ActivationMeanIterationListener data)."""
    comps = []
    if not history:
        comps.append(ComponentText(
            text="no activation data yet — attach an "
                 "ActivationMeanIterationListener"))
    else:
        series = {}
        iters = []
        for h in history:
            iters.append(h.get("iteration", len(iters)))
            for name, v in h.get("activation_means", {}).items():
                series.setdefault(name, []).append(float(v))
        chart = ChartLine(title="mean |activation| per layer")
        for name in sorted(series):
            vals = series[name]
            chart.add_series(name, iters[-len(vals):], vals)
        comps.append(chart)
    return StaticPageUtil.render_html(
        comps, title=f"activations — session {sid}",
        refresh_seconds=REFRESH_SECONDS)


def timeline_page(timeline, anomalies, source: str) -> str:
    """The fleet trace-timeline view (ISSUE 15): rendered from the
    MERGED per-process telemetry shards (telemetry/trace.py), not a
    listener feed — per-process span lanes, the per-(process, span)
    p50/p99 table, and the anomaly findings table. `timeline` is a
    trace.Timeline, `anomalies` the detect_anomalies findings."""
    from deeplearning4j_tpu.telemetry import trace as trace_mod

    comps = []
    if timeline is None or not timeline.events:
        comps.append(ComponentText(
            text="no telemetry yet — start the UI server with "
                 "telemetry_path= (or set DL4J_TPU_TELEMETRY) and run "
                 "a fleet"))
        return StaticPageUtil.render_html(
            comps, title="fleet timeline",
            refresh_seconds=REFRESH_SECONDS)
    procs = timeline.processes
    comps.append(ComponentText(
        text=f"{len(timeline.events)} events from {len(procs)} "
             f"process(es) [{', '.join(procs)}] — source {source}"))
    # anomaly findings first: the reason a human opens this page
    if anomalies:
        rows = [[f.get("anomaly", ""), str(f.get("process", "")),
                 json.dumps({k: v for k, v in f.items()
                             if k not in ("anomaly", "process")})]
                for f in anomalies]
        comps.append(ComponentTable(
            header=["anomaly", "process", "evidence"], content=rows))
    else:
        comps.append(ComponentText(text="0 anomalies"))
    # span lanes: one scatter series per span name, x = seconds into
    # the run, y = process lane index
    lane = {p: i for i, p in enumerate(procs)}
    base = min(float(ev.get("ts", 0.0)) for ev in timeline.events)
    by_name: dict = {}
    for ev in timeline.spans():
        by_name.setdefault(str(ev.get("name")), []).append(ev)
    top = sorted(by_name, key=lambda n: -len(by_name[n]))[:8]
    chart = ChartScatter(title="span starts by process lane "
                               "(top span kinds)")
    for name in top:
        evs = by_name[name]
        xs = [float(ev.get("ts", 0.0)) - float(ev.get("seconds", 0.0))
              - base for ev in evs]
        ys = [float(lane[ev.get("process", "main")]) for ev in evs]
        chart.add_series(name, xs, ys)
    comps.append(chart)
    stats = trace_mod.span_stats(timeline)
    rows = [[p, n, str(row["count"]), f"{row['p50_ms']:.3f}",
             f"{row['p99_ms']:.3f}", f"{row['max_ms']:.3f}"]
            for (p, n), row in sorted(stats.items())]
    comps.append(DecoratorAccordion(
        title="per-span p50/p99 (ms) per process",
        default_collapsed=False,
        components=[ComponentTable(
            header=["process", "span", "count", "p50_ms", "p99_ms",
                    "max_ms"], content=rows)]))
    return StaticPageUtil.render_html(
        comps, title="fleet timeline", refresh_seconds=REFRESH_SECONDS)


def tsne_page(payload, sid: str) -> str:
    """2-D embedding scatter (tsne/coords data: [[x, y], ...] or
    {"coords": [[x, y], ...], "labels": [...]})."""
    comps = []
    coords = payload
    if isinstance(payload, dict):
        coords = payload.get("coords")
    if not coords:
        comps.append(ComponentText(
            text="no tsne coords yet — POST /tsne/coords?sid=..."))
    else:
        chart = ChartScatter(title="t-SNE embedding")
        chart.add_series("points", [float(p[0]) for p in coords],
                         [float(p[1]) for p in coords])
        comps.append(chart)
    return StaticPageUtil.render_html(
        comps, title=f"tsne — session {sid}", refresh_seconds=REFRESH_SECONDS)
