"""Declarative chart/table/text components serialising to JSON (reference:
deeplearning4j-ui-components — components/chart/{Chart, ChartLine,
ChartHistogram, ChartScatter, ChartStackedArea, ChartHorizontalBar}.java,
table/ComponentTable.java, text/ComponentText.java,
component/ComponentDiv.java, decorator/DecoratorAccordion.java,
api/Component.java `componentType` discriminator).

Server-agnostic: a component is data; `to_dict()/to_json()` produce the
wire format, `Component.from_dict` restores it — the same
Jackson-subtype-registry round-trip the reference uses.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional, Sequence

_COMPONENT_TYPES: Dict[str, type] = {}


def _register(cls):
    _COMPONENT_TYPES[cls.__name__] = cls
    return cls


@dataclass
class StyleChart:
    """Chart styling (reference chart/style/StyleChart.java)."""

    width: float = 640
    height: float = 480
    title_style: Optional[dict] = None
    series_colors: Optional[List[str]] = None
    axis_strokewidth: float = 1.0


@dataclass
class Component:
    """Base component (api/Component.java)."""

    def to_dict(self) -> dict:
        d = asdict(self)
        d["componentType"] = type(self).__name__
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d: dict) -> "Component":
        d = dict(d)
        t = d.pop("componentType")
        cls = _COMPONENT_TYPES[t]
        if "style" in d and d["style"] is not None:
            d["style"] = StyleChart(**d["style"])
        kids = d.pop("components", None)
        obj = cls(**d)
        if kids is not None:
            obj.components = [Component.from_dict(k) for k in kids]
        return obj

    @staticmethod
    def from_json(s: str) -> "Component":
        return Component.from_dict(json.loads(s))


@_register
@dataclass
class ChartLine(Component):
    """Multi-series line chart (chart/ChartLine.java)."""

    title: str = ""
    series_names: List[str] = field(default_factory=list)
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    style: Optional[StyleChart] = None

    def add_series(self, name: str, xs: Sequence[float], ys: Sequence[float]):
        if len(xs) != len(ys):
            raise ValueError("x/y length mismatch")
        self.series_names.append(name)
        self.x.append([float(v) for v in xs])
        self.y.append([float(v) for v in ys])
        return self


@_register
@dataclass
class ChartHistogram(Component):
    """Histogram: explicit bin edges + counts (chart/ChartHistogram.java)."""

    title: str = ""
    lower_bounds: List[float] = field(default_factory=list)
    upper_bounds: List[float] = field(default_factory=list)
    y_values: List[float] = field(default_factory=list)
    style: Optional[StyleChart] = None

    def add_bin(self, lower: float, upper: float, y: float):
        self.lower_bounds.append(float(lower))
        self.upper_bounds.append(float(upper))
        self.y_values.append(float(y))
        return self

    @staticmethod
    def of(values, bins: int = 20, title: str = "") -> "ChartHistogram":
        import numpy as np

        counts, edges = np.histogram(np.asarray(values).ravel(), bins=bins)
        h = ChartHistogram(title=title)
        for i, c in enumerate(counts):
            h.add_bin(edges[i], edges[i + 1], float(c))
        return h


@_register
@dataclass
class ChartScatter(Component):
    """Scatter chart (chart/ChartScatter.java)."""

    title: str = ""
    series_names: List[str] = field(default_factory=list)
    x: List[List[float]] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    style: Optional[StyleChart] = None

    def add_series(self, name: str, xs: Sequence[float], ys: Sequence[float]):
        if len(xs) != len(ys):
            raise ValueError("x/y length mismatch")
        self.series_names.append(name)
        self.x.append([float(v) for v in xs])
        self.y.append([float(v) for v in ys])
        return self


@_register
@dataclass
class ChartStackedArea(Component):
    """Stacked area chart (chart/ChartStackedArea.java)."""

    title: str = ""
    x: List[float] = field(default_factory=list)
    labels: List[str] = field(default_factory=list)
    y: List[List[float]] = field(default_factory=list)
    style: Optional[StyleChart] = None


@_register
@dataclass
class ChartHorizontalBar(Component):
    """Horizontal bar chart (chart/ChartHorizontalBar.java)."""

    title: str = ""
    labels: List[str] = field(default_factory=list)
    values: List[float] = field(default_factory=list)
    style: Optional[StyleChart] = None


@_register
@dataclass
class ComponentTable(Component):
    """Table (table/ComponentTable.java)."""

    header: List[str] = field(default_factory=list)
    content: List[List[str]] = field(default_factory=list)


@_register
@dataclass
class ComponentText(Component):
    """Text block (text/ComponentText.java)."""

    text: str = ""


@_register
@dataclass
class ComponentDiv(Component):
    """Container div (component/ComponentDiv.java)."""

    components: List[Any] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "componentType": "ComponentDiv",
            "components": [c.to_dict() for c in self.components],
        }


@_register
@dataclass
class DecoratorAccordion(Component):
    """Collapsible section (decorator/DecoratorAccordion.java)."""

    title: str = ""
    default_collapsed: bool = False
    components: List[Any] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "componentType": "DecoratorAccordion",
            "title": self.title,
            "default_collapsed": self.default_collapsed,
            "components": [c.to_dict() for c in self.components],
        }
