"""UI iteration listeners (reference: ui/weights/HistogramIterationListener
.java — posts ModelAndGradient JSON :206; ui/flow/FlowIterationListener.java
— ModelInfo/LayerInfo topology beans; activation/
UpdateActivationIterationListener).

Each listener builds a JSON-able snapshot per iteration and either POSTs it
to a running UiServer (`url=...`) or writes it into a storage object
(`storage=...`) for in-process use — the reference always needs the HTTP
hop; going direct-to-storage is the embedded mode.
"""

from __future__ import annotations

import json
import logging
import urllib.request
from typing import Optional

log = logging.getLogger(__name__)

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener

from .storage import SessionStorage


def _post(url: str, payload: dict) -> None:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=10):
        pass


class _BaseUiListener(IterationListener):
    kind = ""
    # consecutive POST failures before the listener stops trying — monitoring
    # must never take down training (the reference HistogramIterationListener
    # catches and logs its HTTP errors for the same reason)
    MAX_POST_FAILURES = 5

    def __init__(self, url: Optional[str] = None,
                 storage: Optional[SessionStorage] = None,
                 session_id: str = "default", frequency: int = 1):
        if url is None and storage is None:
            raise ValueError("need url= (HTTP mode) or storage= (embedded)")
        self.url = url.rstrip("/") if url else None
        self.storage = storage
        self.session_id = session_id
        self.frequency = max(1, frequency)
        self._post_failures = 0

    def _emit(self, payload: dict) -> None:
        if self.storage is not None:
            self.storage.put(self.session_id, self.kind, payload)
        if self.url is not None and self._post_failures < self.MAX_POST_FAILURES:
            try:
                _post(f"{self.url}/{self.kind}/update?sid={self.session_id}",
                      payload)
                self._post_failures = 0
            except Exception as e:  # noqa: BLE001 — any transport failure
                self._post_failures += 1
                log.warning("UI POST to %s failed (%s)%s", self.url, e,
                            "; disabling further posts"
                            if self._post_failures >= self.MAX_POST_FAILURES
                            else "")

    def iteration_done(self, model, iteration):
        if iteration % self.frequency:
            return
        self._emit(self.snapshot(model, iteration))

    def snapshot(self, model, iteration) -> dict:
        raise NotImplementedError


def _histogram(arr: np.ndarray, bins: int = 20) -> dict:
    counts, edges = np.histogram(arr.ravel(), bins=bins)
    return {"bins": edges.tolist(), "counts": counts.tolist()}


class HistogramIterationListener(_BaseUiListener):
    """Model param/update histograms + score curve
    (weights/HistogramIterationListener.java; bean:
    weights/ModelAndGradient.java)."""

    kind = "weights"

    def snapshot(self, model, iteration) -> dict:
        params = {}
        for lname, layer in (model.params or {}).items():
            for pname, arr in layer.items():
                params[f"{lname}_{pname}"] = _histogram(np.asarray(arr))
        return {
            "iteration": iteration,
            "score": float(model.score_value),
            "parameters": params,
        }


class FlowIterationListener(_BaseUiListener):
    """Network topology + per-layer meta (flow/FlowIterationListener.java,
    beans/{ModelInfo, LayerInfo})."""

    kind = "flow"

    def snapshot(self, model, iteration) -> dict:
        layers = []
        # MultiLayerNetwork: ordered layer_names; ComputationGraph: topo order
        names = getattr(model, "layer_names", None)
        if names is None and hasattr(model, "topo"):
            names = [n for n in model.topo if n in (model.params or {})]
        for i, name in enumerate(names or []):
            layer_params = (model.params or {}).get(name, {})
            n_params = int(sum(np.asarray(a).size for a in layer_params.values()))
            layers.append({
                "name": str(name),
                "index": i,
                "num_params": n_params,
                "param_names": sorted(layer_params),
            })
        return {
            "iteration": iteration,
            "score": float(model.score_value),
            "layers": layers,
        }


class ActivationMeanIterationListener(_BaseUiListener):
    """Mean |activation| per layer on a probe batch
    (plot/iterationlistener/ActivationMeanIterationListener +
    ui/activation view).

    The jitted train step doesn't expose intermediate activations, so this
    listener runs its own forward pass on a fixed probe input every
    `frequency` iterations (feedForwardToLayer collect mode)."""

    kind = "activations"

    def __init__(self, probe_input, **kw):
        super().__init__(**kw)
        self.probe_input = probe_input

    def snapshot(self, model, iteration) -> dict:
        acts = model.feed_forward(self.probe_input)
        means = {}
        for i, a in enumerate(acts):
            arr = np.asarray(a)
            means[f"layer_{i}"] = float(np.abs(arr).mean())
        return {"iteration": iteration, "activation_means": means}
