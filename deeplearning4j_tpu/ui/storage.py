"""Session-scoped UI storage (reference: ui/storage/{SessionStorage,
HistoryStorage}.java — maps keyed by (sessionId, objectType) with history).

Thread-safe: listeners post from training threads while the HTTP server
reads from request threads.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple


class SessionStorage:
    """Latest-value store keyed by (session_id, object_type)
    (storage/SessionStorage.java)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, str], Any] = {}
        self._update_time: Dict[Tuple[str, str], float] = {}

    def put(self, session_id: str, object_type: str, value: Any) -> None:
        with self._lock:
            self._data[(session_id, object_type)] = value
            self._update_time[(session_id, object_type)] = time.time()

    def get(self, session_id: str, object_type: str) -> Optional[Any]:
        with self._lock:
            return self._data.get((session_id, object_type))

    def sessions(self) -> List[str]:
        with self._lock:
            return sorted({k[0] for k in self._data})

    def object_types(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({t for (s, t) in self._data if s == session_id})

    def last_update(self, session_id: str, object_type: str) -> float:
        with self._lock:
            return self._update_time.get((session_id, object_type), 0.0)


class HistoryStorage(SessionStorage):
    """Appends every put to a bounded history list
    (storage/HistoryStorage.java)."""

    def __init__(self, max_history: int = 1000):
        super().__init__()
        self.max_history = max_history
        self._history: Dict[Tuple[str, str], List[Any]] = defaultdict(list)

    def put(self, session_id: str, object_type: str, value: Any) -> None:
        super().put(session_id, object_type, value)
        with self._lock:
            h = self._history[(session_id, object_type)]
            h.append(value)
            if len(h) > self.max_history:
                del h[: len(h) - self.max_history]

    def history(self, session_id: str, object_type: str) -> List[Any]:
        with self._lock:
            return list(self._history.get((session_id, object_type), []))
