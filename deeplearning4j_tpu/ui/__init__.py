"""UI / observability (reference: deeplearning4j-ui-parent — UiServer.java
Dropwizard app, deeplearning4j-ui-components chart-JSON protocol, weights/
flow/activation/tsne/nearestneighbors views; SURVEY.md §2.6 L9 row).

Host-side by nature. The Dropwizard/Jetty/Jersey stack is replaced by a
stdlib ThreadingHTTPServer speaking the same declarative chart-JSON
component protocol; listeners POST JSON snapshots exactly like the
reference's HistogramIterationListener (HistogramIterationListener.java:206)
or write straight to in-process storage when no server is running.
"""

from .components import (
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    ChartStackedArea,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
    DecoratorAccordion,
    StyleChart,
)
from .storage import HistoryStorage, SessionStorage
from .server import UiServer
from .listeners import (
    ActivationMeanIterationListener,
    FlowIterationListener,
    HistogramIterationListener,
)
from .standalone import StaticPageUtil

__all__ = [
    "ChartHistogram",
    "ChartHorizontalBar",
    "ChartLine",
    "ChartScatter",
    "ChartStackedArea",
    "Component",
    "ComponentDiv",
    "ComponentTable",
    "ComponentText",
    "DecoratorAccordion",
    "StyleChart",
    "HistoryStorage",
    "SessionStorage",
    "UiServer",
    "HistogramIterationListener",
    "FlowIterationListener",
    "ActivationMeanIterationListener",
    "StaticPageUtil",
]
