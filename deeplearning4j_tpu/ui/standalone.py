"""Static page rendering (reference: ui/standalone/StaticPageUtil.java —
renders components to a self-contained HTML page with embedded JSON).

The generated page inlines the component JSON plus a tiny renderer that
draws line/scatter/histogram charts to SVG and tables/text to HTML — no
external JS dependencies (the reference ships its own JS assets)."""

from __future__ import annotations

import html
import json
from typing import Sequence

from .components import Component

_RENDER_JS = """
function renderComponent(c, el) {
  if (c.componentType === 'ComponentText') {
    const p = document.createElement('p'); p.textContent = c.text;
    el.appendChild(p);
  } else if (c.componentType === 'ComponentTable') {
    const t = document.createElement('table'); t.border = '1';
    const hr = t.insertRow();
    (c.header || []).forEach(h => { const th = document.createElement('th');
      th.textContent = h; hr.appendChild(th); });
    (c.content || []).forEach(row => { const r = t.insertRow();
      row.forEach(v => { r.insertCell().textContent = v; }); });
    el.appendChild(t);
  } else if (c.componentType === 'ComponentDiv'
             || c.componentType === 'DecoratorAccordion') {
    const d = document.createElement(
      c.componentType === 'DecoratorAccordion' ? 'details' : 'div');
    if (c.title) { const s = document.createElement('summary');
      s.textContent = c.title; d.appendChild(s); }
    if (c.componentType === 'DecoratorAccordion' && !c.default_collapsed)
      d.open = true;
    (c.components || []).forEach(k => renderComponent(k, d));
    el.appendChild(d);
  } else {
    el.appendChild(renderChartSVG(c));
  }
}
function renderChartSVG(c) {
  const W = (c.style && c.style.width) || 640,
        H = (c.style && c.style.height) || 360, pad = 40;
  const ns = 'http://www.w3.org/2000/svg';
  const svg = document.createElementNS(ns, 'svg');
  svg.setAttribute('width', W); svg.setAttribute('height', H);
  svg.style.border = '1px solid #ccc';
  let xs = [], ys = [];
  if (c.componentType === 'ChartHistogram') {
    xs = c.lower_bounds.concat(c.upper_bounds); ys = [0].concat(c.y_values);
  } else if (c.componentType === 'ChartHorizontalBar') {
    xs = [0].concat(c.values); ys = [0, c.labels.length];
  } else { xs = (c.x || []).flat(); ys = (c.y || []).flat(); }
  if (!xs.length || !ys.length) return svg;
  const xmin = Math.min(...xs), xmax = Math.max(...xs),
        ymin = Math.min(...ys), ymax = Math.max(...ys);
  const sx = v => pad + (v - xmin) / ((xmax - xmin) || 1) * (W - 2 * pad);
  const sy = v => H - pad - (v - ymin) / ((ymax - ymin) || 1) * (H - 2 * pad);
  const colors = ['#1f77b4', '#ff7f0e', '#2ca02c', '#d62728', '#9467bd'];
  if (c.componentType === 'ChartLine' || c.componentType === 'ChartScatter') {
    (c.x || []).forEach((sxs, i) => {
      const col = colors[i % colors.length];
      if (c.componentType === 'ChartLine') {
        const pl = document.createElementNS(ns, 'polyline');
        pl.setAttribute('points',
          sxs.map((v, j) => sx(v) + ',' + sy(c.y[i][j])).join(' '));
        pl.setAttribute('fill', 'none'); pl.setAttribute('stroke', col);
        svg.appendChild(pl);
      } else {
        sxs.forEach((v, j) => {
          const ci = document.createElementNS(ns, 'circle');
          ci.setAttribute('cx', sx(v)); ci.setAttribute('cy', sy(c.y[i][j]));
          ci.setAttribute('r', 3); ci.setAttribute('fill', col);
          svg.appendChild(ci);
        });
      }
    });
  } else if (c.componentType === 'ChartHistogram') {
    c.lower_bounds.forEach((lo, i) => {
      const r = document.createElementNS(ns, 'rect');
      r.setAttribute('x', sx(lo)); r.setAttribute('y', sy(c.y_values[i]));
      r.setAttribute('width', Math.max(1, sx(c.upper_bounds[i]) - sx(lo)));
      r.setAttribute('height', H - pad - sy(c.y_values[i]));
      r.setAttribute('fill', '#1f77b4'); svg.appendChild(r);
    });
  }
  const title = document.createElementNS(ns, 'text');
  title.setAttribute('x', W / 2); title.setAttribute('y', 16);
  title.setAttribute('text-anchor', 'middle');
  title.textContent = c.title || '';
  svg.appendChild(title);
  return svg;
}
"""


class StaticPageUtil:
    """Render components to one self-contained HTML page
    (standalone/StaticPageUtil.renderHTML)."""

    @staticmethod
    def render_html(components: Sequence[Component],
                    title: str = "deeplearning4j_tpu report",
                    refresh_seconds: int = 0) -> str:
        # escape for <script> context: "<" inside JSON strings becomes <
        # so neither "</script>" nor "<!--" (script-data-escaped state) in a
        # ComponentText can break out of the block or inject HTML
        payload = json.dumps([c.to_dict() for c in components]).replace(
            "<", "\\u003c")
        refresh = (f'<meta http-equiv="refresh" content="{int(refresh_seconds)}">'
                   if refresh_seconds else "")
        return f"""<!doctype html>
<html><head><meta charset="utf-8">{refresh}<title>{html.escape(title)}</title>
<script>{_RENDER_JS}</script></head>
<body><h1>{html.escape(title)}</h1><div id="root"></div>
<script>
const COMPONENTS = {payload};
const root = document.getElementById('root');
COMPONENTS.forEach(c => renderComponent(c, root));
</script></body></html>"""

    @staticmethod
    def save_html(components: Sequence[Component], path: str,
                  title: str = "deeplearning4j_tpu report") -> None:
        with open(path, "w") as f:
            f.write(StaticPageUtil.render_html(components, title))
