"""UI server (reference: ui/UiServer.java — Dropwizard/Jetty app hosting
weights, flow, activations, tsne, nearestneighbors REST resources; listeners
POST JSON, browser polls GET).

Stdlib ThreadingHTTPServer replacement. Endpoints (all JSON):

    POST /weights/update?sid=S     histogram snapshots (ModelAndGradient)
    GET  /weights/data?sid=S
    POST /flow/update?sid=S        ModelInfo topology beans
    GET  /flow/data?sid=S
    POST /activations/update?sid=S activation means
    GET  /activations/data?sid=S
    POST /tsne/coords?sid=S        [[x, y], ...] embedding coords
    GET  /tsne/data?sid=S
    GET  /weights|/flow|/activations|/tsne?sid=S  — RENDERED live views
         (self-contained HTML + SVG from ui/views.py, auto-refreshing;
         the reference's in-browser histogram/flow/activation/tsne pages)
    GET  /timeline                 the fleet trace-timeline view: merged
         per-process telemetry shards (telemetry/trace.py) rendered as
         span lanes + per-span p50/p99 + anomaly findings; reads the
         path given as UiServer(telemetry_path=...) or the
         DL4J_TPU_TELEMETRY env var
    GET  /timeline/data            the same merged view as JSON
         ({processes, span_stats, anomalies})
    POST /nearestneighbors/vectors labelled vectors {labels, vectors}
    POST /nearestneighbors/query   {word, k} → {words, distances}
    GET  /sessions                 list of session ids
    GET  /                         minimal HTML index

Run with `UiServer(port=0).start()`; `.url` gives the bound address.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from .storage import HistoryStorage

_INDEX_HTML = """<!doctype html>
<html><head><title>deeplearning4j_tpu UI</title></head>
<body><h1>deeplearning4j_tpu training UI</h1>
<p>Views: <a href="/weights">weights</a> | <a href="/flow">flow</a> |
<a href="/activations">activations</a> | <a href="/tsne">tsne</a> |
<a href="/timeline">timeline</a>
(append ?sid=&lt;session&gt; to pick a session)</p>
<p>Sessions: <span id="s"></span></p>
<script>
fetch('/sessions').then(r => r.json()).then(d => {
  document.getElementById('s').textContent = d.join(', ');
});
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4jtpu-ui/1.0"

    # quiet request logging (reference logs through slf4j, not stdout)
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    @property
    def ui(self) -> "UiServer":
        return self.server.ui_server

    def _json(self, obj, code: int = 200) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self):
        length = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(length) or b"{}")

    def _html(self, body: str, code: int = 200) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/html; charset=utf-8")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802
        from deeplearning4j_tpu.ui import views

        url = urlparse(self.path)
        sid = parse_qs(url.query).get("sid", ["default"])[0]
        route = url.path.rstrip("/")
        if route == "":
            self._html(_INDEX_HTML)
            return
        if route == "/sessions":
            self._json(self.ui.storage.sessions())
            return
        # live in-browser views (the reference's rendered weights/flow/
        # activation/tsne pages) — data views stay on /<kind>/data
        storage = self.ui.storage
        if route == "/weights":
            self._html(views.weights_page(storage.get(sid, "weights"),
                                          storage.history(sid, "weights"),
                                          sid))
            return
        if route == "/flow":
            self._html(views.flow_page(storage.get(sid, "flow"),
                                       storage.history(sid, "flow"), sid))
            return
        if route == "/activations":
            self._html(views.activations_page(
                storage.history(sid, "activations"), sid))
            return
        if route == "/tsne":
            self._html(views.tsne_page(storage.get(sid, "tsne"), sid))
            return
        if route in ("/timeline", "/timeline/data"):
            timeline, anomalies, source = self.ui.load_timeline()
            if route == "/timeline":
                self._html(views.timeline_page(timeline, anomalies,
                                               source))
                return
            from deeplearning4j_tpu.telemetry import trace as trace_mod

            stats = (trace_mod.span_stats(timeline)
                     if timeline is not None else {})
            self._json({
                "source": source,
                "processes": (timeline.processes if timeline is not None
                              else []),
                "span_stats": {f"{p}::{n}": row
                               for (p, n), row in sorted(stats.items())},
                "anomalies": anomalies,
            })
            return
        for kind in ("weights", "flow", "activations", "tsne"):
            if route == f"/{kind}/data":
                self._json(self.ui.storage.get(sid, kind) or {})
                return
            if route == f"/{kind}/history":
                self._json(self.ui.storage.history(sid, kind))
                return
        self._json({"error": f"unknown path {url.path}"}, 404)

    def do_POST(self):  # noqa: N802
        url = urlparse(self.path)
        sid = parse_qs(url.query).get("sid", ["default"])[0]
        route = url.path.rstrip("/")
        try:
            payload = self._read_body()
        except json.JSONDecodeError:
            self._json({"error": "bad json"}, 400)
            return
        for kind in ("weights", "flow", "activations"):
            if route == f"/{kind}/update":
                self.ui.storage.put(sid, kind, payload)
                self._json({"status": "ok"})
                return
        if route == "/tsne/coords":
            self.ui.storage.put(sid, "tsne", payload)
            self._json({"status": "ok"})
            return
        if route == "/nearestneighbors/vectors":
            self.ui.set_vectors(payload["labels"], payload["vectors"])
            self._json({"status": "ok"})
            return
        if route == "/nearestneighbors/query":
            result = self.ui.nearest(payload["word"], int(payload.get("k", 10)))
            if result is None:
                self._json({"error": "unknown word"}, 404)
            else:
                self._json(result)
            return
        self._json({"error": f"unknown path {url.path}"}, 404)


class UiServer:
    """The UI server facade (UiServer.getInstance() in the reference;
    here: instantiate + start/stop)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 telemetry_path: Optional[str] = None):
        self.storage = HistoryStorage()
        # the fleet-timeline source: explicit path beats the env var;
        # None leaves /timeline rendering its setup hint
        self.telemetry_path = telemetry_path
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.ui_server = self
        self._thread: Optional[threading.Thread] = None
        # nearest-neighbors state (reference: VPTree-backed word2vec NN —
        # ui/nearestneighbors; brute-force cosine is exact and fast enough
        # for UI-sized vocabularies, VPTree available for large ones)
        self._nn_lock = threading.Lock()
        self._nn_labels: list[str] = []
        self._nn_vectors: Optional[np.ndarray] = None

    # ------------------------------------------------------------ lifecycle
    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "UiServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    # ------------------------------------------------------ fleet timeline
    def load_timeline(self):
        """(Timeline|None, anomalies, source) for the /timeline views:
        merges the `.pN` shards of the configured telemetry path (ctor
        arg, else DL4J_TPU_TELEMETRY) on every request — the files are
        append-only JSONL, so a refresh IS the live view."""
        import os

        from deeplearning4j_tpu.telemetry import trace as trace_mod
        from deeplearning4j_tpu.telemetry.recorder import ENV_VAR

        path = self.telemetry_path or os.environ.get(ENV_VAR)
        if not path:
            return None, [], "unset"
        try:
            timeline = trace_mod.load_timeline(path)
        except (FileNotFoundError, OSError):
            return None, [], path
        return timeline, trace_mod.detect_anomalies(timeline), path

    # ---------------------------------------------------- nearest neighbors
    def set_vectors(self, labels, vectors) -> None:
        with self._nn_lock:
            self._nn_labels = list(labels)
            v = np.asarray(vectors, dtype=np.float32)
            self._nn_vectors = v / (np.linalg.norm(v, axis=1, keepdims=True) + 1e-12)

    def nearest(self, word: str, k: int = 10):
        with self._nn_lock:
            if self._nn_vectors is None or word not in self._nn_labels:
                return None
            i = self._nn_labels.index(word)
            sims = self._nn_vectors @ self._nn_vectors[i]
            sims[i] = -np.inf
            top = np.argsort(-sims)[:k]
            return {
                "words": [self._nn_labels[j] for j in top],
                "similarities": [float(sims[j]) for j in top],
            }
