"""Gradient checking — the correctness backbone of the test suite.

Reference: gradientcheck/GradientCheckUtil.java:48 (MLN) / :140
(ComputationGraph): central finite differences vs analytic gradients,
per-parameter relative error, eps 1e-6, maxRelError 1e-3, run in f64.

Here the "analytic" gradient is jax.grad of the network loss; the check
verifies the whole loss pipeline (layers, losses, masking, regularization)
differentiates correctly. Runs in float64 on CPU (tests enable x64).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _flatten_params(params):
    leaves, treedef = jax.tree.flatten(params)
    flat = np.concatenate([np.asarray(l, np.float64).ravel() for l in leaves])
    shapes = [(l.shape, l.dtype) for l in leaves]
    return flat, treedef, shapes


def _unflatten(flat, treedef, shapes):
    out, off = [], 0
    for shape, dtype in shapes:
        n = int(np.prod(shape))
        out.append(jnp.asarray(flat[off:off + n], dtype).reshape(shape))
        off += n
    return jax.tree.unflatten(treedef, out)


def check_gradients(net, dataset, *, epsilon: float = 1e-6,
                    max_rel_error: float = 1e-3, min_abs_error: float = 1e-8,
                    print_results: bool = False, subset: int | None = None,
                    seed: int = 12345) -> bool:
    """Central finite difference vs jax.grad for a MultiLayerNetwork (or any
    object exposing params/state/_loss/_batch_dict).

    subset: check only this many randomly-chosen parameters (reference checks
    all; tiny nets are cheap enough to do the same — pass subset for speed).
    """
    if hasattr(net, "_to_mds"):  # ComputationGraph path
        dataset = net._to_mds(dataset)
    batch = net._batch_dict(dataset)
    # fixed rng so dropout/sampling noise is identical across evaluations
    rng = None

    flat0, treedef, shapes = _flatten_params(net.params)

    @jax.jit
    def loss_flat(flat):
        params = _unflatten(flat, treedef, shapes)
        loss, _ = net._loss(params, net.state, rng, batch)
        return loss

    grad_flat = jax.jit(jax.grad(loss_flat))
    analytic = np.asarray(grad_flat(jnp.asarray(flat0, jnp.float64)),
                          np.float64)

    n = flat0.size
    idxs = np.arange(n, dtype=np.int64)
    if subset is not None and subset < n:
        idxs = np.random.default_rng(seed).choice(n, size=subset, replace=False)

    max_err = 0.0
    fails = 0
    for i in idxs:
        plus = flat0.copy()
        plus[i] += epsilon
        minus = flat0.copy()
        minus[i] -= epsilon
        numeric = (float(loss_flat(plus)) - float(loss_flat(minus))) / (2 * epsilon)
        a = analytic[i]
        denom = max(abs(a), abs(numeric))
        rel = 0.0 if denom == 0 else abs(a - numeric) / denom
        if rel > max_rel_error and abs(a - numeric) > min_abs_error:
            fails += 1
            if print_results:
                print(f"param {i}: analytic {a:.6e} numeric {numeric:.6e} rel {rel:.3e}")
        max_err = max(max_err, rel)
    if print_results:
        print(f"checked {len(idxs)} params, max rel error {max_err:.3e}, fails {fails}")
    return fails == 0


def check_gradients_graph(graph, mds, **kw) -> bool:
    """Gradient check for ComputationGraph (reference GradientCheckUtil:140)."""
    return check_gradients(graph, mds, **kw)


class GradientCheckUtil:
    """Namespace matching the reference class name."""

    check_gradients = staticmethod(check_gradients)
    check_gradients_graph = staticmethod(check_gradients_graph)
