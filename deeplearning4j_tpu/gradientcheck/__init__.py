from deeplearning4j_tpu.gradientcheck.gradient_check_util import (  # noqa: F401
    GradientCheckUtil,
    check_gradients,
    check_gradients_graph,
)
