"""Stage 5: precision-flow audit (dtype dataflow + frozen quantization
manifest).

The trace-level twin of the G031-G034 AST rules (precision_rules.py).
Walks every stage-2 entry point's closed jaxpr (shared trace — see
jaxpr_audit.closed_jaxpr) plus the decode/sampling extras below, and
distills a per-entry **precision profile**: the dtype of every
`dot_general`, additive reduction, scan carry, collective, and
`convert_element_type` the program issues, plus the count of
quantize/dequantize converts along the int8 cache path. The profiles
are frozen in analysis/precision_budget.json — the same
freeze/drift/refreeze contract as the stage-2 op budget and stage-3
collective signatures, per the ZeRO-style discipline (arXiv 2004.13336)
of auditing mixed-precision decisions instead of letting them be
emergent:

- P001: sub-f32 accumulation in a reduction chain — an add-accumulated
  scan carry, an additive reduce whose operand is (through shape/convert
  hops) a dot_general or another reduce, a cumulative op, or a psum
  operand, any of them in bfloat16/f16/f8. A single standalone reduce in
  bf16 is NOT a finding, and scopes containing `add_any` are exempt
  from the reduce-chain check: add_any exists ONLY as autodiff's
  transpose-rule gradient fan-in, so its presence marks a backward
  region whose bf16 bias-grad sums mirror the model's chosen training
  dtype (the bench LM modes trace in bf16 by design; the f32 answer
  there is master weights, not rewriting transpose rules). The
  discipline P001 enforces — accumulate in f32, downcast once — is for
  HAND-WRITTEN forward chains: kernels, scans, cumulatives, psums.
- P002: broken quantize<->dequantize pairing on the int8 path — an
  int8->float convert with no scale-multiply consumer (a raw-code read),
  or a float->int8 requantize in a read-modify-write scope whose value
  was never masked past the write head (`jnp.where`/select_n — stale
  garbage inflates the page maxabs and crushes fresh precision; see
  ops/decode_attention.quantized_cache_update).
- P003: convert churn — a convert_element_type whose producer is
  another convert, whose output dtype round-trips back to the inner
  input's dtype, and whose intermediate has NO other consumer. Pure
  HBM-bandwidth ping-pong. An intermediate that other ops (e.g. a VJP
  kernel expecting the working dtype) also read is a real value, not
  churn, and autodiff scopes (add_any present) are exempt like P001 —
  their convert pairs are residual plumbing XLA CSEs away.
- P004: dtype-widening collective — a psum/all-gather/... operand
  strictly wider than the entry's widest floating input. Widening on
  the wire multiplies interconnect bytes silently.
- P005: rank-divergent precision profile — the profile re-derived under
  simulated process_index 0 vs 1 (collective_audit's simulation)
  differs. Like stage 3's C003 this is deadlock-class: replicas that
  disagree about dtype flow compile different programs.
- PB01: profile drift vs the frozen manifest (or an entry missing from
  it). Regenerate deliberately: `tools/graftlint.py --update-precision`.

External fixture entries: a .py passed to `graftlint --stage precision`
that defines ``GRAFTLINT_PRECISION_ENTRIES = {name: builder}``
(builder() -> (fn, args)) gets profiled and P-rule checked without the
frozen-manifest requirement — the demo path for the bf16-accumulation
finding.

jax and the model stack load lazily; importing this module is cheap and
jax-free (the AST stages never touch it).
"""

from __future__ import annotations

import json
import os
import re

from deeplearning4j_tpu.analysis.core import Finding

BUDGET_PATH = os.path.join(os.path.dirname(__file__),
                           "precision_budget.json")

# the hook external fixture modules expose: {entry_name: builder}
ENTRY_HOOK = "GRAFTLINT_PRECISION_ENTRIES"

# Entries beyond the stage-2 set: the int8 paged-cache decode path and
# the two serving-side fused kernels the manifest must cover (ISSUE 20
# acceptance). These also carry the per-entry rank-divergence check
# (P005) — cheap traces, unlike the LM steps, whose rank story stage 3
# already owns.
PRECISION_EXTRA = (
    "decode_attention/cached",
    "decode_attention/q8",
    "decode_attention/q8_update",
    "fused_sampling/sample",
    "fused_neg_softmax/scores",
)

# Additive reductions — where evaluation ORDER compounds rounding.
# max/min/argmax are exact at any width and exempt.
_ADDITIVE_REDUCES = frozenset({"reduce_sum", "reduce_prod", "add_any"})
_CUMULATIVE = frozenset({"cumsum", "cumprod", "cumlogsumexp"})

# Reduction-style collectives whose operand is an accumulator.
_ACC_COLLECTIVES = frozenset({"psum", "psum_scatter", "reduce_scatter"})

# Shape/layout/width hops that carry an accumulation chain through
# without introducing new math — the P001 chain walk crosses these only.
_CHAIN_HOPS = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "expand_dims",
    "convert_element_type", "slice", "dynamic_slice", "rev", "copy",
})

# Pass-through hops for the P002a dequant->scale-multiply consumer walk.
_DEQUANT_HOPS = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "squeeze", "expand_dims",
})

_P002A_DEPTH = 6
_P002B_DEPTH = 14


def entry_names() -> list[str]:
    """Auditable stage-5 entry points (stable order): every stage-2
    entry plus the decode/sampling extras. Safe to call without jax."""
    from deeplearning4j_tpu.analysis import jaxpr_audit

    return jaxpr_audit.entry_names() + list(PRECISION_EXTRA)


# ------------------------------------------------------- extra builders

def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _build_extra(name):
    """-> (fn, args tuple) for one PRECISION_EXTRA entry, abstract
    inputs (serving-scale-ish shapes, nothing executes)."""
    import jax.numpy as jnp

    f32, i8, i32 = jnp.float32, jnp.int8, jnp.int32
    B, S, H, D, PS = 2, 256, 2, 64, 64
    n_pages = S // PS
    if name == "decode_attention/cached":
        from deeplearning4j_tpu.ops.decode_attention import decode_attention

        return decode_attention, (
            _sds((B, H, D), f32), _sds((B, S, H, D), f32),
            _sds((B, S, H, D), f32), _sds((B,), i32))
    if name == "decode_attention/q8":
        from deeplearning4j_tpu.ops.decode_attention import \
            cache_attention_q8

        return (lambda q, kc, vc, ks, vs, lim: cache_attention_q8(
            q, kc, vc, ks, vs, lim, PS)), (
            _sds((B, H, 1, D), f32), _sds((B, S, H, D), i8),
            _sds((B, S, H, D), i8), _sds((B, n_pages, H), f32),
            _sds((B, n_pages, H), f32), _sds((B, 1), i32))
    if name == "decode_attention/q8_update":
        from deeplearning4j_tpu.ops.decode_attention import \
            quantized_cache_update

        T = 8
        return (lambda c, s, nv, r, p: quantized_cache_update(
            c, s, nv, r, p, PS)), (
            _sds((B, S, H, D), i8), _sds((B, n_pages, H), f32),
            _sds((B, T, H, D), f32), _sds((B,), i32), _sds((B, T), i32))
    if name == "fused_sampling/sample":
        from deeplearning4j_tpu.ops.fused_sampling import fused_sample

        V = 1024
        return (lambda lg, nz: fused_sample(lg, nz, temperature=0.8,
                                            top_k=64, top_p=0.9)), (
            _sds((8, V), f32), _sds((8, V), f32))
    if name == "fused_neg_softmax/scores":
        from deeplearning4j_tpu.ops.fused_neg_softmax import \
            neg_softmax_scores

        return neg_softmax_scores, (
            _sds((8, 128), f32), _sds((8, 128), f32),
            _sds((8, 5, 128), f32))
    raise KeyError(name)


def trace_closed(name):
    """Closed jaxpr for any stage-5 entry — the stage-2 names go
    through jaxpr_audit's memo cache (one trace serves both stages in
    `--stage all`); the extras trace here."""
    from deeplearning4j_tpu.analysis import jaxpr_audit

    if name in PRECISION_EXTRA:
        import jax

        fn, args = _build_extra(name)
        return jax.make_jaxpr(fn)(*args)
    return jaxpr_audit.closed_jaxpr(name)


# ------------------------------------------------------------ profiling

def _iter_scopes(jaxpr):
    """Every jaxpr SCOPE (the outer jaxpr plus each pjit/scan/cond/
    pallas sub-jaxpr). Producer/consumer relations only hold within one
    scope, so the dataflow walks analyze scopes independently."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for val in eqn.params.values():
            for sub in (val if isinstance(val, (list, tuple)) else [val]):
                inner = getattr(sub, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    yield from _iter_scopes(inner)
                elif hasattr(sub, "eqns"):
                    yield from _iter_scopes(sub)


def _is_var(v):
    # jax Literal carries .val; Var does not
    return hasattr(v, "aval") and not hasattr(v, "val")


def _dt(v) -> str:
    dtype = getattr(getattr(v, "aval", None), "dtype", None)
    return str(dtype) if dtype is not None else "?"


def _is_sub_f32(v) -> bool:
    import numpy as np

    dtype = getattr(getattr(v, "aval", None), "dtype", None)
    if dtype is None:
        return False
    dtype = np.dtype(dtype) if not hasattr(dtype, "itemsize") else dtype
    try:
        import jax.numpy as jnp

        floating = jnp.issubdtype(dtype, np.floating)
    except Exception:
        floating = np.issubdtype(dtype, np.floating)
    return bool(floating) and dtype.itemsize < 4


def _is_float(v) -> bool:
    import numpy as np

    dtype = getattr(getattr(v, "aval", None), "dtype", None)
    if dtype is None:
        return False
    try:
        import jax.numpy as jnp

        return bool(jnp.issubdtype(dtype, np.floating))
    except Exception:
        return bool(np.issubdtype(dtype, np.floating))


def _float_width(v) -> int:
    """Itemsize of a floating aval, 0 otherwise."""
    if not _is_float(v):
        return 0
    return getattr(v.aval.dtype, "itemsize", 0)


def _producers(scope) -> dict:
    return {out: eqn for eqn in scope.eqns for out in eqn.outvars
            if _is_var(out)}


def _consumers(scope) -> dict:
    cons: dict = {}
    for eqn in scope.eqns:
        for v in eqn.invars:
            if _is_var(v):
                cons.setdefault(v, []).append(eqn)
    return cons


def _chain_hits(var, producers, targets, *, hops, depth=24) -> bool:
    """Walk var's producer chain crossing only `hops` prims; True when a
    producer primitive lands in `targets`."""
    seen = 0
    while _is_var(var) and seen < depth:
        eqn = producers.get(var)
        if eqn is None:
            return False
        prim = eqn.primitive.name
        if prim in targets:
            return True
        if prim not in hops:
            return False
        var = next((v for v in eqn.invars if _is_var(v)), None)
        seen += 1
    return False


def _chain_reaches_var(var, producers, target, *, hops, depth=24) -> bool:
    """Like `_chain_hits` but looking for a specific VAR (the scan carry
    invar) instead of a primitive."""
    seen = 0
    while _is_var(var) and seen < depth:
        if var is target:
            return True
        eqn = producers.get(var)
        if eqn is None:
            return False
        if eqn.primitive.name not in hops:
            return False
        var = next((v for v in eqn.invars if _is_var(v)), None)
        seen += 1
    return False


def _eqn_contains(eqn, target: str) -> bool:
    """Does the eqn ITSELF match `target`, or (for call-like eqns —
    jnp.where/round arrive as `pjit[name=_where]` wrappers) any eqn of
    its sub-jaxprs, recursively?"""
    if eqn.primitive.name == target:
        return True
    for val in eqn.params.values():
        for sub in (val if isinstance(val, (list, tuple)) else [val]):
            inner = getattr(sub, "jaxpr", None)
            body = inner if inner is not None and hasattr(inner, "eqns") \
                else (sub if hasattr(sub, "eqns") else None)
            if body is not None and any(_eqn_contains(e, target)
                                        for e in body.eqns):
                return True
    return False


def _reaches_prim(var, producers, target: str, depth: int) -> bool:
    """Bounded BFS through ALL producers: does `target` appear anywhere
    in var's (shallow) history? Call-like eqns (pjit wrappers) are
    transparent. Conservative in the safe direction — a hit through an
    unrelated operand only *suppresses* a finding."""
    frontier, seen = [var], set()
    for _ in range(depth):
        nxt = []
        for v in frontier:
            if not _is_var(v) or v in seen:
                continue
            seen.add(v)
            eqn = producers.get(v)
            if eqn is None:
                continue
            if _eqn_contains(eqn, target):
                return True
            nxt.extend(eqn.invars)
        if not nxt:
            return False
        frontier = nxt
    return False


def _scale_multiplied(var, consumers, depth=_P002A_DEPTH) -> bool:
    """P002a consumer walk: the dequantized codes must hit a `mul`
    (the per-page scale) within a few pass-through hops."""
    frontier = [var]
    for _ in range(depth):
        nxt = []
        for v in frontier:
            for eqn in consumers.get(v, ()):
                prim = eqn.primitive.name
                if prim == "mul":
                    return True
                if prim in _DEQUANT_HOPS:
                    nxt.extend(o for o in eqn.outvars if _is_var(o))
        if not nxt:
            return False
        frontier = nxt
    return False


def _bump(d: dict, key: str) -> None:
    d[key] = d.get(key, 0) + 1


def profile_closed(closed, name: str):
    """-> (profile dict, P001-P004 findings) for one closed jaxpr.

    The profile is the frozen-manifest unit: dtype-keyed counts of
    dots / additive reductions / scan carries / collectives / converts,
    plus round-trip and quantize/dequantize tallies. JSON-stable and
    rank-comparable (P005 diffs two of these)."""
    from deeplearning4j_tpu.analysis.collective_audit import \
        JAXPR_COLLECTIVES

    profile = {"dots": {}, "reductions": {}, "scan_carries": {},
               "collectives": {}, "converts": {},
               "convert_round_trips": 0, "q8": {"quantize": 0,
                                                "dequantize": 0}}
    findings: list[Finding] = []
    flagged: set[str] = set()

    def flag(rule, message, fixit, snippet):
        if snippet in flagged:       # one finding per (rule, site class)
            return
        flagged.add(snippet)
        findings.append(Finding(rule, name, 0, 0, message, fixit,
                                snippet=snippet, stage="precision"))

    # widest floating ENTRY input — the P004 reference width
    in_width = max((_float_width(v) for v in closed.jaxpr.invars),
                   default=0)

    for scope in _iter_scopes(closed.jaxpr):
        producers = _producers(scope)
        consumers = _consumers(scope)
        # add_any exists only as autodiff's gradient fan-in — its
        # presence marks a backward region, exempt from the chain and
        # churn checks (see the module docstring)
        backward_scope = any(e.primitive.name == "add_any"
                             for e in scope.eqns)
        scope_deq = []            # int8->float converts in this scope
        scope_req = []            # float->int8 converts in this scope

        for eqn in scope.eqns:
            prim = eqn.primitive.name
            out = eqn.outvars[0] if eqn.outvars else None

            if prim == "dot_general":
                ins = ",".join(_dt(v) for v in eqn.invars[:2])
                _bump(profile["dots"], f"{ins}->{_dt(out)}")

            elif prim in _ADDITIVE_REDUCES or prim in _CUMULATIVE:
                _bump(profile["reductions"], f"{prim}:{_dt(out)}")
                if out is not None and _is_sub_f32(out) \
                        and not backward_scope:
                    if prim in _CUMULATIVE:
                        flag("P001",
                             f"`{prim}` accumulates in {_dt(out)} — a "
                             "cumulative chain compounds sub-f32 "
                             "rounding at every step",
                             "compute the cumulative op in f32 "
                             "(preferred_element_type / astype) and "
                             "downcast the result",
                             f"cum-subf32:{prim}:{_dt(out)}")
                    else:
                        operand = next((v for v in eqn.invars
                                        if _is_var(v)), None)
                        if operand is not None and _chain_hits(
                                operand, producers,
                                {"dot_general"} | _ADDITIVE_REDUCES,
                                hops=_CHAIN_HOPS):
                            flag("P001",
                                 f"`{prim}` in {_dt(out)} directly over "
                                 "a dot_general/reduce — a chained "
                                 "reduction accumulating below f32",
                                 "accumulate in f32 "
                                 "(preferred_element_type=jnp.float32 "
                                 "on the dot, or reduce before the "
                                 "downcast)",
                                 f"chain-subf32:{prim}:{_dt(out)}")

            elif prim == "scan":
                ncarry = eqn.params.get("num_carry", 0)
                nconst = eqn.params.get("num_consts", 0)
                body = eqn.params.get("jaxpr")
                inner = getattr(body, "jaxpr", body)
                if inner is not None and hasattr(inner, "outvars"):
                    body_prod = _producers(inner)
                    for i, cv in enumerate(inner.outvars[:ncarry]):
                        _bump(profile["scan_carries"], _dt(cv))
                        if not (_is_var(cv) and _is_sub_f32(cv)):
                            continue
                        peqn = body_prod.get(cv)
                        if peqn is not None and peqn.primitive.name in \
                                ("add", "add_any"):
                            carry_in = inner.invars[nconst + i] \
                                if nconst + i < len(inner.invars) else None
                            if carry_in is None or any(
                                    _chain_reaches_var(v, body_prod,
                                                       carry_in,
                                                       hops=_CHAIN_HOPS)
                                    for v in peqn.invars if _is_var(v)):
                                flag("P001",
                                     f"scan carry {i} add-accumulates "
                                     f"in {_dt(cv)} — running sums "
                                     "below f32 lose low bits every "
                                     "iteration",
                                     "carry the accumulator in f32 and "
                                     "downcast after the scan (the "
                                     "flash/decode kernels' pattern)",
                                     f"carry-subf32:{_dt(cv)}:{i}")

            elif prim in JAXPR_COLLECTIVES:
                operand = next((v for v in eqn.invars if _is_var(v)),
                               None)
                key_dt = _dt(operand) if operand is not None else "?"
                _bump(profile["collectives"], f"{prim}:{key_dt}")
                if prim in _ACC_COLLECTIVES and operand is not None \
                        and _is_sub_f32(operand):
                    flag("P001",
                         f"`{prim}` reduces a {key_dt} operand across "
                         "ranks — the cross-replica sum is itself a "
                         "sub-f32 accumulation chain",
                         "psum in f32 (upcast the operand; downcast "
                         "after)", f"psum-subf32:{prim}:{key_dt}")
                if operand is not None and in_width and \
                        _float_width(operand) > in_width:
                    flag("P004",
                         f"`{prim}` moves a {key_dt} operand while the "
                         "entry's widest floating input is "
                         f"{in_width * 8}-bit — widened bytes on the "
                         "wire",
                         "downcast before the collective (or keep the "
                         "f32 master copy local, ZeRO-style)",
                         f"widening:{prim}:{key_dt}")

            elif prim == "convert_element_type":
                src = eqn.invars[0]
                key = f"{_dt(src)}->{_dt(out)}"
                _bump(profile["converts"], key)
                if _dt(src).startswith("int8") and _is_float(out):
                    profile["q8"]["dequantize"] += 1
                    scope_deq.append(eqn)
                elif _is_float(src) and _dt(out).startswith("int8"):
                    profile["q8"]["quantize"] += 1
                    scope_req.append(eqn)
                # P003: direct convert-of-convert landing back on the
                # inner input's dtype, the intermediate consumed by
                # nothing else — a pure round trip
                if _is_var(src) and not backward_scope:
                    peqn = producers.get(src)
                    if peqn is not None and \
                            peqn.primitive.name == "convert_element_type":
                        inner_src = peqn.invars[0]
                        only_here = (
                            all(c is eqn for c in consumers.get(src, ()))
                            and src not in set(scope.outvars))
                        if only_here and _dt(out) == _dt(inner_src) \
                                and _dt(out) != _dt(src):
                            profile["convert_round_trips"] += 1
                            flag("P003",
                                 f"convert {_dt(inner_src)}->{_dt(src)}"
                                 f"->{_dt(out)} round trip — the value "
                                 "never changed; both converts are HBM "
                                 "bandwidth",
                                 "delete the ping-pong (keep the value "
                                 "in its working dtype)",
                                 f"churn:{_dt(inner_src)}->{_dt(src)}")

        # -------- P002: quantize<->dequantize pairing, per q8 scope
        if scope_deq:
            scope_outs = set(scope.outvars)
            for eqn in scope_deq:
                out = eqn.outvars[0]
                if out in scope_outs:
                    continue      # escapes the scope; caller's problem
                if not _scale_multiplied(out, consumers):
                    flag("P002",
                         "int8 codes converted to float but never "
                         "scale-multiplied nearby — a raw-code read "
                         "(missing dequant) on the q8 cache path",
                         "multiply by the per-(row,page,head) scale "
                         "right after the convert "
                         "(ops/decode_attention dequant idiom)",
                         "q8-read-unscaled")
        if scope_deq and scope_req:
            # read-modify-write scope: the requantize must sit behind a
            # select_n (write-head zeroing) or stale garbage sets scales
            for eqn in scope_req:
                if not _reaches_prim(eqn.invars[0], producers,
                                     "select_n", _P002B_DEPTH):
                    flag("P002",
                         "requantize in a read-modify-write q8 scope "
                         "without masking past the write head — stale "
                         "values from a prior tenancy inflate the page "
                         "maxabs and crush fresh precision",
                         "jnp.where positions past the row's write "
                         "head to 0 before recomputing scales "
                         "(quantized_cache_update's zeroing step)",
                         "q8-requant-unmasked")

    # sort for JSON stability / manifest comparison
    for k in ("dots", "reductions", "scan_carries", "collectives",
              "converts"):
        profile[k] = dict(sorted(profile[k].items()))
    return profile, findings


def trace_profile(name: str):
    """-> (profile, findings) for one named entry."""
    return profile_closed(trace_closed(name), name)


# ----------------------------------------------------- rank simulation

def _build_for(name):
    if name in PRECISION_EXTRA:
        return lambda: _build_extra(name)
    from deeplearning4j_tpu.analysis import jaxpr_audit

    return lambda: jaxpr_audit._build(name)


def check_rank_independence(name: str, build=None) -> list[Finding]:
    """Re-derive the precision profile under simulated process_index
    0 vs 1 (collective_audit's env-contract simulation). A divergent
    profile is deadlock-class (P005), exactly like stage 3's C003: the
    replicas would compile different mixed-precision programs."""
    import jax

    from deeplearning4j_tpu.analysis.collective_audit import (
        SIMULATED_PROCESSES, simulated_process_index)

    build = build or _build_for(name)
    profiles = {}
    for pid in SIMULATED_PROCESSES:
        with simulated_process_index(pid):
            fn, args = build()
            closed = jax.make_jaxpr(fn)(*args)
            profiles[pid], _ = profile_closed(closed, name)
    p0, p1 = (profiles[p] for p in SIMULATED_PROCESSES)
    if p0 != p1:
        diff = sorted(k for k in set(p0) | set(p1)
                      if p0.get(k) != p1.get(k))
        return [Finding(
            "P005", name, 0, 0,
            "rank-divergent precision profile — process 0 and process 1 "
            f"disagree on {diff}: replicas compiling different "
            "mixed-precision programs desync exactly like a divergent "
            "collective sequence (DEADLOCK class)",
            "make dtype decisions rank-invariant; never branch dtypes "
            "on process_index at trace time",
            snippet="rank-divergent-precision", stage="precision")]
    return []


# -------------------------------------------------------------- manifest

def load_budget(path: str | None = None) -> dict[str, dict]:
    try:
        with open(path or BUDGET_PATH) as fh:
            return dict(json.load(fh)["entries"])
    except FileNotFoundError:
        return {}


def write_budget(profiles: dict[str, dict],
                 path: str | None = None) -> None:
    with open(path or BUDGET_PATH, "w") as fh:
        json.dump(
            {"comment": "frozen per-entry precision manifest (graftlint "
                        "stage 5): dtype-keyed counts of dots / additive "
                        "reductions / scan carries / collectives / "
                        "converts plus int8 quantize/dequantize tallies. "
                        "A drift here is a mixed-precision regression "
                        "unless deliberate: tools/graftlint.py "
                        "--update-precision",
             "entries": {k: profiles[k] for k in sorted(profiles)}},
            fh, indent=1, sort_keys=False)
        fh.write("\n")


def _diff_keys(frozen: dict, got: dict) -> list[str]:
    return sorted(k for k in set(frozen) | set(got)
                  if frozen.get(k) != got.get(k))


def audit(names=None, budget_path: str | None = None, *,
          divergence: bool = True):
    """Run the stage-5 audit -> (findings, {entry: profile})."""
    budget = load_budget(budget_path)
    findings, profiles = [], {}
    for name in names if names is not None else entry_names():
        profile, fs = trace_profile(name)
        profiles[name] = profile
        findings.extend(fs)
        frozen = budget.get(name)
        if frozen is None:
            findings.append(Finding(
                "PB01", name, 0, 0,
                "entry point has no frozen precision profile "
                f"(traced {sum(profile['dots'].values())} dot(s), "
                f"{sum(profile['converts'].values())} convert(s))",
                "run `python tools/graftlint.py --update-precision`",
                snippet="missing-precision-profile", stage="precision"))
        elif frozen != profile:
            findings.append(Finding(
                "PB01", name, 0, 0,
                "precision profile drift vs the frozen manifest in "
                f"{_diff_keys(frozen, profile)} — an accumulation "
                "dtype, convert, or quant count changed",
                "find what changed the dtype flow; only then refreeze "
                "(--update-precision)",
                snippet="precision-drift", stage="precision"))
        # rank simulation re-traces, so only the cheap extras carry it
        # (the LM steps' rank story is stage 3's C003 on the
        # distributed entries)
        if divergence and name in PRECISION_EXTRA:
            findings.extend(check_rank_independence(name))
    return findings, profiles


# --------------------------------------------------- external fixtures

def load_entry_module(path: str):
    """Import a fixture .py by path and return its
    GRAFTLINT_PRECISION_ENTRIES hook ({name: builder}), or {}."""
    import importlib.util

    modname = "_graftlint_prec_" + re.sub(r"\W", "_", os.path.abspath(path))
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return getattr(mod, ENTRY_HOOK, {})


def audit_paths(paths) -> tuple[list[Finding], dict[str, dict]]:
    """Profile + P-rule-check every external entry the given .py files
    expose (no frozen-manifest requirement — demo/fixture entries)."""
    import jax

    findings, profiles = [], {}
    for path in paths:
        if not (path.endswith(".py") and os.path.isfile(path)):
            continue
        for name, build in load_entry_module(path).items():
            fn, args = build()
            closed = jax.make_jaxpr(fn)(*args)
            profile, fs = profile_closed(closed, name)
            profiles[name] = profile
            findings.extend(fs)
            findings.extend(check_rank_independence(name, build))
    return findings, profiles
