"""Stage 1: run the G-rules over a file tree.

Pure stdlib — importing this module must NOT import jax, so the AST pass
stays instant as a pre-commit step (`tools/graftlint.py --check`)."""

from __future__ import annotations

import ast
import os

from deeplearning4j_tpu.analysis.core import (Finding, apply_suppressions,
                                              split_baselined)
from deeplearning4j_tpu.analysis.ast_rules import run_rules

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules"}


def iter_py_files(paths):
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def lint_source(source: str, path: str) -> list[Finding]:
    """Lint one file's source. `path` is the repo-relative posix path —
    rules use it for scoping (G002 hot dirs, G007's compat.py opt-out)."""
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as exc:
        return [Finding("G000", path, exc.lineno or 0, exc.offset or 0,
                        f"syntax error: {exc.msg}", "fix the syntax error",
                        "")]
    return apply_suppressions(run_rules(tree, source, path), source)


def lint_paths(paths, root: str | None = None) -> list[Finding]:
    """Lint every .py under `paths`; finding paths are relative to
    `root` (default cwd) so baseline keys are machine-independent."""
    root = os.path.abspath(root or os.getcwd())
    findings = []
    for fpath in iter_py_files(paths):
        with open(fpath, encoding="utf-8") as fh:
            source = fh.read()
        rel = os.path.relpath(os.path.abspath(fpath), root)
        findings.extend(lint_source(source, rel.replace(os.sep, "/")))
    return findings


def lint_report(paths, baseline: set[str], root: str | None = None):
    """-> (new_findings, grandfathered_findings)."""
    return split_baselined(lint_paths(paths, root), baseline)
