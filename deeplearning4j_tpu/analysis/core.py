"""Finding model, inline suppressions, and the checked-in baseline.

A finding's baseline KEY deliberately omits the line number: baselines
must survive unrelated edits above the offending line, so the key is
(rule, path, stripped source line). Two identical offending lines in one
file share a key — acceptable for a grandfather list that is supposed to
shrink to zero, not grow.
"""

from __future__ import annotations

import dataclasses
import json
import re

# `# graftlint: disable=G001` or `# graftlint: disable=G001,G005` on the
# offending line (or the `if`/`def` line of the flagged statement).
_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+)")


# Every lint stage, in execution order. The CLI's --stage choices and
# the --rules inventory derive from this — adding a stage means adding
# it here plus its runner in tools/graftlint.py.
STAGES = ("ast", "jaxpr", "spmd", "concurrency", "precision")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str        # "G001".."G034" (AST passes) / "J001".."J004"
                     # (jaxpr) / "C001".."C003" (collective audit)
                     # / "D001".."D003" (lock-order audit)
                     # / "P001".."P005", "PB01" (precision audit)
    path: str        # repo-relative posix path, or an entry-point name
    line: int        # 1-based; 0 for whole-artifact (jaxpr) findings
    col: int
    message: str
    fixit: str       # how to fix it (every rule carries one)
    snippet: str = ""
    # which lint stage produced it (one of STAGES) so --json consumers
    # (benchdiff-style tooling) can filter without re-deriving the
    # stage from the rule id. Excluded from `key`: baselines must
    # stay valid if a rule migrates stages.
    stage: str = ""

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.path}:{self.snippet}"

    def format(self) -> str:
        loc = f"{self.path}:{self.line}:{self.col}" if self.line else self.path
        return f"{loc}: {self.rule} {self.message}\n    fix: {self.fixit}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def suppressions(source: str) -> dict[int, set[str]]:
    """line (1-based) -> set of rule ids disabled on that line.

    Matched against the finding's reported line, so a disable comment
    sits on the line the linter names (for multi-line statements that is
    the statement's FIRST line)."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def apply_suppressions(findings, source: str):
    sup = suppressions(source)
    return [f for f in findings if f.rule not in sup.get(f.line, ())]


def load_baseline(path: str) -> set[str]:
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return set()
    return set(data.get("findings", []))


def write_baseline(path: str, findings) -> None:
    keys = sorted({f.key for f in findings})
    with open(path, "w") as fh:
        json.dump(
            {"comment": "graftlint grandfathered findings — shrink, never "
                        "grow. Regenerate: tools/graftlint.py --write-baseline",
             "findings": keys}, fh, indent=1)
        fh.write("\n")


def split_baselined(findings, baseline: set[str]):
    """-> (new, grandfathered)."""
    new, old = [], []
    for f in findings:
        (old if f.key in baseline else new).append(f)
    return new, old
