"""graftlint — JAX/TPU static analysis for this repo (ISSUEs 2 + 5 + 17).

Four stages:

1. AST pass (`ast_pass.lint_paths`): rules G001-G014 over the package —
   tracer leaks, host syncs in hot paths, float64 drift, RNG discipline,
   retrace hazards, shard_map arity, util/compat bypasses, import-time
   device captures, rendezvous plumbing outside distributed/bootstrap
   (G001-G009, ast_rules.py), and the SPMD rank-divergence shapes:
   rank-guarded collectives/jit/mesh, host nondeterminism into traced
   values, unbound collective axis names, rank-conditional host syncs
   (G010-G014, spmd_rules.py). Pure stdlib; never imports jax.
2. jaxpr audit (`jaxpr_audit.audit`): traces the public jitted entry
   points with abstract inputs on CPU and asserts the programs are
   transfer-clean (J001), within frozen op-count budgets (J002), and
   float64-free (J003).
3. collective audit (`collective_audit.audit`, `--stage spmd`): ordered
   collective signatures per distributed/parallel entry point checked
   against a frozen budget (C001/C002), plus re-tracing under simulated
   process_index 0 vs 1 — a rank-divergent sequence is a fleet-DEADLOCK
   finding (C003), never a budget diff.
4. concurrency audit (`--stage concurrency`): the host-side threaded
   runtime. AST rules G025-G028 (concurrency_rules.py) — shared-
   attribute races with an inferred attribute->lock guard map, blocking
   calls under held locks, wait/notify/sleep discipline, thread
   lifecycle — plus the whole-package lock-ORDER graph
   (lock_audit.py): any cycle is a host deadlock (D001, the twin of
   C003, always exits 1), sink-callback reentrancy is D002, and edges
   are frozen in analysis/lock_order.json (`--update-locks`; drift is
   D003). Pure stdlib; never imports jax.

CLI: `python tools/graftlint.py --check deeplearning4j_tpu`. Inline
suppression: `# graftlint: disable=G00x`; grandfathered findings live in
tools/graftlint_baseline.json. Gates: tests/test_graftlint.py +
tests/test_spmd_lint.py (tier-1, `pytest -m lint`).
"""

from deeplearning4j_tpu.analysis.ast_pass import (iter_py_files,
                                                  lint_paths, lint_report,
                                                  lint_source)
from deeplearning4j_tpu.analysis.ast_rules import RULE_DOCS
from deeplearning4j_tpu.analysis.concurrency_rules import (guard_map,
                                                           guard_map_for_file)
from deeplearning4j_tpu.analysis.core import (STAGES, Finding,
                                              load_baseline,
                                              split_baselined,
                                              write_baseline)

__all__ = [
    "Finding", "RULE_DOCS", "STAGES", "guard_map", "guard_map_for_file",
    "iter_py_files", "lint_paths", "lint_report",
    "lint_source", "load_baseline", "split_baselined", "write_baseline",
]
