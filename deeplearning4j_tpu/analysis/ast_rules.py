"""The G001-G009 + G016-G024 + G029 AST rules (G010-G015 + G018 live
in spmd_rules.py, G025-G028 in concurrency_rules.py; both register
into ALL_RULES/RULE_DOCS at the bottom of this module).

Every rule errs toward PRECISION over recall: a lint gate that cries
wolf gets suppressed wholesale, while a quiet one keeps running in CI
forever. Each rule documents what it deliberately does not catch.

All name matching goes through the per-file import table (`Imports`), so
`import numpy as onp` / `from jax import random as jr` spellings resolve
to canonical dotted paths before any rule looks at them.
"""

from __future__ import annotations

import ast
import re

from deeplearning4j_tpu.analysis.core import Finding

# Paths whose code runs per training step — the G002 host-sync scope.
HOT_PATH_FRAGMENTS = ("/ops/", "/parallel/", "/nn/layers/")

# Decorators that put a function body under a jax trace.
_JIT_NAMES = {"jax.jit", "jax.pjit", "jit", "pjit",
              "jax.experimental.pjit.pjit"}
_TRACED_DECOS = _JIT_NAMES | {
    "jax.custom_vjp", "jax.custom_jvp", "jax.checkpoint", "jax.remat",
    "jax.vmap", "jax.grad", "jax.value_and_grad"}

# Attribute reads that return STATIC python values even on tracers.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                 "weak_type"}
# Builtins whose result on a traced arg is static (or that never trace).
_STATIC_CALLS = {"len", "isinstance", "type", "hasattr", "getattr", "id",
                 "repr", "str"}

_NP_CTORS = {"zeros", "ones", "empty", "full", "arange", "linspace",
             "eye", "identity"}

# jax.random.* that do NOT consume the key (safe to call repeatedly with
# the same key). Everything else — split included — consumes it.
_KEY_NONCONSUMING = {"fold_in", "key_data", "wrap_key_data", "key_impl",
                     "clone"}

# params treated as PRNG keys for the G004 reuse check, by convention
_KEY_PARAM_RE = re.compile(r"(?:^|_)(?:key|rng|prng)s?$|^(?:key|rng)")

_MUTABLE_DEFAULT_CALLS = {"list", "dict", "set", "bytearray",
                          "defaultdict", "OrderedDict"}

# jnp/jax calls that ALLOCATE a device buffer when run at module level.
_DEVICE_ALLOC = {"jax.numpy." + n for n in
                 _NP_CTORS | {"array", "asarray", "stack", "concatenate"}}
_DEVICE_ALLOC |= {"jax.random.PRNGKey", "jax.random.key",
                  "jax.device_put"}


class Imports:
    """Local alias -> canonical dotted module path, e.g. jnp ->
    jax.numpy, shard_map -> deeplearning4j_tpu.util.compat.shard_map."""

    def __init__(self, tree: ast.AST):
        self.map: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.map[a.asname] = a.name
                    else:
                        root = a.name.split(".")[0]
                        self.map[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.map[a.asname or a.name] = f"{node.module}.{a.name}"

    def canon(self, node: ast.AST) -> str | None:
        """Canonical dotted path of a Name/Attribute chain, or None."""
        parts = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(self.map.get(node.id, node.id))
        return ".".join(reversed(parts))


def _walk_with_parents(tree: ast.AST):
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._gl_parent = parent  # type: ignore[attr-defined]
    return tree


def _parents(node: ast.AST):
    while True:
        node = getattr(node, "_gl_parent", None)
        if node is None:
            return
        yield node


def _decorator_canon(deco: ast.AST, imports: Imports):
    """(canonical name, call node | None) for plain / called / partial-
    wrapped decorators: @jax.jit, @jax.jit(...), @partial(jax.jit, ...)."""
    call = None
    if isinstance(deco, ast.Call):
        call = deco
        name = imports.canon(deco.func)
        if name in ("functools.partial", "partial") and deco.args:
            name = imports.canon(deco.args[0])
        return name, call
    return imports.canon(deco), call


def _static_params(fn: ast.FunctionDef, deco_call: ast.Call | None,
                   deco_name: str) -> set[str]:
    """Param names the decorator marks static (static_argnums/argnames,
    custom_vjp nondiff_argnums — passed as concrete python values)."""
    pos = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: set[str] = set()
    if deco_call is None:
        return static
    for kw in deco_call.keywords:
        if kw.arg in ("static_argnums", "nondiff_argnums",
                      "static_argnames"):
            vals = kw.value.elts if isinstance(
                kw.value, (ast.Tuple, ast.List)) else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant):
                    if isinstance(v.value, int) and 0 <= v.value < len(pos):
                        static.add(pos[v.value])
                    elif isinstance(v.value, str):
                        static.add(v.value)
    return static


def _traced_functions(tree: ast.AST, imports: Imports):
    """(fn, traced param names) for every function whose body jax traces."""
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            name, call = _decorator_canon(deco, imports)
            if name in _TRACED_DECOS:
                params = {a.arg for a in node.args.posonlyargs
                          + node.args.args + node.args.kwonlyargs}
                params -= _static_params(node, call, name)
                yield node, params
                break


def _mentions_traced(expr: ast.AST, tracked: set[str],
                     imports: Imports) -> bool:
    """Does `expr` read a tracked (traced-value) name in a position that
    yields a tracer? `.shape`/`.ndim`/... reads and len()/isinstance()
    calls are static even on tracers and do not count."""
    def visit(node) -> bool:
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return False
        if isinstance(node, ast.Call):
            fname = imports.canon(node.func)
            if fname in _STATIC_CALLS:
                return False
            return visit(node.func) or any(
                visit(a) for a in node.args) or any(
                visit(k.value) for k in node.keywords)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            return node.id in tracked
        return any(visit(c) for c in ast.iter_child_nodes(node))
    return visit(expr)


def _only_identity_tests(test: ast.AST) -> bool:
    """`x is None` / `x is not None` and and/or/not combinations thereof
    — legal on tracers (identity, not value)."""
    if isinstance(test, ast.Compare):
        return all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
    if isinstance(test, ast.BoolOp):
        return all(_only_identity_tests(v) for v in test.values)
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _only_identity_tests(test.operand)
    return False


def _grow_tracked(fn: ast.AST, tracked: set[str], imports: Imports):
    """Fixpoint: names assigned from expressions over tracked names are
    themselves tracked (y = x * 2). Bounded iterations; order-insensitive."""
    for _ in range(4):
        before = len(tracked)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _mentions_traced(
                    node.value, tracked, imports):
                for tgt in node.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            tracked.add(n.id)
            elif isinstance(node, ast.For) and _mentions_traced(
                    node.iter, tracked, imports):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        tracked.add(n.id)
        if len(tracked) == before:
            break


# --------------------------------------------------------------- G001

def g001_traced_bool(tree, imports, path):
    """Python control flow / bool()/float()/int() on traced values inside
    jit-traced functions: ConcretizationTypeError at runtime, or worse, a
    silent retrace per distinct value. Not caught: traced values entering
    via closure instead of params."""
    out = []
    for fn, tracked in _traced_functions(tree, imports):
        tracked = set(tracked)
        _grow_tracked(fn, tracked, imports)
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if _only_identity_tests(node.test):
                    continue
                if _mentions_traced(node.test, tracked, imports):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append((node, f"python `{kind}` on a traced value",
                                "use jnp.where / lax.cond / lax.while_loop,"
                                " or mark the driving arg static"))
            elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Name) and node.func.id in (
                    "bool", "float", "int") and node.args and \
                    _mentions_traced(node.args[0], tracked, imports):
                out.append((node, f"`{node.func.id}()` forces a traced "
                            "value to a python scalar (device sync / "
                            "ConcretizationTypeError)",
                            "keep it as a jnp scalar, or hoist the "
                            "conversion out of the traced function"))
    return [("G001", n, m, f) for n, m, f in out]


# --------------------------------------------------------------- G002

def g002_host_sync(tree, imports, path):
    """Implicit device->host syncs in hot paths (ops/, parallel/,
    nn/layers/): .item(), jax.device_get, np.asarray/np.array on device
    values stall the dispatch pipeline mid-step. Host-side setup code in
    those dirs carries an inline disable with its justification."""
    if not any(frag in path for frag in HOT_PATH_FRAGMENTS):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = imports.canon(node.func)
        if name in ("numpy.asarray", "numpy.array"):
            out.append(("G002", node,
                        f"`{name.replace('numpy', 'np')}` in a hot path "
                        "pulls the value to host (sync) and re-uploads",
                        "stay in jnp (`jnp.asarray`), or move host "
                        "conversion out of the per-step path"))
        elif name == "jax.device_get":
            out.append(("G002", node, "`jax.device_get` in a hot path is "
                        "an explicit device sync",
                        "batch transfers outside the step loop"))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args:
            out.append(("G002", node, "`.item()` in a hot path blocks on "
                        "the device value",
                        "keep the scalar on device; log via jax.debug or "
                        "after the step"))
    return out


# --------------------------------------------------------------- G003

def g003_float64_drift(tree, imports, path):
    """dtype-less np constructors inside functions that also do jnp math:
    np defaults to float64/int64, so the host value either silently
    downcasts at the jnp boundary or (x64 enabled) upcasts the whole
    expression. Not caught: promotion via python float literals."""
    out = []
    fns = [n for n in ast.walk(tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    seen: set[int] = set()
    for fn in fns:
        uses_jnp = any(
            (c := imports.canon(n)) and
            (c.startswith("jax.numpy.") or c.startswith("jax.lax."))
            for n in ast.walk(fn) if isinstance(n, (ast.Attribute, ast.Name)))
        if not uses_jnp:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            name = imports.canon(node.func)
            if name and name.startswith("numpy.") and \
                    name.split(".")[-1] in _NP_CTORS and \
                    not any(kw.arg == "dtype" for kw in node.keywords) and \
                    len(node.args) < _ctor_dtype_pos(name):
                seen.add(id(node))
                out.append(("G003", node,
                            f"dtype-less `{name.replace('numpy', 'np')}` "
                            "in jnp code defaults to float64/int64 "
                            "(silent downcast or x64 promotion)",
                            "pass an explicit dtype= (e.g. np.float32), "
                            "or build it with jnp"))
    return out


def _ctor_dtype_pos(name: str) -> int:
    # positional index where dtype may be passed without the keyword
    return {"numpy.full": 3, "numpy.arange": 99, "numpy.linspace": 99,
            "numpy.eye": 99}.get(name, 2)


# --------------------------------------------------------------- G004

def g004_rng_discipline(tree, imports, path):
    """(a) np.random / stdlib random inside traced functions: baked in at
    trace time, identical every step. (b) a PRNG key consumed by two
    jax.random calls without a split between them: correlated streams."""
    out = []
    for fn, _tracked in _traced_functions(tree, imports):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = imports.canon(node.func) or ""
                if name.startswith("numpy.random.") or \
                        name.startswith("random."):
                    out.append(("G004", node,
                                f"`{name}` inside a traced function is "
                                "frozen at trace time (same draw every "
                                "step)",
                                "thread a jax PRNG key through the "
                                "function and use jax.random"))
    # (b) key reuse, per function scope
    for fn in [n for n in ast.walk(tree)
               if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]:
        # keys born here, plus params that are keys by naming convention
        keys: set[str] = {
            a.arg for a in fn.args.posonlyargs + fn.args.args
            + fn.args.kwonlyargs if _KEY_PARAM_RE.search(a.arg)}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                name = imports.canon(node.value.func)
                if name in ("jax.random.PRNGKey", "jax.random.key"):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            keys.add(tgt.id)
        if not keys:
            continue
        consuming: dict[str, list[ast.Call]] = {k: [] for k in keys}
        rebinds: dict[str, list[int]] = {k: [] for k in keys}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = imports.canon(node.func) or ""
                if name.startswith("jax.random.") and \
                        name.split(".")[-1] not in _KEY_NONCONSUMING | {
                            "PRNGKey", "key"}:
                    for a in node.args:
                        if isinstance(a, ast.Name) and a.id in keys:
                            consuming[a.id].append(node)
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                tgts = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in tgts:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name) and n.id in keys:
                            rebinds[n.id].append(node.lineno)
        for key, uses in consuming.items():
            uses.sort(key=lambda n: n.lineno)
            for prev, cur in zip(uses, uses[1:]):
                # rebind may share the consuming line: key, s = split(key)
                if any(prev.lineno <= rb <= cur.lineno
                       for rb in rebinds[key]):
                    continue
                if _exclusive_paths(prev, cur, fn):
                    continue
                out.append(("G004", cur,
                            f"PRNG key `{key}` consumed again without "
                            f"a split (previous use line {prev.lineno}): "
                            "correlated random streams",
                            f"`{key}, sub = jax.random.split({key})` "
                            "and consume `sub`"))
    return out


def _enclosing_suites(node: ast.AST, fn: ast.AST):
    """(owner, field, suite) for every statement-suite between `node`
    and `fn`, innermost first — the control context of the node."""
    suites = []
    cur = node
    for par in _parents(node):
        for field in ("body", "orelse", "finalbody"):
            suite = getattr(par, field, None)
            if isinstance(suite, list) and any(s is cur for s in suite):
                suites.append((par, field, suite))
        cur = par
        if par is fn:
            break
    return suites


def _exclusive_paths(prev: ast.AST, cur: ast.AST, fn: ast.AST) -> bool:
    """True when `prev` executing implies `cur` cannot: they sit in
    opposite arms of one `if`, or prev's branch ends in return/raise
    (the if/elif-return ladder of weights.init_weight)."""
    prev_suites = _enclosing_suites(prev, fn)
    cur_owner_ids = {id(owner) for owner, _f, _s in
                     _enclosing_suites(cur, fn)}
    cur_suite_ids = {id(s) for _o, _f, s in _enclosing_suites(cur, fn)}
    for owner, field, suite in prev_suites:
        if isinstance(owner, ast.If):
            if id(owner) in cur_owner_ids and id(suite) not in \
                    cur_suite_ids:
                return True  # opposite arms of the same if
            if id(suite) not in cur_suite_ids and suite and isinstance(
                    suite[-1], (ast.Return, ast.Raise, ast.Continue,
                                ast.Break)):
                return True  # prev's arm leaves; cur is unreachable then
    return False


# --------------------------------------------------------------- G005

def g005_retrace_hazards(tree, imports, path):
    """jit re-creation per call — `jax.jit(f)(x)` or jit() inside a
    loop — recompiles every invocation; unhashable static_argnums raise
    at call time. Not caught: jit fns keyed on changing python scalars."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = imports.canon(node.func)
        if isinstance(node.func, ast.Call):
            inner = imports.canon(node.func.func)
            if inner in _JIT_NAMES:
                out.append(("G005", node,
                            "`jax.jit(f)(...)` creates and discards a "
                            "fresh compiled function every call (full "
                            "retrace each time)",
                            "hoist `jit(f)` to module level or cache it"))
        if name in _JIT_NAMES:
            for kw in node.keywords:
                if kw.arg == "static_argnums" and isinstance(
                        kw.value, (ast.List, ast.Dict, ast.Set)):
                    out.append(("G005", node,
                                "non-hashable static_argnums literal",
                                "use an int or tuple of ints"))
            for anc in _parents(node):
                if isinstance(anc, (ast.For, ast.While)):
                    out.append(("G005", node,
                                "jit() inside a loop body compiles a "
                                "fresh function per iteration",
                                "create the jitted function once, "
                                "outside the loop"))
                    break
                if isinstance(anc, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.Lambda)):
                    break
    return out


# --------------------------------------------------------------- G006

def g006_shard_map_arity(tree, imports, path):
    """shard_map in_specs/out_specs arity vs the wrapped function, when
    both are statically visible. Single-spec (pytree-prefix) forms and
    non-local callables are out of scope by design."""
    out = []
    local_defs = {n.name: n for n in ast.walk(tree)
                  if isinstance(n, ast.FunctionDef)}

    def check(call: ast.Call, fn_node, report_at):
        specs = {kw.arg: kw.value for kw in call.keywords
                 if kw.arg in ("in_specs", "out_specs")}
        in_specs = specs.get("in_specs")
        if isinstance(in_specs, (ast.Tuple, ast.List)) and \
                fn_node is not None:
            lo, hi = _arity_range(fn_node)
            if lo is not None and not lo <= len(in_specs.elts) <= hi:
                out.append(("G006", report_at,
                            f"in_specs has {len(in_specs.elts)} specs but "
                            f"`{getattr(fn_node, 'name', '<lambda>')}` "
                            f"takes {lo}"
                            + (f"-{hi}" if hi != lo else "")
                            + " positional args",
                            "one spec per positional arg (or a single "
                            "pytree-prefix spec)"))
        out_specs = specs.get("out_specs")
        if isinstance(out_specs, (ast.Tuple, ast.List)) and \
                isinstance(fn_node, ast.FunctionDef):
            lens = _return_tuple_lens(fn_node)
            if lens and all(n != len(out_specs.elts) for n in lens):
                out.append(("G006", report_at,
                            f"out_specs has {len(out_specs.elts)} specs "
                            f"but `{fn_node.name}` returns "
                            f"{sorted(lens)} values",
                            "match out_specs to the returned tuple"))

    def resolve_target(arg):
        """(fn_node, bound_positional) for direct name / lambda /
        functools.partial over a local def."""
        if isinstance(arg, ast.Lambda):
            return arg, 0
        if isinstance(arg, ast.Name):
            return local_defs.get(arg.id), 0
        if isinstance(arg, ast.Call):
            name = imports.canon(arg.func)
            if name in ("functools.partial", "partial") and arg.args:
                fn, extra = resolve_target(arg.args[0])
                return fn, extra + len(arg.args) - 1
        return None, 0

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = imports.canon(node.func) or ""
            if name == "shard_map" or name.endswith(".shard_map"):
                if node.args:
                    fn_node, bound = resolve_target(node.args[0])
                    if fn_node is not None and bound == 0:
                        check(node, fn_node, node)
                    elif fn_node is None:
                        check(node, None, node)
        elif isinstance(node, ast.FunctionDef):
            for deco in node.decorator_list:
                dname, call = _decorator_canon(deco, imports)
                if call is not None and dname and (
                        dname == "shard_map"
                        or dname.endswith(".shard_map")):
                    check(call, node, call)
    return out


def _arity_range(fn_node):
    args = fn_node.args
    if args.vararg is not None:
        return None, None
    pos = len(args.posonlyargs) + len(args.args)
    return pos - len(args.defaults), pos


def _return_tuple_lens(fn: ast.FunctionDef) -> set[int] | None:
    lens: set[int] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and node.value is not None:
            # only returns belonging to THIS def, not nested ones
            owner = next((p for p in _parents(node) if isinstance(
                p, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))),
                None)
            if owner is not fn:
                continue
            if isinstance(node.value, ast.Tuple):
                lens.add(len(node.value.elts))
            else:
                return None  # opaque return — cannot judge
    return lens or None


# --------------------------------------------------------------- G007

_COMPAT_SHIMS = {
    "jax.shard_map": "deeplearning4j_tpu.util.compat.shard_map",
    "jax.experimental.shard_map.shard_map":
        "deeplearning4j_tpu.util.compat.shard_map",
    "jax.lax.pcast": "deeplearning4j_tpu.util.compat.pcast_varying",
}


def g007_compat_bypass(tree, imports, path):
    """Raw uses of version-moved jax symbols (shard_map /
    TPUCompilerParams / pcast) that must route through util/compat.py so
    the next jax bump stays a one-file change."""
    if path.endswith("util/compat.py"):
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            for a in node.names:
                full = f"{mod}.{a.name}"
                if full in ("jax.shard_map",
                            "jax.experimental.shard_map.shard_map") or \
                        mod == "jax.experimental.shard_map":
                    out.append(("G007", node,
                                f"raw `from {mod} import {a.name}` moved "
                                "between jax 0.4/0.5",
                                "from deeplearning4j_tpu.util.compat "
                                "import shard_map"))
                elif a.name in ("TPUCompilerParams", "CompilerParams") \
                        and "pallas" in mod:
                    out.append(("G007", node,
                                f"raw `{a.name}` import was renamed "
                                "across jax versions",
                                "use util.compat.tpu_compiler_params()"))
        elif isinstance(node, ast.Attribute):
            name = imports.canon(node)
            if name in _COMPAT_SHIMS:
                out.append(("G007", node,
                            f"raw `{name}` moved between jax 0.4/0.5",
                            f"use {_COMPAT_SHIMS[name]}"))
            elif node.attr in ("TPUCompilerParams",):
                out.append(("G007", node,
                            "`TPUCompilerParams` was renamed "
                            "CompilerParams in jax 0.5",
                            "use util.compat.tpu_compiler_params()"))
            elif node.attr == "CompilerParams" and name and \
                    "pallas" in name:
                out.append(("G007", node,
                            "`CompilerParams` does not exist on jax "
                            "0.4.x pallas",
                            "use util.compat.tpu_compiler_params()"))
    return out


# --------------------------------------------------------------- G009

# the single home of the rendezvous layer; everything else routes
# through it (same shape as G007's compat routing)
_RENDEZVOUS_HOME = "distributed/bootstrap.py"

# the env-var contract's one spelling lives in bootstrap's ENV_*
# constants; a literal copy elsewhere silently forks the contract
_RENDEZVOUS_ENV_VARS = {
    "DL4J_TPU_COORDINATOR", "DL4J_TPU_PROCESS_ID",
    "DL4J_TPU_NUM_PROCESSES", "DL4J_TPU_LOCAL_DEVICE_COUNT",
    "DL4J_TPU_FAULTS",
}


def g009_rendezvous_routing(tree, imports, path):
    """Raw `jax.distributed.initialize`/`shutdown` calls or hand-rolled
    rendezvous env plumbing outside distributed/bootstrap.py. The
    bootstrap owns retry/backoff on connect, CPU-fleet collectives
    selection, the env-var contract, and per-process telemetry — a raw
    call sidesteps all four and reintroduces the untested-thin-wrapper
    failure mode (VERDICT r5 Missing #1)."""
    # the contract's home and this rule's own spelling of it are exempt
    if path.endswith((_RENDEZVOUS_HOME, "analysis/ast_rules.py")):
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            name = imports.canon(node)
            if name in ("jax.distributed.initialize",
                        "jax.distributed.shutdown"):
                out.append(("G009", node,
                            f"raw `{name}` bypasses the rendezvous "
                            "bootstrap (retry/backoff, env contract, "
                            "CPU collectives, telemetry)",
                            "use deeplearning4j_tpu.distributed."
                            "bootstrap.initialize()/shutdown()"))
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            if (node.module or "") == "jax.distributed":
                out.append(("G009", node,
                            "raw `from jax.distributed import ...` "
                            "bypasses the rendezvous bootstrap",
                            "use deeplearning4j_tpu.distributed."
                            "bootstrap.initialize()/shutdown()"))
        elif isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and \
                node.value in _RENDEZVOUS_ENV_VARS:
            out.append(("G009", node,
                        f"rendezvous env var {node.value!r} spelled as a "
                        "literal — the contract's one spelling lives in "
                        "distributed/bootstrap.py",
                        "import the ENV_* constant from "
                        "deeplearning4j_tpu.distributed.bootstrap"))
    return out


# --------------------------------------------------------------- G008

def g008_import_time(tree, imports, path):
    """(a) mutable default args — shared across calls; (b) module-level
    jnp allocations — they initialize a backend and pin a buffer at
    IMPORT time, before the program can pick devices/platform."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for d in node.args.defaults + [
                    d for d in node.args.kw_defaults if d is not None]:
                bad = isinstance(d, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
                if isinstance(d, ast.Call) and isinstance(
                        d.func, ast.Name) and \
                        d.func.id in _MUTABLE_DEFAULT_CALLS:
                    bad = True
                if bad:
                    out.append(("G008", d,
                                "mutable default argument is shared "
                                "across calls",
                                "default to None; create inside the "
                                "function"))
    # module-level device allocations: top-level stmts (incl. if/try
    # bodies and class-attr assignments) — anything inside a def runs
    # lazily and is out of scope here.
    def scan(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            name = imports.canon(node.func)
            if name in _DEVICE_ALLOC:
                out.append(("G008", node,
                            f"module-level `{name}` allocates a device "
                            "buffer at import time (captures the default "
                            "backend before it is configured)",
                            "allocate lazily inside a function, or keep "
                            "the constant in numpy"))
        for child in ast.iter_child_nodes(node):
            scan(child)

    for stmt in getattr(tree, "body", []):
        scan(stmt)
    return out


# --------------------------------------------------------------- G016

# The one module allowed to hold tunable Pallas block-size knobs: the
# tuning layer (table + heuristics + override hook). Kernels resolve
# their grids through it; a literal elsewhere re-freezes a knob the
# kerneltune sweep can no longer reach.
_TUNING_LAYER = ("ops/autotune.py",)

_PALLAS_BLOCKSPEC = {"jax.experimental.pallas.BlockSpec",
                     "jax.experimental.pallas.tpu.BlockSpec"}
_PALLAS_CALL = {"jax.experimental.pallas.pallas_call",
                "jax.experimental.pallas.tpu.pallas_call"}

# 128 is the hardware lane/sublane tile (MXU 128x128, VPU 8x128) —
# structural, not tunable; anything larger in a block/grid position is a
# swept knob that belongs in the tuning layer.
_G016_STRUCTURAL_MAX = 128

# module-level constant names that denote block/tile knobs (kernel files
# only): BLOCK_Q_MAX, _ROW_BLOCK, CHUNK_TILES, ...
_G016_CONST_RE = re.compile(r"BLOCK|TILE")


def _g016_literal_over(node: ast.AST):
    """Int literals > 128 anywhere inside a (possibly nested) tuple/list
    expression."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, int) \
                and not isinstance(sub.value, bool) \
                and sub.value > _G016_STRUCTURAL_MAX:
            yield sub


def g016_hardcoded_block_literals(tree, imports, path):
    """Pallas block-size/grid literals hardcoded outside the tuning
    layer (ops/autotune.py): (a) int literals > 128 inside a
    pl.BlockSpec block shape or a pallas_call grid= — the grid must be a
    function of the autotune-resolved block params, not a re-frozen
    constant; (b) module-level UPPERCASE BLOCK/TILE constants in ops/
    kernel files bound to int (or int-tuple) literals > 128 — the swept
    defaults live in autotune.py. 128 itself is the hardware lane tile
    (structural). Not caught: literals laundered through arithmetic
    (512 * 1) or non-BLOCK-named constants — precision over recall."""
    norm = path.replace("\\", "/")
    if any(norm.endswith(t) for t in _TUNING_LAYER):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = imports.canon(node.func)
        if name in _PALLAS_BLOCKSPEC:
            shape = None
            if node.args:
                shape = node.args[0]
            for kw in node.keywords:
                if kw.arg == "block_shape":
                    shape = kw.value
            if shape is not None and isinstance(shape, (ast.Tuple,
                                                        ast.List)):
                for lit in _g016_literal_over(shape):
                    out.append(("G016", lit,
                                f"hardcoded block-size literal "
                                f"{lit.value} in a pl.BlockSpec outside "
                                "the tuning layer — a knob the "
                                "kerneltune sweep cannot reach",
                                "resolve the block through "
                                "ops/autotune.py (flash_blocks/ln_rows/"
                                "xent_blocks) and pass the variable"))
        elif name in _PALLAS_CALL:
            for kw in node.keywords:
                if kw.arg == "grid" and isinstance(kw.value, (ast.Tuple,
                                                              ast.List)):
                    for lit in _g016_literal_over(kw.value):
                        out.append(("G016", lit,
                                    f"hardcoded grid literal {lit.value} "
                                    "in a pallas_call outside the tuning "
                                    "layer",
                                    "derive the grid from the autotune-"
                                    "resolved block sizes"))
    if "/ops/" in norm:
        for stmt in getattr(tree, "body", []):
            targets = []
            if isinstance(stmt, ast.Assign):
                targets = [t for t in stmt.targets
                           if isinstance(t, ast.Name)]
                value = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name) and stmt.value is not None:
                targets = [stmt.target]
                value = stmt.value
            else:
                continue
            for tgt in targets:
                if tgt.id.isupper() and _G016_CONST_RE.search(tgt.id):
                    for lit in _g016_literal_over(value):
                        out.append(("G016", lit,
                                    f"block/tile constant `{tgt.id}` "
                                    f"hardcodes {lit.value} in a kernel "
                                    "file — the swept defaults live in "
                                    "the tuning layer",
                                    "move the default to ops/autotune.py "
                                    "and alias it here"))
    return out


# --------------------------------------------------------------- G017

# Serving hot-path discipline (serving/ only). The continuous-batching
# contract is: requests are padded into the bucket lattice BEFORE any
# jitted call (else every novel length is a retrace worth seconds of
# tail latency), and results come back to host ONCE per batch (else N
# per-request device syncs serialize the pipeline). Exemptions are
# named, not inferred: bucket-shape dispatch (argument/function names
# mentioning bucket/batch/padded/warmup) and the batch-boundary fetch
# (a sync OUTSIDE a per-request loop).
_G017_REQUESTISH = re.compile(r"(^|_)(request|req|prompt)s?($|_|\b)",
                              re.IGNORECASE)
_G017_BUCKETISH = re.compile(r"bucket|batch|padded|warm", re.IGNORECASE)
_G017_SYNC_ATTRS = {"item", "block_until_ready"}
_G017_SYNC_CALLS = {"jax.device_get"}


def _g017_name_strings(node: ast.AST):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _g017_mentions(node: ast.AST, pattern) -> bool:
    return any(pattern.search(s) for s in _g017_name_strings(node))


def _g017_enclosing_fn_name(node: ast.AST) -> str:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur.name
        cur = getattr(cur, "parent", None)
    return ""


def g017_serving_hot_path(tree, imports, path):
    """Serving hot-path rule (serving/ files only), two halves:

    (a) UNBUCKETED JIT ENTRY: a jit-wrapped callable invoked with an
        argument that mentions a request-ish name (request/req/prompt)
        and nothing bucket-ish (bucket/batch/padded/warm) — raw request
        data fed straight into jit compiles one program per novel
        length. Bucket-shape dispatch is exempt by the name carve-out;
        so are warmup/bucket-named enclosing functions.
    (b) PER-REQUEST HOST SYNC: `.item()` / `.block_until_ready()` /
        `jax.device_get` inside a for-loop that iterates request-ish
        values — N device round-trips per batch. The batch-boundary
        fetch (one `np.asarray`/sync per BATCH, outside such loops)
        never flags."""
    norm = path.replace("\\", "/")
    if "/serving/" not in norm:
        return []
    out = []
    # names bound to jit results: `fwd = jax.jit(f)` / `self._jit = ...`
    jit_bound: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and imports.canon(node.value.func) in _JIT_NAMES:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    jit_bound.add(tgt.id)
                elif isinstance(tgt, ast.Attribute):
                    jit_bound.add(tgt.attr)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee = node.func
        is_jit_entry = (
            (isinstance(callee, ast.Name) and callee.id in jit_bound)
            or (isinstance(callee, ast.Attribute)
                and callee.attr in jit_bound)
            or (isinstance(callee, ast.Call)
                and imports.canon(callee.func) in _JIT_NAMES))
        if not is_jit_entry:
            continue
        if _G017_BUCKETISH.search(_g017_enclosing_fn_name(node)):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if _g017_mentions(arg, _G017_REQUESTISH) \
                    and not _g017_mentions(arg, _G017_BUCKETISH):
                out.append(("G017", node,
                            "unbucketed jit entry: raw request data fed "
                            "straight into a jitted callable — every "
                            "novel request shape is a retrace worth "
                            "seconds of tail latency",
                            "pad the request into a bucket batch first "
                            "(serving/batcher.py assemble) and pass the "
                            "bucketed arrays"))
                break
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor)):
            continue
        if not (_g017_mentions(loop.target, _G017_REQUESTISH)
                or _g017_mentions(loop.iter, _G017_REQUESTISH)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            name = imports.canon(node.func)
            is_sync = name in _G017_SYNC_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _G017_SYNC_ATTRS)
            if is_sync:
                out.append(("G017", node,
                            "per-request host sync inside a request "
                            "loop: one device round-trip per request "
                            "serializes the serving pipeline",
                            "fetch ONCE per batch (np.asarray on the "
                            "whole padded output — the batch-boundary "
                            "fetch) and distribute host-side rows"))
    return out


# --------------------------------------------------------------- G019

# Decode-loop discipline (serving/ only) — the generation-side twin of
# G017's host-sync half. The decode loop emits ONE token per active
# slot per step; the contract is ONE batch-boundary fetch of the whole
# next-token vector per step (np.asarray on the [n_slots] array), then
# host-side distribution. A `.item()` / `jax.device_get` /
# `.block_until_ready()` inside a loop over token-ish values is a
# device round-trip PER EMITTED TOKEN — at decode rates that serializes
# the whole generation pipeline behind host latency.
_G019_TOKENISH = re.compile(r"(^|_)(token|tok)s?($|_|\b)|decode",
                            re.IGNORECASE)


def g019_decode_loop_sync(tree, imports, path):
    """Per-token host syncs inside decode loops (serving/ files only):
    a for-loop whose target or iterable mentions token-ish names
    (token/tok/decode) containing `.item()` / `jax.device_get` /
    `.block_until_ready()`. The batch-boundary fetch — one sync for the
    whole step's token vector, OUTSIDE such loops — never flags."""
    norm = path.replace("\\", "/")
    if "/serving/" not in norm:
        return []
    out = []
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor)):
            continue
        if not (_g017_mentions(loop.target, _G019_TOKENISH)
                or _g017_mentions(loop.iter, _G019_TOKENISH)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            name = imports.canon(node.func)
            is_sync = name in _G017_SYNC_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _G017_SYNC_ATTRS)
            if is_sync:
                out.append(("G019", node,
                            "per-token host sync inside a decode loop: "
                            "one device round-trip per emitted token "
                            "serializes the generation pipeline behind "
                            "host latency",
                            "fetch the step's whole next-token vector "
                            "ONCE (np.asarray at the batch boundary) "
                            "and distribute host-side values"))
    return out


# --------------------------------------------------------------- G020

# Input-pipeline discipline: the fit step loops ride
# data/pipeline.iter_prefetched, which runs batch conversion
# (`_batch_dict` / `globalize_batch`) and device placement on a
# prefetch thread. A synchronous conversion INSIDE a step loop — the
# `while it.has_next():` shape every fit loop had before ISSUE 12 —
# serializes host input work in front of every step: at N fleet
# processes that's a per-step input tax the pipeline exists to hide.
_G020_CONVERTERS = frozenset({"_batch_dict", "_globalize_batch",
                              "globalize_batch", "globalize_full"})
_G020_DEVICE_PUTS = frozenset({"jax.device_put"})
# blessed: the pipeline's own synchronous fallback (depth 0 /
# async-unsupported iterators) and the host-prefetch adapter
_G020_BLESSED = ("deeplearning4j_tpu/data/",
                 "deeplearning4j_tpu/datasets/async_iterator.py")


def g020_sync_input_in_step_loop(tree, imports, path):
    """Synchronous batch conversion / device placement inside a fit
    step loop: a `while <x>.has_next():` loop containing a call to
    `_batch_dict` / `_globalize_batch` / `globalize_batch` /
    `globalize_full` or `jax.device_put`. Whole-epoch staging
    (`fit_scanned`'s list comprehension), per-window TBPTT conversion
    (a `for` over range), and batch-boundary fetches never flag — the
    rule keys on the step-loop shape itself."""
    norm = path.replace("\\", "/")
    if any(b in norm for b in _G020_BLESSED):
        return []
    out = []
    for loop in ast.walk(tree):
        if not isinstance(loop, ast.While):
            continue
        has_next = any(
            isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
            and n.func.attr == "has_next"
            for n in ast.walk(loop.test))
        if not has_next:
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            is_converter = (isinstance(node.func, ast.Attribute)
                            and node.func.attr in _G020_CONVERTERS) or \
                imports.canon(node.func) in _G020_CONVERTERS
            is_put = imports.canon(node.func) in _G020_DEVICE_PUTS
            if is_converter or is_put:
                out.append(("G020", node,
                            "synchronous batch conversion/device put "
                            "inside a fit step loop: host input work "
                            "runs serially in front of every step "
                            "instead of overlapping compute",
                            "route the loop through data/pipeline."
                            "iter_prefetched so conversion and the "
                            "device put run on the prefetch thread "
                            "(the depth-k bounded queue of device-"
                            "resident batches)"))
    return out


# --------------------------------------------------------------- G021

# Weight-swap discipline: serving replicas read their params through the
# engine's double-buffered WeightStore (serving/fleet.py), read ONCE per
# batch so a live hot-swap flips between batches and every request
# serves against ONE coherent generation. A direct write to a live
# `.params` reference, or a `resume_from` restore into a serving net
# outside the blessed path, bypasses the standby-slot restore, the
# shape/placement validation, the atomic flip, AND the `weight_swap`
# telemetry record — the swap happens (or half-happens) invisibly, mid-
# batch, with no rollback.
_G021_BLESSED = ("deeplearning4j_tpu/serving/fleet.py",)


def g021_weight_swap_path(tree, imports, path):
    """Param publish/flip outside the blessed swap path (serving/ files
    only; serving/fleet.py exempt): (a) assignment to a `.params`
    attribute — a direct write to what a worker serves; (b) any
    `.resume_from(...)` call — restoring INTO a serving net must route
    through fleet.restore_for_serving / fleet.hot_swap. Reading params
    (`ws.params`, `net.params is None`) never flags."""
    norm = path.replace("\\", "/")
    if "/serving/" not in norm or any(b in norm for b in _G021_BLESSED):
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "params":
                    out.append((
                        "G021", node,
                        "direct write to a live param reference in "
                        "serving code: bypasses the WeightStore double "
                        "buffer — a replica mid-batch can observe a "
                        "half-swapped param set and there is no "
                        "validation, generation record, or rollback",
                        "publish through serving/fleet.py: "
                        "hot_swap(engine, ckpt) restores into a shadow "
                        "net, validates, and flips atomically"))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "resume_from":
            out.append((
                "G021", node,
                "resume_from on a net inside serving code: restores "
                "INTO the served params outside the blessed swap path "
                "(no double buffer, no shape/placement validation, no "
                "weight_swap telemetry, old weights unrecoverable on a "
                "bad checkpoint)",
                "route restores through serving/fleet."
                "restore_for_serving (startup) or fleet.hot_swap "
                "(live)"))
    return out


# --------------------------------------------------------------- G022

# Placement discipline at the USER-FACING layers: examples/, cli/, and
# the elastic runtime are where mesh layouts get hand-guessed — exactly
# the habit the automatic placement search (reshard/search.py) retires.
# A raw `jax.sharding.Mesh(...)` construction, or an axis-role dict
# literal ({"data": ..., "model": ...}) fed to a mesh builder /
# set_mesh, bypasses Placement validation (PlacementError feasibility)
# AND the search's ranking+telemetry — the layout ships unvalidated and
# unrecorded. The blessed spellings are `planner.Placement.of/
# from_json` (validated declarative data; set_mesh consumes it
# directly) and `search_placement`/`searched_global_mesh` (the ranked
# search). Library internals (parallel/, reshard/, distributed/
# global_mesh) stay out of scope: they IMPLEMENT the blessed paths.
_G022_SCOPE_FRAGMENTS = ("/examples/", "/cli/")
_G022_SCOPE_SUFFIXES = ("distributed/elastic.py",)
_G022_ROLE_NAMES = frozenset({"data", "model", "pipe", "seq", "expert"})
_G022_MESH_CALL_TAILS = frozenset({"Mesh", "make_mesh", "make_global_mesh",
                                   "set_mesh"})
_G022_BLESSED_TAILS = frozenset({"search_placement",
                                 "searched_global_mesh"})


def _g022_call_tail(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _g022_is_blessed(node: ast.Call) -> bool:
    """Placement.of / Placement.from_json / search entry points."""
    func = node.func
    tail = _g022_call_tail(func)
    if tail in _G022_BLESSED_TAILS:
        return True
    if isinstance(func, ast.Attribute) and tail in ("of", "from_json"):
        base = func.value
        base_name = (base.attr if isinstance(base, ast.Attribute)
                     else getattr(base, "id", ""))
        return base_name == "Placement"
    return False


def _g022_role_dict(arg: ast.AST) -> bool:
    """A dict literal whose string keys are ALL placement roles (and at
    least one) — the hand-written axis/role map shape. Comprehensions,
    parsed variables, and non-role dicts never flag."""
    if not isinstance(arg, ast.Dict) or not arg.keys:
        return False
    keys = []
    for k in arg.keys:
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return False
        keys.append(k.value)
    return all(k in _G022_ROLE_NAMES for k in keys)


def g022_handrolled_placement(tree, imports, path):
    """Hand-constructed placements at the user-facing layers (examples/,
    cli/, distributed/elastic.py): (a) a raw `jax.sharding.Mesh(...)`
    constructor call; (b) an axis-role dict literal passed to
    make_mesh / make_global_mesh / set_mesh / Mesh. Route through
    `planner.Placement.of` (validated declarative data — set_mesh
    consumes the Placement directly) or `search_placement`/
    `searched_global_mesh` (the ranked search), whose own calls are
    exempt."""
    # leading slash so relative paths ("examples/foo.py") match too
    norm = "/" + path.replace("\\", "/").lstrip("/")
    if not (any(f in norm for f in _G022_SCOPE_FRAGMENTS)
            or any(norm.endswith(s) for s in _G022_SCOPE_SUFFIXES)):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or _g022_is_blessed(node):
            continue
        name = imports.canon(node.func) or ""
        tail = _g022_call_tail(node.func)
        if name == "jax.sharding.Mesh" or name.endswith("sharding.Mesh"):
            out.append(("G022", node,
                        "raw `jax.sharding.Mesh(...)` construction in a "
                        "user-facing layer: the layout skips Placement "
                        "validation (PlacementError feasibility) and the "
                        "placement search's ranking + telemetry",
                        "declare the layout as planner.Placement.of(...) "
                        "and feed it to set_mesh, or let "
                        "search_placement pick it"))
            continue
        if tail not in _G022_MESH_CALL_TAILS:
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            if _g022_role_dict(arg):
                out.append(("G022", node,
                            f"hand-written axis-role dict literal fed to "
                            f"`{tail}` in a user-facing layer — an "
                            "unvalidated, unranked mesh layout (the "
                            "habit the automatic placement search "
                            "retires)",
                            "build the layout with planner.Placement.of "
                            "(set_mesh consumes it directly) or take "
                            "the search_placement winner"))
                break
    return out


# --------------------------------------------------------------- G023

# Telemetry schema discipline: the fleet-timeline tooling
# (telemetry/trace.py merge/stats/anomaly/Perfetto, tools/tracetool.py)
# classifies every record it merges by its event kind and span name.
# An event("...")/span("...") literal invented at a call site is a
# record the registered schema (recorder.py EVENT_KINDS/SPAN_NAMES +
# the docstring table) doesn't know — it parses as noise, joins no
# tree, and silently falls out of stats and anomaly detection. The
# blessed home of new kinds/names is the registry itself: telemetry/
# is exempt (it IS the schema), and dynamic names (f-strings like the
# bench sweep's `mode:<name>` spans) are uncheckable statically and
# stay silent.
_G023_EXEMPT = ("deeplearning4j_tpu/telemetry/",)
_G023_SETS: dict = {}


def _g023_registered():
    """(EVENT_KINDS, SPAN_NAMES) from the registry, cached; resolves
    under the stage-1 no-jax stubs (telemetry/ is stdlib-pure). An
    unresolvable registry disables the rule rather than crashing the
    lint."""
    if "sets" not in _G023_SETS:
        try:
            from deeplearning4j_tpu.telemetry.recorder import (EVENT_KINDS,
                                                               SPAN_NAMES)
            _G023_SETS["sets"] = (EVENT_KINDS, SPAN_NAMES)
        except Exception:  # pragma: no cover - broken stub layouts
            _G023_SETS["sets"] = None
    return _G023_SETS["sets"]


def _g023_str_arg(node: ast.AST):
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def g023_unregistered_telemetry_names(tree, imports, path):
    """An `<obj>.event("<kind>")` whose kind literal is not a
    registered EVENT_KIND, or an `<obj>.span("<name>")` /
    `event("span", name="<name>")` whose name literal is not a
    registered SPAN_NAME, outside telemetry/. Non-literal (variable /
    f-string) names and non-string first arguments (`re.Match.span(0)`)
    never flag."""
    norm = path.replace("\\", "/")
    if any(b in norm for b in _G023_EXEMPT):
        return []
    sets = _g023_registered()
    if sets is None:
        return []
    event_kinds, span_names = sets
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute) \
                or node.func.attr not in ("span", "event") \
                or not node.args:
            continue
        lit = _g023_str_arg(node.args[0])
        if lit is None:
            continue
        if node.func.attr == "span":
            if lit not in span_names:
                out.append(("G023", node,
                            f"span name {lit!r} is not in the registered "
                            "schema (telemetry/recorder.py SPAN_NAMES): "
                            "the fleet-timeline tooling cannot classify "
                            "it — it joins no stats row, no tree, no "
                            "anomaly rule",
                            "register the name in SPAN_NAMES (and the "
                            "recorder docstring table) first, or reuse "
                            "an existing span name"))
            continue
        if lit not in event_kinds:
            out.append(("G023", node,
                        f"event kind {lit!r} is not in the registered "
                        "schema (telemetry/recorder.py EVENT_KINDS): "
                        "merged timelines parse it as noise",
                        "register the kind in EVENT_KINDS (and the "
                        "recorder docstring table) first, or use a "
                        "typed Recorder method"))
        elif lit == "span":
            for kw in node.keywords:
                if kw.arg != "name":
                    continue
                name_lit = _g023_str_arg(kw.value)
                if name_lit is not None and name_lit not in span_names:
                    out.append(("G023", node,
                                f"span name {name_lit!r} (via "
                                "event(\"span\", name=...)) is not in "
                                "the registered schema "
                                "(telemetry/recorder.py SPAN_NAMES)",
                                "register the name in SPAN_NAMES (and "
                                "the recorder docstring table) first"))
    return out


# --------------------------------------------------------------- G024

# Sampling discipline (serving/ only) — the sampling-side twin of
# G019's host-sync half. Token selection belongs ON DEVICE in the one
# fused kernel (ops/fused_sampling.fused_sample: temperature, top-k,
# top-p and the gumbel argmax in a single pass, f32 accumulation).
# Host-side sampling inside a decode loop — an `np.random.*` /
# `random.*` draw, or an `argsort` / `cumsum` over fetched logits to
# rebuild top-k/top-p by hand — ships the [slots, vocab] logit matrix
# to the host EVERY STEP and reorders the vocab in numpy: at decode
# rates that is the pipeline's largest avoidable transfer, and the
# hand-rolled filter drifts from the kernel's tie-breaking.
_G024_HOST_RNG_PREFIXES = ("numpy.random.", "random.")
_G024_SORTISH_ATTRS = frozenset({"argsort", "cumsum"})
_G024_SORTISH_CALLS = frozenset({"numpy.argsort", "numpy.cumsum"})
_G024_LOGITSISH = re.compile(r"logit|prob|score", re.IGNORECASE)


def g024_host_sampling(tree, imports, path):
    """Host-side sampling in decode loops (serving/ files only): inside
    a for-loop whose target or iterable mentions token-ish names
    (token/tok/decode), flag `np.random.*` / `random.*` draws and
    `argsort`/`cumsum` calls over logits-ish values (logit/prob/score).
    The blessed path is ops/fused_sampling.fused_sample — one fused
    on-device kernel per step, with host code handling only the
    returned token ids."""
    norm = path.replace("\\", "/")
    if "/serving/" not in norm:
        return []
    out = []
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor)):
            continue
        if not (_g017_mentions(loop.target, _G019_TOKENISH)
                or _g017_mentions(loop.iter, _G019_TOKENISH)):
            continue
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call):
                continue
            name = imports.canon(node.func) or ""
            if name.startswith(_G024_HOST_RNG_PREFIXES):
                out.append(("G024", node,
                            "host RNG draw inside a decode loop: token "
                            "selection off-device means a per-step "
                            "logit fetch and numpy-side sampling that "
                            "drifts from the kernel's tie-breaking",
                            "sample on device via ops/fused_sampling."
                            "fused_sample (temperature/top-k/top-p in "
                            "one kernel; gumbel noise from a split PRNG "
                            "key) and distribute the returned ids"))
                continue
            sortish = name in _G024_SORTISH_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _G024_SORTISH_ATTRS)
            if not sortish:
                continue
            over_logits = any(
                _g017_mentions(arg, _G024_LOGITSISH)
                for arg in list(node.args)
                + [kw.value for kw in node.keywords]) or (
                isinstance(node.func, ast.Attribute)
                and _g017_mentions(node.func.value, _G024_LOGITSISH))
            if over_logits:
                out.append(("G024", node,
                            "host-side top-k/top-p reconstruction "
                            "(argsort/cumsum over logits) inside a "
                            "decode loop: the [slots, vocab] matrix "
                            "crosses to the host every step",
                            "filter on device via ops/fused_sampling."
                            "fused_sample — its top-k/top-p masking "
                            "runs in the same kernel as the sample"))
    return out


# --------------------------------------------------------------- G029

# Memory-introspection discipline — the observability twin of G002's
# host-sync rule. `dev.memory_stats()` queries the backend allocator,
# `jax.live_arrays()` walks EVERY live buffer in the process, and
# `compiled.memory_analysis()` re-summarizes an executable: host work
# measured in milliseconds, and inside a jit-traced function they
# additionally burn in as compile-time constants (the trace sees one
# snapshot forever). The blessed producers put the walk where the hot
# path can't feel it: telemetry/memstat.py samples at batch boundaries
# / on its own thread, telemetry/costbook.py harvests at warmup-time
# compile. Everyone else consumes their cached `memory`/`cost` events.
_G029_BLESSED = ("deeplearning4j_tpu/telemetry/memstat.py",
                 "deeplearning4j_tpu/telemetry/costbook.py")
_G029_INTROSPECT = frozenset({"memory_stats", "live_arrays",
                              "memory_analysis"})
_G029_CANON = frozenset({"jax.live_arrays"})


def g029_memory_introspection_hot_path(tree, imports, path):
    """A `memory_stats()` / `live_arrays()` / `memory_analysis()` call
    inside a jit-traced function or a per-token / per-request loop.
    Batch-boundary or warmup-time introspection (plain functions, no
    hot loop) stays silent — that IS the sampler contract — and the
    two blessed producer modules are exempt."""
    norm = path.replace("\\", "/")
    if norm.endswith(_G029_BLESSED):
        return []
    out = []
    seen: set[int] = set()

    def scan(scope, where):
        for node in ast.walk(scope):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            attr = (node.func.attr
                    if isinstance(node.func, ast.Attribute) else None)
            name = imports.canon(node.func) or ""
            if attr in _G029_INTROSPECT or name in _G029_CANON:
                seen.add(id(node))
                out.append((
                    "G029", node,
                    f"device-memory introspection ({attr or name}) "
                    f"inside {where}: a full live-buffer walk / "
                    "allocator query on the hot path — and under jit "
                    "it traces as a frozen compile-time constant",
                    "sample at batch boundaries via telemetry/"
                    "memstat.py (MemorySampler.on_step/maybe_sample) "
                    "or harvest at warmup via telemetry/costbook.py, "
                    "then read the cached event/ledger"))

    for fn, _params in _traced_functions(tree, imports):
        scan(fn, "a jit-traced function")
    for loop in ast.walk(tree):
        if not isinstance(loop, (ast.For, ast.AsyncFor)):
            continue
        if (_g017_mentions(loop.target, _G019_TOKENISH)
                or _g017_mentions(loop.iter, _G019_TOKENISH)
                or _g017_mentions(loop.target, _G017_REQUESTISH)
                or _g017_mentions(loop.iter, _G017_REQUESTISH)):
            scan(loop, "a per-token/per-request loop")
    return out


# --------------------------------------------------------------- G030

# Sparse-embedding discipline — the data-movement twin of G016's
# block-literal rule. An embedding step touches a handful of rows out
# of a vocab-sized table; the two ways to lose that sparsity are (a) a
# dense `jnp.take` gather over the full table outside the engine (at
# ep>1 this materializes every shard's rows on every rank instead of
# the masked-psum partial gather) and (b) densifying the sparse
# gradient — `jnp.zeros_like(table).at[idx].add(grads)` allocates and
# all-reduces a full table-shaped buffer where the overlap layer's
# sparse bucket kind (parallel/overlap.plan_sparse_bucket) moves only
# (indices, values) pairs. The blessed sites own those patterns: the
# embedding engine internally (its scatter is per-shard, post-psum),
# the legacy dense reference (nlp/lookup.py — the ep=1 parity anchor),
# and the device pipeline's fused epoch step.
_G030_BLESSED = ("deeplearning4j_tpu/embedding/",
                 "deeplearning4j_tpu/nlp/lookup.py",
                 "deeplearning4j_tpu/nlp/device_pipeline.py")
# identifiers that read as a full embedding table; deliberately exact
# (cum_table / tuning_table / a weight "W" must not match)
_G030_TABLEISH = re.compile(
    r"^(syn0|syn1|syn1neg|embed(ding)?s?(_table)?|emb_table|"
    r"lookup_table|vocab_table)$")
_G030_TABLE_NAMES = frozenset({"syn0", "syn1", "syn1neg"})


def _g030_ident(node: ast.AST) -> str | None:
    """The identifier text of a table-ish operand: bare name, attribute
    leaf (`self.syn0`), or a constant subscript key (`params["table"]`)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Subscript):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return sl.value
    return None


def _g030_is_zeros_like(node: ast.AST, imports) -> bool:
    return (isinstance(node, ast.Call)
            and imports.canon(node.func) in ("jax.numpy.zeros_like",
                                             "numpy.zeros_like"))


def g030_dense_embedding_path(tree, imports, path):
    """A full-table gather (`jnp.take(table, ...)`, `syn0[idx]`) or a
    densified sparse gradient (`jnp.zeros_like(table).at[idx].add(g)`)
    outside the embedding engine's blessed internals — the dense
    pattern the sparse (indices, values) contract exists to replace."""
    norm = path.replace("\\", "/")
    if any(b in norm if b.endswith("/") else norm.endswith(b)
           for b in _G030_BLESSED):
        return []
    out = []
    for node in ast.walk(tree):
        # (a) dense gather: jnp.take over a table-ish operand, or a
        # direct subscript load of the canonical table names
        if isinstance(node, ast.Call) \
                and imports.canon(node.func) == "jax.numpy.take" \
                and node.args:
            ident = _g030_ident(node.args[0])
            if ident and _G030_TABLEISH.match(ident):
                out.append((
                    "G030", node,
                    f"dense jnp.take over the full embedding table "
                    f"({ident!r}) outside the engine: at ep>1 this "
                    "gathers every shard's rows on every rank",
                    "route lookups through embedding/engine.py "
                    "(ShardedEmbeddingEngine.embed / the step's masked "
                    "partial gather + psum)"))
        elif isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in _G030_TABLE_NAMES:
            out.append((
                "G030", node,
                f"direct subscript gather over embedding table "
                f"{node.value.id!r} outside the blessed dense "
                "reference (nlp/lookup.py)",
                "use embedding/engine.py's sharded gather (or the "
                "EngineLookupView accessors, which slice the padded "
                "device table once)"))
        # (b) densified sparse gradient:
        # jnp.zeros_like(T).at[idx].add(values)
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "add" \
                and isinstance(node.func.value, ast.Subscript) \
                and isinstance(node.func.value.value, ast.Attribute) \
                and node.func.value.value.attr == "at" \
                and _g030_is_zeros_like(node.func.value.value.value,
                                        imports):
            out.append((
                "G030", node,
                "sparse gradient densified into a table-shaped buffer "
                "(zeros_like(table).at[idx].add(values)): allocates "
                "and reduces the full vocab where only the touched "
                "rows carry signal",
                "keep gradients as (indices, values) pairs and move "
                "them with parallel/overlap.sparse_bucket_reduce (the "
                "sparse bucket kind); scatter per-shard inside "
                "embedding/engine.py"))
    return out


# stage-3 AST rules (G010-G014) live in spmd_rules.py and register here;
# the import sits below every helper they borrow lazily, so importing
# either module first resolves cleanly.
from deeplearning4j_tpu.analysis.spmd_rules import (  # noqa: E402
    SPMD_RULE_DOCS,
    SPMD_RULES,
)
# stage-4 AST rules (G025-G028, host-concurrency) live in
# concurrency_rules.py and register the same way
from deeplearning4j_tpu.analysis.concurrency_rules import (  # noqa: E402
    CONC_RULE_DOCS,
    CONC_RULE_IDS,
    CONC_RULES,
)
# stage-5 AST rules (G031-G034, precision discipline) live in
# precision_rules.py and register the same way
from deeplearning4j_tpu.analysis.precision_rules import (  # noqa: E402
    PRECISION_RULE_DOCS,
    PRECISION_RULE_IDS,
    PRECISION_RULES,
)

ALL_RULES = [g001_traced_bool, g002_host_sync, g003_float64_drift,
             g004_rng_discipline, g005_retrace_hazards,
             g006_shard_map_arity, g007_compat_bypass, g008_import_time,
             g009_rendezvous_routing,
             g016_hardcoded_block_literals,
             g017_serving_hot_path, g019_decode_loop_sync,
             g020_sync_input_in_step_loop,
             g021_weight_swap_path,
             g022_handrolled_placement,
             g023_unregistered_telemetry_names,
             g024_host_sampling,
             g029_memory_introspection_hot_path,
             g030_dense_embedding_path] + SPMD_RULES + CONC_RULES \
    + PRECISION_RULES

RULE_DOCS = {
    "G001": "python control flow / bool()/float()/int() on traced values",
    "G002": "implicit host sync (.item/np.asarray/device_get) in hot paths",
    "G003": "dtype-less np constructors mixed into jnp code (float64 drift)",
    "G004": "np.random/random in traced code; PRNG key reuse without split",
    "G005": "per-call jit creation / non-hashable static_argnums (retraces)",
    "G006": "shard_map in_specs/out_specs arity vs wrapped function",
    "G007": "version-moved jax symbols bypassing util/compat.py",
    "G008": "mutable default args; module-level jnp allocations",
    "G009": "raw jax.distributed / rendezvous env plumbing bypassing "
            "distributed/bootstrap.py",
    "G016": "Pallas block-size/grid literals hardcoded outside the "
            "tuning layer (ops/autotune.py)",
    "G017": "serving hot-path discipline: unbucketed jit entries and "
            "per-request host syncs in serving/ (bucket dispatch and "
            "the batch-boundary fetch are exempt)",
    "G019": "decode-loop discipline: per-token host syncs "
            "(.item/device_get/block_until_ready) inside token-ish "
            "loops in serving/ — the generation pipeline's per-step "
            "batch-boundary fetch is the blessed pattern",
    "G020": "synchronous globalize_batch/_batch_dict/device-put inside "
            "fit step loops (while has_next) bypassing the data/ input "
            "pipeline — the pipeline's own sync fallback and the "
            "AsyncDataSetIterator adapter are the blessed sites",
    "G021": "param publish/flip outside the blessed serving/fleet.py "
            "swap path: direct `.params` assignment or `resume_from` "
            "in serving/ bypasses the double-buffered WeightStore "
            "(validation, atomic flip, weight_swap telemetry)",
    "G022": "hand-constructed Mesh(...) / axis-role dict literals in "
            "the user-facing layers (examples/, cli/, "
            "distributed/elastic.py) outside the blessed "
            "planner.Placement / search_placement paths — unvalidated, "
            "unranked mesh layouts",
    "G023": "telemetry event kinds / span names invented at the call "
            "site: an event(\"...\")/span(\"...\") string literal "
            "outside telemetry/ that is not in the registered schema "
            "(recorder.py EVENT_KINDS/SPAN_NAMES) — the fleet-timeline "
            "tooling cannot classify such records",
    "G024": "sampling discipline: host-side token sampling "
            "(np.random/random draws, argsort/cumsum over logits) "
            "inside decode loops in serving/ — token selection belongs "
            "in the fused on-device kernel "
            "(ops/fused_sampling.fused_sample)",
    "G029": "memory-introspection discipline: memory_stats()/"
            "live_arrays()/memory_analysis() inside jit-traced "
            "functions or per-token/per-request loops — a live-buffer "
            "walk on the hot path (frozen as a constant under jit); "
            "the blessed producers are telemetry/memstat.py (batch-"
            "boundary sampler) and telemetry/costbook.py (warmup "
            "harvest)",
    "G030": "sparse-embedding discipline: dense jnp.take / subscript "
            "gathers over full-vocab embedding tables, and sparse "
            "gradients densified via zeros_like(table).at[].add(...), "
            "outside the blessed engine internals (embedding/, "
            "nlp/lookup.py, nlp/device_pipeline.py) — gradients travel "
            "as (indices, values) pairs through the overlap layer's "
            "sparse bucket kind",
    **SPMD_RULE_DOCS,
    **CONC_RULE_DOCS,
    **PRECISION_RULE_DOCS,
}


def run_rules(tree: ast.AST, source: str, path: str) -> list[Finding]:
    """All rules over one parsed file -> raw findings (no suppression)."""
    _walk_with_parents(tree)
    imports = Imports(tree)
    lines = source.splitlines()
    findings = []
    for rule in ALL_RULES:
        for rule_id, node, message, fixit in rule(tree, imports, path):
            line = getattr(node, "lineno", 0)
            col = getattr(node, "col_offset", 0)
            snippet = lines[line - 1].strip() if 0 < line <= len(lines) \
                else ""
            stage = ("concurrency" if rule_id in CONC_RULE_IDS
                     else "precision" if rule_id in PRECISION_RULE_IDS
                     else "ast")
            findings.append(Finding(rule_id, path, line, col, message,
                                    fixit, snippet, stage=stage))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
