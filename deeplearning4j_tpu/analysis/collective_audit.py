"""Stage 3: collective-consistency audit (SPMD divergence detection).

The trace-level twin of the G010-G014 AST rules (spmd_rules.py). Walks
each frozen entry point's closed jaxpr (recursing into pjit/scan/cond
sub-jaxprs via jaxpr_audit._iter_eqns) and extracts the **ordered
collective signature** — the (primitive, axis names, operand
shape/dtype) sequence the program issues. Treating that sequence as a
checkable artifact follows arXiv:2112.01075 (collective sequences as
portable, verifiable programs) and arXiv:2004.13336 (sharding decisions
audited, not emergent):

- C001: collective signature drift — the traced sequence differs from
  the frozen one in analysis/collective_budget.json. A reordered,
  added, or dropped collective is how rank-divergence regressions start;
  regenerate deliberately with `tools/graftlint.py --update-collectives`
  (same UX as the stage-2 op budget).
- C002: entry point missing from the frozen signature file.
- C003: rank-divergent collective sequence — the entry point re-traced
  under simulated `process_index` 0 vs 1 (env-contract override +
  patched jax.process_index; virtual devices, no real fleet) issues
  different collective sequences. That program DEADLOCKS a live fleet
  (the jax 0.4.x SIGABRT "Deadline Exceeded" failure mode documented in
  ARCHITECTURE.md §Distributed runtime) — so it is reported as a
  deadlock finding naming both sequences, never as a budget diff.

Entry points cover both ways collectives exist in this repo:

- shard_map programs carry collectives IN the jaxpr (`psum`,
  `ppermute`, ... primitives) — the ring-attention and sequence-parallel
  entries.
- pjit programs get their collectives from GSPMD *after* partitioning,
  so the jaxpr is collective-free; for those the signature is extracted
  from the compiled HLO (`hlo:all-reduce ...` items, ordered by
  channel id) on an 8-virtual-device CPU mesh — the
  `distributed/allreduce_step_2x4` entry is the same set_mesh/fit
  allreduce step tests/test_distributed.py proves on a live 2-process
  x 4-device fleet.

External fixture entries: a .py file passed to `graftlint --stage spmd`
that defines ``GRAFTLINT_SPMD_ENTRIES = {name: builder}`` (builder() ->
(fn, args)) gets each entry divergence-checked — the demo path for the
deadlock finding without freezing a signature.

jax and the model stack load lazily; importing this module is cheap and
jax-free (the AST stage never touches it).
"""

from __future__ import annotations

import contextlib
import json
import os
import re

from deeplearning4j_tpu.analysis.core import Finding

BUDGET_PATH = os.path.join(os.path.dirname(__file__),
                           "collective_budget.json")

# the hook external fixture modules expose: {entry_name: builder}
ENTRY_HOOK = "GRAFTLINT_SPMD_ENTRIES"

SIMULATED_PROCESSES = (0, 1)

# jaxpr-level collective primitives (pmean lowers to psum; axis_index is
# rank-local and issues no communication, so it is not part of the
# deadlock-relevant sequence)
JAXPR_COLLECTIVES = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "pbroadcast", "pcast",
})

# post-GSPMD HLO collective ops (async *-start/-done variants normalize
# to the base name)
HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute", "all-to-all")

_HLO_RE = re.compile(
    r"=\s+(\(?[a-z0-9]+\[[^=]*?)\s+"
    r"(" + "|".join(HLO_COLLECTIVES) + r")(?:-start|-done)?\(")
_CHANNEL_RE = re.compile(r"channel_id=(\d+)")
_LAYOUT_RE = re.compile(r"\{[^}]*\}")


def entry_names() -> list[str]:
    """Auditable stage-3 entry points (stable order). Safe to call
    without jax — used for test parametrization."""
    return [
        "distributed/allreduce_step_2x4",
        "distributed/overlap_step_2x4",
        "reshard/live_transpose_2x4",
        "ring_attention/seq4",
        "sequence_parallel/sp_step_seq2",
    ]


# ----------------------------------------------------------- extraction

def jaxpr_collectives(closed) -> list[str]:
    """Ordered collective signature of a closed jaxpr:
    `primitive@axes operand-shape/dtype` per collective eqn, recursing
    into pjit/scan/cond sub-jaxprs."""
    from deeplearning4j_tpu.analysis.jaxpr_audit import _iter_eqns

    sig = []
    for eqn in _iter_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim not in JAXPR_COLLECTIVES:
            continue
        axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        aval = getattr(eqn.invars[0], "aval", None) if eqn.invars else None
        short = aval.str_short() if hasattr(aval, "str_short") else ""
        sig.append(f"{prim}@{','.join(str(a) for a in axes)} {short}".strip())
    return sig


def hlo_collectives(hlo_text: str) -> list[str]:
    """Ordered collective signature of a compiled HLO module:
    `hlo:op result-shape` per collective, ordered by channel id (XLA
    assigns channel ids in program order; textual order follows
    computation nesting instead)."""
    hits = []
    for line in hlo_text.splitlines():
        m = _HLO_RE.search(line)
        if not m:
            continue
        shape = _LAYOUT_RE.sub("", m.group(1)).strip()
        chan = _CHANNEL_RE.search(line)
        hits.append((int(chan.group(1)) if chan else 1 << 30, len(hits),
                     f"hlo:{m.group(2)} {shape}"))
    return [item for _, _, item in sorted(hits)]


def trace_signature(build, *, hlo: bool = False):
    """-> (signature, eqn_count) for one built entry. `build` is a
    zero-arg callable returning (fn, args); tracing uses abstract
    evaluation (nothing executes), and `hlo=True` additionally compiles
    on the current (virtual-CPU) devices to harvest the post-GSPMD
    collectives pjit hides from the jaxpr."""
    import jax

    from deeplearning4j_tpu.analysis.jaxpr_audit import _iter_eqns

    fn, args = build()
    closed = jax.make_jaxpr(fn)(*args)
    sig = jaxpr_collectives(closed)
    if hlo:
        sig += hlo_collectives(fn.lower(*args).compile().as_text())
    return sig, sum(1 for _ in _iter_eqns(closed.jaxpr))


# ----------------------------------------------------- rank simulation

@contextlib.contextmanager
def simulated_process_index(pid: int):
    """Trace-time rank simulation — no real fleet. Overrides the env
    contract's process id (distributed/bootstrap.py's single spelling)
    and patches jax.process_index, so any rank read an entry performs at
    trace time sees `pid`. Virtual devices stay as-is: collectives only
    need to be *issued* identically, not executed."""
    import jax

    from deeplearning4j_tpu.distributed import bootstrap

    saved = {var: os.environ.get(var)
             for var in (bootstrap.ENV_PROCESS_ID,
                         bootstrap.ENV_NUM_PROCESSES)}
    os.environ[bootstrap.ENV_PROCESS_ID] = str(pid)
    os.environ[bootstrap.ENV_NUM_PROCESSES] = str(len(SIMULATED_PROCESSES))
    real = jax.process_index
    jax.process_index = lambda backend=None: pid
    try:
        yield
    finally:
        jax.process_index = real
        for var, val in saved.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val


def check_divergence(name: str, build) -> list[Finding]:
    """Re-trace one entry under simulated process_index 0 vs 1 and
    assert the collective sequences are identical. A divergent sequence
    is a DEADLOCK finding (C003) naming both sequences; identical
    sequences with different traced op counts is the same class (the
    programs differ, the fleet desyncs) with a count-based message."""
    results = {}
    for pid in SIMULATED_PROCESSES:
        with simulated_process_index(pid):
            results[pid] = trace_signature(build)
    (sig0, n0), (sig1, n1) = (results[p] for p in SIMULATED_PROCESSES)
    if sig0 != sig1:
        return [Finding(
            "C003", name, 0, 0,
            "rank-divergent collective sequence — this program DEADLOCKS "
            f"a live fleet (SIGABRT \"Deadline Exceeded\"): process 0 "
            f"issues {sig0 or '[]'} but process 1 issues {sig1 or '[]'}",
            "remove the rank-dependent branch around the collective "
            "(G010); every process must issue the identical sequence",
            snippet="rank-divergent-collectives", stage="spmd")]
    if n0 != n1:
        return [Finding(
            "C003", name, 0, 0,
            f"rank-divergent traced program — identical collective "
            f"sequences but {n0} vs {n1} traced ops under simulated "
            "process_index 0 vs 1: a rank-dependent value is baked into "
            "the program (G011 shape) and the replicas will desync",
            "make the trace rank-invariant; read the rank only inside "
            "host-side (untraced) code",
            snippet="rank-divergent-ops", stage="spmd")]
    return []


# -------------------------------------------------------- entry points

def _ensure_devices():
    from deeplearning4j_tpu.util.virtual_devices import ensure_cpu_devices

    ensure_cpu_devices(8)


def _build_allreduce_step():
    """The 2-process x 4-device allreduce train step of
    tests/test_distributed.py, on the equivalent 8-virtual-device local
    mesh (same global device count, same set_mesh/fit pjit program; the
    live-fleet test proves execution, this entry freezes the collective
    program it runs)."""
    import jax
    import numpy as np

    _ensure_devices()
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.conf import (DenseLayer,
                                            NeuralNetConfiguration,
                                            OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("sgd").list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_mesh(make_mesh({"data": 8}))
    rng = np.random.default_rng(0)
    x = rng.random((32, 6), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    batch = net._batch_dict(DataSet(x, y))
    step = net._get_train_step()
    return step, (net.params, net.opt_state, net.state,
                  jax.random.PRNGKey(0), batch)


def _build_overlap_step():
    """The ISSUE 7 bucketed-overlap train step on the same 8-device
    mesh/net as the allreduce entry, with a bucket size that forces
    MULTIPLE buckets on the tiny net: the frozen signature IS the
    per-rank bucket sequence (one psum@data per bucket, reverse layer
    order, then the loss/state pmeans) — identical on every simulated
    rank or the fleet deadlocks. shard_map carries its collectives in
    the jaxpr, so no HLO extraction is needed."""
    import jax
    import numpy as np

    _ensure_devices()
    from deeplearning4j_tpu.datasets.api import DataSet
    from deeplearning4j_tpu.nn.conf import (DenseLayer,
                                            NeuralNetConfiguration,
                                            OutputLayer)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("sgd").list()
            .layer(DenseLayer(n_in=6, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    # 128-byte buckets split the 83-param net into several buckets
    net.set_mesh(make_mesh({"data": 8}), overlap=128)
    rng = np.random.default_rng(0)
    x = rng.random((32, 6), dtype=np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    batch = net._batch_dict(DataSet(x, y))
    step = net._get_train_step()
    return step, (net.params, net.opt_state, net.state,
                  jax.random.PRNGKey(0), batch)


def _build_reshard_live():
    """The portable resharding engine's live executor
    (reshard/executor.live_identity): a TP-placed param tree moved
    across a dp<->tp role transpose on the SAME 8 virtual devices — the
    set_mesh re-placement / elastic re-form shape. The jit identity is
    collective-free in the jaxpr; GSPMD lowers the move to the
    collective-permute/all-gather program this entry freezes, so a
    reordered transfer (the C001 drift class) is caught before it can
    desync a live re-form."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    _ensure_devices()
    from deeplearning4j_tpu.reshard.executor import live_identity

    devs = np.asarray(jax.devices()[:8])
    mesh_a = Mesh(devs.reshape(2, 4), ("data", "model"))
    mesh_b = Mesh(devs.reshape(4, 2), ("data", "model"))
    tree = {
        "w": jax.device_put(
            np.arange(64, dtype=np.float32).reshape(8, 8),
            NamedSharding(mesh_a, P(None, "model"))),
        "b": jax.device_put(np.arange(8, dtype=np.float32),
                            NamedSharding(mesh_a, P("model"))),
    }
    shardings = {"w": NamedSharding(mesh_b, P("model", None)),
                 "b": NamedSharding(mesh_b, P())}
    return live_identity(shardings), (tree,)


def _build_ring_attention():
    """ring_self_attention over a 4-way seq mesh (einsum fallback at
    Tl=2): the ppermute ring is the jaxpr-level collective workload."""
    import jax

    _ensure_devices()
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.ring_attention import \
        ring_self_attention

    mesh = make_mesh({"seq": 4})
    sds = jax.ShapeDtypeStruct((1, 1, 8, 4), "float32")
    return (lambda q, k, v: ring_self_attention(q, k, v, mesh)), \
        (sds, sds, sds)


def _build_sp_step():
    """make_sp_train_step on a tiny transformer over a 2-way seq mesh:
    pmean'd grads/loss/state + the ring's ppermutes."""
    import jax
    import numpy as np

    _ensure_devices()
    from deeplearning4j_tpu.models.transformer import transformer_lm
    from deeplearning4j_tpu.parallel.mesh import make_mesh
    from deeplearning4j_tpu.parallel.sequence_parallel import \
        make_sp_train_step

    net = transformer_lm(vocab_size=17, d_model=8, n_heads=2, n_layers=1,
                         d_ff=16, max_length=8, seed=3,
                         seq_parallel_axis="seq")
    net.init()
    step = make_sp_train_step(net, make_mesh({"seq": 2}), seq_axis="seq")
    toks = np.zeros((2, 8), np.int32)
    return step, (net.params, net.opt_state, net.state,
                  jax.random.PRNGKey(0), toks,
                  np.roll(toks, -1, axis=1))


# pjit entries get their collectives from GSPMD, so they need the HLO
# extraction; shard_map entries carry them in the jaxpr
_BUILDERS = {
    "distributed/allreduce_step_2x4": (_build_allreduce_step, True),
    "distributed/overlap_step_2x4": (_build_overlap_step, False),
    "reshard/live_transpose_2x4": (_build_reshard_live, True),
    "ring_attention/seq4": (_build_ring_attention, False),
    "sequence_parallel/sp_step_seq2": (_build_sp_step, False),
}


# -------------------------------------------------------------- audit

def load_budget(path: str | None = None) -> dict[str, list[str]]:
    try:
        with open(path or BUDGET_PATH) as fh:
            return {k: list(v)
                    for k, v in json.load(fh)["signatures"].items()}
    except FileNotFoundError:
        return {}


def write_budget(signatures: dict[str, list[str]],
                 path: str | None = None) -> None:
    with open(path or BUDGET_PATH, "w") as fh:
        json.dump(
            {"comment": "frozen ordered collective signatures per entry "
                        "point (graftlint stage 3). A drift here is a "
                        "rank-divergence regression unless deliberate: "
                        "tools/graftlint.py --update-collectives",
             "signatures": {k: signatures[k] for k in sorted(signatures)}},
            fh, indent=1)
        fh.write("\n")


def audit(names=None, budget_path: str | None = None, *,
          divergence: bool = True):
    """Run the stage-3 audit -> (findings, {entry: signature})."""
    budget = load_budget(budget_path)
    findings, signatures = [], {}
    for name in names if names is not None else entry_names():
        build, want_hlo = _BUILDERS[name]
        sig, _count = trace_signature(build, hlo=want_hlo)
        signatures[name] = sig
        frozen = budget.get(name)
        if frozen is None:
            findings.append(Finding(
                "C002", name, 0, 0,
                f"entry point has no frozen collective signature (traced "
                f"{len(sig)} collective(s))",
                "run `python tools/graftlint.py --update-collectives`",
                snippet="missing-signature", stage="spmd"))
        elif frozen != sig:
            findings.append(Finding(
                "C001", name, 0, 0,
                f"collective signature drift — frozen {frozen} but the "
                f"trace now issues {sig}: a reordered/added/dropped "
                "collective is how rank-divergence regressions start",
                "find what changed the collective sequence; only then "
                "refreeze (--update-collectives)",
                snippet="signature-drift", stage="spmd"))
        if divergence:
            findings.extend(check_divergence(name, build))
    return findings, signatures


def load_entry_module(path: str):
    """Import a fixture .py by path and return its GRAFTLINT_SPMD_ENTRIES
    hook ({name: builder}), or {} when it defines none."""
    import importlib.util

    modname = "_graftlint_spmd_" + re.sub(r"\W", "_", os.path.abspath(path))
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return getattr(mod, ENTRY_HOOK, {})


def audit_paths(paths) -> tuple[list[Finding], dict[str, list[str]]]:
    """Divergence-check every external entry the given .py files expose
    (no frozen-signature requirement — these are demo/fixture entries)."""
    findings, signatures = [], {}
    for path in paths:
        if not (path.endswith(".py") and os.path.isfile(path)):
            continue
        for name, build in load_entry_module(path).items():
            sig, _count = trace_signature(build)
            signatures[name] = sig
            findings.extend(check_divergence(name, build))
    return findings, signatures
