"""graftlint stage 4, part 2: whole-package lock-ORDER audit.

The collective audit (stage 3) catches the *device-side* deadlock
class statically: rank-divergent collective sequences (C003). This
module is its host-side twin. It builds a directed lock-order graph
over the whole package — for every ``with lockA:`` region, every lock
acquired inside the region (directly, or one call-hop deep through
helpers the analysis can resolve) adds an edge ``lockA -> lockB`` —
and then:

- D001  a CYCLE in the graph is a deadlock finding naming both
        acquisition chains file:line. Two threads entering the cycle
        from different edges block each other forever. Cycles always
        exit 1 from the CLI, baseline or not.
- D002  lock acquisition inside a telemetry sink/collector callback
        invoked while a lock is held — the sink-reentrancy shape the
        /metrics wiring introduced in PR 15: ``Recorder.event`` fans
        out to registered sinks, and a sink that takes a lock (the
        MetricsRegistry histogram update) runs under whatever the
        emitter holds.
- D003  lock-order drift — the blessed edge set is FROZEN in
        ``analysis/lock_order.json`` (same discipline as the jaxpr op
        budget and the collective signatures). A new edge that closes
        no cycle is drift: reviewed, then refrozen with
        ``tools/graftlint.py --update-locks``. A frozen edge that
        vanished is stale and also drift.

Edge nodes are ``<relpath>:<Class>.<lockgroup>`` (class locks) or
``<relpath>:<name>`` (module-level locks); a lock group is the
attribute set sharing one underlying lock, e.g. the Channel's two
conditions over one Lock are the single node
``data/prefetcher.py:Channel._not_empty|_not_full``.

Call-hop resolution is deliberately conservative: ``self.m()``
resolves exactly within the class; a cross-class ``obj.m()`` resolves
only when exactly one lock-acquiring method in the package bears that
name and the name is not generic (put/get/join/...); everything else
is skipped rather than guessed. The graph under-approximates — a
reported cycle is real.

Pure stdlib; runs with jax poisoned, like stages 1 and 4a.
"""

from __future__ import annotations

import ast
import json
import os

from deeplearning4j_tpu.analysis.concurrency_rules import (
    ClassModel,
    _callback_loop_attr,
    _module_locks,
    _own_nodes,
    _self_attr,
)
from deeplearning4j_tpu.analysis.core import Finding

LOCKS_PATH = os.path.join(os.path.dirname(__file__), "lock_order.json")

RULE_DOCS = {
    "D001": "lock-order cycle: two `with` chains acquire the same locks "
            "in opposite order — threads entering from different edges "
            "deadlock; the host-side twin of C003 (always exits 1)",
    "D002": "sink reentrancy: registered telemetry sink/collector "
            "callbacks invoked while a lock is held — a sink that "
            "acquires a lock (metrics histogram update) runs under "
            "whatever the emitter holds",
    "D003": "lock-order drift: an edge not in the frozen "
            "analysis/lock_order.json (or a stale frozen edge) — "
            "review the new acquisition order, then refreeze with "
            "--update-locks",
}

# Method names too generic to resolve cross-class by name alone
# (queue.Queue.put vs Channel.put, threading vs domain join/close...).
_GENERIC_NAMES = frozenset({
    "put", "get", "join", "wait", "wait_for", "acquire", "release",
    "start", "stop", "close", "run", "set", "clear", "is_set",
    "describe", "send", "recv", "submit", "next", "read", "write",
    "flush", "poll", "step", "reset", "update", "add", "append",
    "pop", "remove", "notify", "notify_all",
})


class _FileInfo:
    def __init__(self, path: str, rel: str):
        from deeplearning4j_tpu.analysis.ast_rules import (
            Imports, _walk_with_parents)
        self.path = path
        self.rel = rel
        with open(path, encoding="utf-8") as fh:
            self.source = fh.read()
        self.tree = _walk_with_parents(ast.parse(self.source))
        self.imports = Imports(self.tree)
        self.models = [ClassModel(n, self.imports)
                       for n in ast.walk(self.tree)
                       if isinstance(n, ast.ClassDef)]
        self.by_class = {id(m.node): m for m in self.models}
        self.mod_locks = _module_locks(self.tree, self.imports)
        self.mod_funcs = {n.name: n for n in self.tree.body
                          if isinstance(n, ast.FunctionDef)}

    def model_at(self, node) -> ClassModel | None:
        from deeplearning4j_tpu.analysis.ast_rules import _parents
        for p in _parents(node):
            if isinstance(p, ast.ClassDef):
                return self.by_class.get(id(p))
        return None

    def lock_nodes(self, expr, model) -> set[str]:
        """Graph node ids a with-item / acquisition expr resolves to."""
        out = set()
        if model is not None:
            g = model.group_of_expr(expr)
            if g:
                out.add(f"{self.rel}:{model.name}.{g}")
        if isinstance(expr, ast.Name) and expr.id in self.mod_locks:
            out.add(f"{self.rel}:{expr.id}")
        return out


def _direct_acquires(fn, info: _FileInfo, model) -> set[str]:
    """Node ids of locks a call to *fn* acquires directly (own
    statements only — nested defs run later, on other threads)."""
    out: set[str] = set()
    for node in _own_nodes(fn):
        if isinstance(node, ast.With):
            for item in node.items:
                out |= info.lock_nodes(item.context_expr, model)
    return out


def _is_property(fn) -> bool:
    return any(isinstance(d, ast.Name) and d.id == "property"
               for d in fn.decorator_list)


def _build(paths: list[str], root: str):
    """(edges, findings) for the files in *paths*.

    edges: {(src, dst): (rel, line, context)} — first site wins.
    findings: raw D002 findings (cycles/drift are computed by callers).
    """
    from deeplearning4j_tpu.analysis.ast_pass import iter_py_files

    files: list[tuple[str, str]] = []
    for p in paths:
        if os.path.isdir(p):
            for f in iter_py_files([p]):
                files.append((f, os.path.relpath(f, root).replace(
                    os.sep, "/")))
        else:
            files.append((p, os.path.relpath(p, root).replace(
                os.sep, "/")))

    infos = []
    for path, rel in files:
        try:
            infos.append(_FileInfo(path, rel))
        except SyntaxError:
            continue  # stage 1 reports G000 for these

    # package-wide name maps: method/property name -> node ids it
    # acquires, kept only while unambiguous and non-generic
    meth_map: dict[str, set[str]] = {}
    prop_map: dict[str, set[str]] = {}
    for info in infos:
        for model in info.models:
            for mname, fn in model.methods.items():
                acq = _direct_acquires(fn, info, model)
                if not acq:
                    continue
                dest = prop_map if _is_property(fn) else meth_map
                dest.setdefault(mname, set()).update(acq)

    edges: dict[tuple[str, str], tuple[str, int, str]] = {}
    findings: list[Finding] = []

    def add_edge(held: set[str], dsts: set[str], info, node, why: str):
        line = getattr(node, "lineno", 0)
        for src in sorted(held):
            for dst in sorted(dsts):
                if src != dst:
                    edges.setdefault((src, dst), (info.rel, line, why))

    for info in infos:
        lines = info.source.splitlines()
        for w in ast.walk(info.tree):
            if not isinstance(w, ast.With):
                continue
            model = info.model_at(w)
            held = set()
            for item in w.items:
                held |= info.lock_nodes(item.context_expr, model)
            if not held:
                continue
            for stmt in w.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                    continue
                for node in [stmt] + list(_own_nodes(stmt)):
                    if isinstance(node, ast.With):
                        acq = set()
                        for item in node.items:
                            acq |= info.lock_nodes(item.context_expr,
                                                   model)
                        add_edge(held, acq, info, node, "nested with")
                    elif isinstance(node, ast.Call):
                        _edge_for_call(node, held, info, model,
                                       meth_map, add_edge)
                        cb = _callback_loop_attr(node)
                        if cb is not None:
                            line = node.lineno
                            snippet = lines[line - 1].strip() \
                                if 0 < line <= len(lines) else ""
                            findings.append(Finding(
                                "D002", info.rel, line,
                                node.col_offset,
                                f"registered callbacks from "
                                f"`self.{cb}` run while holding "
                                f"{'/'.join(sorted(held))} — a sink "
                                f"that acquires a lock (the metrics "
                                f"histogram update) executes under "
                                f"the emitter's lock: the "
                                f"sink-reentrancy deadlock shape",
                                "snapshot the callback list under the "
                                "lock, invoke the callbacks after "
                                "releasing it",
                                snippet, stage="concurrency"))
                    elif isinstance(node, ast.Attribute) and \
                            isinstance(node.ctx, ast.Load):
                        parent = getattr(node, "_gl_parent", None)
                        if isinstance(parent, ast.Call) and \
                                parent.func is node:
                            continue  # handled as a call
                        name = node.attr
                        if name in _GENERIC_NAMES:
                            continue
                        targets = prop_map.get(name, set())
                        if len(targets) == 1:
                            add_edge(held, targets, info, node,
                                     f"property .{name}")
    return edges, findings


def _edge_for_call(call, held, info, model, meth_map, add_edge):
    func = call.func
    attr = _self_attr(func)
    if attr is not None:
        # exact: a same-class helper
        if model is not None and attr in model.methods:
            acq = _direct_acquires(model.methods[attr], info, model)
            add_edge(held, acq, info, call, f"self.{attr}()")
        return
    if isinstance(func, ast.Name):
        fn = info.mod_funcs.get(func.id)
        if fn is not None:
            acq = _direct_acquires(fn, info, None)
            # module fns can also take module locks of this file
            for node in _own_nodes(fn):
                if isinstance(node, ast.With):
                    for item in node.items:
                        acq |= info.lock_nodes(item.context_expr, None)
            add_edge(held, acq, info, call, f"{func.id}()")
        return
    if isinstance(func, ast.Attribute):
        name = func.attr
        if name in _GENERIC_NAMES:
            return
        targets = meth_map.get(name, set())
        if len(targets) == 1:
            add_edge(held, targets, info, call, f".{name}()")


# ------------------------------------------------------------------ cycles

def _find_cycles(edges) -> list[list[str]]:
    """Deduped simple cycles of the edge graph, as node paths
    (first node repeated at the end)."""
    adj: dict[str, list[str]] = {}
    for (src, dst) in edges:
        adj.setdefault(src, []).append(dst)
    for dsts in adj.values():
        dsts.sort()
    cycles, seen = [], set()

    def dfs(node, path, onpath, visited):
        for dst in adj.get(node, ()):
            if dst in onpath:
                cyc = path[path.index(dst):] + [dst]
                key = frozenset(cyc)
                if key not in seen:
                    seen.add(key)
                    cycles.append(cyc)
            elif dst not in visited:
                visited.add(dst)
                onpath.add(dst)
                dfs(dst, path + [dst], onpath, visited)
                onpath.discard(dst)

    for start in sorted(adj):
        dfs(start, [start], {start}, {start})
    return cycles


def _cycle_findings(cycles, edges) -> list[Finding]:
    out = []
    for cyc in cycles:
        chain_bits, rel0, line0 = [], "", 0
        for a, b in zip(cyc, cyc[1:]):
            rel, line, why = edges[(a, b)]
            if not rel0:
                rel0, line0 = rel, line
            chain_bits.append(f"{a} -> {b} ({rel}:{line}, {why})")
        out.append(Finding(
            "D001", rel0, line0, 0,
            "lock-order cycle — threads acquiring these locks in "
            "opposite order deadlock: " + "; ".join(chain_bits),
            "pick one global order for the locks in the cycle and "
            "acquire in that order everywhere (or drop to a single "
            "lock / release before the cross-acquisition)",
            "", stage="concurrency"))
    return out


# ------------------------------------------------------------------ frozen

def load_locks(path: str = LOCKS_PATH) -> list[str] | None:
    try:
        with open(path, encoding="utf-8") as fh:
            return list(json.load(fh).get("edges", []))
    except FileNotFoundError:
        return None


def write_locks(edge_strs, path: str = LOCKS_PATH) -> None:
    payload = {
        "_comment": "graftlint stage 4: blessed lock-order edges "
                    "(held -> acquired). Reviewed acquisition orders; "
                    "refreeze with tools/graftlint.py --update-locks. "
                    "A cycle among these can never be frozen — D001 "
                    "always fails the run.",
        "edges": sorted(edge_strs),
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")


def _edge_strs(edges) -> list[str]:
    return sorted(f"{a} -> {b}" for (a, b) in edges)


# ------------------------------------------------------------------ entry

def _package_root() -> tuple[str, str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return pkg, os.path.dirname(pkg)


def audit(locks_path: str = LOCKS_PATH):
    """Full package audit against the frozen edge set.

    Returns (findings, edge_strs). D001 for cycles, D002 for sink
    reentrancy, D003 for drift vs analysis/lock_order.json.
    """
    pkg, root = _package_root()
    edges, findings = _build([pkg], root)
    cycles = _find_cycles(edges)
    findings.extend(_cycle_findings(cycles, edges))
    cyclic_nodes = {n for cyc in cycles for n in cyc}

    cur = _edge_strs(edges)
    frozen = load_locks(locks_path)
    if frozen is None:
        findings.append(Finding(
            "D003", os.path.relpath(locks_path, root).replace(
                os.sep, "/"), 0, 0,
            "no frozen lock-order edge set — the lock graph is "
            "unreviewed",
            "inspect the current edges, then freeze them with "
            "tools/graftlint.py --update-locks",
            "", stage="concurrency"))
        return findings, cur
    frozen_set = set(frozen)
    for (a, b), (rel, line, why) in sorted(edges.items()):
        s = f"{a} -> {b}"
        if s in frozen_set or a in cyclic_nodes or b in cyclic_nodes:
            continue
        findings.append(Finding(
            "D003", rel, line, 0,
            f"new lock-order edge not in the frozen set: {s} "
            f"(via {why}) — a nested acquisition the last review "
            f"never blessed",
            "confirm the acquisition order is consistent "
            "everywhere, then refreeze with --update-locks",
            "", stage="concurrency"))
    for s in sorted(frozen_set - set(cur)):
        findings.append(Finding(
            "D003", "analysis/lock_order.json", 0, 0,
            f"stale frozen lock-order edge no longer in the code: {s}",
            "refreeze with --update-locks to drop it",
            "", stage="concurrency"))
    return findings, cur


def audit_paths(paths):
    """Fixture mode: audit explicit .py files with no frozen-set
    comparison — cycles (D001) and sink reentrancy (D002) only."""
    abspaths = [os.path.abspath(p) for p in paths]
    root = os.path.dirname(abspaths[0]) if abspaths else os.getcwd()
    edges, findings = _build(abspaths, root)
    findings.extend(_cycle_findings(_find_cycles(edges), edges))
    return findings, _edge_strs(edges)


def current_edges():
    """(edge_strs, by-edge site map) for the live package — what
    --update-locks freezes."""
    pkg, root = _package_root()
    edges, _ = _build([pkg], root)
    return _edge_strs(edges), edges
